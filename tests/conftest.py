"""Test configuration.

Mirrors the reference's envtest trick (SURVEY.md §4): run everything on
CPU with a virtual 8-device platform so mesh/sharding code is exercised
without TPU hardware.
"""

import os
import sys
from pathlib import Path

# Must be set before jax is imported anywhere.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Tests run on the virtual 8-device CPU platform by default — the env
# may carry JAX_PLATFORMS pointing at real/tunneled TPU hardware (e.g.
# "axon"), and the config API outranks it. Opt into hardware tests
# explicitly with ACTIVEMONITOR_TEST_TPU=1.
if os.environ.get("ACTIVEMONITOR_TEST_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache, same pattern the probe battery uses
# (probes/suite.enable_persistent_compile_cache): the suite compiles
# hundreds of small 8-device mesh programs and their SUM is what the
# tier-1 wall clock pays — a warm cache turns repeat runs from
# compile-bound into execute-bound. Opt out with
# ACTIVEMONITOR_TEST_NO_COMPILE_CACHE=1 (e.g. to time cold compiles).
if os.environ.get("ACTIVEMONITOR_TEST_NO_COMPILE_CACHE") != "1":
    try:
        import jax

        _cache_dir = os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "activemonitor-tpu",
            "xla-test-cache",
        )
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as exc:  # cache is a speedup, never a gate
        sys.stderr.write(f"xla test compile cache disabled: {exc}\n")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# pytest-asyncio is not installed in this image; run coroutine tests
# with asyncio.run via the pyfunc hook instead.
import asyncio
import inspect


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run test in an event loop")
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 gate (-m 'not slow') — deep "
        "compile-heavy coverage that the soak/full tiers run",
    )


def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
