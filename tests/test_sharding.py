"""Sharded controller fleet — router, shard elections, fencing,
work-stealing, and the statusz rollup (controller/sharding.py).

Everything time-driven runs on the FakeClock against the stub API
server, the same determinism discipline as the leader-election tier
(tests/test_leader_k8s.py).
"""

import asyncio
from collections import Counter

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    InMemoryHealthCheckClient,
    ShardCoordinator,
    ShardFencedError,
    ShardFilteredClient,
    ShardRouter,
)
from activemonitor_tpu.controller.sharding import (
    DEPTH_ANNOTATION,
    shard_lease_name,
)
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.utils.clock import FakeClock

from tests.kube_harness import advance, drive_until, stub_env

LEASE = 15.0


def make_hc(name: str, namespace: str = "health"):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": namespace},
            "spec": {"repeatAfterSec": 300},
        }
    )


from tests.kube_harness import hard_kill_shards as crash  # noqa: E402


def coordinator(api, clock, shards, shard_id, metrics=None, **kw):
    return ShardCoordinator(
        api=api,
        namespace="health",
        shards=shards,
        shard_id=shard_id,
        identity=f"replica-{shard_id}",
        clock=clock,
        metrics=metrics,
        lease_seconds=LEASE,
        **kw,
    )


# ---------------------------------------------------------------------
# consistent-hash router
# ---------------------------------------------------------------------


def test_router_is_deterministic_and_covers_every_shard():
    a, b = ShardRouter(5), ShardRouter(5)
    keys = [f"health/chk-{i:05d}" for i in range(5000)]
    assert [a.shard_for(k) for k in keys] == [b.shard_for(k) for k in keys]
    counts = Counter(a.shard_for(k) for k in keys)
    assert set(counts) == set(range(5))
    # consistent hashing is never perfectly uniform; the bound that
    # matters for capacity planning is "no shard is a hotspot"
    assert max(counts.values()) < 2 * min(counts.values())


def test_router_scale_up_moves_a_minority_of_keys():
    """Adding a shard must remap roughly 1/(N+1) of the keys — the
    consistent-hash property that makes scale-up a partial handoff
    instead of a full fleet reshuffle."""
    keys = [f"health/chk-{i:05d}" for i in range(6000)]
    r3, r4 = ShardRouter(3), ShardRouter(4)
    moved = sum(1 for k in keys if r3.shard_for(k) != r4.shard_for(k))
    assert moved / len(keys) < 0.45  # modulo hashing would move ~0.75
    # and every moved key landed on the NEW shard's id space
    assert all(
        r4.shard_for(k) == 3 for k in keys if r3.shard_for(k) != r4.shard_for(k)
    )


def test_router_single_shard_owns_everything():
    r = ShardRouter(1)
    assert {r.shard_for(f"k-{i}") for i in range(100)} == {0}


def test_shard_lease_names_are_distinct_and_prefixed():
    names = {shard_lease_name(s) for s in range(16)}
    assert len(names) == 16
    assert all(n.startswith("689451f8.keikoproj.io-shard-") for n in names)


def test_cli_shards_flag_requires_k8s_client(capsys):
    """--shards > 1 without the Kubernetes store is a usage error (the
    shard map lives in coordination Leases), surfaced as exit 2 before
    any side effects."""
    from activemonitor_tpu.__main__ import main

    rc = main(
        ["run", "--shards", "3", "--shard-id", "1", "--client", "file",
         "--metrics-bind-address", "0", "--health-probe-bind-address", "0"]
    )
    assert rc == 2
    assert "--shards" in capsys.readouterr().err
    # a typo'd 0/negative must error, not silently run unsharded with
    # no election (four such replicas would all reconcile everything)
    rc = main(
        ["run", "--shards", "0", "--client", "file",
         "--metrics-bind-address", "0", "--health-probe-bind-address", "0"]
    )
    assert rc == 2
    assert "--shards" in capsys.readouterr().err
    # and a shard-id outside [0, shards) is a usage error even sharded
    rc = main(
        ["run", "--shards", "3", "--shard-id", "3", "--client", "file",
         "--metrics-bind-address", "0", "--health-probe-bind-address", "0"]
    )
    assert rc == 2
    assert "--shard-id" in capsys.readouterr().err


# ---------------------------------------------------------------------
# shard-filtered client
# ---------------------------------------------------------------------


@pytest.mark.asyncio
async def test_shard_filtered_client_filters_list_and_watch_live():
    inner = InMemoryHealthCheckClient()
    owned = {"hc-a"}
    client = ShardFilteredClient(inner, lambda ns, name: name in owned)
    seen = []
    # the wrapper registers the inner subscription at watch() CALL time
    # (list-then-watch contract) — before any apply below
    watch_iter = client.watch()

    async def consume():
        async for ev in watch_iter:
            seen.append((ev.type, ev.name))

    task = asyncio.create_task(consume())
    try:
        await inner.apply(make_hc("hc-a"))
        await inner.apply(make_hc("hc-b"))
        listed = [hc.metadata.name for hc in await client.list()]
        assert listed == ["hc-a"]
        # unfiltered verbs pass through (handoff races read across shards)
        assert await client.get("health", "hc-b") is not None
        await asyncio.sleep(0.05)
        assert seen == [("ADDED", "hc-a")]
        # ownership is LIVE: adopting hc-b's shard admits its events
        # without re-establishing the stream
        owned.add("hc-b")
        await inner.apply(make_hc("hc-b"))
        await asyncio.sleep(0.05)
        assert ("MODIFIED", "hc-b") in seen
        assert [hc.metadata.name for hc in await client.list()] == ["hc-a", "hc-b"]
    finally:
        task.cancel()


@pytest.mark.asyncio
async def test_k8s_client_owns_predicate_filters_before_parse():
    from activemonitor_tpu.controller.client_k8s import KubernetesHealthCheckClient

    async with stub_env() as (server, api):
        seeder = KubernetesHealthCheckClient(api)
        for name in ("hc-a", "hc-b", "hc-c"):
            await seeder.apply(make_hc(name))
        owned = {"hc-a", "hc-c"}
        client = KubernetesHealthCheckClient(
            api, owns=lambda ns, name: name in owned
        )
        listed = [hc.metadata.name for hc in await client.list()]
        assert listed == ["hc-a", "hc-c"]
        seen = []

        async def consume():
            async for ev in client.watch():
                seen.append((ev.type, ev.name))

        task = asyncio.create_task(consume())
        try:
            await seeder.apply(make_hc("hc-b"))
            await seeder.apply(make_hc("hc-c"))

            async def got_c():
                return ("MODIFIED", "hc-c") in seen

            for _ in range(100):
                if await got_c():
                    break
                await asyncio.sleep(0.05)
            assert ("MODIFIED", "hc-c") in seen
            assert not any(name == "hc-b" for _t, name in seen)
        finally:
            task.cancel()


# ---------------------------------------------------------------------
# shard elections: home preference, adoption, shed
# ---------------------------------------------------------------------


@pytest.mark.asyncio
async def test_home_shards_acquired_eagerly_peers_stand_by():
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 2, 0)
        b = coordinator(api, clock, 2, 1)
        try:
            await asyncio.wait_for(
                asyncio.gather(a.start(), b.start()), 5
            )
            # each replica holds exactly its home shard…
            await advance(clock, LEASE * 2)
            assert a.owned_shards() == [0]
            assert b.owned_shards() == [1]
            # …and the leases carry the holders' identities
            lease0 = server.obj(
                "coordination.k8s.io", "v1", "leases", "health", shard_lease_name(0)
            )
            lease1 = server.obj(
                "coordination.k8s.io", "v1", "leases", "health", shard_lease_name(1)
            )
            assert lease0["spec"]["holderIdentity"] == "replica-0"
            assert lease1["spec"]["holderIdentity"] == "replica-1"
        finally:
            await a.stop()
            await b.stop()


@pytest.mark.asyncio
async def test_dead_owners_shard_is_adopted_by_the_survivor():
    """Crash-safe handoff at the lease layer: a dead owner's shard is
    adopted by the survivor's standby once the lease expires (no
    release, no cooperation from the corpse required)."""
    acquired = []

    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 2, 0)
        b = coordinator(api, clock, 2, 1)

        async def on_acquired(shard):
            acquired.append(("a", shard))

        a.on_acquired = on_acquired
        try:
            await asyncio.wait_for(asyncio.gather(a.start(), b.start()), 5)
            # b dies WITHOUT releasing (crash): every lease rots
            crash(b)

            # survivor's standby takes shard 1 over once the lease expires
            await drive_until(
                clock,
                lambda: asyncio.sleep(0, 1 in a.set.owned),
                max_seconds=LEASE * 6,
            )
            assert sorted(a.owned_shards()) == [0, 1]
            assert ("a", 1) in acquired
            assert a.owns_key("health/anything")  # owns every shard now
        finally:
            await a.stop()
            await b.stop()


@pytest.mark.asyncio
async def test_fence_rejects_paused_old_owners_write():
    """The split-brain acceptance slice: a paused old owner (renew loop
    dead, lease taken over) asking to write must get ShardFencedError —
    verified against the server via the recorded resourceVersion
    fencing token — and the shard is released locally."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        metrics_b = MetricsCollector()
        a = coordinator(api, clock, 1, 0)
        a.identity = "replica-old"
        a.set.identity = "replica-old"
        try:
            await asyncio.wait_for(a.start(), 5)
            key = "health/fenced-check"
            # fresh owner: writes admitted without any extra I/O
            requests_before = len(server.requests)
            await a.admit_write(key)
            assert len(server.requests) == requests_before

            # pause the owner: renew loop dies, lease left to rot
            elector = a.set.owned[0]
            elector._renew_task.cancel()

            # another replica takes the expired lease over (a second
            # coordinator with the same home shard, different identity)
            b = coordinator(api, clock, 1, 0, metrics=metrics_b)
            b.identity = "replica-new"
            b.set.identity = "replica-new"
            start_b = asyncio.create_task(b.start())
            await drive_until(
                clock,
                lambda: asyncio.sleep(0, 0 in b.set.owned),
                max_seconds=LEASE * 6,
            )
            await start_b

            # the paused owner's late write: stale local knowledge →
            # server verification → fenced, and the shard drops locally
            with pytest.raises(ShardFencedError):
                await a.admit_write(key)
            assert elector.lost.is_set()
            # once dropped, the fast local check rejects without I/O too
            with pytest.raises(ShardFencedError):
                await a.admit_write(key)
            # the NEW owner's writes are admitted
            await b.admit_write(key)
            await b.stop()
        finally:
            await a.stop()


# ---------------------------------------------------------------------
# depth publication + work stealing
# ---------------------------------------------------------------------


@pytest.mark.asyncio
async def test_depth_rides_lease_renewals_as_annotation():
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 1, 0)
        try:
            await asyncio.wait_for(a.start(), 5)
            a.publish_depth(37)
            await advance(clock, LEASE)  # a few renewals
            lease = server.obj(
                "coordination.k8s.io", "v1", "leases", "health", shard_lease_name(0)
            )
            assert lease["metadata"]["annotations"][DEPTH_ANNOTATION] == "37"
            depths = await a.fleet_depths()
            assert depths[0] == ("replica-0", 37)
        finally:
            await a.stop()


@pytest.mark.asyncio
async def test_work_stealing_sheds_adopted_shard_on_depth_divergence():
    """An overloaded replica owning an adopted shard sheds it when its
    depth diverges above the fleet median of live shard OWNERS; an
    underloaded peer's standby adopts the freed lease. The home shard
    is never shed, and a lone owner (nobody to steal for) never sheds."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 3, 0, steal_threshold=10)
        b = coordinator(api, clock, 3, 1, steal_threshold=10)
        c = coordinator(api, clock, 3, 2, steal_threshold=10)
        try:
            await asyncio.wait_for(
                asyncio.gather(a.start(), b.start(), c.start()), 5
            )
            # b crashes; a (or c) adopts shard 1 — drive until adopted
            crash(b)
            await drive_until(
                clock,
                lambda: asyncio.sleep(
                    0, 1 in a.set.owned or 1 in c.set.owned
                ),
                max_seconds=LEASE * 6,
            )
            heavy, light = (a, c) if 1 in a.set.owned else (c, a)
            light.publish_depth(0)
            await advance(clock, LEASE)  # the light owner publishes depth

            # balanced fleet: no shed
            assert await heavy.rebalance(my_depth=5) is None
            assert len(heavy.owned_shards()) == 2

            # diverged: the heavy owner sheds its ADOPTED shard (1),
            # never its home
            shed = await heavy.rebalance(my_depth=500)
            assert shed == 1
            await advance(clock, 1)
            assert heavy.owned_shards() == [heavy.shard_id]

            # the freed lease was relinquished, so the light owner's
            # standby adopts without waiting out an expiry — and the
            # heavy owner's shed cooldown keeps it from re-adopting
            await drive_until(
                clock, lambda: asyncio.sleep(0, 1 in light.set.owned),
                max_seconds=LEASE * 8,
            )
            assert sorted(light.owned_shards()) == sorted(
                {light.shard_id, 1}
            )
            assert heavy.owned_shards() == [heavy.shard_id]

            # a lone owner never sheds (nobody visible to steal for)
            depths = await heavy.fleet_depths()
            assert depths[1][0] == light.identity
        finally:
            await a.stop()
            await b.stop()
            await c.stop()


@pytest.mark.asyncio
async def test_fenced_submit_never_launches_a_duplicate_workflow():
    """The fence guards the SUBMIT, not just the status write: a paused
    old owner resuming mid-cycle must not launch a workflow at all (a
    fenced write after a real submit would just make the adopter re-run
    the duplicated cycle a third time). The fenced cycle is also not an
    error — no quarantine fuel, no requeue."""
    from activemonitor_tpu.controller import (
        EventRecorder,
        HealthCheckReconciler,
        InMemoryHealthCheckClient,
        InMemoryRBACBackend,
        RBACProvisioner,
    )
    from activemonitor_tpu.engine import FakeWorkflowEngine
    from activemonitor_tpu.resilience import STATE_HEALTHY

    WF = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"
    async with stub_env() as (server, api):
        clock = FakeClock()
        old = coordinator(api, clock, 1, 0)
        old.identity = "replica-old"
        old.set.identity = "replica-old"
        await asyncio.wait_for(old.start(), 5)
        client = InMemoryHealthCheckClient()
        engine = FakeWorkflowEngine()
        reconciler = HealthCheckReconciler(
            client=client,
            engine=engine,
            rbac=RBACProvisioner(InMemoryRBACBackend()),
            recorder=EventRecorder(),
            metrics=MetricsCollector(),
            clock=clock,
        )
        reconciler.shards = old
        hc = HealthCheck.from_dict(
            {
                "metadata": {"name": "fenced-sub", "namespace": "health"},
                "spec": {
                    "repeatAfterSec": 300,
                    "level": "cluster",
                    "workflow": {
                        "generateName": "fenced-sub-",
                        "workflowtimeout": 30,
                        "resource": {
                            "namespace": "health",
                            "serviceAccount": "sa",
                            "source": {"inline": WF},
                        },
                    },
                },
            }
        )
        await client.apply(hc)

        # pause the owner; a new incarnation takes the lease over
        old.set.owned[0]._renew_task.cancel()
        new = coordinator(api, clock, 1, 0)
        new.identity = "replica-new"
        new.set.identity = "replica-new"
        start_new = asyncio.create_task(new.start())
        await drive_until(
            clock, lambda: asyncio.sleep(0, 0 in new.set.owned),
            max_seconds=LEASE * 6,
        )
        await start_new
        try:
            # the paused owner resumes its cycle: the submit is fenced
            # BEFORE any workflow is created, quietly (returns None)
            assert await reconciler.reconcile("health", "fenced-sub") is None
            assert engine.submitted == []
            # and the fenced cycle counted no pre-terminal error
            assert (
                reconciler.resilience.checks.state("health/fenced-sub")
                == STATE_HEALTHY
            )
        finally:
            await new.stop()
            await old.stop()
            await reconciler.shutdown()


@pytest.mark.asyncio
async def test_restarted_home_replica_gets_its_shard_back():
    """Rolling-update safety: after a crash+adoption, the restarted
    home replica can't out-elect a healthy adopter (its eager acquire
    only beats EXPIRED leases) — the adopter must hand the shard back
    once the home replica's member lease is renewed AGAIN (a stamp
    newer than the adoption; the dead incarnation's last renewal must
    not count). Without this, the restarted replica blocks forever in
    start() and the rollout wedges."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 2, 0)
        b = coordinator(api, clock, 2, 1)
        try:
            await asyncio.wait_for(asyncio.gather(a.start(), b.start()), 5)
            crash(b)
            await drive_until(
                clock, lambda: asyncio.sleep(0, 1 in a.set.owned),
                max_seconds=LEASE * 6,
            )
            # no home replica yet: nothing to return (the dead
            # incarnation's member stamp predates the adoption)
            assert await a.rebalance(my_depth=0) is None
            assert sorted(a.owned_shards()) == [0, 1]

            # the home replica restarts; start() blocks until it owns
            # its shard — exactly the wedge the home-return breaks
            b2 = coordinator(api, clock, 2, 1)
            b2_started = asyncio.create_task(b2.start())
            # b2 first re-takes its member (presence) lease...
            await drive_until(
                clock, lambda: asyncio.sleep(0, b2.set.member is not None),
                max_seconds=LEASE * 8,
            )
            # ...then a's next sweep returns the shard and b2 acquires
            shed = None
            for _ in range(12):
                shed = await a.rebalance(my_depth=0)
                if shed is not None:
                    break
                await advance(clock, LEASE / 3)
            assert shed == 1
            await drive_until(
                clock, lambda: asyncio.sleep(0, 1 in b2.set.owned),
                max_seconds=LEASE * 8,
            )
            await asyncio.wait_for(b2_started, 5)  # start() unwedged
            assert b2.owned_shards() == [1]
            assert a.owned_shards() == [0]
            await b2.stop()
        finally:
            await a.stop()
            await b.stop()


@pytest.mark.asyncio
async def test_fast_home_restart_reclaims_before_steady_state_peers():
    """The standby grace must hold in STEADY STATE, not just at boot:
    peers park inside the elector's contend loop forever, so a grace
    that only delays the first loop entry evaporates after the first
    sweep — and every rolling-update restart would pay a double
    handoff (peer adopt + resync, home-return + resync). A home
    replica restarting within the grace window must win the reclaim
    race against peers that have been standing by for many leases."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 2, 0)
        b = coordinator(api, clock, 2, 1)
        try:
            await asyncio.wait_for(asyncio.gather(a.start(), b.start()), 5)
            # steady state: standbys have long been parked in acquire()
            await advance(clock, LEASE * 4)
            assert a.owned_shards() == [0] and b.owned_shards() == [1]

            crash(a)
            # a fast restart: well inside the peers' one-lease grace
            a2 = coordinator(api, clock, 2, 0)
            a2_started = asyncio.create_task(a2.start())
            await drive_until(
                clock, lambda: asyncio.sleep(0, 0 in a2.set.owned),
                max_seconds=LEASE * 6,
            )
            await asyncio.wait_for(a2_started, 5)
            assert a2.owned_shards() == [0]
            # the peer never adopted the shard in between — the restart
            # cost ZERO cross-replica handoffs
            assert b.owned_shards() == [1]
            assert b.set.adopt_order == [1]
            await a2.stop()
        finally:
            await a.stop()
            await b.stop()


@pytest.mark.asyncio
async def test_sole_adopted_shard_is_still_handed_home():
    """A replica can end up owning ONLY an adopted shard (its home
    shard fenced/demoted away while the peer was dead). The rebalance
    sweep's never-shed-the-last-shard guard must not sit above
    home-return — it is a STEALING guard, not a returning guard — or
    the adopted shard is never handed back and the restarted home
    replica wedges in start() forever."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 2, 0)
        b = coordinator(api, clock, 2, 1)
        try:
            await asyncio.wait_for(asyncio.gather(a.start(), b.start()), 5)
            crash(b)
            await drive_until(
                clock, lambda: asyncio.sleep(0, 1 in a.set.owned),
                max_seconds=LEASE * 6,
            )
            # record the shard-1 member baseline (sweep while b is dead)
            assert await a.rebalance(my_depth=0) is None
            # a's HOME shard is taken over by another holder (the fence
            # verdict's scenario) and the elector demoted: a now holds
            # only the adopted shard 1 — its eager home re-acquire can't
            # beat the intruder's unexpired lease
            elector0 = a.set.owned[0]
            lease = await api.get(elector0.path)
            lease["spec"]["holderIdentity"] = "intruder"
            lease["spec"]["leaseDurationSeconds"] = 3600
            await api.replace(elector0.path, lease)
            elector0.demote()
            await drive_until(
                clock, lambda: asyncio.sleep(0, a.owned_shards() == [1]),
                max_seconds=LEASE / 3, step=1.0,
            )

            b2 = coordinator(api, clock, 2, 1)
            b2_started = asyncio.create_task(b2.start())
            await drive_until(
                clock, lambda: asyncio.sleep(0, b2.set.member is not None),
                max_seconds=LEASE * 8,
            )
            shed = None
            for _ in range(6):
                # the scenario under test is owning JUST the adopted
                # shard — if a re-took its expired home lease the sweep
                # would pass via the ordinary two-shard home-return path
                assert a.owned_shards() == [1]
                shed = await a.rebalance(my_depth=0)
                if shed is not None:
                    break
                await advance(clock, 1.0)
            assert shed == 1
            await drive_until(
                clock, lambda: asyncio.sleep(0, 1 in b2.set.owned),
                max_seconds=LEASE * 8,
            )
            await asyncio.wait_for(b2_started, 5)
            assert b2.owned_shards() == [1]
            await b2.stop()
        finally:
            await a.stop()
            await b.stop()


@pytest.mark.asyncio
async def test_pre_shed_gate_defers_shed_until_writes_drain():
    """A voluntary shed is deferred while the shard's queued status
    writes haven't drained — the adopter must inherit durable truth,
    not re-run the cycles those writes record."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 2, 0, steal_threshold=1)
        b = coordinator(api, clock, 2, 1, steal_threshold=1)
        try:
            await asyncio.wait_for(asyncio.gather(a.start(), b.start()), 5)
            # a adopts shard 1 after b's crash
            crash(b)
            await drive_until(
                clock, lambda: asyncio.sleep(0, 1 in a.set.owned),
                max_seconds=LEASE * 6,
            )
            # a fresh peer is visible in the fleet (so the median math
            # would otherwise admit the shed)
            c = coordinator(api, clock, 2, 1, steal_threshold=1)
            start_c = asyncio.create_task(c.start(wait_first=False))
            await advance(clock, 1)

            drained = {"ok": False}

            async def pre_shed(_shard):
                return drained["ok"]

            a.pre_shed = pre_shed
            # c adopts the expired member (presence) lease and publishes
            # its idle depth — only then is it visible to the median
            await drive_until(
                clock, lambda: asyncio.sleep(0, c.set.member is not None),
                max_seconds=LEASE * 6,
            )
            await advance(clock, LEASE)  # depths published
            assert await a.rebalance(my_depth=1000) is None  # deferred
            assert sorted(a.owned_shards()) == [0, 1]
            drained["ok"] = True
            assert await a.rebalance(my_depth=1000) == 1  # drained: shed
            start_c.cancel()
            await c.stop()
        finally:
            await a.stop()
            await b.stop()


# ---------------------------------------------------------------------
# statusz: per-shard block + fleet rollup
# ---------------------------------------------------------------------


@pytest.mark.asyncio
async def test_member_depths_exclude_stale_ghost_leases():
    """A crashed replica's member lease keeps its holderIdentity
    forever (nothing re-contends a presence slot except a same-slot
    twin) — its stale depth must drop out of the work-stealing median
    once renewTime goes stale, or a ghost at depth 0 would drag the
    median down and trigger sheds for nobody."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 2, 0)
        b = coordinator(api, clock, 2, 1)
        try:
            await asyncio.wait_for(asyncio.gather(a.start(), b.start()), 5)
            a.publish_depth(40)
            b.publish_depth(20)
            await advance(clock, LEASE)  # both renew with their depths
            depths = await a.member_depths()
            assert depths == {"replica-0": 40, "replica-1": 20}

            crash(b)
            await advance(clock, LEASE * 3)  # b's renewTime goes stale
            depths = await a.member_depths()
            assert "replica-1" not in depths
            assert set(depths) == {"replica-0"}
        finally:
            await a.stop()
            await b.stop()


@pytest.mark.asyncio
async def test_verification_get_does_not_extend_the_fence_fast_path():
    """The stale-path verification GET proves the lease was held at
    verification time but does NOT renew it — so it must not refresh
    the no-I/O fast-path window (a paused owner could otherwise admit a
    post-takeover write unverified). Every stale-path admit keeps
    paying the GET until a real renewal lands."""
    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 1, 0)
        try:
            await asyncio.wait_for(a.start(), 5)
            elector = a.set.owned[0]
            elector._renew_task.cancel()  # pause: no more real writes
            last_write = elector.last_write
            await clock.advance(LEASE * 0.8)  # past the 2/3 fresh window

            requests_before = len(server.requests)
            await a.admit_write("health/x")  # verified via GET (still held)
            assert len(server.requests) == requests_before + 1
            assert elector.last_write == last_write  # NOT refreshed
            # the very next admit pays the GET again — no fast path
            await a.admit_write("health/x")
            assert len(server.requests) == requests_before + 2
        finally:
            await a.stop()


def test_rollup_sums_double_claimed_shard_counts():
    """While a handoff is in flight two replicas may both report a
    shard; the rollup SUMS their counts so the overlap surfaces as
    counts exceeding the deduped check total — last-wins would read
    clean exactly when it should flag double ownership."""
    from activemonitor_tpu.obs.slo import rollup_statusz

    def payload(identity, count):
        return {
            "fleet": {
                "checks": count,
                "window_runs": 0,
                "generated_at": "",
                "degraded": False,
                "status_writes_queued": 0,
                "sharding": {
                    "shards": 1,
                    "identity": identity,
                    "owned": [0],
                    "checks_per_shard": {"0": count},
                    "fenced_writes": 0,
                },
            },
            "checks": [
                {"key": f"health/chk-{i}", "window": {"results": 0}}
                for i in range(count)
            ],
        }

    rollup = rollup_statusz([payload("old-owner", 3), payload("new-owner", 3)])
    assert rollup["fleet"]["checks"] == 3  # deduped by key
    assert rollup["fleet"]["sharding"]["checks_per_shard"]["0"] == 6
    assert (
        sum(rollup["fleet"]["sharding"]["checks_per_shard"].values())
        > rollup["fleet"]["checks"]
    )  # the double-ownership signal


def test_rollup_carries_worst_breaker_and_summed_remedy_tokens():
    """Each replica has its own circuit breaker and remedy bucket; the
    merged fleet line must report the WORST breaker state (not a
    fabricated default — the renderer used to print 'open' for every
    degraded rollup because the field was dropped) and the summed
    remedy budget."""
    from activemonitor_tpu.obs.slo import rollup_statusz

    def payload(state, degraded, tokens):
        return {
            "fleet": {
                "checks": 0,
                "window_runs": 0,
                "generated_at": "",
                "degraded": degraded,
                "breaker": {"name": "kube", "state": state, "trips": 1},
                "status_writes_queued": 0,
                "remedy_tokens": tokens,
            },
            "checks": [],
        }

    rollup = rollup_statusz(
        [payload("closed", False, 2.5), payload("half-open", True, 1.0)]
    )
    assert rollup["fleet"]["degraded"] is True
    assert rollup["fleet"]["breaker"]["state"] == "half-open"
    assert rollup["fleet"]["remedy_tokens"] == pytest.approx(3.5)

    # an unrecognized state string outranks every known one (better to
    # over-alarm than to hide a breaker the renderer doesn't know)
    rollup = rollup_statusz(
        [payload("open", True, None), payload("melted", True, None)]
    )
    assert rollup["fleet"]["breaker"]["state"] == "melted"
    assert rollup["fleet"]["remedy_tokens"] is None

    # replicas without a resilience layer report breaker=None — the
    # rollup must not invent one
    rollup = rollup_statusz(
        [
            {
                "fleet": {"checks": 0, "breaker": None, "degraded": False},
                "checks": [],
            }
        ]
    )
    assert rollup["fleet"]["breaker"] is None


@pytest.mark.asyncio
async def test_adoption_resync_failure_is_retried_by_the_shard_loop():
    """A transient list() failure during shard adoption must not strand
    the shard's existing checks unmonitored (the watch only yields
    FUTURE events): the failed resync parks in _resync_pending and the
    shard loop retries it until it lands."""
    from activemonitor_tpu.controller import (
        EventRecorder,
        HealthCheckReconciler,
        InMemoryRBACBackend,
        RBACProvisioner,
    )
    from activemonitor_tpu.controller.client_k8s import (
        KubernetesHealthCheckClient,
    )
    from activemonitor_tpu.controller.manager import Manager
    from activemonitor_tpu.engine import FakeWorkflowEngine

    async with stub_env() as (server, api):
        clock = FakeClock()
        # two shards, one replica: the home shard rides the boot resync
        # (no separate list — the startup-cost finding), and shard 1 is
        # ADOPTED later, which is the path that must resync on its own
        coord = coordinator(api, clock, 2, 0, metrics=MetricsCollector())
        inner = KubernetesHealthCheckClient(api, owns=coord.owns_event)
        fail = {"n": 0}

        class FlakyList:
            def __getattr__(self, name):
                return getattr(inner, name)

            async def list(self, namespace=None):
                if fail["n"] > 0:
                    fail["n"] -= 1
                    raise RuntimeError("transient list outage")
                return await inner.list(namespace)

        reconciler = HealthCheckReconciler(
            client=FlakyList(),
            engine=FakeWorkflowEngine(),
            rbac=RBACProvisioner(InMemoryRBACBackend()),
            recorder=EventRecorder(),
            metrics=MetricsCollector(),
            clock=clock,
        )
        manager = Manager(
            client=FlakyList(),
            reconciler=reconciler,
            max_parallel=2,
            shard_coordinator=coord,
        )
        try:
            await manager.start()  # home shard: boot resync, no extra list
            assert manager._resync_pending == set()
            # shard 1 is orphaned (no owner); the standby adopts it
            # after its grace — with the list broken at adoption time
            fail["n"] = 1
            await drive_until(
                clock, lambda: asyncio.sleep(0, 1 in coord.set.owned),
                max_seconds=LEASE * 6,
            )
            assert manager._resync_pending == {1}
            # the shard loop's next sweep retries and clears it
            await advance(clock, 15)
            assert manager._resync_pending == set()
        finally:
            await manager.stop()


def test_status_table_renders_the_sharded_fleet_rollup():
    """`am-tpu status --url a --url b` merges the replicas' payloads;
    the table leads with the fleet line plus a SHARDS line mapping each
    shard to its owning replica."""
    from activemonitor_tpu.__main__ import render_status_table
    from activemonitor_tpu.obs.slo import rollup_statusz

    def payload(identity, owned, checks):
        return {
            "fleet": {
                "checks": len(checks),
                "window_runs": len(checks),
                "goodput_ratio": 1.0,
                "generated_at": "2026-08-03T00:00:00+00:00",
                "degraded": False,
                "breaker": None,
                "status_writes_queued": 0,
                "remedy_tokens": None,
                "anomalies": {"warning": 0, "degraded": 0},
                "sharding": {
                    "shards": 2,
                    "shard_id": owned[0],
                    "identity": identity,
                    "owned": owned,
                    "checks_per_shard": {str(owned[0]): len(checks)},
                    "workqueue_depth": 0,
                    "fenced_writes": 0,
                },
            },
            "checks": [
                {
                    "key": f"health/{name}",
                    "healthcheck": name,
                    "namespace": "health",
                    "state": "healthy",
                    "analysis": None,
                    "remedy_budget_remaining": None,
                    "last_status": "Succeeded",
                    "last_trace_id": "",
                    "runs_recorded": 1,
                    "window": {
                        "seconds": 3600,
                        "results": 1,
                        "availability": 1.0,
                        "p50_seconds": 1.0,
                        "p95_seconds": 1.0,
                        "p99_seconds": 1.0,
                    },
                    "slo": None,
                    "history": [],
                }
                for name in checks
            ],
        }

    rollup = rollup_statusz(
        [
            payload("replica-a", [0], ["chk-0", "chk-1"]),
            payload("replica-b", [1], ["chk-2"]),
        ]
    )
    assert rollup["fleet"]["checks"] == 3
    assert sum(rollup["fleet"]["sharding"]["checks_per_shard"].values()) == 3
    table = render_status_table(rollup)
    assert "replicas=2" in table
    assert "SHARDS 2" in table
    assert "0:replica-a" in table and "1:replica-b" in table
    assert "chk-2" in table


def test_remedy_rate_apportioned_by_owned_shards():
    """--remedy-rate is a FLEET cap: each replica's bucket refills at
    rate × owned/N, re-applied on every handoff. (Regression: a static
    rate/N split silently shrank the fleet budget whenever survivors
    carried adopted shards — 4 replicas × 8 shards ran at half the
    configured cap.) Re-rating the live bucket must never grant a
    fresh burst."""
    from activemonitor_tpu.controller import (
        EventRecorder,
        HealthCheckReconciler,
        InMemoryRBACBackend,
        RBACProvisioner,
    )
    from activemonitor_tpu.controller.manager import Manager
    from activemonitor_tpu.engine import FakeWorkflowEngine, succeed_after

    class FakeSet:
        owned = {0: None}

    class FakeShards:
        shards = 8
        shard_id = 0
        set = FakeSet()

        def shard_for(self, key):
            return 0

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=FakeWorkflowEngine(succeed_after(1)),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
        clock=clock,
    )
    shards = FakeShards()
    manager = Manager(
        client=client,
        reconciler=reconciler,
        shard_coordinator=shards,
        remedy_rate=60.0,
    )
    bucket = reconciler.resilience.remedy_bucket
    # boot: the home-shard share, not the full fleet rate
    assert bucket.rate_per_second == pytest.approx(60.0 / 8 / 60.0)

    # drain most of the burst so a fresh-burst regression is visible
    while bucket.try_take():
        pass
    leftover = bucket.available()

    # survivor adopts two more shards: its share follows ownership,
    # IN PLACE (same bucket), with the accrued tokens preserved
    FakeShards.set.owned = {0: None, 1: None, 2: None}
    manager._apportion_remedy_rate()
    assert reconciler.resilience.remedy_bucket is bucket
    assert bucket.rate_per_second == pytest.approx(60.0 * 3 / 8 / 60.0)
    assert bucket.available() == pytest.approx(leftover)

    # fleet invariant: every shard owned exactly once ⇒ shares sum to
    # the configured cap (here: 3/8 + 5 surviving homes × 1/8 = 1)
    assert (
        sum([3, 1, 1, 1, 1, 1]) / FakeShards.shards * 60.0
        == pytest.approx(60.0)
    )

    # handoff back down: the share shrinks, tokens clamp to burst
    FakeShards.set.owned = {0: None}
    manager._apportion_remedy_rate()
    assert bucket.rate_per_second == pytest.approx(60.0 / 8 / 60.0)

    # a shardless standby keeps a minimal bucket (in-flight runs can
    # still reach the remedy gate during the fence window), never None
    FakeShards.set.owned = {}
    manager._apportion_remedy_rate()
    assert reconciler.resilience.remedy_bucket is not None
    assert bucket.rate_per_second == pytest.approx(60.0 / 8 / 60.0)


def test_unsharded_rollup_carries_no_sharding_block():
    """Rolling up a classic --leader-elect fleet (every replica reports
    sharding=null) must yield sharding=None, not a truthy empty block —
    the status table used to print a bogus `SHARDS 0` line for it."""
    from activemonitor_tpu.__main__ import render_status_table
    from activemonitor_tpu.obs.slo import rollup_statusz

    def payload(checks):
        return {
            "fleet": {
                "checks": len(checks),
                "window_runs": 0,
                "goodput_ratio": None,
                "generated_at": "",
                "degraded": False,
                "breaker": None,
                "status_writes_queued": 0,
                "remedy_tokens": None,
                "anomalies": {"warning": 0, "degraded": 0},
                "sharding": None,
            },
            "checks": [
                {
                    "key": f"health/{name}",
                    "healthcheck": name,
                    "namespace": "health",
                    "state": "healthy",
                    "analysis": None,
                    "remedy_budget_remaining": None,
                    "last_status": "Succeeded",
                    "last_trace_id": "",
                    "runs_recorded": 0,
                    "window": {
                        "seconds": 3600,
                        "results": 0,
                        "availability": None,
                        "p50_seconds": None,
                        "p95_seconds": None,
                        "p99_seconds": None,
                    },
                    "slo": None,
                    "history": [],
                }
                for name in checks
            ],
        }

    rollup = rollup_statusz([payload(["chk-0"]), payload(["chk-1"])])
    assert rollup["fleet"]["sharding"] is None
    table = render_status_table(rollup)
    assert "SHARDS" not in table
    assert "chk-0" in table and "chk-1" in table


@pytest.mark.asyncio
async def test_statusz_sharding_block_and_fleet_rollup_sum():
    from activemonitor_tpu.obs.slo import FleetStatus, rollup_statusz

    async with stub_env() as (server, api):
        clock = FakeClock()
        a = coordinator(api, clock, 2, 0, metrics=MetricsCollector())
        b = coordinator(api, clock, 2, 1, metrics=MetricsCollector())
        try:
            await asyncio.wait_for(asyncio.gather(a.start(), b.start()), 5)
            checks = [make_hc(f"chk-{i:03d}") for i in range(40)]

            def statusz_for(coord):
                fleet = FleetStatus(clock, coord.metrics)
                fleet.sharding = coord
                owned = [
                    hc for hc in checks if coord.owns_key(hc.key)
                ]
                for hc in owned:
                    fleet.record(hc, ok=True, latency=1.0, workflow="wf")
                return fleet.statusz(owned)

            pa, pb = statusz_for(a), statusz_for(b)
            # per-replica block: owned shards + the counts gauge agree
            assert pa["fleet"]["sharding"]["owned"] == [0]
            assert pb["fleet"]["sharding"]["owned"] == [1]
            count_a = sum(pa["fleet"]["sharding"]["checks_per_shard"].values())
            assert count_a == len(pa["checks"])
            assert a.metrics.sample_value(
                "healthcheck_shard_checks", {"shard": "0"}
            ) == count_a

            # the fleet rollup: per-shard ownership counts sum to the
            # check total, every shard has exactly one owner
            rollup = rollup_statusz([pa, pb])
            assert rollup["fleet"]["replicas"] == 2
            assert rollup["fleet"]["checks"] == len(checks)
            assert (
                sum(rollup["fleet"]["sharding"]["checks_per_shard"].values())
                == len(checks)
            )
            assert rollup["fleet"]["sharding"]["owners"] == {
                "0": "replica-0",
                "1": "replica-1",
            }
            assert rollup["fleet"]["goodput_ratio"] == 1.0
        finally:
            await a.stop()
            await b.stop()
