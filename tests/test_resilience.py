"""Resilience layer (ISSUE 3): circuit breaker, per-check state machine
(healthy → flapping → quarantined), remedy storm control, degraded-mode
status-write queueing — units on fake clocks plus reconciler-level
lifecycles with FakeEngine, including the remedy-cap acceptance slice
(suppressed with an event + counter while the bucket is dry, admitted
after refill).
"""

import asyncio
import random

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.engine import FakeWorkflowEngine
from activemonitor_tpu.engine.base import PHASE_FAILED, PHASE_SUCCEEDED
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.resilience import (
    BreakerOpenError,
    CheckStateTracker,
    CircuitBreaker,
    ResilienceCoordinator,
    STATE_CLOSED,
    STATE_FLAPPING,
    STATE_HALF_OPEN,
    STATE_HEALTHY,
    STATE_OPEN,
    STATE_QUARANTINED,
    TokenBucket,
)
from activemonitor_tpu.utils.clock import FakeClock

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"


class Transient(Exception):
    status = 503


class Deterministic(Exception):
    status = 404


def make_hc(name="hc-res", repeat=60, remedy_prefix=None, remedy_limit=0):
    spec = {
        "repeatAfterSec": repeat,
        "level": "cluster",
        "backoffMax": 1,
        "backoffMin": 1,
        "workflow": {
            "generateName": f"{name}-",
            "workflowtimeout": 30,
            "resource": {
                "namespace": "health",
                "serviceAccount": "sa",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if remedy_prefix is not None:
        spec["remedyworkflow"] = {
            "generateName": remedy_prefix,
            "workflowtimeout": 30,
            "resource": {
                "namespace": "health",
                "serviceAccount": "sa",
                "source": {"inline": WF_INLINE},
            },
        }
        if remedy_limit:
            spec["remedyRunsLimit"] = remedy_limit
            spec["remedyResetInterval"] = 3600
    return HealthCheck.from_dict(
        {"metadata": {"name": name, "namespace": "health"}, "spec": spec}
    )


async def settle():
    for _ in range(60):
        await asyncio.sleep(0)


def build_reconciler(engine, clock, metrics=None, resilience=None):
    metrics = metrics or MetricsCollector()
    return HealthCheckReconciler(
        client=InMemoryHealthCheckClient(),
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
        resilience=resilience,
    )


# ---------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------


@pytest.mark.asyncio
async def test_breaker_trips_on_failure_rate_and_recovers_half_open():
    clock = FakeClock()
    transitions = []
    breaker = CircuitBreaker(
        "api",
        clock=clock,
        failure_threshold=3,
        recovery_seconds=30.0,
        on_transition=lambda old, new: transitions.append((old, new)),
    )
    assert breaker.state == STATE_CLOSED and breaker.allow()
    breaker.observe(Transient())
    breaker.observe(Transient())
    assert breaker.state == STATE_CLOSED  # below threshold
    breaker.observe(Transient())
    assert breaker.state == STATE_OPEN
    assert not breaker.allow()
    assert transitions == [(STATE_CLOSED, STATE_OPEN)]
    assert 0 < breaker.retry_after() <= 30.0
    # open window elapses on the injected clock only
    await clock.advance(29.0)
    assert not breaker.allow()
    await clock.advance(2.0)
    assert breaker.state == STATE_HALF_OPEN and breaker.allow()
    # half-open probe succeeds: closed
    breaker.observe(None)
    assert breaker.state == STATE_CLOSED
    assert transitions[-1] == (STATE_HALF_OPEN, STATE_CLOSED)


@pytest.mark.asyncio
async def test_breaker_half_open_failure_reopens_for_a_full_window():
    clock = FakeClock()
    breaker = CircuitBreaker(
        "api", clock=clock, failure_threshold=1, recovery_seconds=10.0
    )
    breaker.observe(Transient())
    assert breaker.state == STATE_OPEN
    await clock.advance(11.0)
    assert breaker.state == STATE_HALF_OPEN
    breaker.observe(Transient())  # the probe failed
    assert breaker.state == STATE_OPEN
    assert breaker.retry_after() == pytest.approx(10.0)
    assert breaker.snapshot()["trips"] == 2


def test_breaker_interleaved_successes_do_not_mask_a_write_storm():
    """The rate-window rationale: every conflict-retried status write
    interleaves a healthy GET with the failing PATCH, so consecutive
    counting would never trip — the window counting must."""
    breaker = CircuitBreaker(
        "api", clock=FakeClock(), failure_threshold=3, failure_window=60.0
    )
    for _ in range(2):
        breaker.observe(None)  # healthy read
        breaker.observe(Transient())  # failing write
    assert breaker.state == STATE_CLOSED
    breaker.observe(None)
    breaker.observe(Transient())  # third failure inside the window
    assert breaker.state == STATE_OPEN


@pytest.mark.asyncio
async def test_breaker_failures_outside_the_window_age_out():
    clock = FakeClock()
    breaker = CircuitBreaker(
        "api", clock=clock, failure_threshold=2, failure_window=10.0
    )
    breaker.observe(Transient())
    await clock.advance(11.0)
    breaker.observe(Transient())  # the first failure has aged out
    assert breaker.state == STATE_CLOSED


def test_breaker_deterministic_errors_and_rejections_never_count():
    breaker = CircuitBreaker("api", clock=FakeClock(), failure_threshold=1)
    breaker.observe(Deterministic())  # 4xx: the server is answering
    assert breaker.state == STATE_CLOSED
    # the breaker must never feed on (or close off) its own rejections
    breaker.observe(BreakerOpenError("api", 1.0))
    assert breaker.state == STATE_CLOSED
    breaker.observe(ConnectionRefusedError())  # connection-level: counts
    assert breaker.state == STATE_OPEN
    breaker.observe(BreakerOpenError("api", 1.0))
    assert breaker.state == STATE_OPEN  # not closed by its own rejection


# ---------------------------------------------------------------------
# per-check state machine
# ---------------------------------------------------------------------


def test_tracker_flap_detection_and_calm_recovery():
    tracker = CheckStateTracker()  # window 8, threshold 3, calm 4
    key = "ns/hc"
    assert tracker.note_verdict(key, True) is None
    assert tracker.note_verdict(key, False) is None  # 1 flip
    assert tracker.note_verdict(key, True) is None  # 2 flips
    transition = tracker.note_verdict(key, False)  # 3 flips
    assert transition == (STATE_HEALTHY, STATE_FLAPPING)
    assert tracker.state(key) == STATE_FLAPPING
    assert tracker.damp_factor(key) == 2.0
    # three equal verdicts are not yet calm...
    for _ in range(3):
        assert tracker.note_verdict(key, True) is None
    # ...the fourth is
    assert tracker.note_verdict(key, True) == (STATE_FLAPPING, STATE_HEALTHY)
    assert tracker.damp_factor(key) == 1.0
    # the calm transition starts a clean window: the pre-calm flips
    # still in the ring must not re-trip flapping on the next verdicts
    # (the damp/undamp oscillation a stale window would cause)
    for _ in range(6):
        assert tracker.note_verdict(key, True) is None
        assert tracker.state(key) == STATE_HEALTHY


def test_tracker_quarantine_streak_reset_and_clear():
    tracker = CheckStateTracker(quarantine_after=3)
    key = "ns/hc"
    assert tracker.note_preterminal_error(key) is None
    assert tracker.note_preterminal_error(key) is None
    tracker.note_submit_ok(key)  # a clean submit breaks the streak
    assert tracker.note_preterminal_error(key) is None
    assert tracker.note_preterminal_error(key) is None
    transition = tracker.note_preterminal_error(key)
    assert transition == (STATE_HEALTHY, STATE_QUARANTINED)
    assert tracker.state(key) == STATE_QUARANTINED
    # a straggler verdict from an in-flight workflow must not resurrect
    assert tracker.note_verdict(key, True) is None
    assert tracker.state(key) == STATE_QUARANTINED
    # further errors are absorbed silently
    assert tracker.note_preterminal_error(key) is None
    tracker.clear(key)
    assert tracker.state(key) == STATE_HEALTHY
    assert tracker.error_streak(key) == 0


def test_tracker_persisted_bit_and_forget():
    tracker = CheckStateTracker(quarantine_after=1)
    key = "ns/hc"
    tracker.note_preterminal_error(key)
    assert not tracker.persisted(key)
    tracker.mark_persisted(key)
    assert tracker.persisted(key)
    tracker.forget(key)
    assert tracker.state(key) == STATE_HEALTHY
    # durable adoption (restart path) marks persisted directly
    tracker.quarantine(key)
    assert tracker.state(key) == STATE_QUARANTINED and tracker.persisted(key)


# ---------------------------------------------------------------------
# token bucket
# ---------------------------------------------------------------------


@pytest.mark.asyncio
async def test_token_bucket_exhausts_and_refills_on_the_injected_clock():
    clock = FakeClock()
    bucket = TokenBucket(rate_per_minute=1.0, clock=clock)
    assert bucket.try_take()  # starts full (burst 1)
    assert not bucket.try_take()
    assert bucket.seconds_until() == pytest.approx(60.0)
    await clock.advance(30.0)
    assert not bucket.try_take()  # half a token
    await clock.advance(30.0)
    assert bucket.try_take()
    assert bucket.available() == pytest.approx(0.0)


@pytest.mark.asyncio
async def test_token_bucket_burst_caps_accrual():
    clock = FakeClock()
    bucket = TokenBucket(rate_per_minute=60.0, burst=2.0, clock=clock)
    await clock.advance(600.0)  # ten minutes of refill...
    assert bucket.available() == pytest.approx(2.0)  # ...capped at burst
    assert bucket.try_take() and bucket.try_take() and not bucket.try_take()


def test_token_bucket_rejects_nonpositive_rate():
    with pytest.raises(ValueError):
        TokenBucket(rate_per_minute=0.0)


# ---------------------------------------------------------------------
# coordinator
# ---------------------------------------------------------------------


@pytest.mark.asyncio
async def test_coordinator_degraded_gauge_and_stretched_requeue_delay():
    clock = FakeClock()
    metrics = MetricsCollector()
    res = ResilienceCoordinator(
        clock,
        metrics,
        breaker=CircuitBreaker(
            "api", clock=clock, failure_threshold=1, recovery_seconds=30.0
        ),
        rng=random.Random(42),
    )
    assert not res.degraded
    assert res.requeue_delay(1.0) == 1.0
    assert metrics.sample_value("healthcheck_controller_degraded", {}) == 0.0
    res.breaker.observe(Transient())
    assert res.degraded
    assert metrics.sample_value("healthcheck_controller_degraded", {}) == 1.0
    # stretched-and-jittered, never below the base, never above the
    # breaker's recovery window
    for _ in range(20):
        delay = res.requeue_delay(1.0)
        assert 1.0 <= delay <= 30.0
    # the envelope is TIME-based (the remaining open window), not a
    # shared advancing schedule: even after many draws, deep into the
    # window the bound follows retry_after(), and concurrent callers
    # can't collapse each other's stretch to the floor
    await clock.advance(25.0)
    for _ in range(20):
        assert 1.0 <= res.requeue_delay(1.0) <= 5.0 + 1e-9
    await clock.advance(6.0)
    res.refresh()  # half-open: still degraded
    assert res.degraded
    res.breaker.observe(None)
    res.refresh()
    assert not res.degraded
    assert metrics.sample_value("healthcheck_controller_degraded", {}) == 0.0
    assert res.requeue_delay(1.0) == 1.0


def test_coordinator_status_queue_latest_wins_and_replay_order():
    clock = FakeClock()
    metrics = MetricsCollector()
    res = ResilienceCoordinator(clock, metrics)
    hc_a, hc_b = make_hc("a"), make_hc("b")
    hc_a.status.success_count = 1
    res.queue_status_write(hc_a)
    res.queue_status_write(hc_b)
    hc_a.status.success_count = 2
    res.queue_status_write(hc_a)  # fresher status for a queued key
    assert res.pending_status_writes() == 2
    assert metrics.sample_value("healthcheck_status_write_queue_depth", {}) == 2
    assert res.queued_status("health/a").success_count == 2
    key, queued = res.next_status_write()
    assert key == "health/a" and queued.status.success_count == 2
    # a failed replay goes back to the FRONT
    res.requeue_status_write(key, queued)
    assert res.next_status_write()[0] == "health/a"
    res.drop_status_write("health/b")
    assert res.pending_status_writes() == 0
    assert res.queued_status("health/b") is None


def test_coordinator_remedy_admission_and_snapshot():
    clock = FakeClock()
    res = ResilienceCoordinator(clock, None, remedy_rate=1.0)
    assert res.admit_remedy()
    assert not res.admit_remedy()
    snap = res.snapshot()
    assert snap["degraded"] is False
    assert snap["remedy_tokens"] == pytest.approx(0.0)
    assert snap["breaker"]["state"] == STATE_CLOSED
    res.configure_remedy_rate(0.0)  # cap removed
    assert res.admit_remedy() and res.remedy_tokens() is None


# ---------------------------------------------------------------------
# reconciler: quarantine lifecycle
# ---------------------------------------------------------------------


class ExplodingEngine:
    """Deterministically broken submit path (a ValueError is NOT
    transient, so the breaker stays closed and the errors count against
    the CHECK, not the fleet)."""

    name = "exploding"

    def __init__(self):
        self.submits = 0

    async def submit(self, manifest):
        self.submits += 1
        raise ValueError("deterministically broken")

    async def get(self, namespace, name):
        return None


@pytest.mark.asyncio
async def test_quarantine_lifecycle_stop_mark_clear_resume():
    clock = FakeClock()
    metrics = MetricsCollector()
    engine = ExplodingEngine()
    reconciler = build_reconciler(engine, clock, metrics)
    client = reconciler.client
    hc = make_hc("hc-q")
    await client.apply(hc)
    key = "health/hc-q"

    # 5 consecutive pre-terminal errors (default threshold) quarantine
    for i in range(5):
        await reconciler.reconcile("health", "hc-q")
        expected = STATE_QUARANTINED if i >= 4 else STATE_HEALTHY
        assert reconciler.resilience.checks.state(key) == expected
    assert engine.submits == 5

    # the durable mark landed and is user-visible
    stored = await client.get("health", "hc-q")
    assert stored.status.state == STATE_QUARANTINED
    assert "quarantined" in stored.status.error_message
    assert metrics.sample_value(
        "healthcheck_check_state",
        {"healthcheck_name": "hc-q", "namespace": "health", "state": "quarantined"},
    ) == 1.0
    events = reconciler.recorder.events_for("health", "hc-q")
    assert any("quarantined" in e.message for e in events)

    # further reconciles do NOT touch the engine: the schedule is parked
    await reconciler.reconcile("health", "hc-q")
    assert engine.submits == 5
    assert not reconciler.timers.exists(key)

    # the user clears .status.state -> the next reconcile resumes (and
    # the now-working engine gets a submission)
    stored.status.state = ""
    await client.update_status(stored)
    reconciler.engine = FakeWorkflowEngine()
    await reconciler.reconcile("health", "hc-q")
    assert reconciler.resilience.checks.state(key) == STATE_HEALTHY
    assert len(reconciler.engine.submitted) == 1
    events = reconciler.recorder.events_for("health", "hc-q")
    assert any("Quarantine cleared" in e.message for e in events)
    await reconciler.shutdown()


@pytest.mark.asyncio
async def test_durable_quarantine_mark_is_adopted_after_restart():
    """A fresh reconciler (restarted controller, empty tracker) must
    honor a Quarantined mark found in durable status instead of
    resubmitting the broken check."""
    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    hc = make_hc("hc-adopt")
    applied = await client.apply(hc)
    applied.status.state = STATE_QUARANTINED
    await client.update_status(applied)

    engine = FakeWorkflowEngine()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
        clock=clock,
    )
    await reconciler.reconcile("health", "hc-adopt")
    assert engine.submitted == []
    assert (
        reconciler.resilience.checks.state("health/hc-adopt")
        == STATE_QUARANTINED
    )
    await reconciler.shutdown()


@pytest.mark.asyncio
async def test_errors_during_degraded_mode_do_not_quarantine():
    """An apiserver outage is the fleet's problem: with the breaker
    open, per-check error streaks must not accumulate — innocents would
    be quarantined by the outage."""
    clock = FakeClock()
    engine = ExplodingEngine()
    reconciler = build_reconciler(engine, clock)
    await reconciler.client.apply(make_hc("hc-deg"))
    # trip the shared breaker: the controller is degraded
    for _ in range(5):
        reconciler.resilience.breaker.observe(Transient())
    assert reconciler.resilience.degraded
    for _ in range(8):
        await reconciler.reconcile("health", "hc-deg")
    assert reconciler.resilience.checks.state("health/hc-deg") == STATE_HEALTHY
    assert reconciler.resilience.checks.error_streak("health/hc-deg") == 0
    await reconciler.shutdown()


# ---------------------------------------------------------------------
# reconciler: flap damping
# ---------------------------------------------------------------------


def scripted_engine(script):
    """FakeEngine whose Nth submitted workflow follows the Nth script
    entry (polls-until-terminal, verdict)."""
    import collections as _collections

    engine = FakeWorkflowEngine()
    queue = _collections.deque(script)
    assigned = {}

    def completer(wf, count):
        name = wf["metadata"]["name"]
        if name not in assigned:
            if not queue:
                return None
            assigned[name] = queue.popleft()
        polls, ok = assigned[name]
        if count < polls:
            return None
        if ok:
            return {"phase": PHASE_SUCCEEDED}
        return {"phase": PHASE_FAILED, "message": "scripted failure"}

    engine._default_completer = completer
    return engine


@pytest.mark.asyncio
async def test_flapping_check_is_damped_then_restored():
    clock = FakeClock()
    metrics = MetricsCollector()
    # T,F,T,F -> 3 flips -> flapping; then four Ts calm it back down
    engine = scripted_engine(
        [(1, True), (1, False)] * 2 + [(1, True)] * 4
    )
    reconciler = build_reconciler(engine, clock, metrics)
    client = reconciler.client
    await client.apply(make_hc("hc-flap", repeat=60))
    key = "health/hc-flap"

    async def run_one(first=False, cadence=60.0):
        if not first:
            await clock.advance(cadence)
        await settle()
        await clock.advance(1.0)
        await settle()

    await reconciler.reconcile("health", "hc-flap")
    await run_one(first=True)
    for _ in range(3):
        await run_one()
    # four verdicts in: T,F,T,F -> flapping, damped 2x
    assert reconciler.resilience.checks.state(key) == STATE_FLAPPING
    stored = await client.get("health", "hc-flap")
    assert stored.status.state == STATE_FLAPPING
    assert metrics.sample_value(
        "healthcheck_check_state",
        {"healthcheck_name": "hc-flap", "namespace": "health", "state": "flapping"},
    ) == 1.0
    hc = await client.get("health", "hc-flap")
    assert reconciler._effective_repeat_after(hc) == 120
    assert any(
        "flapping" in e.message
        for e in reconciler.recorder.events_for("health", "hc-flap")
    )

    # damping is real: 60s (the raw cadence) does NOT fire the next run
    submitted_before = len(engine.submitted)
    await clock.advance(60.0)
    await settle()
    assert len(engine.submitted) == submitted_before
    # ...the damped 120s does
    await clock.advance(60.0)
    await settle()
    await clock.advance(1.0)
    await settle()
    assert len(engine.submitted) == submitted_before + 1

    # three more calm runs at the damped cadence restore the schedule
    for _ in range(3):
        await run_one(cadence=120.0)
    assert reconciler.resilience.checks.state(key) == STATE_HEALTHY
    stored = await client.get("health", "hc-flap")
    assert stored.status.state == ""
    assert reconciler._effective_repeat_after(stored) == 60
    assert any(
        "stabilized" in e.message
        for e in reconciler.recorder.events_for("health", "hc-flap")
    )
    await reconciler.shutdown()


# ---------------------------------------------------------------------
# reconciler: remedy storm control (the acceptance slice)
# ---------------------------------------------------------------------


@pytest.mark.asyncio
async def test_fleet_remedy_cap_suppresses_then_admits_after_refill():
    clock = FakeClock()
    metrics = MetricsCollector()
    engine = FakeWorkflowEngine()
    from activemonitor_tpu.engine.fake import fail_after, succeed_after

    # every healthcheck workflow fails on its first poll; every remedy
    # workflow succeeds on its first poll
    engine._default_completer = fail_after(1)
    engine.on_prefix("remedy-", succeed_after(1))
    reconciler = build_reconciler(engine, clock, metrics)
    reconciler.resilience.configure_remedy_rate(1.0)  # 1/min, burst 1
    client = reconciler.client

    # hc-a's failure consumes the only token; its remedy runs
    await client.apply(make_hc("hc-a", repeat=600, remedy_prefix="remedy-a-"))
    await reconciler.reconcile("health", "hc-a")
    await reconciler.wait_watches()
    assert metrics.sample_value(
        "healthcheck_remedy_runs_total",
        {"healthcheck_name": "hc-a", "namespace": "health", "result": "admitted"},
    ) == 1.0
    assert any(
        w["metadata"]["name"].startswith("remedy-a-")
        for w in engine.submitted
    )

    # hc-b fails with the bucket dry: remedy suppressed, evented, counted
    await client.apply(make_hc("hc-b", repeat=60, remedy_prefix="remedy-b-"))
    await reconciler.reconcile("health", "hc-b")
    await reconciler.wait_watches()
    assert metrics.sample_value(
        "healthcheck_remedy_runs_total",
        {"healthcheck_name": "hc-b", "namespace": "health", "result": "suppressed"},
    ) == 1.0
    assert not any(
        w["metadata"]["name"].startswith("remedy-b-")
        for w in engine.submitted
    )
    assert any(
        "Remedy suppressed by the fleet-wide remedy rate cap" in e.message
        for e in reconciler.recorder.events_for("health", "hc-b")
    )
    stored = await client.get("health", "hc-b")
    assert stored.status.remedy_total_runs == 0

    # after refill, hc-b's next failing run gets its remedy admitted
    await clock.advance(60.0)  # refills the bucket AND fires hc-b's timer
    await settle()
    await clock.advance(1.0)
    await settle()
    await reconciler.wait_watches()
    assert metrics.sample_value(
        "healthcheck_remedy_runs_total",
        {"healthcheck_name": "hc-b", "namespace": "health", "result": "admitted"},
    ) == 1.0
    assert any(
        w["metadata"]["name"].startswith("remedy-b-")
        for w in engine.submitted
    )
    stored = await client.get("health", "hc-b")
    assert stored.status.remedy_success_count == 1
    await reconciler.shutdown()


# ---------------------------------------------------------------------
# reconciler: degraded-mode status-write queue + replay
# ---------------------------------------------------------------------


class FlakyStatusClient:
    """Delegates to an InMemory client but fails the next N status
    writes with a transient 503 — the write-storm shape that trips the
    breaker and exercises the replay queue."""

    def __init__(self, inner):
        self._inner = inner
        self.fail_status = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    async def update_status(self, hc):
        if self.fail_status > 0:
            self.fail_status -= 1
            raise Transient("injected status-write 503")
        return await self._inner.update_status(hc)


@pytest.mark.asyncio
async def test_status_write_queues_while_degraded_and_replays_on_recovery():
    clock = FakeClock()
    metrics = MetricsCollector()
    engine = scripted_engine([(1, True)])
    client = FlakyStatusClient(InMemoryHealthCheckClient())
    breaker = CircuitBreaker(
        "api", clock=clock, failure_threshold=1, recovery_seconds=30.0
    )
    resilience = ResilienceCoordinator(
        clock, metrics, breaker=breaker, rng=random.Random(7)
    )
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
        resilience=resilience,
    )
    await client.apply(make_hc("hc-queue", repeat=60))
    key = "health/hc-queue"

    # the run completes, but every status-write attempt 503s: the
    # exhausted ladder trips the breaker (threshold 1) and the write is
    # parked instead of crashing the cycle
    client.fail_status = 10
    await reconciler.reconcile("health", "hc-queue")
    await settle()
    await clock.advance(1.0)  # terminal poll
    # the transient-retry ladder sleeps ~7.75s on the clock
    for _ in range(10):
        await clock.advance(1.0)
        await settle()
    await reconciler.wait_watches()

    assert resilience.pending_status_writes() == 1
    assert resilience.degraded
    assert metrics.sample_value("healthcheck_controller_degraded", {}) == 1.0
    stored = await client.get("health", "hc-queue")
    assert stored.status.success_count == 0  # nothing landed durably
    assert resilience.queued_status(key).success_count == 1  # parked
    assert len(engine.submitted) == 1
    # the cadence survived: the next run is on the books
    assert reconciler.timers.exists(key)

    # a watch-event reconcile while the write is parked must NOT
    # double-submit: the queued status overlays the stale durable one
    await reconciler.reconcile("health", "hc-queue")
    assert len(engine.submitted) == 1

    # recovery: the open window elapses, the transport heals, and the
    # replay sweep lands the parked write and closes the breaker
    client.fail_status = 0
    await clock.advance(31.0)
    replayed = await reconciler.replay_status_writes()
    assert replayed == 1
    assert resilience.pending_status_writes() == 0
    assert not resilience.degraded
    resilience.refresh()
    assert metrics.sample_value("healthcheck_controller_degraded", {}) == 0.0
    assert metrics.sample_value("healthcheck_status_write_queue_depth", {}) == 0.0
    stored = await client.get("health", "hc-queue")
    assert stored.status.success_count == 1
    assert len(engine.submitted) == 1  # still exactly one workflow
    await reconciler.shutdown()


def test_breaker_exemption_is_scoped_to_the_coordination_group():
    """Only coordination.k8s.io lease writes bypass the gate — a CR
    that happens to be NAMED 'leases' must not slip through."""
    from activemonitor_tpu.kube.client import _breaker_exempt

    assert _breaker_exempt(
        "/apis/coordination.k8s.io/v1/namespaces/health/leases/am-leader"
    )
    assert _breaker_exempt("/apis/coordination.k8s.io/v1/namespaces/x/leases")
    assert not _breaker_exempt(
        "/apis/activemonitor.keikoproj.io/v1alpha1/namespaces/ns/"
        "healthchecks/leases/status"
    )
    assert not _breaker_exempt("/api/v1/namespaces/leases/events")


@pytest.mark.asyncio
async def test_cluster_status_write_moves_fields_back_to_defaults():
    """The status MERGE patch must state every field explicitly: a
    cleared Quarantined mark, an emptied errorMessage, and a remedy
    reset (zeroed counters, nulled timestamps) all have to LAND — an
    exclude-defaults dump can never move a field back to its default
    through a merge patch."""
    from tests.kube_harness import stub_env
    from activemonitor_tpu.controller.client_k8s import (
        KubernetesHealthCheckClient,
    )

    async with stub_env() as (_server, api):
        client = KubernetesHealthCheckClient(api)
        hc = make_hc("sticky")
        applied = await client.apply(hc)
        applied.status.state = STATE_QUARANTINED
        applied.status.error_message = "quarantined: broken"
        applied.status.remedy_total_runs = 3
        applied.status.remedy_success_count = 3
        import datetime

        applied.status.remedy_finished_at = datetime.datetime.now(
            datetime.timezone.utc
        )
        written = await client.update_status(applied)
        assert written.status.state == STATE_QUARANTINED
        # now clear the mark and reset the remedy, like the reconciler
        written.status.state = ""
        written.status.error_message = ""
        written.status.reset_remedy("HealthCheck Passed so Remedy is reset")
        cleared = await client.update_status(written)
        assert cleared.status.state == ""
        assert cleared.status.error_message == ""
        assert cleared.status.remedy_total_runs == 0
        assert cleared.status.remedy_success_count == 0
        assert cleared.status.remedy_finished_at is None
        # and a fresh read agrees (nothing stuck server-side)
        fresh = await client.get("health", "sticky")
        assert fresh.status.state == ""
        assert fresh.status.remedy_total_runs == 0
        assert fresh.status.remedy_finished_at is None


@pytest.mark.asyncio
async def test_engine_submit_is_gated_while_breaker_open():
    clock = FakeClock()
    engine = FakeWorkflowEngine()
    reconciler = build_reconciler(engine, clock)
    for _ in range(5):
        reconciler.resilience.breaker.observe(Transient())
    await reconciler.client.apply(make_hc("hc-gate"))
    delay = await reconciler.reconcile("health", "hc-gate")
    # rejected fast, no workflow created, requeued on the stretched ladder
    assert engine.submitted == []
    assert delay is not None and delay >= 1.0
    await reconciler.shutdown()
