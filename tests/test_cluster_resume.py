"""Cluster-mode checkpoint/resume and load behavior.

The reference's durability model (SURVEY.md §5.4): the CR status
subresource is the only durable state; on restart the controller
re-lists, rebuilds its in-memory schedule idempotently, and the
FinishedAt dedupe prevents double-running recent checks. Here that
contract is exercised across a REAL controller restart against the
stub API server — the data outlives the manager because it lives in
the (stub) apiserver, exactly like etcd.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import RBACProvisioner
from activemonitor_tpu.controller.client_k8s import KubernetesHealthCheckClient
from activemonitor_tpu.controller.events import KubernetesEventRecorder
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.controller.rbac import KubernetesRBACBackend
from activemonitor_tpu.controller.reconciler import HealthCheckReconciler
from activemonitor_tpu.engine.argo import WF_GROUP, WF_PLURAL, WF_VERSION, ArgoWorkflowEngine
from activemonitor_tpu.kube import api_path
from activemonitor_tpu.metrics import MetricsCollector

from tests.kube_harness import stub_env

WF_INLINE = """
apiVersion: argoproj.io/v1alpha1
kind: Workflow
spec:
  entrypoint: main
"""


def make_hc(name, repeat=3600):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": "health"},
            "spec": {
                "repeatAfterSec": repeat,
                "level": "cluster",
                "workflow": {
                    "generateName": f"{name}-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": f"{name}-sa",
                        "source": {"inline": WF_INLINE},
                    },
                },
            },
        }
    )


def build_controller(api):
    client = KubernetesHealthCheckClient(api)
    reconciler = HealthCheckReconciler(
        client=client,
        engine=ArgoWorkflowEngine(api),
        rbac=RBACProvisioner(KubernetesRBACBackend(api)),
        recorder=KubernetesEventRecorder(api),
        metrics=MetricsCollector(),
    )
    return client, Manager(client=client, reconciler=reconciler, max_parallel=4)


async def wait_for(predicate, timeout=10.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        result = await predicate()
        if result:
            return result
        assert asyncio.get_event_loop().time() < deadline, "condition not met"
        await asyncio.sleep(0.05)


async def complete_workflows(server, api):
    """Play the Argo controller: succeed every pending workflow."""
    for wf in server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
        if (wf.get("status") or {}).get("phase") not in ("Succeeded", "Failed"):
            await api.merge_patch(
                api_path(
                    WF_GROUP, WF_VERSION, WF_PLURAL,
                    wf["metadata"]["namespace"], wf["metadata"]["name"], "status",
                ),
                {"status": {"phase": "Succeeded"}},
            )


@pytest.mark.asyncio
async def test_restart_resumes_without_double_running_recent_checks():
    async with stub_env() as (server, api):
        client, manager = build_controller(api)
        await manager.start()
        try:
            await client.apply(make_hc("resume-hc"))
            await wait_for(
                lambda: asyncio.sleep(0, server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))
            )
            await complete_workflows(server, api)

            async def succeeded():
                hc = await client.get("health", "resume-hc")
                return hc if hc and hc.status.status == "Succeeded" else None

            await wait_for(succeeded)
        finally:
            await manager.stop()
        runs_before = len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))
        assert runs_before == 1

        # controller restart: fresh manager + reconciler, SAME apiserver.
        # boot resync re-lists and reconciles, and the FinishedAt dedupe
        # must not resubmit a check that just ran (reference :264-267)
        client2, manager2 = build_controller(api)
        await manager2.start()
        try:
            await asyncio.sleep(0.5)  # boot resync + any reconciles settle
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == runs_before
            hc = await client2.get("health", "resume-hc")
            assert hc.status.success_count == 1  # status survived the restart
            # and the schedule was rebuilt: the timer exists again
            assert manager2.reconciler.timers.exists("health/resume-hc")
        finally:
            await manager2.stop()


@pytest.mark.asyncio
async def test_restart_reruns_overdue_checks():
    """A check whose FinishedAt is older than its interval must run
    again right after restart (resume means resume, not amnesia)."""
    async with stub_env() as (server, api):
        client, manager = build_controller(api)
        await manager.start()
        try:
            await client.apply(make_hc("overdue-hc", repeat=1))
            await wait_for(
                lambda: asyncio.sleep(0, server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))
            )
            await complete_workflows(server, api)

            async def succeeded():
                hc = await client.get("health", "overdue-hc")
                return hc if hc and hc.status.success_count >= 1 else None

            await wait_for(succeeded)
        finally:
            await manager.stop()

        await asyncio.sleep(1.1)  # the 1s interval elapses while "down"
        client2, manager2 = build_controller(api)
        await manager2.start()
        try:
            await wait_for(
                lambda: asyncio.sleep(
                    0,
                    len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) >= 2 or None,
                )
            )
        finally:
            await manager2.stop()


@pytest.mark.asyncio
async def test_cluster_mode_check_storm():
    """Load: a fleet of checks applied at once against the stub
    apiserver; every one must run, succeed, and carry real RBAC —
    the cluster-tier version of tests/test_stress.py."""
    N = 20
    async with stub_env() as (server, api):
        client, manager = build_controller(api)
        await manager.start()
        try:
            for i in range(N):
                await client.apply(make_hc(f"storm-{i:02d}"))

            async def all_submitted():
                return len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) >= N or None

            await wait_for(all_submitted, timeout=20)
            await complete_workflows(server, api)

            async def all_succeeded():
                checks = await client.list()
                done = [hc for hc in checks if hc.status.status == "Succeeded"]
                return len(done) == N or None

            await wait_for(all_succeeded, timeout=20)
            # every check got its own real ServiceAccount
            sas = {
                o["metadata"]["name"] for o in server.objs("", "v1", "serviceaccounts")
            }
            assert {f"storm-{i:02d}-sa" for i in range(N)} <= sas
        finally:
            await manager.stop()
