"""Metrics tests (reference test model: internal/metrics/collector_test.go —
malformed custom-metric table against a private registry)."""

import pytest

from activemonitor_tpu.metrics import (
    MetricsCollector,
    WORKFLOW_LABEL_HEALTHCHECK,
    WORKFLOW_LABEL_REMEDY,
)


@pytest.fixture()
def collector():
    return MetricsCollector()


def labels(name, wf=WORKFLOW_LABEL_HEALTHCHECK):
    return {"healthcheck_name": name, "workflow": wf}


def test_record_success_sets_all_vecs(collector):
    collector.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 100.0, 107.5)
    assert collector.sample_value("healthcheck_success_count", labels("hc-a")) == 1
    assert collector.sample_value("healthcheck_runtime_seconds", labels("hc-a")) == 7.5
    assert collector.sample_value("healthcheck_starttime", labels("hc-a")) == 100.0
    assert collector.sample_value("healthcheck_finishedtime", labels("hc-a")) == 107.5


def test_record_failure_increments_error(collector):
    collector.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 100.0, 101.0)
    collector.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 102.0, 103.0)
    assert collector.sample_value("healthcheck_error_count", labels("hc-a")) == 2
    assert collector.sample_value("healthcheck_success_count", labels("hc-a")) is None


def test_remedy_label_dimension(collector):
    collector.record_success("hc-a", WORKFLOW_LABEL_REMEDY, 0, 1)
    assert (
        collector.sample_value(
            "healthcheck_success_count", labels("hc-a", WORKFLOW_LABEL_REMEDY)
        )
        == 1
    )


def test_exposition_contains_reference_metric_names(collector):
    collector.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 0, 1)
    text = collector.exposition().decode()
    # exact names, no _total suffix (scrape contract of the reference)
    assert "healthcheck_success_count{" in text
    assert "healthcheck_runtime_seconds{" in text


def test_custom_metrics_from_outputs(collector):
    status = {
        "outputs": {
            "parameters": [
                {
                    "name": "metrics",
                    "value": '{"metrics": [{"name": "ici-allreduce-gbps", '
                    '"value": 123.4, "metrictype": "gauge", "help": "ICI bw"}]}',
                }
            ]
        }
    }
    n = collector.record_custom_metrics("tpu-probe", status)
    assert n == 1
    # both hc name and metric name sanitized: "-" -> "_"
    assert (
        collector.sample_value(
            "tpu_probe_ici_allreduce_gbps", {"healthcheck_name": "tpu-probe"}
        )
        == 123.4
    )


def test_custom_metric_name_overlap_deduped(collector):
    # deliberate divergence from collector.go:90 (design.md #12): the
    # hc-name prefix merges with the metric name's leading overlap
    # instead of stuttering
    status = {
        "outputs": {
            "parameters": [
                {
                    "name": "metrics",
                    "value": '{"metrics": [{"name": "ici-allreduce-busbw-gbps", '
                    '"value": 600.0}]}',
                }
            ]
        }
    }
    assert collector.record_custom_metrics("tpu-ici-allreduce", status) == 1
    assert (
        collector.sample_value(
            "tpu_ici_allreduce_busbw_gbps",
            {"healthcheck_name": "tpu-ici-allreduce"},
        )
        == 600.0
    )
    # the stuttered reference name must NOT exist
    assert (
        collector.sample_value(
            "tpu_ici_allreduce_ici_allreduce_busbw_gbps",
            {"healthcheck_name": "tpu-ici-allreduce"},
        )
        is None
    )


def test_same_check_merged_name_collision_skipped(collector):
    # check a-b emitting b-c and c: both merge to a_b_c — the second
    # must be skipped (logged), never silently overwrite the first
    status = {
        "outputs": {
            "parameters": [
                {
                    "name": "metrics",
                    "value": '{"metrics": [{"name": "b-c", "value": 1.0}, '
                    '{"name": "c", "value": 2.0}]}',
                }
            ]
        }
    }
    assert collector.record_custom_metrics("a-b", status) == 1
    assert collector.sample_value("a_b_c", {"healthcheck_name": "a-b"}) == 1.0


def test_prefix_dedupe_rules():
    from activemonitor_tpu.metrics.collector import _prefix_dedupe

    assert _prefix_dedupe("tpu_ici_allreduce", "ici_allreduce_busbw_gbps") == (
        "tpu_ici_allreduce_busbw_gbps"
    )
    assert _prefix_dedupe("hc", "bw") == "hc_bw"  # no overlap: plain join
    assert _prefix_dedupe("hc", "hc") == "hc"  # full overlap
    # overlap matches whole tokens only — "al" vs "allreduce" is no match
    assert _prefix_dedupe("tpu_al", "allreduce_gbps") == "tpu_al_allreduce_gbps"


def test_custom_metrics_updates_existing_gauge(collector):
    def status(v):
        return {
            "outputs": {
                "parameters": [
                    {"name": "m", "value": '{"metrics": [{"name": "bw", "value": %f}]}' % v}
                ]
            }
        }

    collector.record_custom_metrics("hc", status(1.0))
    collector.record_custom_metrics("hc", status(2.0))
    assert collector.sample_value("hc_bw", {"healthcheck_name": "hc"}) == 2.0


@pytest.mark.parametrize(
    "value",
    [
        "not json at all",
        '{"metrics": "not-a-list"}',
        '{"metrics": [{"value": 1.0}]}',  # missing name
        '{"metrics": [{"name": "x", "value": "NaN-ish-string"}]}',
        '{"metrics": [42]}',
        '{"other": []}',
        "",
    ],
)
def test_malformed_custom_metrics_are_skipped(collector, value):
    status = {"outputs": {"parameters": [{"name": "m", "value": value}]}}
    assert collector.record_custom_metrics("hc", status) == 0


def test_no_outputs_is_noop(collector):
    assert collector.record_custom_metrics("hc", {}) == 0
    assert collector.record_custom_metrics("hc", {"outputs": None}) == 0
    assert collector.record_custom_metrics("hc", {"outputs": {"parameters": None}}) == 0


REFERENCE_SCRAPE_NAMES = (
    # the exact names the reference exposes (collector.go:19-48) —
    # dashboards and alerts scrape these verbatim
    "healthcheck_success_count",
    "healthcheck_error_count",
    "healthcheck_runtime_seconds",
    "healthcheck_starttime",
    "healthcheck_finishedtime",
)


def test_scrape_text_pins_reference_names_without_total_suffix(collector):
    """The exposition contract, asserted on the scrape text itself:
    prometheus_client appends `_total` to Counter samples, so the two
    reference counters are deliberately Gauges (collector.py) — this
    test is the tripwire that keeps that workaround from regressing."""
    collector.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 0, 1)
    collector.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 1, 2)
    lines = collector.exposition().decode().splitlines()
    for name in REFERENCE_SCRAPE_NAMES:
        assert any(
            line.startswith(name + "{") for line in lines
        ), f"reference metric {name} missing from scrape"
        assert not any(
            line.startswith(name + "_total{") for line in lines
        ), f"{name} grew a _total suffix — scrape contract broken"


def test_scrape_text_exposes_controller_runtime_parity_families(collector):
    collector.record_reconcile("success", 0.25)
    collector.record_queue_add(1)
    collector.record_queue_get(0, 0.05)
    collector.record_work_duration(0.2)
    collector.set_active_workers(1)
    collector.set_max_concurrent(10)
    collector.record_engine_submit("fake")
    collector.record_engine_poll("fake")
    collector.record_watch_restart("health")
    lines = collector.exposition().decode().splitlines()

    def sample(prefix):
        return any(line.startswith(prefix) for line in lines)

    assert sample('controller_runtime_reconcile_total{controller="healthcheck",result="success"}')
    assert sample("controller_runtime_reconcile_time_seconds_bucket{")
    assert sample("controller_runtime_reconcile_time_seconds_count{")
    assert sample('controller_runtime_active_workers{controller="healthcheck"}')
    assert sample("controller_runtime_max_concurrent_reconciles{")
    assert sample('workqueue_depth{name="healthcheck"}')
    assert sample('workqueue_adds_total{name="healthcheck"}')
    assert sample("workqueue_queue_duration_seconds_bucket{")
    assert sample("workqueue_work_duration_seconds_bucket{")
    assert sample('engine_submit_total{engine="fake"}')
    assert sample('engine_poll_total{engine="fake"}')
    assert sample('workflow_watch_restarts_total{namespace="health"}')


def test_reconcile_and_queue_recorders_accumulate(collector):
    collector.record_reconcile("success", 0.5)
    collector.record_reconcile("success", 1.5)
    collector.record_reconcile("error", 0.1)
    assert (
        collector.sample_value(
            "controller_runtime_reconcile_total",
            {"controller": "healthcheck", "result": "success"},
        )
        == 2
    )
    assert (
        collector.sample_value(
            "controller_runtime_reconcile_time_seconds_sum",
            {"controller": "healthcheck"},
        )
        == 2.1
    )
    collector.record_queue_add(3)
    assert collector.sample_value("workqueue_depth", {"name": "healthcheck"}) == 3
    collector.record_queue_get(2, 0.25)
    assert collector.sample_value("workqueue_depth", {"name": "healthcheck"}) == 2
    assert (
        collector.sample_value(
            "workqueue_queue_duration_seconds_sum", {"name": "healthcheck"}
        )
        == 0.25
    )
    # negative wait (clock skew) is clamped, never raises
    collector.record_queue_get(1, -5.0)
    assert (
        collector.sample_value(
            "workqueue_queue_duration_seconds_sum", {"name": "healthcheck"}
        )
        == 0.25
    )


def test_two_collectors_do_not_share_registries():
    # the reference's global registry caused a documented race
    # (collector_test.go:82-88); per-instance registries avoid it
    a = MetricsCollector()
    b = MetricsCollector()
    a.record_success("hc", WORKFLOW_LABEL_HEALTHCHECK, 0, 1)
    assert b.sample_value("healthcheck_success_count", labels("hc")) is None
