"""CRD generation and controller-CLI tests."""

import json

import yaml

from activemonitor_tpu.__main__ import main
from activemonitor_tpu.api.crd import build_crd, crd_yaml


def test_crd_shape():
    crd = build_crd()
    assert crd["metadata"]["name"] == "healthchecks.activemonitor.keikoproj.io"
    spec = crd["spec"]
    assert spec["group"] == "activemonitor.keikoproj.io"
    assert spec["names"]["shortNames"] == ["hc", "hcs"]
    version = spec["versions"][0]
    assert version["name"] == "v1alpha1"
    assert version["subresources"] == {"status": {}}
    cols = {c["jsonPath"] for c in version["additionalPrinterColumns"]}
    assert ".status.status" in cols
    assert ".status.successCount" in cols


def test_crd_schema_has_reference_spec_fields():
    crd = build_crd()
    props = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]["properties"]
    spec_props = props["spec"]["properties"]
    # the full field surface of the reference CRD
    # (api/v1alpha1/healthcheck_types.go:32-44)
    for field in [
        "repeatAfterSec",
        "description",
        "workflow",
        "level",
        "schedule",
        "remedyworkflow",
        "backoffFactor",
        "backoffMax",
        "backoffMin",
        "remedyRunsLimit",
        "remedyResetInterval",
    ]:
        assert field in spec_props, field
    wf = spec_props["workflow"]["properties"]
    assert set(wf) >= {"generateName", "resource", "workflowtimeout", "rbacRules"}
    status_props = props["status"]["properties"]
    assert "remedyTriggeredAt" in status_props  # parity quirk preserved
    assert "totalHealthCheckRuns" in status_props


def test_crd_slo_block_uses_v1_legal_exclusive_bounds():
    """apiextensions.k8s.io/v1 JSONSchemaProps declares
    exclusiveMinimum/Maximum as BOOLEANS beside minimum/maximum;
    pydantic's draft-2020-12 numeric form would make the whole CRD
    fail to decode at apply time."""
    crd = build_crd()
    props = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]["properties"]
    slo = props["spec"]["properties"]["slo"]
    objective = slo["properties"]["objective"]
    assert objective["minimum"] == 0.0
    assert objective["exclusiveMinimum"] is True
    assert objective["maximum"] == 1.0
    assert objective["exclusiveMaximum"] is True
    window = slo["properties"]["windowSeconds"]
    assert window["minimum"] == 0
    assert window["exclusiveMinimum"] is True

    def no_numeric_exclusive_bounds(node):
        if isinstance(node, dict):
            for key in ("exclusiveMinimum", "exclusiveMaximum"):
                if key in node:
                    assert isinstance(node[key], bool), node
            for value in node.values():
                no_numeric_exclusive_bounds(value)
        elif isinstance(node, list):
            for value in node:
                no_numeric_exclusive_bounds(value)

    no_numeric_exclusive_bounds(crd)


def test_crd_has_no_refs_or_nulls():
    text = crd_yaml()
    assert "$ref" not in text
    assert "$defs" not in text
    doc = yaml.safe_load(text)

    def no_null_types(node):
        if isinstance(node, dict):
            assert node.get("type") != "null"
            for v in node.values():
                no_null_types(v)
        elif isinstance(node, list):
            for v in node:
                no_null_types(v)

    no_null_types(doc)


def test_cli_crd_and_version(capsys):
    assert main(["crd"]) == 0
    out = capsys.readouterr().out
    assert yaml.safe_load(out)["kind"] == "CustomResourceDefinition"
    assert main(["version"]) == 0


def test_cli_apply_get_delete(tmp_path, capsys):
    manifest = tmp_path / "hc.yaml"
    manifest.write_text(
        """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata: {name: cli-check, namespace: health}
spec: {repeatAfterSec: 60, level: cluster}
"""
    )
    store = str(tmp_path / "store")
    assert main(["apply", "--store", store, "-f", str(manifest)]) == 0
    assert main(["get", "hc", "--store", store]) == 0
    out = capsys.readouterr().out
    assert "cli-check" in out
    assert "LATEST STATUS" in out
    assert main(["delete", "cli-check", "-n", "health", "--store", store]) == 0
    assert main(["get", "hc", "--store", store]) == 0
    assert "No resources found" in capsys.readouterr().out


def test_cli_delete_missing_returns_error(tmp_path):
    store = str(tmp_path / "store")
    assert main(["delete", "ghost", "--store", store]) == 1


def test_cli_get_output_yaml_and_json(tmp_path, capsys):
    manifest = tmp_path / "hc.yaml"
    manifest.write_text(
        """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata: {name: fmt-check, namespace: health}
spec: {repeatAfterSec: 60, level: cluster}
"""
    )
    store = str(tmp_path / "store")
    assert main(["apply", "--store", store, "-f", str(manifest)]) == 0
    capsys.readouterr()
    assert main(["get", "hc", "--store", store, "-o", "yaml"]) == 0
    doc = yaml.safe_load(capsys.readouterr().out)
    assert doc["metadata"]["name"] == "fmt-check"
    assert main(["get", "hc", "fmt-check", "-n", "health", "--store", store, "-o", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["spec"]["repeatAfterSec"] == 60
    assert main(["get", "hc", "ghost", "--store", store]) == 1


def test_cli_describe(tmp_path, capsys):
    manifest = tmp_path / "hc.yaml"
    manifest.write_text(
        """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata: {name: desc-check, namespace: default}
spec: {repeatAfterSec: 60, level: cluster}
"""
    )
    store = str(tmp_path / "store")
    assert main(["apply", "--store", store, "-f", str(manifest)]) == 0
    capsys.readouterr()
    assert main(["describe", "desc-check", "--store", store]) == 0
    out = capsys.readouterr().out
    assert "Name:       desc-check" in out
    assert "repeatAfterSec: 60" in out
    assert "Events (0 recorded):" in out
    assert main(["describe", "ghost", "--store", store]) == 1


def test_file_event_recorder_persists_and_caps(tmp_path):
    from activemonitor_tpu.api import HealthCheck
    from activemonitor_tpu.controller.events import FileEventRecorder

    hc = HealthCheck.from_dict(
        {"metadata": {"name": "ev", "namespace": "default"}, "spec": {}}
    )
    rec = FileEventRecorder(str(tmp_path), max_lines=10)
    for i in range(25):
        rec.event(hc, "Normal", "Normal", f"message-{i}")
    events = FileEventRecorder.read_events(str(tmp_path), "default", "ev")
    assert len(events) <= 10
    assert events[-1]["message"] == "message-24"


def test_probe_suite_quick(capsys):
    from activemonitor_tpu.probes import suite

    result = suite.run(
        quick=True,
        skip=[
            "matmul", "hbm", "ici-allreduce", "collectives", "ring-attention",
            "flash-attention", "training-step", "decode", "serving",
            "serving-disagg", "dcn-allreduce", "straggler", "transfer",
            "checkpoint",
        ],
    )
    assert result.ok
    assert result.details["probes_run"] == 3  # devices, memory, compile-smoke
    names = {m.name for m in result.metrics}
    assert "tpu-device-count" in names
    assert "xla-compile-seconds" in names


def test_json_log_format():
    import json as _json
    import logging
    import sys

    from activemonitor_tpu.utils.logfmt import JsonFormatter, configure_logging

    # formatter semantics, no global state involved
    fmt = JsonFormatter()
    record = logging.LogRecord(
        "activemonitor.test", logging.INFO, __file__, 1, "hello %s", ("x",), None
    )
    doc = _json.loads(fmt.format(record))
    assert doc["msg"] == "hello x"
    assert doc["level"] == "info"
    assert doc["logger"] == "activemonitor.test"

    exc_record = logging.LogRecord(
        "activemonitor.test", logging.ERROR, __file__, 1, "boom", (), None
    )
    try:
        raise ValueError("kapow")
    except ValueError:
        exc_record.exc_info = sys.exc_info()
    doc = _json.loads(fmt.format(exc_record))
    assert "kapow" in doc["exception"]

    # configure wires the formatter onto the root handler. Detach the
    # existing handlers FIRST: basicConfig(force=True) would close any
    # it finds (a closed pytest log handler breaks every later test),
    # then restore handlers and level afterwards.
    root = logging.getLogger()
    saved_handlers = root.handlers[:]
    saved_level = root.level
    for h in saved_handlers:
        root.removeHandler(h)
    try:
        configure_logging("INFO", "json")
        assert isinstance(root.handlers[0].formatter, JsonFormatter)
    finally:
        for h in root.handlers[:]:
            root.removeHandler(h)
        for h in saved_handlers:
            root.addHandler(h)
        root.setLevel(saved_level)
