"""Local-process workflow engine — single-host probe execution.

Where the reference always delegates to the Argo controller to run probe
pods (SURVEY.md §2 #14), TPU probes frequently run on the very host that
owns the TPU: a GKE TPU VM, a bare v5e host, or a dev box. This engine
executes a bounded subset of the Argo Workflow shape directly as local
subprocesses, so the full check → probe → status → metrics loop works
with no cluster at all.

Supported template forms (the subset the probe library and the reference
examples use):

- ``container``: ``command`` + ``args`` exec'd locally (the image field
  is ignored — the local host IS the probe environment)
- ``script``: ``source`` written to a temp file and run with ``command``
- ``steps``: sequential groups of template references

``spec.entrypoint`` selects the template;
``spec.activeDeadlineSeconds`` bounds execution (timeout ⇒ Failed, like
Argo). Children run via the synchronous subprocess API on worker
threads (``asyncio.to_thread``) rather than asyncio's subprocess
transport: the transport only reports exit once the stdout pipe hits
EOF, and a killed child's grandchildren (e.g. anything ``sh -c``
forked) keep that pipe open — ``Popen`` lets the timeout path reap
with ``wait()`` without draining the pipe.

A probe's final stdout line, when it parses as the custom-metrics JSON
contract (reference: internal/metrics/collector.go:68-115), is exposed
as ``status.outputs.parameters[0]`` exactly like an Argo global output
parameter, so custom metrics flow identically in all engines.
"""

from __future__ import annotations

import asyncio
import copy
import json
import os
import sys
import tempfile
import time
from typing import Dict, List, Optional

from activemonitor_tpu.engine.base import (
    PHASE_FAILED,
    PHASE_RUNNING,
    PHASE_SUCCEEDED,
    generate_name,
)


class _StepFailed(RuntimeError):
    pass


class _DeadlineExceeded(RuntimeError):
    pass


class LocalProcessEngine:
    name = "local"  # engine label on submit/poll counters

    def __init__(self, env: Optional[dict] = None, default_ttl_seconds: float = 3600.0):
        self._workflows: Dict[str, dict] = {}
        self._tasks: Dict[str, asyncio.Task] = {}
        self._finished_at: Dict[str, float] = {}
        self._env = env
        # terminal workflows are pruned after their manifest's
        # ttlSecondsAfterFinished (or this default) — the local stand-in
        # for Argo's TTL controller, so a long-lived daemon's workflow
        # map doesn't grow without bound
        self._default_ttl = default_ttl_seconds

    async def submit(self, manifest: dict) -> str:
        self._prune()
        manifest = copy.deepcopy(manifest)
        meta = manifest.setdefault("metadata", {})
        name = meta.get("name") or generate_name(meta.get("generateName", "wf-"))
        meta["name"] = name
        namespace = meta.get("namespace", "default")
        key = f"{namespace}/{name}"
        manifest["status"] = {"phase": PHASE_RUNNING}
        # a reused key must shed its old finished-timestamp, or a later
        # prune would evict the RUNNING resubmission
        self._finished_at.pop(key, None)
        self._workflows[key] = manifest
        self._tasks[key] = asyncio.create_task(self._run(key, manifest))
        return name

    # effective TTLs are floored so a finished workflow always outlives
    # the reconciler's slowest status poll: the poll backoff maxes at
    # workflowtimeout/2, and activeDeadlineSeconds carries that timeout
    # into the manifest — so the floor is max(60s, activeDeadlineSeconds)
    MIN_TTL_SECONDS = 60.0

    def _prune(self) -> None:
        now = time.monotonic()
        doomed = []
        for key, finished in self._finished_at.items():
            spec = (self._workflows.get(key) or {}).get("spec") or {}
            ttl = spec.get("ttlSecondsAfterFinished", self._default_ttl)
            try:
                ttl = float(ttl)
            except (TypeError, ValueError):
                ttl = self._default_ttl
            try:
                deadline = float(spec.get("activeDeadlineSeconds") or 0)
            except (TypeError, ValueError):
                deadline = 0.0
            if now - finished > max(ttl, self.MIN_TTL_SECONDS, deadline):
                doomed.append(key)
        for key in doomed:
            self._workflows.pop(key, None)
            self._tasks.pop(key, None)
            self._finished_at.pop(key, None)

    async def get(self, namespace: str, name: str) -> Optional[dict]:
        wf = self._workflows.get(f"{namespace}/{name}")
        return copy.deepcopy(wf) if wf is not None else None

    async def shutdown(self) -> None:
        """Wait out all in-flight workflow tasks (tests / clean exit)."""
        tasks = [t for t in self._tasks.values() if not t.done()]
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    async def _run(self, key: str, manifest: dict) -> None:
        try:
            await self._run_inner(manifest)
        finally:
            # only the task currently owning the key may stamp it:
            # a stale overlapping run must not mark a resubmitted
            # RUNNING workflow as finished (and thus prunable)
            if self._tasks.get(key) is asyncio.current_task():
                self._finished_at[key] = time.monotonic()

    async def _run_inner(self, manifest: dict) -> None:
        spec = manifest.get("spec") or {}
        deadline = spec.get("activeDeadlineSeconds")
        deadline_at = (
            time.monotonic() + float(deadline) if deadline else None
        )
        outputs_lines: List[str] = []
        try:
            await self._run_template_by_name(
                spec, spec.get("entrypoint", ""), outputs_lines, deadline_at
            )
        except _DeadlineExceeded:
            manifest["status"] = {
                "phase": PHASE_FAILED,
                "message": f"exceeded activeDeadlineSeconds {deadline}",
            }
            return
        except _StepFailed as e:
            manifest["status"] = {"phase": PHASE_FAILED, "message": str(e)}
            self._attach_outputs(manifest, outputs_lines)
            return
        except Exception as e:  # malformed template etc.
            manifest["status"] = {"phase": PHASE_FAILED, "message": repr(e)}
            return
        manifest["status"] = {"phase": PHASE_SUCCEEDED}
        self._attach_outputs(manifest, outputs_lines)

    def _attach_outputs(self, manifest: dict, lines: List[str]) -> None:
        """Expose a trailing metrics-contract JSON line as a global
        output parameter, mirroring Argo's outputs.parameters shape."""
        for line in reversed(lines):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                doc = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(doc, dict) and "metrics" in doc:
                manifest["status"]["outputs"] = {
                    "parameters": [{"name": "metrics", "value": line}]
                }
                return

    async def _run_template_by_name(
        self,
        spec: dict,
        name: str,
        collect: List[str],
        deadline_at: Optional[float],
    ) -> None:
        templates = {t.get("name"): t for t in spec.get("templates", [])}
        if name not in templates:
            raise ValueError(f"entrypoint template {name!r} not found")
        await self._run_template(spec, templates[name], collect, deadline_at)

    async def _run_template(
        self,
        spec: dict,
        template: dict,
        collect: List[str],
        deadline_at: Optional[float],
    ) -> None:
        if "steps" in template:
            for group in template["steps"]:
                steps = group if isinstance(group, list) else [group]
                for step in steps:
                    await self._run_template_by_name(
                        spec, step.get("template", ""), collect, deadline_at
                    )
            return
        if "container" in template:
            c = template["container"]
            argv = list(c.get("command", [])) + [str(a) for a in c.get("args", [])]
            if not argv:
                raise ValueError("container template has no command")
            await self._exec(argv, collect, deadline_at)
            return
        if "script" in template:
            s = template["script"]
            interpreter = list(s.get("command", [sys.executable]))
            suffix = ".py" if "python" in " ".join(interpreter) else ".sh"
            with tempfile.NamedTemporaryFile("w", suffix=suffix, delete=False) as f:
                f.write(s.get("source", ""))
                path = f.name
            try:
                await self._exec(interpreter + [path], collect, deadline_at)
            finally:
                os.unlink(path)
            return
        raise ValueError(f"unsupported template shape: {sorted(template.keys())}")

    async def _exec(
        self, argv: List[str], collect: List[str], deadline_at: Optional[float]
    ) -> None:
        if deadline_at is not None and time.monotonic() >= deadline_at:
            raise _DeadlineExceeded()
        remaining = (
            None if deadline_at is None else max(0.01, deadline_at - time.monotonic())
        )
        out, returncode = await asyncio.to_thread(
            self._exec_sync, argv, remaining
        )
        if returncode is None:
            raise _DeadlineExceeded()
        collect.extend(out.decode("utf-8", "replace").splitlines())
        if returncode != 0:
            tail = out.decode("utf-8", "replace").strip().splitlines()[-3:]
            raise _StepFailed(f"{argv[0]} exited {returncode}: {' | '.join(tail)}")

    def _exec_sync(self, argv: List[str], timeout: Optional[float]):
        """Runs on a worker thread. Returns (output, returncode); a None
        returncode means the deadline was hit and the child was killed."""
        import subprocess

        proc = subprocess.Popen(
            argv,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=self._env,
            start_new_session=True,  # own process group so the deadline
            # path can kill forked grandchildren too
        )
        try:
            out, _ = proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            import signal

            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            # reap with wait(), NOT communicate(): grandchildren inherit
            # the stdout pipe, so draining to EOF would block until the
            # whole process tree exits, not just our child
            proc.wait()
            if proc.stdout is not None:
                proc.stdout.close()
            return b"", None
        return out, proc.returncode
