"""Test configuration.

Mirrors the reference's envtest trick (SURVEY.md §4): run everything on
CPU with a virtual 8-device platform so mesh/sharding code is exercised
without TPU hardware.
"""

import os
import sys
from pathlib import Path

# Must be set before jax is imported anywhere.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Tests run on the virtual 8-device CPU platform by default — the env
# may carry JAX_PLATFORMS pointing at real/tunneled TPU hardware (e.g.
# "axon"), and the config API outranks it. Opt into hardware tests
# explicitly with ACTIVEMONITOR_TEST_TPU=1.
if os.environ.get("ACTIVEMONITOR_TEST_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# pytest-asyncio is not installed in this image; run coroutine tests
# with asyncio.run via the pyfunc hook instead.
import asyncio
import inspect


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run test in an event loop")


def pytest_pyfunc_call(pyfuncitem):
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None
