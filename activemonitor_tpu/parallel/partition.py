"""One sharding surface — regex partition rules resolved over pytrees.

Sharding used to be hand-threaded per op: every shard_map call site
built its own `PartitionSpec`s inline, so re-meshing a composed
DP×TP×PP probe meant editing kernel code. This module is the single
surface the ops layer goes through instead (ROADMAP item 5, the
SNIPPETS.md [2] `named_tree_map` + regex-rule pattern):

- :func:`named_tree_map` — tree_map whose callback also receives the
  leaf's '/'-joined path name ("layers/wqkv", "opt/mu/embed").
- :func:`match_partition_rules` — resolve an ordered list of
  ``(regex, PartitionSpec)`` rules over an arbitrary pytree. FIRST
  match wins (``re.search``), scalars/size-1 leaves never partition,
  and unmatched leaves fall back to replicated (``P()``) unless the
  caller asks for a hard error. Because the rules are plain data, a
  mesh layout is an edit to a rules dict, not to kernel code — the
  Maple portability argument (PAPERS.md) applied to our ops.
- :func:`validate_rules` / :func:`validate_specs` — a rule naming a
  mesh axis the mesh doesn't carry is a ValueError up front, never a
  tracer crash from inside shard_map.
- :func:`make_shard_fns` / :func:`make_gather_fns` /
  :func:`shard_tree` — per-leaf placement/gather callables derived
  from resolved specs (the fmengine ``make_shard_and_gather_fns``
  shape).
- :func:`shard_map` — THE single entry point over the
  ``utils/compat.py`` vintage adapter. Every manual-collective region
  in the tree routes through here (lint-enforced:
  ``shard-map-outside-partition`` in hack/lint.py), so spec validation
  happens in exactly one place and a JAX API move is absorbed in
  exactly one file pair.
"""

from __future__ import annotations

import math
import re
from typing import Any, Callable, Iterable, Mapping, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from activemonitor_tpu.utils.compat import shard_map as _compat_shard_map

# Rules are ordered (pattern, spec) pairs; a Mapping works too (dicts
# preserve insertion order, which IS the precedence order).
Rules = Iterable[Tuple[str, P]]


def _is_spec(x) -> bool:
    # PartitionSpec is a tuple subclass on legacy JAX, so every spec
    # tree walk must stop AT the spec instead of descending into it
    return isinstance(x, P)


def _key_name(entry) -> str:
    """One path entry (DictKey/SequenceKey/GetAttrKey/...) → its bare
    name, without the type's repr decoration."""
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def tree_path_name(path, sep: str = "/") -> str:
    """'/'-joined name of a jax.tree_util key path."""
    return sep.join(_key_name(entry) for entry in path)


def named_tree_map(fn: Callable[[str, Any], Any], tree, *, sep: str = "/",
                   is_leaf=None):
    """``tree_map`` that hands the callback ``(name, leaf)`` where
    ``name`` is the sep-joined key path ("layers/wqkv") — the walker
    the regex rules match against."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: fn(tree_path_name(path, sep), leaf),
        tree,
        is_leaf=is_leaf,
    )


def normalize_rules(rules: Rules | Mapping[str, P]) -> Tuple[Tuple[Any, P], ...]:
    """(pattern, spec) pairs with patterns compiled; accepts a Mapping
    (insertion order = precedence) or any (pattern, spec) sequence."""
    pairs = rules.items() if isinstance(rules, Mapping) else rules
    out = []
    for pattern, spec in pairs:
        out.append((re.compile(pattern), spec))
    return tuple(out)


def spec_axes(spec: P) -> set:
    """Mesh axis names a PartitionSpec mentions (tuple entries — one
    dim sharded over several axes — included)."""
    axes: set = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            axes.update(entry)
        else:
            axes.add(entry)
    return axes


def validate_specs(specs, mesh: Mesh) -> None:
    """Every axis named by any spec in the tree must exist on the mesh
    — a ValueError here, not a tracer crash inside shard_map later."""
    mesh_axes = set(mesh.axis_names)
    for spec in jax.tree.leaves(specs, is_leaf=_is_spec):
        if not _is_spec(spec):
            continue
        unknown = spec_axes(spec) - mesh_axes
        if unknown:
            raise ValueError(
                f"PartitionSpec {spec} names mesh ax"
                f"{'es' if len(unknown) > 1 else 'is'} "
                f"{sorted(unknown)} absent from the mesh "
                f"{dict(mesh.shape)}"
            )


def validate_rules(rules: Rules | Mapping[str, P], mesh: Mesh) -> None:
    """Every mesh axis any RULE names must exist on the mesh; the error
    carries the offending pattern so a rules-dict typo is a one-line
    fix, not a shard_map stack trace."""
    mesh_axes = set(mesh.axis_names)
    for regex, spec in normalize_rules(rules):
        unknown = spec_axes(spec) - mesh_axes
        if unknown:
            raise ValueError(
                f"partition rule {regex.pattern!r} -> {spec} names mesh "
                f"ax{'es' if len(unknown) > 1 else 'is'} {sorted(unknown)} "
                f"absent from the mesh {dict(mesh.shape)}"
            )


def match_partition_rules(
    rules: Rules | Mapping[str, P],
    tree,
    *,
    sep: str = "/",
    mesh: Mesh | None = None,
    on_unmatched: str = "replicate",
) -> Any:
    """Resolve regex partition rules over ``tree`` into a parallel tree
    of PartitionSpecs.

    Precedence is FIRST MATCH WINS in rule order (``re.search`` against
    the leaf's sep-joined path name) — an earlier broad rule shadows a
    later specific one, so order rules most-specific-first. Scalar and
    size-1 leaves always resolve to ``P()`` (nothing to partition).
    Unmatched leaves fall back to replicated ``P()``;
    ``on_unmatched="error"`` turns that into a ValueError naming the
    leaf (the fmengine behavior) for param trees that must be fully
    covered. Passing ``mesh`` validates the rules' axes up front."""
    if on_unmatched not in ("replicate", "error"):
        raise ValueError(
            f"on_unmatched must be 'replicate' or 'error', got {on_unmatched!r}"
        )
    compiled = normalize_rules(rules)
    if mesh is not None:
        validate_rules(rules, mesh)

    def resolve(name: str, leaf) -> P:
        shape = getattr(leaf, "shape", None)
        if shape is not None and (len(shape) == 0 or math.prod(shape) == 1):
            return P()  # never partition scalars
        for regex, spec in compiled:
            if regex.search(name) is not None:
                return spec
        if on_unmatched == "error":
            raise ValueError(f"no partition rule matched leaf {name!r}")
        return P()  # replicated fallback

    return named_tree_map(resolve, tree, sep=sep)


# the canonical two-tier topology axes: slow cross-slice DCN outside,
# fast intra-slice ICI inside (parallel/mesh.make_multihost_mesh order)
TIER_AXES = ("dcn", "ici")


def resolve_tiers(mesh: Mesh, axis: str) -> Tuple[Tuple[str, ...], str]:
    """Map a logical collective axis onto the mesh's topology tiers.

    The ops layer asks for a reduction/gather over a LOGICAL axis
    ("data", "ep", "pp"); the answer depends on the mesh, not the call
    site — this is the one rule that lets the hot paths dispatch
    hierarchically on two-tier meshes with zero call-site changes:

    - the mesh carries ``axis`` → ``((axis,), reason)``: the flat
      path, as before.
    - the mesh carries the ``("dcn", "ici")`` tier pair instead →
      ``(("dcn", "ici"), "")``: the collective spans both tiers and
      parallel/autotune dispatches the hierarchical composition.
    - the tier pair with a degenerate single-slice dcn →
      ``(("ici",), reason)``: flat over ici, the reason recorded.

    A mesh carrying neither is a ValueError naming both spellings —
    the same fail-early discipline as :func:`validate_rules`.
    """
    shape = dict(mesh.shape)
    if axis in shape:
        return (axis,), f"flat: mesh carries {axis!r}"
    dcn, ici = TIER_AXES
    if dcn in shape and ici in shape:
        if shape[dcn] > 1:
            return TIER_AXES, ""
        return (ici,), "degenerate single-slice mesh (dcn=1): flat ici path"
    raise ValueError(
        f"mesh {shape} carries neither axis {axis!r} nor the "
        f"{TIER_AXES} tier pair"
    )


def sharding_tree(specs, mesh: Mesh):
    """Spec tree → NamedSharding tree (validated against the mesh)."""
    validate_specs(specs, mesh)
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), specs, is_leaf=_is_spec
    )


def make_shard_fns(specs, mesh: Mesh):
    """Per-leaf placement callables derived from resolved specs: each
    fn device_puts its leaf onto the spec's NamedSharding (host arrays
    in, globally-sharded arrays out)."""
    validate_specs(specs, mesh)

    def one(spec: P):
        sharding = NamedSharding(mesh, spec)
        return lambda x: jax.device_put(x, sharding)

    return jax.tree.map(one, specs, is_leaf=_is_spec)


def make_gather_fns(specs, mesh: Mesh):
    """Per-leaf gather callables: the inverse of :func:`make_shard_fns`
    — each fn replicates its (possibly sharded) leaf and returns a
    host-readable full array."""
    validate_specs(specs, mesh)
    replicated = NamedSharding(mesh, P())

    def one(_spec: P):
        return lambda x: jax.device_get(jax.device_put(x, replicated))

    return jax.tree.map(one, specs, is_leaf=_is_spec)


def shard_tree(tree, rules: Rules | Mapping[str, P], mesh: Mesh, *,
               sep: str = "/", on_unmatched: str = "replicate"):
    """Resolve ``rules`` over ``tree`` and place every leaf on its
    resolved sharding. Returns (sharded_tree, specs)."""
    specs = match_partition_rules(
        rules, tree, sep=sep, mesh=mesh, on_unmatched=on_unmatched
    )
    fns = make_shard_fns(specs, mesh)
    return jax.tree.map(lambda fn, x: fn(x), fns, tree), specs


def shard_map(
    f,
    *,
    mesh: Mesh,
    in_specs,
    out_specs,
    check_vma: bool = True,
    axis_names: frozenset = frozenset(),
):
    """THE shard_map entry point — the only call site of the
    ``utils/compat.py`` vintage adapter (lint-pinned). Validates every
    spec (and the manual-axes set) against the mesh before tracing, so
    a bad rules dict fails with the axis name instead of a tracer
    crash."""
    validate_specs(in_specs, mesh)
    validate_specs(out_specs, mesh)
    unknown = frozenset(axis_names) - set(mesh.axis_names)
    if unknown:
        raise ValueError(
            f"axis_names {sorted(unknown)} absent from the mesh "
            f"{dict(mesh.shape)}"
        )
    return _compat_shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=check_vma,
        axis_names=frozenset(axis_names),
    )
