"""Observability: span tracing correlated with logs, events, metrics."""

from activemonitor_tpu.obs.trace import (
    Span,
    Tracer,
    current_span,
    current_trace_id,
    detached,
)

__all__ = [
    "Span",
    "Tracer",
    "current_span",
    "current_trace_id",
    "detached",
]
