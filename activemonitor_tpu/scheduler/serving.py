"""Continuous-batching admission scheduler + open-loop traffic model.

The control half of the serving runtime (ROADMAP item 5): pure policy
over the paged KV cache's block budget (ops/kv_cache.KVBlockManager),
with the model execution and the clock both owned by the caller — the
scheduler never touches jax and never reads time (``hack/lint.py``
bans wall-clock calls here; every timestamp arrives as an argument, so
scripted-clock tests are deterministic by construction).

Admission model: every engine step, arrived requests are admitted FIFO
into the in-flight batch while (a) a batch slot is free under the
``max_batch`` ceiling and (b) the block manager can reserve the
sequence's FULL capacity (prompt + output tokens) up front — so an
admitted sequence can never hit a mid-flight out-of-blocks, and the
only refusal point is admission, where refusals are structured counts
(``refusals["batch"]`` / ``refusals["blocks"]``), never exceptions.
Head-of-line order is preserved (no skip-ahead past a blocked head:
a stream of small requests must not starve a large one).

Phases are separated the way serving runtimes separate them: a newly
admitted sequence runs PREFILL (the caller banks the whole prompt and
reports the first generated token — TTFT), then joins the shared
DECODE batch; finished sequences retire, their blocks recycle, and the
freed slot admits the next arrival — all within one engine step.

Traffic is OPEN-LOOP (:func:`open_loop_requests`): seeded Poisson
arrivals with mixed prompt/output lengths, generated up front so the
arrival process never adapts to service latency (the FlowMesh serving
framing: closed-loop generators hide overload by slowing down with the
server; an open-loop one keeps offering load and lets TTFT show the
queueing truth).

Accounting is conservation-by-construction: ``admitted = completed +
in-flight`` for sequences AND generated tokens, per tenant and in
total (:meth:`ContinuousBatchingScheduler.conservation`) — the serving
probe gates on the equality being exact.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Tuple

from activemonitor_tpu.ops.kv_cache import KVBlockManager
from activemonitor_tpu.scheduler.arrivals import PoissonArrivals


@dataclass(frozen=True)
class Request:
    """One serving request as the open-loop generator emits it."""

    rid: int
    tenant: str
    arrival: float  # seconds since soak start
    prompt_len: int
    output_tokens: int  # generated tokens wanted (>= 1; #1 from prefill)
    # explicit prompt token ids (the tenant/prefix-mix generator sets
    # them so the prefix cache can content-address the prompt); None
    # keeps the classic generator's contract — the engine draws a
    # seeded random prompt per rid, byte-identical to before
    prompt_tokens: Optional[Tuple[int, ...]] = None


def open_loop_requests(
    n_requests: int,
    rate_rps: float,
    seed: int,
    prompt_len_choices: Sequence[int] = (4, 6, 8),
    output_choices: Sequence[int] = (2, 3, 5),
    tenants: Sequence[str] = ("tenant-a", "tenant-b"),
) -> List[Request]:
    """Seeded Poisson arrival schedule: exponential inter-arrivals at
    ``rate_rps``, prompt/output lengths drawn from small choice sets
    (bounded sets keep the engine's per-prompt-length compiles bounded
    too), tenants round-robin. Same seed ⇒ byte-identical schedule —
    the determinism the scheduler-trace test pins. The arrival process
    is the shared :class:`~activemonitor_tpu.scheduler.arrivals.
    PoissonArrivals` contract (one rng, fixed draw order: arrival,
    prompt, output — pinned by the trace tests, so this generator and
    the front door's cannot drift on what "seeded" means)."""
    if n_requests < 1:
        raise ValueError(
            f"need n_requests >= 1 and rate_rps > 0, got "
            f"{n_requests}/{rate_rps}"
        )
    try:
        process = PoissonArrivals(rate_rps, seed)
    except ValueError:
        raise ValueError(
            f"need n_requests >= 1 and rate_rps > 0, got "
            f"{n_requests}/{rate_rps}"
        ) from None
    out: List[Request] = []
    for rid in range(n_requests):
        now = process.next()
        out.append(
            Request(
                rid=rid,
                tenant=tenants[rid % len(tenants)],
                arrival=now,
                prompt_len=process.choice(prompt_len_choices),
                output_tokens=process.choice(output_choices),
            )
        )
    return out


def mixed_open_loop_requests(
    n_requests: int,
    rate_rps: float,
    seed: int,
    *,
    tenants: Sequence[str] = ("tenant-a", "tenant-b"),
    prefix_len: int = 8,
    hot_fraction: float = 0.6,
    prompt_len_choices: Sequence[int] = (12, 16),
    output_choices: Sequence[int] = (2, 3, 5),
    vocab: int = 256,
) -> List[Request]:
    """The tenant/prefix-mix workload as serving ``Request``s: seeded
    Poisson arrivals where ``hot_fraction`` of prompts open with one
    shared system-prompt prefix across every tenant (the traffic the
    content-addressed prefix cache banks once) and the rest are cold
    unique prompts. A thin wrapper over :class:`~activemonitor_tpu.
    scheduler.arrivals.TenantPrefixMix` — the SAME generator the front
    door can shape traffic with — leaving :func:`open_loop_requests`'s
    draw order untouched, so existing seeded traces stay
    byte-identical."""
    from activemonitor_tpu.scheduler.arrivals import TenantPrefixMix

    mix = TenantPrefixMix(
        rate_rps,
        seed,
        tenants=tenants,
        prefix_len=prefix_len,
        hot_fraction=hot_fraction,
        prompt_len_choices=prompt_len_choices,
        output_choices=output_choices,
        vocab=vocab,
    )
    return [
        Request(
            rid=a.rid,
            tenant=a.tenant,
            arrival=a.arrival,
            prompt_len=len(a.prompt_tokens),
            output_tokens=a.output_tokens,
            prompt_tokens=a.prompt_tokens,
        )
        for a in mix.generate(n_requests)
    ]


@dataclass
class SequenceState:
    """One admitted sequence's lifecycle bookkeeping."""

    req: Request
    slot: int  # fixed batch-slot index while in flight
    admitted_at: float
    generated: int = 0  # tokens produced so far (prefill's counts)
    first_token_at: Optional[float] = None
    # the first SHARED decode step's token (generated == 2) — with
    # arrival/admitted_at/first_token_at this decomposes TTFT into
    # queue-wait / prefill / first-decode (obs/criticalpath.py);
    # None for 1-token requests that retire at prefill
    first_decode_at: Optional[float] = None
    finished_at: Optional[float] = None
    tokens: List[int] = field(default_factory=list)  # generated token ids


class ContinuousBatchingScheduler:
    """Admission + phase + retirement policy over the block budget.

    The caller drives it once per engine step::

        arrived = sched.admit(now)            # new sequences (prefill phase)
        ... prefill each; sched.record_first_token(seq, token, now) ...
        batch = sched.decode_batch()          # the in-flight decode set
        ... one paged decode step ...
        sched.record_decode_step(tokens_by_slot, now)  # retire + recycle

    ``capacity_tokens`` per sequence is ``prompt + output`` — the last
    generated token's K/V slot is reserved though never banked, a
    documented one-slot slack that keeps the reservation arithmetic
    obvious (and shows up honestly in the fragmentation ratio).
    """

    def __init__(
        self,
        requests: Sequence[Request],
        manager: KVBlockManager,
        max_batch: int,
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.manager = manager
        self.max_batch = max_batch
        self.waiting: Deque[Request] = deque(
            sorted(requests, key=lambda r: (r.arrival, r.rid))
        )
        self.active: Dict[int, SequenceState] = {}  # slot -> state
        self.completed: List[SequenceState] = []
        self._free_slots: List[int] = list(range(max_batch - 1, -1, -1))
        self._admitted = 0
        self._tokens_emitted = 0
        # independent per-tenant tallies, counted at the admit/emit
        # EVENTS — conservation() cross-checks them against the sums
        # derived from the sequence objects, so a tenant-attribution
        # bug cannot hide behind balanced global totals
        self._tenant_admitted: Dict[str, int] = {}
        self._tenant_tokens: Dict[str, int] = {}
        self.refusals: Dict[str, int] = {"batch": 0, "blocks": 0}
        self.occupancy_samples: List[float] = []
        # (event, rid, t): the admission-order trace the seeded
        # determinism test pins — same seed, same schedule, same trace
        self.trace: List[Tuple[str, int, float]] = []

    # -- queries ---------------------------------------------------------
    @property
    def done(self) -> bool:
        return not self.waiting and not self.active

    def next_arrival(self) -> Optional[float]:
        return self.waiting[0].arrival if self.waiting else None

    def decode_batch(self) -> List[SequenceState]:
        """In-flight sequences that have had their first token (i.e.
        prefilled) and still owe output, in slot order."""
        return [
            self.active[slot]
            for slot in sorted(self.active)
            if self.active[slot].first_token_at is not None
            and self.active[slot].generated < self.active[slot].req.output_tokens
        ]

    # -- the step protocol ----------------------------------------------
    def capacity_tokens(self, req: Request) -> int:
        return req.prompt_len + req.output_tokens

    def admit(self, now: float) -> List[SequenceState]:
        """Admit arrived requests FIFO while a slot AND the full block
        reservation are available. A blocked head stops admission for
        this step (no skip-ahead) and counts a structured refusal."""
        admitted: List[SequenceState] = []
        while self.waiting and self.waiting[0].arrival <= now:
            req = self.waiting[0]
            if not self._free_slots:
                self.refusals["batch"] += 1
                self.trace.append(("defer-batch", req.rid, now))
                break
            blocks = self.manager.allocate(req.rid, self.capacity_tokens(req))
            if blocks is None:
                self.refusals["blocks"] += 1
                self.trace.append(("defer-blocks", req.rid, now))
                break
            self.waiting.popleft()
            self.manager.append(req.rid, req.prompt_len)  # prompt K/V banked
            seq = SequenceState(
                req=req, slot=self._free_slots.pop(), admitted_at=now
            )
            self.active[seq.slot] = seq
            self._admitted += 1
            self._tenant_admitted[req.tenant] = (
                self._tenant_admitted.get(req.tenant, 0) + 1
            )
            self.trace.append(("admit", req.rid, now))
            admitted.append(seq)
        return admitted

    def record_first_token(
        self, seq: SequenceState, token: int, now: float
    ) -> None:
        """Prefill produced the sequence's first generated token (the
        TTFT event). A 1-token request completes right here."""
        seq.generated = 1
        seq.first_token_at = now
        seq.tokens.append(token)
        self._emit_token(seq)
        self.trace.append(("first-token", seq.req.rid, now))
        if seq.generated >= seq.req.output_tokens:
            self._retire(seq, now)

    def record_decode_step(
        self, tokens_by_slot: Dict[int, int], now: float
    ) -> List[SequenceState]:
        """One shared decode step finished: each participating sequence
        banked the K/V of the token it fed in and produced one more
        token. Finished sequences retire and their blocks recycle.
        Returns the retired list; also samples batch occupancy."""
        stepped = 0
        finished: List[SequenceState] = []
        for slot, token in sorted(tokens_by_slot.items()):
            seq = self.active.get(slot)
            if seq is None:
                continue
            self.manager.append(seq.req.rid, 1)
            seq.generated += 1
            if seq.generated == 2 and seq.first_decode_at is None:
                seq.first_decode_at = now
            seq.tokens.append(token)
            self._emit_token(seq)
            stepped += 1
            if seq.generated >= seq.req.output_tokens:
                self._retire(seq, now)
                finished.append(seq)
        self.occupancy_samples.append(stepped / self.max_batch)
        return finished

    def _emit_token(self, seq: SequenceState) -> None:
        self._tokens_emitted += 1
        self._tenant_tokens[seq.req.tenant] = (
            self._tenant_tokens.get(seq.req.tenant, 0) + 1
        )

    def _retire(self, seq: SequenceState, now: float) -> None:
        seq.finished_at = now
        self.manager.free(seq.req.rid)
        del self.active[seq.slot]
        self._free_slots.append(seq.slot)
        self.completed.append(seq)
        self.trace.append(("retire", seq.req.rid, now))

    # -- accounting ------------------------------------------------------
    def conservation(self) -> dict:
        """The exact-conservation ledger: admitted sequences and
        emitted tokens must equal completed + in-flight, in total AND
        per tenant. The per-tenant side cross-checks two independent
        accounts — event-time tallies (counted at admit/emit) against
        sums derived from the sequence objects — so a
        tenant-attribution bug cannot hide behind balanced global
        totals. ``ok`` is the AND of every equality — the serving
        probe's accounting gate."""
        in_flight = list(self.active.values())
        tokens_completed = sum(s.generated for s in self.completed)
        tokens_in_flight = sum(s.generated for s in in_flight)
        tenants: Dict[str, Dict[str, int]] = {}
        for seq, bucket in [(s, "completed") for s in self.completed] + [
            (s, "in_flight") for s in in_flight
        ]:
            row = tenants.setdefault(
                seq.req.tenant,
                {"completed": 0, "in_flight": 0, "tokens": 0},
            )
            row[bucket] += 1
            row["tokens"] += seq.generated
        tenants_ok = True
        for tenant in set(tenants) | set(self._tenant_admitted) | set(
            self._tenant_tokens
        ):
            row = tenants.setdefault(
                tenant, {"completed": 0, "in_flight": 0, "tokens": 0}
            )
            row["admitted"] = self._tenant_admitted.get(tenant, 0)
            row["tokens_emitted"] = self._tenant_tokens.get(tenant, 0)
            tenants_ok = tenants_ok and (
                row["admitted"] == row["completed"] + row["in_flight"]
                and row["tokens_emitted"] == row["tokens"]
            )
        return {
            "admitted": self._admitted,
            "completed": len(self.completed),
            "in_flight": len(in_flight),
            "tokens_emitted": self._tokens_emitted,
            "tokens_completed": tokens_completed,
            "tokens_in_flight": tokens_in_flight,
            "tenants": tenants,
            "ok": (
                tenants_ok
                and self._admitted == len(self.completed) + len(in_flight)
                and self._tokens_emitted
                == tokens_completed + tokens_in_flight
            ),
        }
