"""Device timing that survives remote-tunneled TPUs.

Two hazards in timing XLA work (SURVEY.md §7 hard part (d)):

1. compile time — handled by warmup before measurement;
2. dispatch/transport overhead — on tunneled devices (e.g. a TPU behind
   a network PJRT proxy) ``block_until_ready`` can return before the
   device finishes and every host sync costs a network roundtrip that
   dwarfs the op (observed ~70 ms vs a ~6 ms matmul).

The fix for both: force a scalar host readback (a transfer cannot lie)
and measure the *difference* between a chain of k ops and a chain of 2k
ops — constant overhead cancels, leaving pure device time per op.
"""

from __future__ import annotations

from typing import Callable



def median_readback_seconds(fn: Callable, *args, iters: int = 5, warmup: int = 2) -> float:
    """Median wall-clock of fn(*args) forced through a scalar readback.
    ``fn`` must return something float()-able (a scalar array)."""
    return _readback_samples(fn, *args, iters=iters, warmup=warmup)[iters // 2]


def _readback_samples(fn: Callable, *args, iters: int, warmup: int) -> list:
    import time

    for _ in range(warmup):
        float(fn(*args))
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        float(fn(*args))
        samples.append(time.perf_counter() - t0)
    samples.sort()
    return samples


def _interleaved_min_pair(
    fn1: Callable, fn2: Callable, *args, iters: int, warmup: int = 2
) -> tuple:
    """(min t1, min t2) with the two chains sampled alternately.

    Sampling all of t1 then all of t2 lets anything that drifts between
    the phases (clock throttle, tunnel congestion) land entirely on one
    side of the difference; alternating spreads it across both. Both
    mins see the same noise environment, so the min-bias of the delta
    shrinks with iters instead of depending on which phase was lucky."""
    import time

    for _ in range(warmup):
        float(fn1(*args))
        float(fn2(*args))
    t1s, t2s = [], []
    for _ in range(iters):
        t0 = time.perf_counter()
        float(fn1(*args))
        t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        float(fn2(*args))
        t2s.append(time.perf_counter() - t0)
    return min(t1s), min(t2s)


# shared noise-floor policy for chain-delta measurements (also used by
# probes that run their own chains, e.g. the training-step probe)
CHAIN_GROWTH = 4
CHAIN_RETRIES = 2


def needs_longer_chain(t1: float, t2: float) -> bool:
    """True when the (t2 - t1) delta is inside the noise floor and the
    chain should be lengthened before trusting the rate."""
    return (t2 - t1) < max(0.05 * t1, 1e-3)


def chain_delta_seconds(
    make_chain: Callable[[int], Callable],
    *args,
    k1: int = 4,
    k2: int = 12,
    iters: int = 5,
    _retries: int = CHAIN_RETRIES,
) -> float:
    """Per-op device seconds via the difference method.

    ``make_chain(k)`` must return a jitted callable running k
    *data-dependent* repetitions of the op and returning a scalar.
    Data dependence matters: independent ops get overlapped or CSE'd by
    XLA and the difference collapses to zero.

    When the measured difference is inside the noise floor (ops much
    faster than dispatch jitter — tiny payloads, fast hardware), the
    chain is lengthened and remeasured up to ``_retries`` times so the
    delta towers over the noise instead of reporting a garbage rate.

    The two chains are sampled ALTERNATELY (see _interleaved_min_pair):
    phase-separated sampling let drift land on one side of the
    difference, which is how the MXU probe once reported a physically
    impossible >1.0-of-rated rate.
    """
    fn1, fn2 = make_chain(k1), make_chain(k2)
    t1, t2 = _interleaved_min_pair(fn1, fn2, *args, iters=iters)
    for _ in range(_retries):
        if not needs_longer_chain(t1, t2):
            break
        k1, fn1 = k2, fn2
        k2 = k2 * CHAIN_GROWTH
        fn2 = make_chain(k2)
        # fn1 is already warm; one warmup pass compiles fn2. Both sides
        # of the delta come from THIS round — never min a side against a
        # previous round, or cross-round drift skews the difference
        t1, t2 = _interleaved_min_pair(fn1, fn2, *args, iters=iters, warmup=1)
    return max((t2 - t1) / (k2 - k1), 1e-9)
