"""Observability: span tracing correlated with logs, events, metrics,
plus the per-check result history and rolling-window SLO layer."""

from activemonitor_tpu.obs.history import CheckResult, ResultHistory
from activemonitor_tpu.obs.slo import (
    FleetStatus,
    SLOConfig,
    SLOState,
    evaluate,
    fleet_goodput,
    slo_config_from_spec,
)
from activemonitor_tpu.obs.trace import (
    Span,
    Tracer,
    current_span,
    current_trace_id,
    detached,
)

__all__ = [
    "CheckResult",
    "FleetStatus",
    "ResultHistory",
    "SLOConfig",
    "SLOState",
    "Span",
    "Tracer",
    "current_span",
    "current_trace_id",
    "detached",
    "evaluate",
    "fleet_goodput",
    "slo_config_from_spec",
]
