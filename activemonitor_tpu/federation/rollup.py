"""The federated rollup: replicas → cluster → federation.

``obs/slo.rollup_statusz`` merges sharded REPLICAS of one cluster;
this module applies the SAME merge math one level up, over whole
clusters, through the shared :func:`~activemonitor_tpu.obs.slo.
merge_blocks` seam — one implementation of the run-weighted goodput
mean, the attribution merge, the lookup-weighted front-door ratios,
and the critical-path skew fallback, so the two levels can never
disagree about what a number means.

Conservation survives the second level for free: a cluster serving an
OLD-BINARY payload (no ``goodput`` attribution block — a whole cluster
mid rolling update, not just a replica) has its entire lost share
folded into ``unknown`` by the same ``merge_goodput_blocks`` rule PR 7
proved across replicas, so the federation's per-bucket ratios still
sum to ``1 - goodput_ratio`` exactly.

Checks concatenate and dedupe first-seen by key, annotated with the
cluster that reported them — the capability router lands each check on
exactly one cluster, so a collision is the same transient
double-report the replica-level dedupe already absorbs.
"""

from __future__ import annotations

from typing import Dict, Mapping

from activemonitor_tpu.obs import slo


def federate_statusz(cluster_payloads: Mapping[str, dict]) -> dict:
    """Merge per-cluster ``/statusz`` payloads (each itself a replica
    payload or a :func:`~activemonitor_tpu.obs.slo.rollup_statusz`
    output) into ONE federation view, keyed by cluster name. The fleet
    block mirrors the rollup's schema plus ``clusters`` /
    ``per_cluster``; each merged check entry gains a ``cluster`` field
    naming the cluster that reported it."""
    names = sorted(cluster_payloads)
    payloads = [cluster_payloads[name] for name in names]
    shared = slo.merge_blocks(payloads, level=slo.MERGE_LEVEL_CLUSTER)
    merged: Dict[str, dict] = {}
    per_cluster: Dict[str, dict] = {}
    for name, payload in zip(names, payloads):
        fleet = payload.get("fleet") or {}
        per_cluster[name] = {
            "replicas": int(fleet.get("replicas") or 1),
            "checks": len(payload.get("checks") or []),
            "window_runs": int(fleet.get("window_runs") or 0),
            "goodput_ratio": fleet.get("goodput_ratio"),
            "degraded": bool(fleet.get("degraded")),
            "generated_at": str(fleet.get("generated_at") or ""),
            # an old binary ships no attribution block: its lost share
            # lands under `unknown` in the merged goodput above — flag
            # the skew here so the dashboard can say WHICH cluster
            "skewed": not isinstance(fleet.get("goodput"), dict),
        }
        for entry in payload.get("checks") or []:
            key = entry.get("key", "")
            if key not in merged:
                tagged = dict(entry)
                tagged["cluster"] = name
                merged[key] = tagged
    entries = [merged[key] for key in sorted(merged)]
    agg = slo.aggregate_entries(entries)
    return {
        "fleet": {
            "clusters": len(payloads),
            "replicas": shared["replicas"],
            "checks": len(entries),
            "window_runs": agg["window_runs"],
            "goodput_ratio": shared["goodput_ratio"],
            "goodput": shared["goodput"],
            "generated_at": shared["generated_at"],
            "degraded": shared["degraded"],
            "breaker": shared["breaker"],
            "status_writes_queued": shared["status_writes_queued"],
            "remedy_tokens": shared["remedy_tokens"],
            "anomalies": agg["anomalies"],
            "matrix": shared["matrix"],
            "frontdoor": shared["frontdoor"],
            "adaptive": shared["adaptive"],
            "journal": shared["journal"],
            "critical_path": shared["critical_path"],
            "per_cluster": per_cluster,
        },
        "checks": entries,
    }
