"""Observability: span tracing correlated with logs, events, metrics,
plus the per-check result history, the rolling-window SLO layer, the
lost-goodput attribution engine, the degradation flight recorder, and
the roofline layer (cost-model evidence under every fraction)."""

from activemonitor_tpu.obs.attribution import (
    BUCKETS,
    Attribution,
    classify_run,
    subsystem_for_metric,
)
from activemonitor_tpu.obs.flightrec import FlightRecorder
from activemonitor_tpu.obs.history import CheckResult, ResultHistory
from activemonitor_tpu.obs.roofline import (
    BOUNDS,
    RooflineVerdict,
    classify,
    classify_comm,
)
from activemonitor_tpu.obs.slo import (
    FleetStatus,
    SLOConfig,
    SLOState,
    evaluate,
    fleet_goodput,
    slo_config_from_spec,
)
from activemonitor_tpu.obs.trace import (
    Span,
    Tracer,
    current_span,
    current_trace_id,
    detached,
)

__all__ = [
    "Attribution",
    "BOUNDS",
    "BUCKETS",
    "RooflineVerdict",
    "classify",
    "classify_comm",
    "CheckResult",
    "FleetStatus",
    "FlightRecorder",
    "classify_run",
    "subsystem_for_metric",
    "ResultHistory",
    "SLOConfig",
    "SLOState",
    "Span",
    "Tracer",
    "current_span",
    "current_trace_id",
    "detached",
    "evaluate",
    "fleet_goodput",
    "slo_config_from_spec",
]
