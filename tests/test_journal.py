"""Durable telemetry journal tests (ISSUE 16): segmented rotation +
compaction, the all-or-nothing corrupt-chain restore discipline, the
restart-survival acceptance slice (a FakeClock fleet killed mid-window
and restarted against its journal reports bit-identical SLO
availability / error-budget burn / goodput attribution through
/statusz, the gauges, and the `am-tpu goodput` rendering), the
record→replay determinism acceptance (trace → schedule → front door →
same tenant mix / arrival order / outcomes, landing a baseline-tracked
``frontdoor-replay`` matrix cell), the flight-recorder size cap, and
the `hack/journal_check.py` integrity gate run as a subprocess.
"""

import asyncio
import json
import subprocess
import sys
from pathlib import Path

import pytest

from activemonitor_tpu.analysis import matrix as matrix_mod
from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.frontdoor.traffic import (
    open_loop_checks,
    replayed_checks,
)
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.obs import FleetStatus, ResultHistory
from activemonitor_tpu.obs.flightrec import KIND_BREAKER, FlightRecorder
from activemonitor_tpu.obs.history import CheckResult
from activemonitor_tpu.obs.journal import (
    JOURNAL_VERSION,
    TelemetryJournal,
    list_segments,
    read_journal,
    rotate_capped,
)
from activemonitor_tpu.obs.replay import drive_requests, load_trace
from activemonitor_tpu.obs.slo import (
    DEFAULT_WINDOW_SECONDS,
    merge_journal_blocks,
    rollup_statusz,
)
from activemonitor_tpu.utils.clock import FakeClock
from activemonitor_tpu.__main__ import main, render_goodput, render_journal

REPO = Path(__file__).resolve().parent.parent

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"


def make_hc(name="hc-dur", slo=None):
    spec = {
        "repeatAfterSec": 60,
        "level": "cluster",
        "workflow": {
            "generateName": f"{name}-",
            "workflowtimeout": 30,
            "resource": {
                "namespace": "health",
                "serviceAccount": "sa",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if slo is not None:
        spec["slo"] = slo
    return HealthCheck.from_dict(
        {"metadata": {"name": name, "namespace": "health"}, "spec": spec}
    )


def tick(clock, seconds=60.0):
    # FakeClock.advance is async (it wakes sleepers); these tests only
    # need the timestamp to move — same idiom as test_matrix
    clock._t += seconds


def make_result(clock, ok=True, bucket="", why="", latency=1.0):
    return CheckResult(
        ts=clock.now(),
        ok=ok,
        latency=latency,
        workflow="wf-j",
        trace_id="tr-j",
        bucket=bucket,
        why=why,
    )


def seeded_arrival_dir(tmp_path, n=30, name="j"):
    """A journal dir of ``n`` arrival events across several 1 KiB
    segments (each line is ~160 bytes, so ~6 per segment)."""
    path = str(tmp_path / name)
    journal = TelemetryJournal(path, clock=FakeClock(), max_bytes=1024)
    for i in range(n):
        journal.record_arrival(
            tenant=f"t-{i % 2}", check="ns/hc", outcome="run", gap=1.0
        )
    journal.close()
    return path


# ---------------------------------------------------------------------
# segments: rotation, compaction, chain continuation
# ---------------------------------------------------------------------


def test_segments_rotate_at_the_size_cap(tmp_path):
    path = seeded_arrival_dir(tmp_path)
    segments = list_segments(path)
    assert len(segments) >= 3
    # contiguous chain from 1, every segment under cap + one line
    assert [seq for seq, _ in segments] == list(
        range(1, len(segments) + 1)
    )
    events, warnings = read_journal(path)
    assert warnings == []
    assert len(events) == 30
    assert all(ev["stream"] == "arrival" for ev in events)


def test_compaction_bounds_the_directory(tmp_path):
    journal = TelemetryJournal(
        str(tmp_path / "j"), clock=FakeClock(), max_bytes=1024, max_segments=2
    )
    for _ in range(40):
        journal.record_arrival(
            tenant="t", check="ns/hc", outcome="run", gap=1.0
        )
    journal.close()
    segments = list_segments(str(tmp_path / "j"))
    assert len(segments) <= 2
    assert journal.compacted_segments > 0
    # the surviving suffix of the chain still reads clean (contiguity
    # is judged from the oldest SURVIVOR, not from segment 1)
    events, warnings = read_journal(str(tmp_path / "j"))
    assert warnings == [] and events


def test_reopen_continues_the_chain_on_a_new_segment(tmp_path):
    path = str(tmp_path / "j")
    first = TelemetryJournal(path, clock=FakeClock())
    for _ in range(3):
        first.record_arrival(tenant="t", check="ns/hc", outcome="run", gap=1.0)
    first.close()
    second = TelemetryJournal(path, clock=FakeClock())
    second.record_arrival(tenant="t", check="ns/hc", outcome="run", gap=1.0)
    # never appends into a segment an earlier incarnation may have torn
    assert [seq for seq, _ in list_segments(path)] == [1, 2]
    events, warnings = read_journal(path)
    assert warnings == [] and len(events) == 4


def test_append_never_raises_into_the_recording_path(tmp_path):
    blocker = tmp_path / "blocked"
    blocker.write_text("i am a file, not a directory")
    journal = TelemetryJournal(str(blocker), clock=FakeClock())
    journal.record_arrival(tenant="t", check="ns/hc", outcome="run", gap=0.0)
    assert journal.dropped == 1
    assert journal.appended["arrival"] == 0


def test_oversized_single_event_cannot_wedge_the_writer(tmp_path):
    journal = TelemetryJournal(
        str(tmp_path / "j"), clock=FakeClock(), max_bytes=1024
    )
    journal.record_arrival(
        tenant="t", check="ns/hc", outcome="refused", reason="x" * 5000, gap=0.0
    )
    journal.record_arrival(tenant="t", check="ns/hc", outcome="run", gap=1.0)
    assert journal.dropped == 0
    assert journal.appended["arrival"] == 2
    events, warnings = read_journal(str(tmp_path / "j"))
    assert warnings == [] and len(events) == 2


def test_lag_tracks_the_newest_event_on_the_injected_clock(tmp_path):
    clock = FakeClock()
    journal = TelemetryJournal(str(tmp_path / "j"), clock=clock)
    assert journal.lag_seconds() == 0.0
    journal.record_arrival(tenant="t", check="ns/hc", outcome="run", gap=0.0)
    tick(clock, 42.0)
    assert journal.lag_seconds() == pytest.approx(42.0)


def test_rotate_capped_shifts_and_drops_the_oldest(tmp_path):
    path = tmp_path / "sink.jsonl"
    assert rotate_capped(str(path), 10) is False  # absent: nothing to do
    for round_no in range(6):
        path.write_text(f"round-{round_no}\n" * 50)
        assert rotate_capped(str(path), 10, keep=2) is True
        assert not path.exists()  # active moved aside; append recreates
    assert (tmp_path / "sink-1.jsonl").exists()
    assert (tmp_path / "sink-2.jsonl").exists()
    assert not (tmp_path / "sink-3.jsonl").exists()  # keep bounds it
    path.write_text("tiny")
    assert rotate_capped(str(path), 1 << 20) is False  # under the cap
    assert rotate_capped(str(path), 0) is False  # cap disabled


# ---------------------------------------------------------------------
# corrupt / truncated segments: all-or-nothing fresh restore
# ---------------------------------------------------------------------


def assert_fresh_restore(journal_dir, reason):
    events, warnings = read_journal(journal_dir)
    assert events == []
    assert [w["reason"] for w in warnings] == [reason]
    journal = TelemetryJournal(journal_dir, clock=FakeClock())
    history = ResultHistory(FakeClock())
    out = journal.replay_into(history)
    # fresh restore: nothing replayed, nothing double-counted, the
    # structured warning parked for /statusz
    assert out["replayed"] == {"result": 0, "attribution": 0, "arrival": 0}
    assert journal.restore_warning["reason"] == reason
    assert len(history) == 0
    return journal


def test_mid_line_truncation_restores_fresh(tmp_path):
    path = seeded_arrival_dir(tmp_path)
    _seq, last = list_segments(path)[-1]
    raw = Path(last).read_bytes()
    Path(last).write_bytes(raw[:-10])  # SIGKILL mid-write, doctored
    journal = assert_fresh_restore(path, "corrupt-line")
    assert "truncated" in journal.restore_warning["detail"]
    # a new append after the fresh restore opens a NEW segment past the
    # torn chain — the corruption is never appended into
    before = [seq for seq, _ in list_segments(path)]
    journal.record_arrival(tenant="t", check="ns/hc", outcome="run", gap=0.0)
    assert max(s for s, _ in list_segments(path)) == max(before) + 1


def test_version_skew_restores_fresh(tmp_path):
    path = seeded_arrival_dir(tmp_path)
    _seq, first = list_segments(path)[0]
    lines = Path(first).read_text().splitlines()
    header = json.loads(lines[0])
    header["v"] = JOURNAL_VERSION + 1
    lines[0] = json.dumps(header)
    Path(first).write_text("\n".join(lines) + "\n")
    journal = assert_fresh_restore(path, "version-skew")
    assert str(JOURNAL_VERSION + 1) in journal.restore_warning["detail"]


def test_missing_segment_restores_fresh(tmp_path):
    path = seeded_arrival_dir(tmp_path)
    segments = list_segments(path)
    assert len(segments) >= 3
    Path(segments[1][1]).unlink()  # hole in the middle of the chain
    journal = assert_fresh_restore(path, "missing-segment")
    assert str(segments[1][0]) in journal.restore_warning["detail"]


def test_corrupt_header_restores_fresh(tmp_path):
    path = seeded_arrival_dir(tmp_path)
    _seq, first = list_segments(path)[0]
    Path(first).write_text("")  # an empty segment has no header
    assert_fresh_restore(path, "corrupt-header")


def test_clean_kill_between_appends_loses_nothing(tmp_path):
    # the writer flushes whole lines, so abandoning the handle (a
    # SIGKILL between appends) leaves a clean chain that restores fully
    path = str(tmp_path / "j")
    journal = TelemetryJournal(path, clock=FakeClock())
    for _ in range(5):
        journal.record_arrival(tenant="t", check="ns/hc", outcome="run", gap=1.0)
    # no close(): the process just died
    events, warnings = read_journal(path)
    assert warnings == [] and len(events) == 5


# ---------------------------------------------------------------------
# boot replay into the fleet + /statusz + rollup
# ---------------------------------------------------------------------


def test_attach_journal_replays_then_subscribes_without_double_count(tmp_path):
    path = str(tmp_path / "j")
    clock = FakeClock()
    hc = make_hc()
    fleet1 = FleetStatus(clock, MetricsCollector())
    journal1 = TelemetryJournal(path, clock=clock)
    fleet1.attach_journal(journal1)
    fleet1.record(hc, ok=True, latency=1.0, workflow="wf-1")
    fleet1.record(hc, ok=False, latency=2.0, workflow="wf-2")
    assert journal1.appended["result"] == 2
    journal1.close()

    fleet2 = FleetStatus(clock, MetricsCollector())
    journal2 = TelemetryJournal(path, clock=clock)
    fleet2.attach_journal(journal2)
    assert journal2.replayed["result"] == 2
    # replayed events were NOT re-journaled (restore bypasses the
    # subscriber tap); only genuinely new records append
    assert journal2.appended["result"] == 0
    assert [r.workflow for r in fleet2.history.results(hc.key)] == [
        "wf-1",
        "wf-2",
    ]
    # the /statusz last-status map is restored from the replayed tail
    assert fleet2._last_status[hc.key] == "Failed"
    fleet2.record(hc, ok=True, latency=1.0, workflow="wf-3")
    assert journal2.appended["result"] == 1
    events, warnings = read_journal(path)
    assert warnings == []
    assert sum(1 for ev in events if ev["stream"] == "result") == 3


def test_statusz_journal_block_and_rollup(tmp_path):
    clock = FakeClock()
    hc = make_hc()
    with_journal = FleetStatus(clock, MetricsCollector())
    journal = TelemetryJournal(str(tmp_path / "j"), clock=clock)
    with_journal.attach_journal(journal)
    with_journal.record(hc, ok=True, latency=1.0, workflow="wf")
    without = FleetStatus(clock, MetricsCollector())
    without.record(hc, ok=True, latency=1.0, workflow="wf")

    p1 = with_journal.statusz([hc])
    p2 = without.statusz([hc])
    assert p1["fleet"]["journal"]["appended"]["result"] == 1
    assert p1["fleet"]["journal"]["segment_count"] >= 1
    assert p2["fleet"]["journal"] is None
    merged = rollup_statusz([p1, p2])
    block = merged["fleet"]["journal"]
    assert block["replicas"] == 1
    assert block["appended"]["result"] == 1


def test_merge_journal_blocks_sums_counters_and_keeps_worst_lag():
    assert merge_journal_blocks([]) is None
    merged = merge_journal_blocks(
        [
            {
                "appended": {"result": 2, "arrival": 1},
                "replayed": {"result": 2},
                "dropped": 1,
                "compacted_segments": 0,
                "segment_count": 2,
                "lag_seconds": 5.0,
                "restore_warning": None,
            },
            {
                "appended": {"result": 3},
                "replayed": {},
                "dropped": 0,
                "compacted_segments": 4,
                "segment_count": 1,
                "lag_seconds": 9.0,
                "restore_warning": {"reason": "corrupt-line", "detail": "d"},
            },
        ]
    )
    assert merged["replicas"] == 2
    assert merged["appended"] == {"arrival": 1, "result": 5}
    assert merged["replayed"] == {"result": 2}
    assert merged["segment_count"] == 3
    assert merged["dropped"] == 1 and merged["compacted_segments"] == 4
    assert merged["lag_seconds"] == 9.0  # the fleet's WORST, not the sum
    assert merged["restore_warning"]["reason"] == "corrupt-line"


# ---------------------------------------------------------------------
# acceptance: restart survival (kill mid-window, bit-identical windows)
# ---------------------------------------------------------------------

SLO = {"objective": 0.9, "windowSeconds": int(DEFAULT_WINDOW_SECONDS)}
SLO_LABELS = {"healthcheck_name": "hc-dur", "namespace": "health"}


def test_restart_survival_acceptance(tmp_path):
    """A FakeClock fleet killed mid-window and restarted against its
    journal reports SLO availability, error-budget burn and goodput
    attribution identical (±1e-9; the dict comparisons are exact) to an
    uninterrupted twin — through /statusz, the gauges, and the `am-tpu
    goodput` rendering. Conservation (Σ per-subsystem lost ratios =
    1 − goodput) holds on both sides of the kill."""
    journal_dir = str(tmp_path / "journal")
    clock = FakeClock()
    hc = make_hc(slo=SLO)
    control_metrics = MetricsCollector()
    control = FleetStatus(clock, control_metrics)

    fleet1 = FleetStatus(clock, MetricsCollector())
    journal1 = TelemetryJournal(journal_dir, clock=clock)
    fleet1.attach_journal(journal1)

    head = [i % 4 != 3 for i in range(12)]  # 9 ok, 3 failed
    for ok in head:
        tick(clock)
        control.record(hc, ok=ok, latency=2.0, workflow="wf")
        fleet1.record(hc, ok=ok, latency=2.0, workflow="wf")
    journal1.close()  # the kill: in-memory rings die with fleet1

    metrics2 = MetricsCollector()
    fleet2 = FleetStatus(clock, metrics2)
    journal2 = TelemetryJournal(journal_dir, clock=clock, metrics=metrics2)
    fleet2.attach_journal(journal2)
    assert journal2.restore_warning is None
    assert journal2.replayed["result"] == 12

    tail = [True, True, False, True, True, True, True, True]  # 7 ok, 1 failed
    for ok in tail:
        tick(clock)
        control.record(hc, ok=ok, latency=2.0, workflow="wf")
        fleet2.record(hc, ok=ok, latency=2.0, workflow="wf")

    payload_c = control.statusz([hc])
    payload_j = fleet2.statusz([hc])

    # /statusz: fleet goodput + the full attribution decomposition are
    # bit-identical (isoformat timestamps and JSON floats round-trip
    # exactly, so the windows ARE the same numbers, not near ones)
    assert payload_j["fleet"]["goodput_ratio"] == pytest.approx(
        payload_c["fleet"]["goodput_ratio"], abs=1e-9
    )
    assert payload_j["fleet"]["goodput"] == payload_c["fleet"]["goodput"]
    expected = (9 + 7) / 20
    assert payload_j["fleet"]["goodput_ratio"] == pytest.approx(expected)
    # conservation: Σ lost ratios = 1 − goodput, on the restarted side
    block = payload_j["fleet"]["goodput"]
    lost = sum(v or 0.0 for v in block["attribution"].values())
    assert lost == pytest.approx(1.0 - payload_j["fleet"]["goodput_ratio"], abs=1e-9)

    # the per-check SLO block (availability / budget / burn) matches
    entry_c = payload_c["checks"][0]
    entry_j = payload_j["checks"][0]
    assert entry_j["slo"] == entry_c["slo"]
    assert entry_j["attribution"] == entry_c["attribution"]

    # the gauges: both collectors report the same window
    for family in (
        "healthcheck_slo_availability_ratio",
        "healthcheck_error_budget_remaining",
        "healthcheck_slo_burn_rate",
    ):
        want = control_metrics.sample_value(family, SLO_LABELS)
        got = metrics2.sample_value(family, SLO_LABELS)
        assert got == pytest.approx(want, abs=1e-9), family
    assert metrics2.sample_value(
        "healthcheck_fleet_goodput_ratio", {}
    ) == pytest.approx(
        control_metrics.sample_value("healthcheck_fleet_goodput_ratio", {}),
        abs=1e-9,
    )

    # the `am-tpu goodput` rendering is byte-identical
    assert render_goodput(payload_j) == render_goodput(payload_c)

    # the journal block itself reports the split: 12 replayed, 8 new
    jblock = payload_j["fleet"]["journal"]
    assert jblock["replayed"]["result"] == 12
    assert jblock["appended"]["result"] == 8
    # and the level gauges export through the pinned families
    fleet2.refresh_journal_metrics()
    assert metrics2.sample_value("healthcheck_journal_segments", {}) >= 1
    assert metrics2.sample_value("healthcheck_journal_lag_seconds", {}) >= 0.0


# ---------------------------------------------------------------------
# acceptance: record → replay determinism + the matrix cell
# ---------------------------------------------------------------------

TRACE_CHECKS = ("bench/hc-a", "bench/hc-b", "bench/hc-c")


def record_trace(journal_dir, n=48, seed=7):
    requests = open_loop_checks(n, 200.0, seed, TRACE_CHECKS)
    journal = TelemetryJournal(journal_dir, clock=FakeClock())
    summary = asyncio.run(drive_requests(requests, journal=journal))
    journal.close()
    return requests, journal, summary


def test_record_replay_reproduces_the_recorded_workload(tmp_path):
    journal_dir = str(tmp_path / "trace")
    requests, journal, first = record_trace(journal_dir)
    assert first["conservation_ok"]
    assert journal.appended["arrival"] == 48

    schedule, warnings = load_trace(journal_dir)
    assert warnings == [] and len(schedule) == 48
    replay_reqs = replayed_checks(schedule)
    # recorded tenant mix and per-request identity order, reproduced
    assert [r.tenant for r in replay_reqs] == [r.tenant for r in requests]
    assert [r.check for r in replay_reqs] == [r.check for r in requests]
    # arrival ORDER and spacing: the recorded inter-arrival gaps are
    # the original schedule's (the timeline is shifted to the first
    # arrival, gaps are preserved)
    deltas = [
        requests[i].arrival - requests[i - 1].arrival for i in range(1, 48)
    ]
    rdeltas = [
        replay_reqs[i].arrival - replay_reqs[i - 1].arrival
        for i in range(1, 48)
    ]
    assert rdeltas == pytest.approx(deltas, abs=1e-9)

    second = asyncio.run(drive_requests(replay_reqs))
    assert second["conservation_ok"]
    assert second["outcomes"] == first["outcomes"]
    assert second["tenant_mix"] == first["tenant_mix"]
    assert second["outcome_counts"] == first["outcome_counts"]
    # per-tenant conservation is exact on the replayed side too
    assert second["conservation"]["ok"] is True


def test_frontdoor_replay_matrix_cell(tmp_path, monkeypatch):
    monkeypatch.delenv("ACTIVEMONITOR_REPLAY_TRACE", raising=False)
    cell = matrix_mod.CellSpec("frontdoor-replay", (), "float32", "-")
    assert cell.cell_id == "frontdoor-replay/1chip/f32"

    # canonical seeded round trip when no trace is wired
    result = matrix_mod.execute_cell(cell)
    assert result.status == matrix_mod.STATUS_OK
    replay = result.details["replay"]
    assert replay["source"] == "canonical-seeded"
    assert replay["requests"] == matrix_mod.REPLAY_CANON_REQUESTS
    assert replay["conserved"] is True
    assert result.value and result.value > 0

    # a recorded trace wired via the env knob drives the SAME cell
    journal_dir = str(tmp_path / "trace")
    _requests, _journal, recorded = record_trace(journal_dir, n=24)
    monkeypatch.setenv("ACTIVEMONITOR_REPLAY_TRACE", journal_dir)
    traced = matrix_mod.execute_cell(cell)
    assert traced.status == matrix_mod.STATUS_OK
    assert traced.details["replay"]["source"] == journal_dir
    assert traced.details["replay"]["requests"] == 24
    assert traced.details["replay"]["tenant_mix"] == recorded["tenant_mix"]

    # a torn trace is a structured skip, never a bogus measurement
    _seq, last = list_segments(journal_dir)[-1]
    raw = Path(last).read_bytes()
    Path(last).write_bytes(raw[:-10])
    torn = matrix_mod.execute_cell(cell)
    assert torn.status == matrix_mod.STATUS_SKIPPED
    assert matrix_mod.SKIP_NO_TRACE in torn.reason


def test_frontdoor_replay_cell_lands_a_tracked_baseline(tmp_path, monkeypatch):
    monkeypatch.delenv("ACTIVEMONITOR_REPLAY_TRACE", raising=False)
    clock = FakeClock()
    path = tmp_path / "BENCH_BASELINES.json"
    observatory = matrix_mod.MatrixObservatory(
        clock=clock, path=str(path), warmup_runs=1
    )
    cell = matrix_mod.CellSpec("frontdoor-replay", (), "float32", "-")
    tick(clock)
    summary = observatory.observe_round([matrix_mod.execute_cell(cell)])
    entry = summary["cells"]["frontdoor-replay/1chip/f32"]
    assert entry["status"] == "ok"
    # the BENCH_BASELINES.json sidecar carries the cell like any other
    doc = json.loads(path.read_text())
    assert "frontdoor-replay/1chip/f32" in doc["last_round"]["cells"]
    # the next round compares against the learned baseline
    tick(clock)
    summary2 = observatory.observe_round([matrix_mod.execute_cell(cell)])
    entry2 = summary2["cells"]["frontdoor-replay/1chip/f32"]
    assert isinstance(entry2.get("vs_baseline"), float)


def test_frontdoor_replay_expansion_is_single_chip_f32_only():
    spec = dict(matrix_mod.DEFAULT_SPEC)
    spec["ops"] = ["frontdoor-replay"]
    spec["meshes"] = [{"sp": 8}]
    spec["dtypes"] = ["bf16", "f32"]
    cells, skipped = matrix_mod.expand(spec, n_devices=8)
    assert [c.cell_id for c in cells] == ["frontdoor-replay/1chip/f32"]
    # the bf16 column exercises the unsupported-dtype structured skip
    assert any(
        s.cell.cell_id == "frontdoor-replay/1chip/bf16" for s in skipped
    )


# ---------------------------------------------------------------------
# flight recorder: size-capped durable sink (regression)
# ---------------------------------------------------------------------


def test_flightrec_sink_is_size_capped(tmp_path):
    recorder = FlightRecorder(
        clock=FakeClock(), flight_dir=str(tmp_path), max_bytes=2048
    )
    for i in range(40):
        recorder.record(KIND_BREAKER, "ns/hc", note="x" * 200, i=i)
    active = tmp_path / "flightrec.jsonl"
    # the active file keeps its pinned name (tests and jq pipelines
    # read it) and stays bounded: under the cap plus one bundle
    assert active.exists()
    assert active.stat().st_size <= 2048 + 4096
    assert (tmp_path / "flightrec-1.jsonl").exists()
    assert not (tmp_path / "flightrec-5.jsonl").exists()  # keep=4 bounds it
    bundles = list(FlightRecorder.read_jsonl(str(active)))
    assert bundles and all(b["kind"] == KIND_BREAKER for b in bundles)


# ---------------------------------------------------------------------
# CLI: am-tpu journal / record / replay
# ---------------------------------------------------------------------


def test_cli_record_journal_replay_roundtrip(tmp_path, capsys):
    d = str(tmp_path / "trace")
    assert main(["record", "--journal-dir", d, "--requests", "16"]) == 0
    out = capsys.readouterr().out
    assert "recorded: 16 requests driven  conservation=ok" in out
    assert "arrivals appended=16" in out

    assert main(["journal", "--journal-dir", d]) == 0
    out = capsys.readouterr().out
    assert "journal-000001.jsonl" in out  # the segment table
    assert "arrival" in out  # the stream counts
    assert "replay coverage: 16 arrivals" in out

    assert main(["replay", "--journal-dir", d]) == 0
    out = capsys.readouterr().out
    assert "replayed: 16 requests driven  conservation=ok" in out


def test_cli_replay_refuses_empty_or_torn_journals(tmp_path, capsys):
    empty = tmp_path / "empty"
    empty.mkdir()
    assert main(["replay", "--journal-dir", str(empty)]) == 1
    assert "no arrival events" in capsys.readouterr().err
    torn = tmp_path / "torn"
    torn.mkdir()
    (torn / "journal-000001.jsonl").write_text("")
    (torn / "journal-000003.jsonl").write_text("")
    assert main(["replay", "--journal-dir", str(torn)]) == 1
    assert "missing-segment" in capsys.readouterr().err


def test_cli_record_rejects_bad_flags(tmp_path, capsys):
    rc = main(["record", "--journal-dir", str(tmp_path), "--requests", "0"])
    assert rc == 2
    assert "--requests" in capsys.readouterr().err


def test_render_journal_views():
    assert "no journal recorded" in render_journal(None)
    block = {
        "replicas": 2,
        "segment_count": 3,
        "appended": {"result": 5, "arrival": 2},
        "replayed": {"result": 5},
        "dropped": 1,
        "compacted_segments": 0,
        "lag_seconds": 2.0,
        "restore_warning": {"reason": "corrupt-line", "detail": "x:3"},
    }
    text = render_journal(block)
    assert "replicas=2" in text
    assert "lag=2.0s" in text
    assert "APPENDED" in text and "REPLAYED" in text
    assert "restored fresh: corrupt-line (x:3)" in text
    assert "dropped=1" in text


# ---------------------------------------------------------------------
# hack/journal_check.py: the integrity gate, run as CI runs it
# ---------------------------------------------------------------------


def run_journal_check(journal_dir):
    return subprocess.run(
        [sys.executable, str(REPO / "hack" / "journal_check.py"), journal_dir],
        capture_output=True,
        text=True,
    )


def test_journal_check_passes_a_clean_journal(tmp_path):
    path = str(tmp_path / "j")
    clock = FakeClock()
    journal = TelemetryJournal(path, clock=clock)
    journal.record_result(
        "ns/hc", make_result(clock, ok=False, bucket="hbm", why="bw floor")
    )
    journal.record_result("ns/hc", make_result(clock, ok=True))
    journal.record_arrival(tenant="t", check="ns/hc", outcome="run", gap=0.0)
    journal.close()
    proc = run_journal_check(path)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout
    assert "result=2" in proc.stdout and "attribution=1" in proc.stdout


def test_journal_check_flags_broken_conservation_and_torn_chains(tmp_path):
    path = str(tmp_path / "j")
    clock = FakeClock()
    journal = TelemetryJournal(path, clock=clock)
    journal.record_result(
        "ns/hc", make_result(clock, ok=False, bucket="hbm", why="bw floor")
    )
    journal.close()
    # a bucket-carrying result line with no attribution twin: the
    # cross-stream conservation check must catch it
    _seq, active = list_segments(path)[-1]
    with open(active, "a") as f:
        f.write(
            json.dumps(
                {
                    "v": JOURNAL_VERSION,
                    "stream": "result",
                    "key": "ns/hc",
                    "ts": "2026-01-01T00:00:00+00:00",
                    "ok": False,
                    "latency_seconds": 1.0,
                    "bucket": "ici",
                    "why": "orphaned",
                }
            )
            + "\n"
        )
    proc = run_journal_check(path)
    assert proc.returncode == 1
    assert "conservation" in proc.stdout

    torn = seeded_arrival_dir(tmp_path, name="torn")
    segments = list_segments(torn)
    Path(segments[1][1]).unlink()
    proc = run_journal_check(torn)
    assert proc.returncode == 1
    assert "missing-segment" in proc.stdout

    proc = run_journal_check(str(tmp_path / "nope"))
    assert proc.returncode == 1
    assert "missing-dir" in proc.stdout
