"""Bounded per-HealthCheck result history.

The CR status is a durable checkpoint of the LAST run plus lifetime
counters — it cannot answer "how did this check do over the past hour",
which is the question an SLO is (PAPERS.md: ML Productivity Goodput
reports availability over a rolling window, not point-in-time
verdicts). This module keeps the raw material for that answer: one
bounded ring of :class:`CheckResult` per check, fed from the
reconciler's status-write path — the single place every run (success,
failure, synthesized timeout) converges.

Design constraints, shared with the tracer (obs/trace.py):

- **injectable clock**: result timestamps come from
  :class:`~activemonitor_tpu.utils.clock.Clock`, so fake-clock tests
  script exact windows and quantiles.
- **bounded memory**: one ``deque(maxlen=capacity)`` per check; a
  long-lived controller records forever in constant memory. Deleted
  checks are dropped via :meth:`forget` from the reconciler's
  deleted-resource path.
- **never raises into the recording path**: history is observability;
  the reconciler's status write must not fail because a ring did.
"""

from __future__ import annotations

import collections
import logging
from dataclasses import dataclass, field
from datetime import datetime
from typing import Callable, Deque, Dict, List, Optional

from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.history")

# per-check results retained; at a 60 s cadence this is ~4 h of history,
# comfortably more than any sane SLO window for an active prober
DEFAULT_CAPACITY = 256


@dataclass(frozen=True)
class CheckResult:
    """One finished run of one HealthCheck."""

    ts: datetime  # finish wall time (clock.now() at record)
    ok: bool
    latency: float  # submit → terminal-phase seconds
    workflow: str  # workflow object name, joins to engine/Argo state
    trace_id: str  # joins to /debug/traces and correlated logs
    # the run's numeric custom-metric samples (contract spelling, e.g.
    # "mxu-matmul-tflops") — the raw material the anomaly detectors and
    # the /debug endpoints read; empty for runs without a contract
    metrics: Dict[str, float] = field(default_factory=dict)
    # the payload's own phase timings (the stdout contract's "timings"
    # block) — the ReFrame-style raw material goodput attribution reads
    timings: Dict[str, float] = field(default_factory=dict)
    # the payload's roofline verdicts (the contract's "roofline" block,
    # obs/roofline.py): metric-prefix -> {bound, intensity, fraction,
    # cost_source, ...} — the cost-model evidence /statusz, `am-tpu
    # roofline`, attribution and flight bundles read; empty for runs
    # without a block (quick mode, old probes)
    roofline: Dict[str, Dict] = field(default_factory=dict)
    # lost-goodput attribution, stamped AT RECORD TIME while the cycle's
    # spans / anomaly verdicts / breaker state are all still live
    # (obs/attribution.py); "" for unremarkable ok runs
    bucket: str = ""
    why: str = ""

    def to_dict(self) -> dict:
        return {
            "ts": self.ts.isoformat(),
            "ok": self.ok,
            "latency_seconds": self.latency,
            "workflow": self.workflow,
            "trace_id": self.trace_id,
            "metrics": dict(self.metrics),
            "timings": dict(self.timings),
            "roofline": dict(self.roofline),
            "bucket": self.bucket,
            "why": self.why,
        }


class ResultHistory:
    """Per-check rings of finished runs, keyed by ``namespace/name``."""

    def __init__(
        self, clock: Optional[Clock] = None, capacity: int = DEFAULT_CAPACITY
    ):
        self.clock = clock or Clock()
        self._capacity = max(1, capacity)
        self._rings: Dict[str, Deque[CheckResult]] = {}
        # record-time observers (frontdoor/coalesce.py fans in-flight
        # waiters out on the very result the reconciler records) —
        # exceptions are swallowed per the never-raises constraint above
        self._subscribers: List[Callable[[str, CheckResult], None]] = []

    def subscribe(self, fn: Callable[[str, CheckResult], None]) -> None:
        """Call ``fn(key, result)`` after every recorded run. The hook
        runs on the recording path, so it must be cheap; a raising
        subscriber is logged and dropped from that record, never
        propagated into the reconciler's status write."""
        self._subscribers.append(fn)

    def record(
        self,
        key: str,
        *,
        ok: bool,
        latency: float,
        workflow: str = "",
        trace_id: str = "",
        metrics: Optional[Dict[str, float]] = None,
        timings: Optional[Dict[str, float]] = None,
        roofline: Optional[Dict[str, Dict]] = None,
        bucket: str = "",
        why: str = "",
    ) -> CheckResult:
        """Append one finished run; the oldest entry falls off a full
        ring. The timestamp is stamped HERE from the injected clock so
        every caller records on the same timeline the windows use."""
        result = CheckResult(
            ts=self.clock.now(),
            ok=bool(ok),
            latency=max(0.0, float(latency)),
            workflow=workflow,
            trace_id=trace_id,
            metrics=dict(metrics or {}),
            timings=dict(timings or {}),
            roofline=dict(roofline or {}),
            bucket=bucket,
            why=why,
        )
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = collections.deque(maxlen=self._capacity)
        ring.append(result)
        for fn in self._subscribers:
            try:
                fn(key, result)
            except Exception:
                log.exception("result subscriber failed for %s", key)
        return result

    def restore(self, key: str, result: CheckResult) -> None:
        """Append an already-built result WITHOUT stamping a timestamp
        and WITHOUT notifying subscribers — the journal's boot-replay
        path (obs/journal.py). Replayed results keep the timestamps
        they were recorded with (the windows must survive the restart
        unchanged), and the journal itself is a subscriber: notifying
        here would re-journal every replayed event — the double-count
        the split record/restore API exists to prevent."""
        ring = self._rings.get(key)
        if ring is None:
            ring = self._rings[key] = collections.deque(maxlen=self._capacity)
        ring.append(result)

    def results(self, key: str) -> List[CheckResult]:
        """All retained results for a check, oldest first."""
        return list(self._rings.get(key, ()))

    def tail(self, key: str, n: int = 10) -> List[CheckResult]:
        """The newest ``n`` results, oldest-of-the-tail first — the
        /statusz history excerpt."""
        ring = self._rings.get(key)
        if not ring or n <= 0:
            return []
        return list(ring)[-n:]

    def last(self, key: str) -> Optional[CheckResult]:
        ring = self._rings.get(key)
        return ring[-1] if ring else None

    def checks(self) -> List[str]:
        """Keys with at least one recorded result."""
        return list(self._rings.keys())

    def forget(self, key: str) -> None:
        """Drop a deleted check's ring (reconciler's deleted path)."""
        self._rings.pop(key, None)

    def __len__(self) -> int:
        return sum(len(ring) for ring in self._rings.values())
