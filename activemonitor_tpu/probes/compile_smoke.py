"""XLA compile smoke-test probe.

Detects the stuck-compile failure mode (SURVEY.md §5.3 TPU detectors):
jits the canonical probe transformer forward, wall-clocks cold compile
and warm execution, and fails if compile exceeds its deadline. First
TPU compiles legitimately take tens of seconds — the default threshold
reflects that; persistent-cache hits make subsequent runs fast.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from activemonitor_tpu.models.probe_model import (
    ProbeModelConfig,
    forward,
    init_params,
    tiny_config,
)
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult


def run(
    compile_deadline_seconds: float = 120.0,
    batch: int = 4,
    seq: int = 128,
    tiny: bool = False,
) -> ProbeResult:
    cfg = tiny_config() if tiny else ProbeModelConfig()
    seq = min(seq, cfg.max_seq_len)
    params = init_params(jax.random.key(0), cfg)
    tokens = jnp.zeros((batch, seq), jnp.int32)

    fwd = jax.jit(lambda p, t: forward(p, t, cfg))
    t0 = time.perf_counter()
    jax.block_until_ready(fwd(params, tokens))
    compile_seconds = time.perf_counter() - t0

    t0 = time.perf_counter()
    jax.block_until_ready(fwd(params, tokens))
    exec_seconds = time.perf_counter() - t0

    ok = compile_seconds <= compile_deadline_seconds
    return ProbeResult(
        ok=ok,
        summary=(
            f"compile {compile_seconds:.2f}s (deadline {compile_deadline_seconds:.0f}s), "
            f"exec {exec_seconds * 1e3:.2f}ms"
        ),
        metrics=[
            ProbeMetric(
                "xla-compile-seconds",
                compile_seconds,
                help="Cold jit compile wall-clock of the probe transformer forward",
            ),
            ProbeMetric(
                "xla-exec-milliseconds",
                exec_seconds * 1e3,
                help="Warm execution wall-clock of the compiled forward",
            ),
        ],
        details={
            "batch": batch,
            "seq": seq,
            "d_model": cfg.d_model,
            "n_layers": cfg.n_layers,
        },
    )
