"""Chaos tier: fault injection against the stub API server.

The reference gets its resilience ladder (SURVEY.md §5.3 — panic
recover, 1s requeue, RetryOnConflict, synthesized failures) but never
tests it against a misbehaving API server. This tier does: 5xx storms,
conflict storms, dropped watch streams and a slow API server, asserting
the controller recovers every time — no dead schedules, no duplicate
state, no hung watches.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import RBACProvisioner
from activemonitor_tpu.controller.client_k8s import KubernetesHealthCheckClient
from activemonitor_tpu.controller.events import KubernetesEventRecorder
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.controller.rbac import KubernetesRBACBackend
from activemonitor_tpu.controller.reconciler import HealthCheckReconciler
from activemonitor_tpu.engine.argo import (
    WF_GROUP,
    WF_PLURAL,
    WF_VERSION,
    ArgoWorkflowEngine,
)
from activemonitor_tpu.kube import api_path
from activemonitor_tpu.metrics import MetricsCollector

from tests.kube_harness import stub_env

INLINE_HELLO = """
apiVersion: argoproj.io/v1alpha1
kind: Workflow
metadata:
  generateName: chaos-
spec:
  entrypoint: main
  templates:
    - name: main
      container:
        image: python:3.12-slim
        command: [python, -c, "print('hello')"]
"""


def chaos_check(name="chaos-check"):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": "health"},
            "spec": {
                "repeatAfterSec": 60,
                "level": "namespace",
                "workflow": {
                    "generateName": "chaos-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "chaos-sa",
                        "source": {"inline": INLINE_HELLO},
                    },
                },
            },
        }
    )


def build_controller(api, max_parallel=2):
    client = KubernetesHealthCheckClient(api)
    reconciler = HealthCheckReconciler(
        client=client,
        engine=ArgoWorkflowEngine(api),
        rbac=RBACProvisioner(KubernetesRBACBackend(api)),
        recorder=KubernetesEventRecorder(api),
        metrics=MetricsCollector(),
    )
    return client, Manager(
        client=client, reconciler=reconciler, max_parallel=max_parallel
    )


async def wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        result = await predicate()
        if result:
            return result
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError("condition not met")
        await asyncio.sleep(interval)


def argo_player(server, api):
    """Background task playing the Argo controller: marks every
    submitted Workflow Succeeded, forever (survives resubmissions)."""

    async def play():
        done = set()
        while True:
            for wf in server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
                name = wf["metadata"]["name"]
                if name in done:
                    continue
                done.add(name)
                await api.merge_patch(
                    api_path(
                        WF_GROUP, WF_VERSION, WF_PLURAL,
                        wf["metadata"]["namespace"], name, "status",
                    ),
                    {"status": {"phase": "Succeeded"}},
                )
            await asyncio.sleep(0.05)

    return asyncio.create_task(play())


@pytest.mark.asyncio
async def test_watch_stream_drop_reconnects():
    """An abruptly closed watch stream must not lose later events."""
    async with stub_env() as (server, api):
        client = KubernetesHealthCheckClient(api)
        seen = []

        async def consume():
            async for event in client.watch():
                seen.append((event.type, event.name))

        task = asyncio.create_task(consume())
        try:
            await client.apply(chaos_check("first"))
            await wait_for(lambda: asyncio.sleep(0, ("ADDED", "first") in seen))

            assert server.drop_watches() >= 1
            # event created while the client is between streams: the
            # resume-from-last-rv reconnect must deliver it
            await client.apply(chaos_check("second"))
            await wait_for(lambda: asyncio.sleep(0, ("ADDED", "second") in seen))
        finally:
            task.cancel()


@pytest.mark.asyncio
async def test_workflow_submit_500_storm_recovers():
    """The first submits fail with 500s; the requeue ladder must retry
    until the API server heals, then the check completes normally."""
    async with stub_env() as (server, api):
        server.inject_fault(f"/{WF_PLURAL}", status=500, times=3, method="POST")
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        try:
            await client.apply(chaos_check())

            async def succeeded():
                hc = await client.get("health", "chaos-check")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded)
            assert hc.status.success_count == 1
            # all three injected faults were actually consumed
            assert all(f["remaining"] == 0 for f in server.faults)
        finally:
            player.cancel()
            await manager.stop()


@pytest.mark.asyncio
async def test_status_write_500_storm_does_not_kill_schedule():
    """A 5xx burst on the terminal status write outliving the conflict
    retries must requeue the check, not silently drop its schedule
    (reference requeues on any reconcile error, :204)."""
    async with stub_env() as (server, api):
        server.inject_fault(
            "/healthchecks/chaos-check/status", status=500, times=4, method="PATCH"
        )
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        try:
            await client.apply(chaos_check())

            async def succeeded():
                hc = await client.get("health", "chaos-check")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded)
            assert hc.status.success_count >= 1
            assert all(f["remaining"] == 0 for f in server.faults)
            # the schedule survived: the next run is on the books
            assert manager.reconciler.timers.exists("health/chaos-check")
        finally:
            player.cancel()
            await manager.stop()


@pytest.mark.asyncio
async def test_status_conflict_storm_retries_without_rerun():
    """409s within the RetryOnConflict budget are absorbed: exactly one
    workflow run, no requeue, status written."""
    async with stub_env() as (server, api):
        server.inject_fault(
            "/healthchecks/chaos-check/status", status=409, times=3, method="PATCH"
        )
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        try:
            await client.apply(chaos_check())

            async def succeeded():
                hc = await client.get("health", "chaos-check")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded)
            # conflicts were retried inside the write, not by re-running
            # the workflow
            assert hc.status.success_count == 1
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 1
        finally:
            player.cancel()
            await manager.stop()


@pytest.mark.asyncio
async def test_slow_apiserver_full_lifecycle():
    """Uniform API latency slows everything but breaks nothing."""
    async with stub_env() as (server, api):
        server.latency = 0.05
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        try:
            await client.apply(chaos_check())

            async def succeeded():
                hc = await client.get("health", "chaos-check")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded, timeout=30.0)
            assert hc.status.success_count == 1
        finally:
            player.cancel()
            await manager.stop()
