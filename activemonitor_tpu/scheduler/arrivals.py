"""Seeded Poisson arrival process — the one open-loop traffic contract.

Both open-loop generators in the tree — the serving probe's request
schedule (:func:`scheduler.serving.open_loop_requests`) and the front
door's check-request schedule (:func:`frontdoor.traffic.
open_loop_checks`) — draw their arrival times from this process, so
"same seed ⇒ byte-identical schedule" is ONE contract with one
implementation, not two generators that can drift apart.

The determinism contract is the *draw order* against a single
``random.Random(seed)``: one ``expovariate`` per arrival, with any
payload draws (prompt lengths, tenants, check identities) interleaved
by the caller through :meth:`PoissonArrivals.choice` on the SAME rng.
Callers must keep their draw order stable across refactors — the
serving scheduler-trace tests pin it byte-for-byte.

Open-loop on purpose (the FlowMesh serving framing): the schedule is
generated up front and never adapts to service latency, so overload
shows up as queueing delay instead of a coordinated-omission slowdown.
No wall clock anywhere — arrival times are plain floats on the
caller's timeline (``hack/lint.py`` bans ``time.time()`` here like the
other clock-disciplined modules).
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import List, Sequence, Tuple, TypeVar

T = TypeVar("T")


class PoissonArrivals:
    """Seeded exponential inter-arrival generator plus the rng the
    caller interleaves payload draws on.

    ``next()`` advances the cumulative arrival time by one
    ``expovariate(rate_per_s)`` draw and returns it; ``choice(seq)``
    draws a payload attribute from the same rng (tuple-normalized, so
    list vs tuple spellings of a choice set cannot change the draw).
    """

    def __init__(self, rate_per_s: float, seed: int):
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
        self.rate_per_s = rate_per_s
        self.rng = random.Random(seed)
        self.now = 0.0

    def next(self) -> float:
        """The next arrival's time (seconds since schedule start)."""
        self.now += self.rng.expovariate(self.rate_per_s)
        return self.now

    def choice(self, seq: Sequence[T]) -> T:
        """One payload draw from the shared rng (draw-order is part of
        the determinism contract — see module docstring)."""
        return self.rng.choice(tuple(seq))


@dataclass(frozen=True)
class MixedArrival:
    """One arrival of the tenant/prefix-mix trace: an explicit token
    prompt (shared hot prefix or cold unique), ready for either the
    serving scheduler (``scheduler.serving.mixed_open_loop_requests``
    wraps it into a ``Request``) or front-door traffic shaping."""

    rid: int
    tenant: str
    arrival: float  # seconds since schedule start
    prompt_tokens: Tuple[int, ...]
    output_tokens: int
    hot: bool  # prompt starts with the shared system-prompt prefix


class TenantPrefixMix:
    """Seeded tenant/prefix-mix trace generator — the disaggregated
    serving workload's shape (ISSUE 20), shared by the serving probe
    and front-door traffic so "hot shared prefix" means ONE thing.

    A fraction of arrivals (``hot_fraction``) open with the same
    system-prompt token prefix across every tenant — the traffic the
    content-addressed prefix cache (ops/kv_cache.PrefixCache) banks
    once — and the rest carry unique cold prompts. Total prompt
    lengths stay inside the bounded ``prompt_len_choices`` set (the
    same bounded-compiles contract as :func:`scheduler.serving.
    open_loop_requests`), so hot and cold requests share shapes.

    Determinism is the module's one-rng contract, with this generator's
    OWN pinned draw order per arrival: expovariate inter-arrival,
    tenant, hot-coin (``random()``), prompt length, output length, then
    one ``randrange`` per non-prefix prompt token. The shared prefix
    itself is drawn once at construction from the same rng, BEFORE any
    arrivals. :class:`PoissonArrivals` is untouched — the existing
    serving/front-door schedules stay byte-identical per seed.
    """

    def __init__(
        self,
        rate_per_s: float,
        seed: int,
        *,
        tenants: Sequence[str] = ("tenant-a", "tenant-b"),
        prefix_len: int = 8,
        hot_fraction: float = 0.6,
        prompt_len_choices: Sequence[int] = (12, 16),
        output_choices: Sequence[int] = (2, 3, 5),
        vocab: int = 256,
    ):
        if prefix_len < 1 or vocab < 2 or not tenants:
            raise ValueError(
                f"need prefix_len >= 1, vocab >= 2 and tenants, got "
                f"{prefix_len}/{vocab}/{len(tuple(tenants))}"
            )
        if min(prompt_len_choices) <= prefix_len:
            raise ValueError(
                f"every prompt_len choice must exceed prefix_len="
                f"{prefix_len} (a hot prompt is prefix + unique tail), "
                f"got {tuple(prompt_len_choices)}"
            )
        if not 0.0 <= hot_fraction <= 1.0:
            raise ValueError(f"hot_fraction must be in [0,1], got {hot_fraction}")
        self.process = PoissonArrivals(rate_per_s, seed)
        self.tenants = tuple(tenants)
        self.hot_fraction = float(hot_fraction)
        self.prompt_len_choices = tuple(prompt_len_choices)
        self.output_choices = tuple(output_choices)
        self.vocab = int(vocab)
        rng = self.process.rng
        self.prefix: Tuple[int, ...] = tuple(
            rng.randrange(self.vocab) for _ in range(prefix_len)
        )
        self._next_rid = 0

    def generate(self, n_arrivals: int) -> List[MixedArrival]:
        """The next ``n_arrivals`` of the trace (resumable: a second
        call continues the same schedule)."""
        if n_arrivals < 1:
            raise ValueError(f"need n_arrivals >= 1, got {n_arrivals}")
        rng = self.process.rng
        out: List[MixedArrival] = []
        start = self._next_rid
        self._next_rid += n_arrivals
        for i in range(n_arrivals):
            now = self.process.next()
            tenant = self.process.choice(self.tenants)
            hot = rng.random() < self.hot_fraction
            plen = self.process.choice(self.prompt_len_choices)
            output = self.process.choice(self.output_choices)
            tail_len = plen - len(self.prefix) if hot else plen
            tail = tuple(rng.randrange(self.vocab) for _ in range(tail_len))
            tokens = (self.prefix + tail) if hot else tail
            out.append(
                MixedArrival(
                    rid=start + i,
                    tenant=tenant,
                    arrival=now,
                    prompt_tokens=tokens,
                    output_tokens=output,
                    hot=hot,
                )
            )
        return out
