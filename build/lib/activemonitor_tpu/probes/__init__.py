"""TPU probe payload library (the TPU-native graft; see BASELINE.md)."""

from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult

__all__ = ["ProbeMetric", "ProbeResult"]
