"""Metrics tests (reference test model: internal/metrics/collector_test.go —
malformed custom-metric table against a private registry)."""

import pytest

from activemonitor_tpu.metrics import (
    MetricsCollector,
    WORKFLOW_LABEL_HEALTHCHECK,
    WORKFLOW_LABEL_REMEDY,
)


@pytest.fixture()
def collector():
    return MetricsCollector()


def labels(name, wf=WORKFLOW_LABEL_HEALTHCHECK):
    return {"healthcheck_name": name, "workflow": wf}


def test_record_success_sets_all_vecs(collector):
    collector.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 100.0, 107.5)
    assert collector.sample_value("healthcheck_success_count", labels("hc-a")) == 1
    assert collector.sample_value("healthcheck_runtime_seconds", labels("hc-a")) == 7.5
    assert collector.sample_value("healthcheck_starttime", labels("hc-a")) == 100.0
    assert collector.sample_value("healthcheck_finishedtime", labels("hc-a")) == 107.5


def test_record_failure_increments_error(collector):
    collector.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 100.0, 101.0)
    collector.record_failure("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 102.0, 103.0)
    assert collector.sample_value("healthcheck_error_count", labels("hc-a")) == 2
    assert collector.sample_value("healthcheck_success_count", labels("hc-a")) is None


def test_remedy_label_dimension(collector):
    collector.record_success("hc-a", WORKFLOW_LABEL_REMEDY, 0, 1)
    assert (
        collector.sample_value(
            "healthcheck_success_count", labels("hc-a", WORKFLOW_LABEL_REMEDY)
        )
        == 1
    )


def test_exposition_contains_reference_metric_names(collector):
    collector.record_success("hc-a", WORKFLOW_LABEL_HEALTHCHECK, 0, 1)
    text = collector.exposition().decode()
    # exact names, no _total suffix (scrape contract of the reference)
    assert "healthcheck_success_count{" in text
    assert "healthcheck_runtime_seconds{" in text


def test_custom_metrics_from_outputs(collector):
    status = {
        "outputs": {
            "parameters": [
                {
                    "name": "metrics",
                    "value": '{"metrics": [{"name": "ici-allreduce-gbps", '
                    '"value": 123.4, "metrictype": "gauge", "help": "ICI bw"}]}',
                }
            ]
        }
    }
    n = collector.record_custom_metrics("tpu-probe", status)
    assert n == 1
    # both hc name and metric name sanitized: "-" -> "_"
    assert (
        collector.sample_value(
            "tpu_probe_ici_allreduce_gbps", {"healthcheck_name": "tpu-probe"}
        )
        == 123.4
    )


def test_custom_metric_name_overlap_deduped(collector):
    # deliberate divergence from collector.go:90 (design.md #12): the
    # hc-name prefix merges with the metric name's leading overlap
    # instead of stuttering
    status = {
        "outputs": {
            "parameters": [
                {
                    "name": "metrics",
                    "value": '{"metrics": [{"name": "ici-allreduce-busbw-gbps", '
                    '"value": 600.0}]}',
                }
            ]
        }
    }
    assert collector.record_custom_metrics("tpu-ici-allreduce", status) == 1
    assert (
        collector.sample_value(
            "tpu_ici_allreduce_busbw_gbps",
            {"healthcheck_name": "tpu-ici-allreduce"},
        )
        == 600.0
    )
    # the stuttered reference name must NOT exist
    assert (
        collector.sample_value(
            "tpu_ici_allreduce_ici_allreduce_busbw_gbps",
            {"healthcheck_name": "tpu-ici-allreduce"},
        )
        is None
    )


def test_same_check_merged_name_collision_skipped(collector):
    # check a-b emitting b-c and c: both merge to a_b_c — the second
    # must be skipped (logged), never silently overwrite the first
    status = {
        "outputs": {
            "parameters": [
                {
                    "name": "metrics",
                    "value": '{"metrics": [{"name": "b-c", "value": 1.0}, '
                    '{"name": "c", "value": 2.0}]}',
                }
            ]
        }
    }
    assert collector.record_custom_metrics("a-b", status) == 1
    assert collector.sample_value("a_b_c", {"healthcheck_name": "a-b"}) == 1.0


def test_prefix_dedupe_rules():
    from activemonitor_tpu.metrics.collector import _prefix_dedupe

    assert _prefix_dedupe("tpu_ici_allreduce", "ici_allreduce_busbw_gbps") == (
        "tpu_ici_allreduce_busbw_gbps"
    )
    assert _prefix_dedupe("hc", "bw") == "hc_bw"  # no overlap: plain join
    assert _prefix_dedupe("hc", "hc") == "hc"  # full overlap
    # overlap matches whole tokens only — "al" vs "allreduce" is no match
    assert _prefix_dedupe("tpu_al", "allreduce_gbps") == "tpu_al_allreduce_gbps"


def test_custom_metrics_updates_existing_gauge(collector):
    def status(v):
        return {
            "outputs": {
                "parameters": [
                    {"name": "m", "value": '{"metrics": [{"name": "bw", "value": %f}]}' % v}
                ]
            }
        }

    collector.record_custom_metrics("hc", status(1.0))
    collector.record_custom_metrics("hc", status(2.0))
    assert collector.sample_value("hc_bw", {"healthcheck_name": "hc"}) == 2.0


@pytest.mark.parametrize(
    "value",
    [
        "not json at all",
        '{"metrics": "not-a-list"}',
        '{"metrics": [{"value": 1.0}]}',  # missing name
        '{"metrics": [{"name": "x", "value": "NaN-ish-string"}]}',
        '{"metrics": [42]}',
        '{"other": []}',
        "",
    ],
)
def test_malformed_custom_metrics_are_skipped(collector, value):
    status = {"outputs": {"parameters": [{"name": "m", "value": value}]}}
    assert collector.record_custom_metrics("hc", status) == 0


def test_no_outputs_is_noop(collector):
    assert collector.record_custom_metrics("hc", {}) == 0
    assert collector.record_custom_metrics("hc", {"outputs": None}) == 0
    assert collector.record_custom_metrics("hc", {"outputs": {"parameters": None}}) == 0


def test_two_collectors_do_not_share_registries():
    # the reference's global registry caused a documented race
    # (collector_test.go:82-88); per-instance registries avoid it
    a = MetricsCollector()
    b = MetricsCollector()
    a.record_success("hc", WORKFLOW_LABEL_HEALTHCHECK, 0, 1)
    assert b.sample_value("healthcheck_success_count", labels("hc")) is None
