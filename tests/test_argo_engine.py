"""Argo engine tests against a stub CustomObjectsApi (no cluster)."""

import pytest

from activemonitor_tpu.engine.argo import ArgoWorkflowEngine


class _NotFound(Exception):
    status = 404


class _ServerError(Exception):
    status = 500


class StubCustomObjectsApi:
    def __init__(self):
        self.objects = {}
        self.calls = []

    def create_namespaced_custom_object(self, group, version, namespace, plural, body):
        assert (group, version, plural) == ("argoproj.io", "v1alpha1", "workflows")
        name = body["metadata"].get("name") or body["metadata"]["generateName"] + "abc12"
        body = {**body, "metadata": {**body["metadata"], "name": name}}
        self.objects[f"{namespace}/{name}"] = body
        self.calls.append(("create", namespace, name))
        return body

    def get_namespaced_custom_object(self, group, version, namespace, plural, name):
        self.calls.append(("get", namespace, name))
        key = f"{namespace}/{name}"
        if key not in self.objects:
            raise _NotFound(key)
        return self.objects[key]


MANIFEST = {
    "apiVersion": "argoproj.io/v1alpha1",
    "kind": "Workflow",
    "metadata": {"generateName": "probe-", "namespace": "health"},
    "spec": {"entrypoint": "main"},
}


@pytest.mark.asyncio
async def test_submit_returns_generated_name():
    stub = StubCustomObjectsApi()
    eng = ArgoWorkflowEngine(custom_objects_api=stub)
    name = await eng.submit(MANIFEST)
    assert name.startswith("probe-")
    assert ("create", "health", name) in stub.calls


@pytest.mark.asyncio
async def test_get_found_and_not_found():
    stub = StubCustomObjectsApi()
    eng = ArgoWorkflowEngine(custom_objects_api=stub)
    name = await eng.submit(MANIFEST)
    wf = await eng.get("health", name)
    assert wf["metadata"]["name"] == name
    assert await eng.get("health", "ghost") is None  # 404 -> None


@pytest.mark.asyncio
async def test_get_other_errors_propagate():
    class Broken(StubCustomObjectsApi):
        def get_namespaced_custom_object(self, *a):
            raise _ServerError("boom")

    eng = ArgoWorkflowEngine(custom_objects_api=Broken())
    with pytest.raises(_ServerError):
        await eng.get("health", "x")


@pytest.mark.asyncio
async def test_reconciler_works_through_argo_engine():
    """Full reconcile loop over the stubbed Argo API: submit, poll,
    scripted completion, status + reschedule."""
    from activemonitor_tpu.api import HealthCheck
    from activemonitor_tpu.controller import (
        EventRecorder,
        HealthCheckReconciler,
        InMemoryHealthCheckClient,
        InMemoryRBACBackend,
        RBACProvisioner,
    )
    from activemonitor_tpu.metrics import MetricsCollector
    from activemonitor_tpu.utils.clock import FakeClock

    stub = StubCustomObjectsApi()
    orig_get = stub.get_namespaced_custom_object

    def completing_get(group, version, namespace, plural, name):
        obj = orig_get(group, version, namespace, plural, name)
        obj["status"] = {"phase": "Succeeded"}
        return obj

    stub.get_namespaced_custom_object = completing_get

    client = InMemoryHealthCheckClient()
    clock = FakeClock()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=ArgoWorkflowEngine(custom_objects_api=stub),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
        clock=clock,
    )
    hc = HealthCheck.from_dict(
        {
            "metadata": {"name": "argo-hc", "namespace": "health"},
            "spec": {
                "repeatAfterSec": 60,
                "level": "cluster",
                "workflow": {
                    "generateName": "argo-hc-",
                    "workflowtimeout": 10,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "sa",
                        "source": {
                            "inline": "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"
                        },
                    },
                },
            },
        }
    )
    created = await client.apply(hc)
    await reconciler.reconcile(created.namespace, created.name)
    await clock.advance(0)
    await reconciler.wait_watches()
    st = (await client.get("health", "argo-hc")).status
    assert st.status == "Succeeded"
    assert st.success_count == 1
