"""Concurrent workqueue behavior under the new telemetry: N workers
draining M enqueued checks, with the depth/latency families asserted
against the injectable clock — no real sleeps anywhere (ISSUE 1
satellite). The reconcile body is a scripted hold on the fake clock so
queue waves are fully deterministic: 4 workers × 3 waves of 10 s.
"""

import asyncio

import pytest

from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.utils.clock import FakeClock

WORKERS = 4
CHECKS = 12
HOLD_SECONDS = 10.0

Q = {"name": "healthcheck"}
C = {"controller": "healthcheck"}


def make_manager(clock):
    client = InMemoryHealthCheckClient()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=FakeWorkflowEngine(),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
        clock=clock,
    )
    return Manager(client=client, reconciler=reconciler, max_parallel=WORKERS)


async def settle():
    for _ in range(50):
        await asyncio.sleep(0)


@pytest.mark.asyncio
async def test_n_workers_drain_m_checks_with_monotone_depth():
    clock = FakeClock()
    manager = make_manager(clock)
    metrics = manager.reconciler.metrics

    async def held_reconcile(_namespace, _name):
        await clock.sleep(HOLD_SECONDS)
        return None

    manager.reconciler.reconcile = held_reconcile
    await manager.start()
    try:
        for i in range(CHECKS):
            manager.enqueue("health", f"hc-{i}")
        # all adds landed before any worker ran (no await yet)
        assert metrics.sample_value("workqueue_adds_total", Q) == CHECKS
        assert metrics.sample_value("workqueue_depth", Q) == CHECKS

        depths = [metrics.sample_value("workqueue_depth", Q)]
        await settle()  # workers claim the first wave
        depths.append(metrics.sample_value("workqueue_depth", Q))
        assert metrics.sample_value(
            "controller_runtime_active_workers", C
        ) == WORKERS
        for _wave in range(CHECKS // WORKERS):
            await clock.advance(HOLD_SECONDS)
            depths.append(metrics.sample_value("workqueue_depth", Q))

        # depth shrank monotonically and hit zero at drain
        assert depths == sorted(depths, reverse=True)
        assert depths[0] == CHECKS
        assert depths[-1] == 0.0
        assert manager._queue.qsize() == 0
        assert metrics.sample_value(
            "controller_runtime_active_workers", C
        ) == 0

        # queue-wait latency: wave k waited k * HOLD_SECONDS, so the sum
        # over 3 waves of 4 is 4*(0 + 10 + 20) — exact on the fake clock
        assert (
            metrics.sample_value("workqueue_queue_duration_seconds_count", Q)
            == CHECKS
        )
        assert metrics.sample_value(
            "workqueue_queue_duration_seconds_sum", Q
        ) == pytest.approx(4 * (0 + 10 + 20))

        # work duration: every item held the worker for exactly 10 s
        assert (
            metrics.sample_value("workqueue_work_duration_seconds_count", Q)
            == CHECKS
        )
        assert metrics.sample_value(
            "workqueue_work_duration_seconds_sum", Q
        ) == pytest.approx(CHECKS * HOLD_SECONDS)

        # every reconcile completed cleanly and was timed
        assert metrics.sample_value(
            "controller_runtime_reconcile_total",
            {"controller": "healthcheck", "result": "success"},
        ) == CHECKS
        assert metrics.sample_value(
            "controller_runtime_reconcile_time_seconds_count", C
        ) == CHECKS
        assert metrics.sample_value(
            "controller_runtime_max_concurrent_reconciles", C
        ) == WORKERS
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_coalesced_enqueues_count_every_add_but_queue_once():
    clock = FakeClock()
    manager = make_manager(clock)
    metrics = manager.reconciler.metrics
    # client-go semantics: adds_total counts every Add() — coalesced
    # included — while the queue itself holds the key once
    manager.enqueue("health", "hc-a")
    manager.enqueue("health", "hc-a")
    manager.enqueue("health", "hc-a")
    assert metrics.sample_value("workqueue_adds_total", Q) == 3
    assert metrics.sample_value("workqueue_depth", Q) == 1
    assert manager._queue.qsize() == 1


@pytest.mark.asyncio
async def test_crashing_reconcile_counts_as_error_result():
    clock = FakeClock()
    manager = make_manager(clock)
    metrics = manager.reconciler.metrics

    async def crashing_reconcile(_namespace, _name):
        raise RuntimeError("boom")

    manager.reconciler.reconcile = crashing_reconcile
    await manager.start()
    try:
        manager.enqueue("health", "hc-a")
        await settle()
        assert metrics.sample_value(
            "controller_runtime_reconcile_total",
            {"controller": "healthcheck", "result": "error"},
        ) == 1
    finally:
        await manager.stop()
