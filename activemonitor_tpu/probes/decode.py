"""Decode-step probe — serving-path health.

Times the autoregressive hot loop (single-token decode with a KV cache)
that inference workloads live in. Training-shaped probes can look
healthy while the serving path is broken or slow — small matmuls, cache
scatter updates, and per-token dispatch stress entirely different parts
of the stack than big batched matmuls.

Exports per-token latency and decoded tokens/s; the correctness gate is
cache consistency: teacher-forcing the batched (no-cache) forward on
the cached greedy continuation must reproduce the cached path's logits
within numeric tolerance. Exact token equality is deliberately NOT the
gate — on TPU the two paths lower to differently-shaped matmuls whose
accumulation orders differ, so near-tie argmax flips are expected and
benign; a broken cache shows up as large logit divergence, not a tie
flip. Token agreement is still exported as an informational metric.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from activemonitor_tpu.models.probe_model import (
    ProbeModelConfig,
    decode_step,
    forward,
    init_kv_cache,
    init_params,
    prefill,
    tiny_config,
)
from activemonitor_tpu.ops.kv_cache import kv_bytes_per_token
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.utils.timing import chain_delta_seconds


def run(
    tiny: bool = False,
    batch: int = 8,
    prompt_len: int = 16,
    decode_tokens: int = 32,
    iters: int = 5,
    use_flash: bool = False,
    roofline: bool = True,
) -> ProbeResult:
    """``use_flash`` times the loop through the fused decode kernel
    (ops/flash_attention.flash_decode). Either way a fused-vs-dense
    logits agreement check runs, so a real-TPU battery validates the
    kernel's Mosaic compilation."""
    cfg = tiny_config() if tiny else ProbeModelConfig()
    if prompt_len < 1 or decode_tokens < 1:
        raise ValueError("prompt_len and decode_tokens must be >= 1")
    if prompt_len + 2 > cfg.max_seq_len:
        raise ValueError(
            f"prompt_len {prompt_len} leaves no decode room in "
            f"max_seq_len {cfg.max_seq_len}"
        )
    # the cache is sized for prompt + decode_tokens + 1; a model whose
    # max_seq_len cannot hold that used to clamp SILENTLY and decode
    # fewer distinct positions than requested. The clamp stays (the
    # probe still measures something on a small model) but is now
    # recorded in the details with the effective token budget, so the
    # artifact says the position window shrank instead of implying the
    # full request ran.
    requested_seq = prompt_len + decode_tokens + 1
    max_seq = min(cfg.max_seq_len, requested_seq)
    decode_tokens_effective = max_seq - prompt_len - 1
    params = init_params(jax.random.key(0), cfg)
    prompt = jax.random.randint(
        jax.random.key(1), (batch, prompt_len), 0, cfg.vocab_size
    )

    step = jax.jit(
        lambda p, c, t, pos: decode_step(p, c, t, pos, cfg, use_flash=use_flash)
    )

    # correctness: decode greedily via the cache, then teacher-force the
    # batched forward on the SAME tokens and compare logits per position
    cache = init_kv_cache(cfg, batch, max_seq)
    # batched prefill (the serving cold half: one MXU-shaped pass banks
    # the whole prompt's K/V; prefill==stepping is pinned by unit tests)
    logits, cache = jax.jit(
        lambda p, c, t: prefill(p, c, t, cfg, use_flash=use_flash)
    )(params, cache, prompt)
    # the cache has room for max_seq - prompt_len generated positions
    n_check = min(4, max_seq - prompt_len - 1)
    cached_tokens = []
    cached_logits = [logits]  # prediction for position prompt_len
    token = jnp.argmax(logits, axis=-1)
    for i in range(n_check):
        cached_tokens.append(token)
        logits, cache = step(
            params, cache, token, jnp.asarray(prompt_len + i)
        )
        cached_logits.append(logits)
        token = jnp.argmax(logits, axis=-1)

    # one batched pass over prompt + cached continuation: position
    # (prompt_len - 1 + i) predicts the i-th checked step. One
    # vectorized on-device comparison, one scalar readback (host syncs
    # cost ~70 ms each through a tunneled device).
    cached_tokens_arr = jnp.stack(cached_tokens, 1)  # [batch, n_check]
    seq = jnp.concatenate([prompt, cached_tokens_arr], axis=1)
    full_logits = forward(params, seq, cfg)
    lc_all = jnp.stack(cached_logits, 1)  # [batch, n_check+1, vocab]
    lf_all = full_logits[:, prompt_len - 1 : prompt_len + n_check]
    scale = jnp.maximum(jnp.max(jnp.abs(lf_all)), 1e-6)
    full_tokens = jnp.argmax(lf_all[:, :n_check], axis=-1)
    max_rel_diff, token_agreement = (
        float(v)
        for v in jax.device_get(
            jnp.stack(
                [
                    jnp.max(jnp.abs(lf_all - lc_all)) / scale,
                    jnp.mean((full_tokens == cached_tokens_arr).astype(jnp.float32)),
                ]
            )
        )
    )
    # bf16-decomposed f32 matmuls on TPU differ up to ~1e-2 relative
    # between shapes (observed 7.5e-3 on v5e, 8.6e-3 on CPU tiny); a
    # broken cache (stale/shifted K/V) reads O(1) — orders above this.
    # NaN anywhere makes max_rel_diff NaN, and NaN <= x is False, so
    # broken-device NaN logits FAIL the gate rather than slipping by.
    # token_agreement is informational: how often argmax agreed anyway.
    consistent = max_rel_diff <= 0.05

    # fused-vs-dense agreement on one step from the live cache: both
    # attention paths must produce the same logits — and running the
    # fused kernel here means a real-TPU battery validates its Mosaic
    # compilation even when the timed loop is dense
    other = jax.jit(
        lambda p, c, t, pos: decode_step(
            p, c, t, pos, cfg, use_flash=not use_flash
        )
    )
    check_pos = jnp.asarray(prompt_len + n_check)
    logits_a, _ = step(params, cache, token, check_pos)
    logits_b, _ = other(params, cache, token, check_pos)
    flash_rel_diff = float(
        jnp.max(jnp.abs(logits_a - logits_b))
        / jnp.maximum(jnp.max(jnp.abs(logits_a)), 1e-6)
    )
    consistent = consistent and flash_rel_diff <= 0.05

    # throughput: a lax.scan of decode steps (token feeds the next step;
    # one traced step, so long chains compile as fast as short ones).
    # Single decode steps are microseconds on TPU — the k spread must be
    # wide enough for the delta to tower over dispatch/tunnel jitter.
    def make_chain(k):
        @jax.jit
        def chain(params, cache, token):
            def body(carry, i):
                cache, token = carry
                # wrap position so long chains never overrun the cache
                pos = jnp.asarray(prompt_len, jnp.int32) + jnp.mod(
                    i, max_seq - prompt_len
                )
                logits, cache = decode_step(
                    params, cache, token, pos, cfg, use_flash=use_flash
                )
                return (cache, jnp.argmax(logits, axis=-1)), logits[0, 0]

            (_, _), outs = jax.lax.scan(
                body, (cache, token), jnp.arange(k, dtype=jnp.int32)
            )
            return outs.sum()

        return chain

    cache2 = init_kv_cache(cfg, batch, max_seq)
    token0 = prompt[:, 0]
    seconds = chain_delta_seconds(
        make_chain, params, cache2, token0, k1=32, k2=288, iters=iters
    )
    tokens_per_second = batch / seconds

    metrics = [
        ProbeMetric(
            "decode-step-milliseconds",
            seconds * 1e3,
            help="Per-token decode latency with KV cache",
        ),
        ProbeMetric(
            "decode-tokens-per-second",
            tokens_per_second,
            help="Aggregate decoded tokens/s across the batch",
        ),
        ProbeMetric(
            "decode-consistency",
            1.0 if consistent else 0.0,
            help="1 when cached logits match the teacher-forced batched "
            "forward within tolerance",
        ),
        ProbeMetric(
            "decode-token-agreement",
            token_agreement,
            help="Fraction of greedy tokens agreeing across paths "
            "(informational: near-tie argmax flips are benign)",
        ),
        ProbeMetric(
            "decode-kv-bytes-per-token",
            kv_bytes_per_token(cfg),
            help="HBM bytes one generated token adds to the KV cache — "
            "the shared roofline-ceiling input the serving probe "
            "cross-checks (serving-kv-bytes-per-token)",
        ),
    ]
    result = ProbeResult(
        ok=consistent,
        summary=(
            f"decode {seconds * 1e3:.2f}ms/token, {tokens_per_second:,.0f} tok/s, "
            f"cache consistency {'OK' if consistent else 'MISMATCH'} "
            f"(teacher-forced rel diff {max_rel_diff:.1e}, "
            f"fused-vs-dense {flash_rel_diff:.1e})"
        ),
        metrics=metrics,
        details={
            "batch": batch,
            "prompt_len": prompt_len,
            "max_seq": max_seq,
            "decode_tokens_requested": decode_tokens,
            "decode_tokens_effective": decode_tokens_effective,
            "decode_tokens_clamped": decode_tokens_effective < decode_tokens,
            "attention": "flash" if use_flash else "dense",
            "seconds_per_token": seconds,
            "max_rel_logit_diff": max_rel_diff,
            "flash_vs_dense_rel_diff": round(flash_rel_diff, 6),
            "token_agreement": token_agreement,
        },
    )
    # roofline verdict under the latency (obs/roofline.py): a decode
    # step streams every parameter plus the live KV cache per token —
    # ~2 FLOPs per weight byte, far left of the ridge, so the healthy
    # verdict is memory-bound near its bandwidth ceiling; a decode step
    # reading compute-bound means the batch is carrying it (or the
    # model is tiny). Analytic cost model: the measured program is a
    # scanned multi-step chain whose XLA totals are per-chain, not
    # per-token.
    from activemonitor_tpu.models.probe_model import param_count
    from activemonitor_tpu.obs import roofline as roofline_model

    dtype_bytes = jnp.dtype(cfg.dtype).itemsize
    param_bytes = param_count(cfg) * dtype_bytes
    cache_bytes = (
        2 * batch * max_seq * cfg.n_layers * cfg.kv_heads
        * cfg.head_dim * dtype_bytes
    )
    roofline_model.apply(
        result,
        roofline_model.capture(
            "decode",
            seconds=seconds,
            model_flops=2.0 * param_count(cfg) * batch,
            model_bytes=float(param_bytes + cache_bytes),
            enabled=roofline,
        ),
    )
    return result
