"""Property-based cron invariants (hypothesis).

The example-based suite (test_cron.py) pins known behaviors; this one
asserts the invariants that must hold for EVERY expression the parser
accepts — the robfig-compatible contract the reconciler's scheduling
math builds on. A violation here is a wedged or double-fired schedule
in production, whatever the expression.
"""

import datetime

import pytest

hypothesis = pytest.importorskip(
    "hypothesis", reason="hypothesis not installed in this container"
)
from hypothesis import given, settings, strategies as st

from activemonitor_tpu.scheduler.cron import parse_cron

UTC = datetime.timezone.utc


def field(lo, hi, names=()):
    """One cron field: *, a value, a range, a step, or a small list."""
    value = st.integers(lo, hi).map(str)
    if names:
        value = st.one_of(value, st.sampled_from(names))
    rng = st.tuples(st.integers(lo, hi), st.integers(lo, hi)).map(
        lambda ab: f"{min(ab)}-{max(ab)}"
    )
    step = st.tuples(rng, st.integers(1, 10)).map(lambda rs: f"{rs[0]}/{rs[1]}")
    star_step = st.integers(1, 15).map(lambda s: f"*/{s}")
    atom = st.one_of(st.just("*"), value, rng, step, star_step)
    return st.lists(atom, min_size=1, max_size=3).map(",".join)


DOW_NAMES = ("SUN", "MON", "TUE", "WED", "THU", "FRI", "SAT")
MON_NAMES = ("JAN", "FEB", "MAR", "APR", "MAY", "JUN",
             "JUL", "AUG", "SEP", "OCT", "NOV", "DEC")

exprs = st.tuples(
    field(0, 59),          # minute
    field(0, 23),          # hour
    field(1, 28),          # day of month (≤28: every month has it)
    field(1, 12, MON_NAMES),
    field(0, 6, DOW_NAMES),
).map(" ".join)

times = st.datetimes(
    min_value=datetime.datetime(2024, 1, 1),
    max_value=datetime.datetime(2028, 12, 31),
).map(lambda d: d.replace(tzinfo=UTC))

zones = st.sampled_from(
    ["UTC", "America/New_York", "Asia/Tokyo", "Europe/Berlin",
     "Australia/Sydney", "Pacific/Chatham"]  # incl. :45 offset + DST
)


@settings(max_examples=200, deadline=None)
@given(expr=exprs, after=times)
def test_next_is_strictly_future_and_on_schedule(expr, after):
    s = parse_cron(expr)
    fire = s.next(after)
    assert fire > after
    # the fire matches every field of the expression
    minute_f, hour_f, dom_f, _mon_f, _dow_f = expr.split()
    local = fire
    if "*" not in minute_f and "/" not in minute_f and "," not in minute_f \
            and "-" not in minute_f:
        assert local.minute == int(minute_f), (expr, fire)
    if "*" not in hour_f and "/" not in hour_f and "," not in hour_f \
            and "-" not in hour_f:
        assert local.hour == int(hour_f), (expr, fire)


@settings(max_examples=100, deadline=None)
@given(expr=exprs, after=times)
def test_chained_fires_strictly_increase(expr, after):
    s = parse_cron(expr)
    t = after
    prev_utc = after.astimezone(UTC)
    for _ in range(4):
        t = s.next(t)
        t_utc = t.astimezone(UTC)
        assert t_utc > prev_utc, (expr, after, t)
        prev_utc = t_utc


@settings(max_examples=100, deadline=None)
@given(expr=exprs, after=times, zone=zones)
def test_tz_prefixed_chain_is_monotonic_in_utc(expr, after, zone):
    """Whatever the zone (DST gaps, 13:45 offsets), chained fires move
    strictly forward in REAL time — the invariant the timer wheel's
    delay math depends on."""
    s = parse_cron(f"TZ={zone} {expr}")
    t = after
    prev_utc = after.astimezone(UTC)
    for _ in range(3):
        t = s.next(t)
        t_utc = t.astimezone(UTC)
        assert t_utc > prev_utc, (zone, expr, after, t)
        prev_utc = t_utc


@settings(max_examples=100, deadline=None)
@given(after=times, zone=zones, minute=st.integers(0, 59),
       hour=st.integers(0, 23))
def test_daily_fire_lands_on_requested_wall_time_or_dst_shift(
    after, zone, minute, hour
):
    """A daily 'M H * * *' fire lands exactly on the requested local
    wall time — except on a DST transition day, where the canonical
    normalization may shift it by the gap (never by more than 2h, and
    never into the past)."""
    s = parse_cron(f"TZ={zone} {minute} {hour} * * *")
    fire = s.next(after)
    assert fire > after
    if fire.minute == minute and fire.hour == hour:
        return  # nominal wall time
    # shifted: must be a DST-gap day — the shift equals the UTC-offset
    # change across the fire, bounded by 2 hours
    same_day_earlier = fire - datetime.timedelta(hours=3)
    gap = fire.utcoffset() - same_day_earlier.utcoffset()
    assert gap != datetime.timedelta(0), (zone, minute, hour, fire)
    assert abs(gap) <= datetime.timedelta(hours=2)
