"""Kubernetes-native scrape authn/z — TokenReview + SubjectAccessReview.

The reference guards /metrics with controller-runtime's
``WithAuthenticationAndAuthorization`` filter
(/root/reference/cmd/main.go:74-81): every scrape's bearer token is
validated by the API server (TokenReview) and the resulting identity
is authorized for the endpoint (SubjectAccessReview on the
non-resource URL). This module is that filter for the aiohttp metrics
endpoint: the cluster decides who may scrape, per identity, with RBAC
— no shared static secret to rotate.

Decisions are cached per token for a short TTL (the filter would
otherwise issue two API-server round trips per scrape; controller-
runtime caches the same way). Infra failures return ``None`` so the
caller can apply its fallback policy (static token if configured,
else fail closed) — an API-server blip must not silently open the
endpoint.
"""

from __future__ import annotations

import hashlib
import heapq
import time
from typing import Dict, List, Optional, Tuple

from activemonitor_tpu.kube.client import KubeApi

TOKENREVIEW_PATH = "/apis/authentication.k8s.io/v1/tokenreviews"
SAR_PATH = "/apis/authorization.k8s.io/v1/subjectaccessreviews"


class KubeScrapeAuthorizer:
    """allowed(token) -> True | False | None (infra failure)."""

    def __init__(
        self,
        api: KubeApi,
        path: str = "/metrics",
        verb: str = "get",
        cache_ttl: float = 60.0,
        negative_ttl: float = 10.0,
        monotonic=time.monotonic,
        max_entries: int = 1024,
    ):
        self._api = api
        self._path = path
        self._verb = verb
        self._ttl = cache_ttl
        # denials age out faster: a scraper whose token/RBAC was just
        # provisioned must not keep eating 401s for a full positive TTL
        # (controller-runtime's filter uses a short failure TTL the
        # same way)
        self._neg_ttl = negative_ttl
        self._monotonic = monotonic
        self._max_entries = max_entries
        # sha256(token) -> (expiry, verdict); only definitive verdicts
        # cached. Hashing keeps raw bearer tokens out of process memory
        # dumps, and eviction is per-entry so junk-token spam cannot
        # flush the legitimate scraper's verdict wholesale
        self._cache: Dict[str, Tuple[float, bool]] = {}
        # (expiry, key) min-heap mirroring the cache, with lazy
        # invalidation (a re-remembered key leaves its old heap entry
        # behind; the pop loop skips entries whose expiry no longer
        # matches). Keeps eviction O(log n) per insert — a junk-token
        # flood at capacity must not pay a full-cache scan per request
        self._expiries: List[Tuple[float, str]] = []

    @staticmethod
    def _key(token: str) -> str:
        return hashlib.sha256(token.encode()).hexdigest()

    async def allowed(self, token: str) -> Optional[bool]:
        if not token:
            return False
        now = self._monotonic()
        key = self._key(token)
        hit = self._cache.get(key)
        if hit is not None and hit[0] > now:
            return hit[1]

        try:
            review = await self._api.create(
                TOKENREVIEW_PATH,
                {
                    "apiVersion": "authentication.k8s.io/v1",
                    "kind": "TokenReview",
                    "spec": {"token": token},
                },
            )
        except Exception:
            # includes 401/403 on OUR credentials (a setup problem —
            # missing system:auth-delegator binding — not a verdict on
            # the scraper): every failure to ASK is an infra failure,
            # never a deny
            return None
        status = review.get("status") or {}
        if not status.get("authenticated"):
            self._remember(key, False, now)
            return False
        user = status.get("user") or {}

        try:
            sar = await self._api.create(
                SAR_PATH,
                {
                    "apiVersion": "authorization.k8s.io/v1",
                    "kind": "SubjectAccessReview",
                    "spec": {
                        "user": user.get("username", ""),
                        "groups": user.get("groups") or [],
                        "uid": user.get("uid", ""),
                        "nonResourceAttributes": {
                            "path": self._path,
                            "verb": self._verb,
                        },
                    },
                },
            )
        except Exception:
            return None
        verdict = bool((sar.get("status") or {}).get("allowed"))
        self._remember(key, verdict, now)
        return verdict

    def _remember(self, key: str, verdict: bool, now: float) -> None:
        if key not in self._cache and len(self._cache) >= self._max_entries:
            # bound memory under token churn WITHOUT collateral damage:
            # the heap yields expired entries first, then the soonest-
            # to-expire — a spammer cycling junk tokens (shortest,
            # negative TTLs) evicts its own junk, not the legitimate
            # scraper's fresh verdict
            while self._expiries and len(self._cache) >= self._max_entries:
                exp, k = heapq.heappop(self._expiries)
                live = self._cache.get(k)
                if live is not None and live[0] == exp:
                    del self._cache[k]
        ttl = self._ttl if verdict else self._neg_ttl
        expiry = now + ttl
        self._cache[key] = (expiry, verdict)
        heapq.heappush(self._expiries, (expiry, key))
        if len(self._expiries) > 2 * self._max_entries:
            # compact stale (re-remembered) heap entries so the heap
            # stays O(max_entries) even under verdict refresh churn
            self._expiries = [
                (exp, k)
                for k, (exp, _v) in self._cache.items()
            ]
            heapq.heapify(self._expiries)
