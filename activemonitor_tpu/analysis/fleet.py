"""Cross-check straggler ranking over analysis cohorts.

A per-check baseline answers "is this slice worse than it used to be";
a cohort answers "is this slice worse than its PEERS right now" — the
straggler question a fleet of identical v5e-8 slices actually asks.
Checks sharing a ``spec.analysis.cohort`` label contribute their latest
value per metric; a member whose value sits far from the cohort median
(in cohort-MAD sigmas) is an outlier even if its own baseline has
quietly adapted to a slow decline — the failure mode per-check
statistics cannot see.

Pure bookkeeping (no clock, no I/O), same shape as the flap tracker:
the engine owns when to record and what an outlier verdict does.
"""

from __future__ import annotations

import statistics
from typing import Dict, List, Optional, Tuple

from activemonitor_tpu.analysis.baseline import (
    ABSOLUTE_SCALE_FLOOR,
    MAD_TO_SIGMA,
    RELATIVE_SCALE_FLOOR,
)

# fewer members can't support a median/MAD verdict: with two, each
# member is always exactly one MAD from the median of the pair
MIN_COHORT_SIZE = 3

DEFAULT_OUTLIER_SIGMAS = 3.0


class CohortIndex:
    """Latest value per (cohort, metric, check) + outlier ranking."""

    def __init__(self) -> None:
        # (cohort, metric) -> {check key -> latest value}
        self._values: Dict[Tuple[str, str], Dict[str, float]] = {}
        # check key -> cohort it last reported under (forget/move cleanup)
        self._member_cohort: Dict[str, str] = {}

    def record(self, cohort: str, metric: str, key: str, value: float) -> None:
        if not cohort or not metric:
            return
        previous = self._member_cohort.get(key)
        if previous is not None and previous != cohort:
            # the spec's cohort label changed: the check's samples must
            # not keep skewing the old cohort's median
            self.forget(key)
        self._member_cohort[key] = cohort
        self._values.setdefault((cohort, metric), {})[key] = float(value)

    def forget(self, key: str) -> None:
        self._member_cohort.pop(key, None)
        for members in self._values.values():
            members.pop(key, None)

    def members(self, cohort: str) -> List[str]:
        keys: set = set()
        for (c, _metric), values in self._values.items():
            if c == cohort:
                keys.update(values)
        return sorted(keys)

    def cohorts(self) -> List[str]:
        """Every cohort with at least one recorded value — the sweep
        surface for fleet-wide consumers (resilience/adapt.py walks it
        looking for contended members)."""
        return sorted({c for (c, _metric) in self._values})

    def scores(self, cohort: str, metric: str) -> Dict[str, float]:
        """Per-member deviation from the cohort median in cohort-MAD
        sigmas (signed: negative = below the cohort). Empty below
        :data:`MIN_COHORT_SIZE` members — no verdict beats a made-up
        one, same convention as the SLO layer's empty window."""
        values = self._values.get((cohort, metric)) or {}
        if len(values) < MIN_COHORT_SIZE:
            return {}
        center = statistics.median(values.values())
        mad = statistics.median(abs(v - center) for v in values.values())
        floor = max(ABSOLUTE_SCALE_FLOOR, RELATIVE_SCALE_FLOOR * abs(center))
        scale = max(floor, MAD_TO_SIGMA * mad)
        return {key: (value - center) / scale for key, value in values.items()}

    def outliers(
        self, cohort: str, metric: str, sigmas: float = DEFAULT_OUTLIER_SIGMAS
    ) -> List[Tuple[str, float]]:
        """Members beyond ``sigmas`` from the cohort median, worst
        first — the straggler ranking."""
        flagged = [
            (key, score)
            for key, score in self.scores(cohort, metric).items()
            if abs(score) >= sigmas
        ]
        return sorted(flagged, key=lambda item: -abs(item[1]))

    def is_outlier(
        self,
        cohort: str,
        metric: str,
        key: str,
        sigmas: float = DEFAULT_OUTLIER_SIGMAS,
    ) -> bool:
        score = self.scores(cohort, metric).get(key)
        return score is not None and abs(score) >= sigmas

    def worst_score(self, cohort: str, key: str) -> Optional[float]:
        """The member's largest-magnitude deviation across every metric
        its cohort tracks (None outside any scored cohort) — one number
        for the /statusz analysis block."""
        worst: Optional[float] = None
        for (c, metric) in list(self._values.keys()):
            if c != cohort:
                continue
            score = self.scores(cohort, metric).get(key)
            if score is not None and (worst is None or abs(score) > abs(worst)):
                worst = score
        return worst
