"""Declarative scenario matrix — the bench/probe observatory.

bench.py used to run one hand-picked metric per round, so scenario
coverage grew only when someone wrote a new probe, and a regression
between rounds was invisible unless a human diffed artifacts. This
module is the ReFrame-style answer (PAPERS.md, arXiv:2404.10536 —
benchmarking ML on heterogeneous architectures): a config-file spec —
mesh shape × dtype × op × schedule variant — expands into a run
matrix, and every cell's result flows through the SAME evidence stack
the controller's checks already ride:

- **cells are config, not code**: each cell re-meshes by a
  partition-rule tuple (the ops layer resolves layouts from rules,
  parallel/partition.py, PR 10) and picks its collective from the
  autotune decision table (parallel/autotune.py, PR 8) — adding a
  scenario is an edit to ``config/bench_matrix.json``, not a PR.
- **per-(cell, metric) rolling baselines** (analysis/baseline.py)
  persisted to a durable ``BENCH_BASELINES.json`` sidecar
  (:func:`activemonitor_tpu.analysis.baseline.save_blob`) so they
  survive across rounds; a corrupt or version-skewed sidecar restores
  FRESH with a structured warning, never a crash or half-parsed stats.
- **hysteresis verdicts** (analysis/detector.py, ``jump_to_raw``): a
  lone noisy round never moves the reported state; two confirming
  rounds escalate it to the confirmed raw level.
- **a roofline stamp per cell** (obs/roofline.py): a confirmed
  regression names WHICH ceiling moved (compute/memory/comm), with the
  cost source labeled (always ``model`` here — analytic estimates,
  interpret-mode runs are never compared against a TPU bar).
- **auto-bisect on confirmed regression**: the cell re-runs exactly
  once against the prior artifact's value, and a flight-recorder
  bundle (obs/flightrec.py, ``matrix-regression``) captures both
  rounds' cell evidence plus the bisect verdict.

Surfaces: the pinned ``healthcheck_matrix_*`` Prometheus families
(metrics/collector.py), the ``/statusz`` fleet ``matrix`` block
(obs/slo.py — :class:`SidecarView` serves the durable sidecar to a
controller that didn't run the round), the ``am-tpu matrix`` CLI verb,
and bench.py stamping ``matrix_summary`` into every artifact on both
the TPU and CPU-fallback paths (the fallback labels ``interpret_mode``
and carries ``fallback_reason`` into every cell).

Clock discipline like the rest of analysis/: no wall-clock reads
(``hack/lint.py`` bans them here) — the executor's timer is injectable
(the :class:`~activemonitor_tpu.probes.base.PhaseTimings` idiom), and
all verdict machinery runs on the injectable Clock so scripted-timing
tests are deterministic.
"""

from __future__ import annotations

import logging
import math
import time
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Tuple

from activemonitor_tpu.analysis import baseline as baseline_store
from activemonitor_tpu.analysis.baseline import CheckBaselines
from activemonitor_tpu.analysis.detector import (
    DetectorConfig,
    Hysteresis,
    LEVEL_DEGRADED,
    LEVEL_OK,
    combine_raw_levels,
    default_detectors,
    finite,
    level_name,
)
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.matrix")

MATRIX_VERSION = 1

# the durable sidecar's conventional basename (bench.py writes it next
# to the BENCH_r*.json artifacts; the controller's --matrix-state
# points at the same file)
SIDECAR_BASENAME = "BENCH_BASELINES.json"

STATUS_OK = "ok"
STATUS_SKIPPED = "skipped"
STATUS_ERROR = "error"

# structured per-cell skip reasons (the silent-omission ban: a cell
# that cannot run is a visible, machine-readable hole with the thing
# it lacked named — never a crash, never silently absent)
SKIP_UNKNOWN_OP = "unknown-op"
SKIP_UNKNOWN_DTYPE = "unknown-dtype"
SKIP_UNSUPPORTED_DTYPE = "unsupported-dtype"
SKIP_MISSING_AXIS = "missing-mesh-axis"
SKIP_UNKNOWN_SCHEDULE = "unknown-schedule"
SKIP_DEVICES = "insufficient-devices"
SKIP_QUICK = "quick-mode"
# frontdoor-replay pointed at an ACTIVEMONITOR_REPLAY_TRACE journal
# that is empty or restored fresh (torn chain): a structured skip, not
# a bogus zero-request measurement
SKIP_NO_TRACE = "no-trace"

# schedule tokens an accepts_schedule op can honor: "auto" (the
# autotune decision table) plus the zoo tokens the tuned dispatch
# implements (parallel/autotune._ALL_REDUCE_IMPL — mirrored here so
# expansion stays jax-free; the expansion test pins the mirror against
# the probe layer's GRAD_SYNC_SCHEDULES)
KNOWN_SCHEDULES = ("auto", "xla", "rsag", "recdouble", "tree")

# auto-bisect outcomes (healthcheck_matrix_bisect_runs_total{outcome=})
BISECT_REPRODUCED = "reproduced"
BISECT_RECOVERED = "recovered"
BISECT_ERROR = "error"

_DTYPE_ALIASES = {
    "bf16": "bfloat16",
    "bfloat16": "bfloat16",
    "f32": "float32",
    "fp32": "float32",
    "float32": "float32",
}
_DTYPE_SHORT = {"bfloat16": "bf16", "float32": "f32"}


def canonical_dtype(token) -> Optional[str]:
    """Canonical dtype name for a spec token, or None (unknown tokens
    become structured skips, not KeyErrors)."""
    return _DTYPE_ALIASES.get(str(token).strip().lower())


@dataclass(frozen=True)
class OpDef:
    """One scenario op's declared requirements — what :func:`expand`
    validates cells against, so an impossible combination is a
    structured skip at expansion time, never a tracer crash inside a
    runner."""

    name: str
    required_axes: Tuple[str, ...]  # mesh axes the op shards over
    dtypes: Tuple[str, ...]  # canonical dtype names it supports
    # which autotune decision table the op's dominant collective rides
    # ("allreduce" | "allgather" | "" = no tuned collective), and
    # whether an EXPLICIT schedule token can actually be threaded into
    # the op — an op whose dispatch is internal (always schedule
    # "auto") must not expand over variants it cannot honor: the
    # matrix would report distinct scenarios for identical runs
    collective: str = ""
    accepts_schedule: bool = False
    # whether the op expands over the spec's payload octaves
    # ("payloads_kb") — raw collective ops whose regime IS the payload
    # (the hierarchical all-reduce's latency-vs-bandwidth crossover);
    # compute ops carry their own fixed shapes and never multiply
    accepts_payload: bool = False
    # whether the op expands over the spec's batch ceilings
    # ("batch_ceilings") — the serving op's admission regime IS the
    # in-flight batch ceiling, the way a collective's is its payload
    accepts_batch: bool = False
    # fixed op-declared scenario variants (ISSUE 20: the serving-disagg
    # op's topology ladder — colocated baseline, pool split, split with
    # prefix cache, split with speculation). Declared on the op, not
    # the spec: the ladder is the op's contract, and a spec cannot
    # invent a variant no runner implements
    variants: Tuple[str, ...] = ()


# payload octaves (KB) a payload-accepting op expands over when the
# spec doesn't say: one cell below the default latency threshold
# (64 KB — parallel/autotune.DEFAULT_LATENCY_THRESHOLD_BYTES) and one
# well above it, so both sides of the small-message crossover get a
# baseline from round one
DEFAULT_PAYLOADS_KB = (16, 4096)

# batch-ceiling octaves a batch-accepting op (serving) expands over
# when the spec doesn't say: a narrow and a wide admission ceiling, so
# occupancy-vs-latency tradeoffs get a baseline from round one
DEFAULT_BATCH_CEILINGS = (2, 4)


# the op registry: flash/ring/moe/pipeline/decode/training-step — the
# scenario classes ROADMAP item 2 names. decode is deliberately
# float32-only (its fused-vs-dense gate is a numerics contract): a
# bf16 decode cell in the spec exercises the unsupported-dtype skip.
# moe's token gather is an internal autotune.all_gather("auto") —
# tuned, but not variant-addressable; ring rides hand-written ppermute
# schedules, not the autotune table.
OPS: Dict[str, OpDef] = {
    "flash": OpDef("flash", (), ("bfloat16", "float32")),
    "ring": OpDef("ring", ("sp",), ("bfloat16", "float32")),
    "moe": OpDef(
        "moe", ("ep",), ("bfloat16", "float32"), collective="allgather"
    ),
    "pipeline": OpDef(
        "pipeline",
        ("pp",),
        ("bfloat16", "float32"),
        collective="allreduce",
        accepts_schedule=True,
    ),
    "decode": OpDef("decode", (), ("float32",)),
    "training-step": OpDef(
        "training-step",
        ("data", "model"),
        ("bfloat16", "float32"),
        collective="allreduce",
        accepts_schedule=True,
    ),
    # the hierarchical DCN×ICI all-reduce (parallel/schedules.py):
    # dispatch is the tuned two-tier surface (autotune.hier_plan picks
    # latency vs bandwidth per payload), so it expands over payload
    # octaves, not schedule variants — the payload IS the scenario
    "hier-allreduce": OpDef(
        "hier-allreduce",
        ("dcn", "ici"),
        ("bfloat16", "float32"),
        collective="allreduce",
        accepts_payload=True,
    ),
    # the continuous-batching serving loop (ops/kv_cache.py paged KV +
    # scheduler/serving.py admission; probes/serving.py engine): kv
    # heads shard over "model" via the kv partition rules, and the
    # scenario dimension is the admission BATCH CEILING, not a payload
    # or schedule. float32-only like decode (the continuous-vs-static
    # logits gate is a numerics contract).
    "serving": OpDef(
        "serving", ("model",), ("float32",), accepts_batch=True
    ),
    # the disaggregated serving ladder (ISSUE 20: scheduler/pools.py
    # split + ops/kv_cache.py prefix cache + speculative decoding):
    # one cell per topology variant under the SAME mixed hot-prefix
    # workload, so colocated-vs-split regressions are adjacent rows.
    # Needs devices for both pools ("model" axis product), so the
    # {model:16} spec row lands as a structured device-deficit skip —
    # an infeasible pool shape is a visible skip, not a hole.
    "serving-disagg": OpDef(
        "serving-disagg",
        ("model",),
        ("float32",),
        variants=("colo", "split", "split-prefix", "split-spec"),
    ),
    # recorded front-door traffic replayed through the real submit path
    # (obs/replay.py over obs/journal.py's arrival stream): the bench
    # measures the traffic users actually sent, not a synthetic Poisson
    # stand-in. Single-chip and jax-free (the workload is admission +
    # coalescing arithmetic); float32-only so the spec's bf16 column
    # exercises the unsupported-dtype skip like decode's.
    "frontdoor-replay": OpDef("frontdoor-replay", (), ("float32",)),
}


@dataclass(frozen=True)
class CellSpec:
    """One expanded matrix cell. ``mesh`` is the ordered partition-rule
    tuple of (axis, size) pairs the cell re-meshes by — restricted to
    the op's required axes, so two meshes that agree on them yield the
    SAME cell (deduped at expansion). ``payload_kb`` is set only for
    payload-accepting ops (None keeps every pre-existing cell id
    stable — baselines in the sidecar survive the field's arrival)."""

    op: str
    mesh: Tuple[Tuple[str, int], ...]
    dtype: str  # canonical dtype name
    schedule: str  # "auto" | explicit zoo token | "-" (no collective)
    payload_kb: Optional[int] = None  # payload octave (accepts_payload ops)
    batch: Optional[int] = None  # admission ceiling (accepts_batch ops)
    variant: Optional[str] = None  # op-declared topology variant

    @property
    def mesh_id(self) -> str:
        if not self.mesh:
            return "1chip"
        return "x".join(f"{axis}{size}" for axis, size in self.mesh)

    @property
    def cell_id(self) -> str:
        short = _DTYPE_SHORT.get(self.dtype, self.dtype)
        parts = [self.op, self.mesh_id, short]
        if self.schedule != "-":
            parts.append(self.schedule)
        if self.payload_kb is not None:
            parts.append(f"{self.payload_kb}kb")
        if self.batch is not None:
            parts.append(f"b{self.batch}")
        if self.variant is not None:
            parts.append(self.variant)
        return "/".join(parts)

    @property
    def devices_needed(self) -> int:
        n = 1
        for _axis, size in self.mesh:
            n *= size
        return n


@dataclass
class CellResult:
    """One cell's outcome for one round — measured by a runner,
    scripted by a test executor, or pre-skipped at expansion."""

    cell: CellSpec
    status: str
    reason: str = ""
    value: Optional[float] = None  # headline measurement
    metric: str = "seconds"  # headline metric name
    unit: str = "s"
    seconds: float = 0.0  # measured seconds per op (roofline input)
    flops: float = 0.0  # analytic cost model: FLOPs per op
    bytes_accessed: float = 0.0  # analytic cost model: HBM bytes per op
    schedule: str = ""  # resolved collective schedule token
    details: Dict = field(default_factory=dict)


def skipped_result(cell: CellSpec, reason_code: str, detail: str) -> CellResult:
    return CellResult(
        cell,
        STATUS_SKIPPED,
        reason=f"{reason_code}: {detail}",
        details={"skip": {"code": reason_code, "detail": detail}},
    )


# ---------------------------------------------------------------------
# spec loading + expansion
# ---------------------------------------------------------------------

DEFAULT_SPEC: dict = {
    "version": MATRIX_VERSION,
    "ops": [
        "flash", "ring", "moe", "pipeline", "decode", "training-step",
        "hier-allreduce", "serving", "serving-disagg", "frontdoor-replay",
    ],
    "meshes": [
        {"sp": 8},
        {"ep": 8},
        {"data": 2, "model": 2, "pp": 2},
        # the two-tier rows: 2x4 runs on the 8-device test platform;
        # 2x8 is the deliberate single-process impossibility that must
        # land as a structured device-deficit skip, not a hole
        {"dcn": 2, "ici": 4},
        {"dcn": 2, "ici": 8},
    ],
    "dtypes": ["bf16", "f32"],
    "schedules": ["auto"],
    "payloads_kb": list(DEFAULT_PAYLOADS_KB),
    "batch_ceilings": list(DEFAULT_BATCH_CEILINGS),
}


def load_spec(path: Optional[str]) -> Tuple[dict, Optional[dict]]:
    """The matrix spec from a config file, defensively: a missing path
    is the default spec (no warning — config is optional); anything
    unreadable/corrupt/mis-shaped is the default spec PLUS a structured
    warning, so a fat-fingered config degrades to known coverage
    instead of crashing the bench round."""
    if not path:
        return dict(DEFAULT_SPEC), None
    import json

    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return dict(DEFAULT_SPEC), None
    except (OSError, json.JSONDecodeError) as exc:
        return dict(DEFAULT_SPEC), {
            "reason": "spec-unreadable",
            "detail": f"{path}: {exc}"[:200],
        }
    if not isinstance(doc, dict):
        return dict(DEFAULT_SPEC), {
            "reason": "spec-shape",
            "detail": f"{path}: top level is {type(doc).__name__}",
        }
    spec = dict(DEFAULT_SPEC)
    for key in (
        "ops", "meshes", "dtypes", "schedules", "payloads_kb",
        "batch_ceilings",
    ):
        value = doc.get(key)
        if isinstance(value, list) and value:
            spec[key] = value
    if "version" in doc:
        spec["version"] = doc["version"]
    return spec, None


def expand(
    spec: dict, n_devices: Optional[int] = None
) -> Tuple[List[CellSpec], List[CellResult]]:
    """Expand a spec into ``(runnable, skipped)``.

    Every invalid combination is a structured skipped
    :class:`CellResult` naming exactly what the cell lacked (the absent
    mesh axis, the unsupported dtype, the device deficit) — the matrix
    has no silent holes and expansion never raises on spec content.
    Cells that agree on an op's required axes dedupe (first mesh wins);
    ops that use no collective do not multiply over schedule variants.
    """
    runnable: List[CellSpec] = []
    skipped: List[CellResult] = []
    seen: set = set()
    # payload octaves for accepts_payload ops, parsed ONCE per expand:
    # malformed tokens degrade to the default octaves (known coverage
    # over a crashed round)
    parsed_payloads: List[int] = []
    for token in spec.get("payloads_kb") or list(DEFAULT_PAYLOADS_KB):
        try:
            value = int(token)
        except (TypeError, ValueError):
            continue
        if value > 0:
            parsed_payloads.append(value)
    payload_octaves = parsed_payloads or list(DEFAULT_PAYLOADS_KB)
    # batch ceilings for accepts_batch ops, same degradation contract
    parsed_batches: List[int] = []
    for token in spec.get("batch_ceilings") or list(DEFAULT_BATCH_CEILINGS):
        try:
            value = int(token)
        except (TypeError, ValueError):
            continue
        if value > 0:
            parsed_batches.append(value)
    batch_ceilings = parsed_batches or list(DEFAULT_BATCH_CEILINGS)
    for op_token in spec.get("ops") or []:
        op = OPS.get(str(op_token))
        for mesh_doc in spec.get("meshes") or [{}]:
            mesh_doc = mesh_doc if isinstance(mesh_doc, dict) else {}
            try:
                full_mesh = tuple(
                    (str(axis), int(size)) for axis, size in mesh_doc.items()
                )
            except (TypeError, ValueError):
                full_mesh = ()
            for dtype_token in spec.get("dtypes") or ["f32"]:
                canonical = canonical_dtype(dtype_token)
                schedules = list(spec.get("schedules") or ["auto"])
                if op is None or not op.collective:
                    schedules = ["-"]
                elif not op.accepts_schedule:
                    # internal dispatch is always "auto": explicit
                    # variants cannot be threaded in, so expanding
                    # them would label identical runs as distinct
                    # scenarios
                    schedules = ["auto"]
                # payload octaves only for ops whose regime IS the
                # payload (the hierarchical all-reduce crossover);
                # batch ceilings only for the serving-shaped ops whose
                # regime is the admission ceiling
                payloads: List[Optional[int]] = (
                    list(payload_octaves)
                    if op is not None and op.accepts_payload
                    else [None]
                )
                batches: List[Optional[int]] = (
                    list(batch_ceilings)
                    if op is not None and op.accepts_batch
                    else [None]
                )
                variants: List[Optional[str]] = (
                    list(op.variants)
                    if op is not None and op.variants
                    else [None]
                )
                for schedule, payload_kb, batch, variant in (
                    (s, p, b, v)
                    for s in schedules
                    for p in payloads
                    for b in batches
                    for v in variants
                ):
                    cell = CellSpec(
                        op=str(op_token),
                        mesh=full_mesh,
                        dtype=canonical or str(dtype_token),
                        schedule=str(schedule),
                        payload_kb=payload_kb,
                        batch=batch,
                        variant=variant,
                    )
                    if cell.cell_id in seen:
                        # alias dtype tokens ("bf16" + "bfloat16") and
                        # repeated entries canonicalize to the same
                        # cell: one row, one count — runnable or skip
                        continue
                    if op is None:
                        seen.add(cell.cell_id)
                        skipped.append(
                            skipped_result(
                                cell,
                                SKIP_UNKNOWN_OP,
                                f"op {op_token!r} not in registry "
                                f"({', '.join(sorted(OPS))})",
                            )
                        )
                        continue
                    missing = [
                        axis
                        for axis in op.required_axes
                        if axis not in dict(full_mesh)
                    ]
                    if missing:
                        # inherently mesh-specific: the skip names THIS
                        # mesh, so it keeps the full-mesh cell id
                        seen.add(cell.cell_id)
                        skipped.append(
                            skipped_result(
                                cell,
                                SKIP_MISSING_AXIS,
                                f"op {op.name!r} needs mesh axis "
                                f"{missing[0]!r}; mesh has "
                                f"{dict(full_mesh) or '{}'}",
                            )
                        )
                        continue
                    # the cell's partition-rule tuple: ONLY the op's
                    # required axes (two meshes agreeing on them are
                    # the same scenario) — restricted BEFORE the dtype
                    # checks, so a dtype skip carries the same
                    # canonical id its runnable siblings use and
                    # dedupes across meshes like they do
                    cell = replace(
                        cell,
                        mesh=tuple(
                            (axis, dict(full_mesh)[axis])
                            for axis in op.required_axes
                        ),
                    )
                    if cell.cell_id in seen:
                        continue  # dedupe, not a hole: same scenario
                    seen.add(cell.cell_id)
                    if canonical is None:
                        skipped.append(
                            skipped_result(
                                cell,
                                SKIP_UNKNOWN_DTYPE,
                                f"dtype token {dtype_token!r} is not a "
                                "known dtype",
                            )
                        )
                        continue
                    if canonical not in op.dtypes:
                        skipped.append(
                            skipped_result(
                                cell,
                                SKIP_UNSUPPORTED_DTYPE,
                                f"op {op.name!r} does not support "
                                f"{canonical} (supports: "
                                f"{', '.join(op.dtypes)})",
                            )
                        )
                        continue
                    if (
                        cell.schedule != "-"
                        and cell.schedule not in KNOWN_SCHEDULES
                    ):
                        # a config typo must read as a structured skip,
                        # not a raw ValueError from deep in a runner
                        skipped.append(
                            skipped_result(
                                cell,
                                SKIP_UNKNOWN_SCHEDULE,
                                f"schedule {cell.schedule!r} is not a "
                                "known token (known: "
                                f"{', '.join(KNOWN_SCHEDULES)})",
                            )
                        )
                        continue
                    if (
                        n_devices is not None
                        and cell.devices_needed > n_devices
                    ):
                        skipped.append(
                            skipped_result(
                                cell,
                                SKIP_DEVICES,
                                f"needs {cell.devices_needed} devices, "
                                f"have {n_devices}",
                            )
                        )
                        continue
                    runnable.append(cell)
    return runnable, skipped


def quick_slice(cells: List[CellSpec], limit: int = 2) -> List[CellSpec]:
    """The cheap tier-1 slice: single-device cells first (flash/decode
    compile in seconds on the CPU platform), then whatever else, capped
    at ``limit`` — the full matrix is the slow-marked soak's job."""
    ordered = sorted(cells, key=lambda c: (c.devices_needed, c.cell_id))
    return ordered[: max(0, limit)]


# ---------------------------------------------------------------------
# the default executor (the only jax-touching corner; imports lazy)
# ---------------------------------------------------------------------


def _time_op(fn, args, iters: int, timer: Callable[[], float]) -> float:
    """Min-of-iters seconds for one compiled op (first call pays the
    compile and is discarded)."""
    import jax

    jax.block_until_ready(fn(*args))
    best = math.inf
    for _ in range(max(1, iters)):
        start = timer()
        jax.block_until_ready(fn(*args))
        best = min(best, timer() - start)
    return max(best, 1e-9)


def _cell_mesh(cell: CellSpec):
    import jax

    from activemonitor_tpu.parallel.mesh import make_mesh

    need = cell.devices_needed
    devices = jax.devices()
    if need > len(devices):
        raise _CellSkip(
            SKIP_DEVICES, f"needs {need} devices, have {len(devices)}"
        )
    return make_mesh(
        tuple(axis for axis, _size in cell.mesh),
        tuple(size for _axis, size in cell.mesh),
        devices=devices[:need],
    )


class _CellSkip(Exception):
    def __init__(self, code: str, detail: str):
        super().__init__(f"{code}: {detail}")
        self.code = code
        self.detail = detail


def _resolve_schedule(
    cell: CellSpec,
    axis_n: int,
    payload_bytes: int,
    dtype,
    collective: str = "allreduce",
):
    """The collective schedule the cell rides: an explicit token
    passes through; ``auto`` consults the op's OWN autotune decision
    table (``collective`` names it — an all-gather op must not stamp
    an allreduce-table token) and falls back to the XLA builtin when
    nothing is tuned for this (axis size, payload octave, dtype)."""
    if cell.schedule not in ("auto", "-"):
        return cell.schedule
    if cell.schedule == "-":
        return ""
    from activemonitor_tpu.parallel import autotune

    return autotune.lookup(collective, axis_n, payload_bytes, dtype) or "xla"


def _run_flash(cell: CellSpec, iters: int, timer) -> CellResult:
    import jax
    import jax.numpy as jnp

    from activemonitor_tpu.ops.flash_attention import flash_attention

    dt = jnp.dtype(cell.dtype)
    b, s, h, d = 1, 128, 2, 64
    keys = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), dt) for kk in keys)
    fn = jax.jit(
        lambda a, bb, c: flash_attention(
            a, bb, c, causal=True, block_q=64, block_k=64
        )
    )
    seconds = _time_op(fn, (q, k, v), iters, timer)
    flops = 4.0 * b * h * s * s * d * 0.5  # causal halves the score work
    hbm = 4.0 * b * s * h * d * dt.itemsize
    return CellResult(
        cell, STATUS_OK, value=seconds, seconds=seconds,
        flops=flops, bytes_accessed=hbm,
    )


def _run_ring(cell: CellSpec, iters: int, timer) -> CellResult:
    import jax
    import jax.numpy as jnp

    from activemonitor_tpu.ops.ring_attention import ring_attention

    mesh = _cell_mesh(cell)
    n = dict(cell.mesh)["sp"]
    dt = jnp.dtype(cell.dtype)
    b, s, h, d = 1, 16 * n, 2, 16
    keys = jax.random.split(jax.random.key(1), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), dt) for kk in keys)
    fn = jax.jit(
        lambda a, bb, c: ring_attention(
            a, bb, c, mesh, "sp", causal=True, variant="overlap"
        )
    )
    seconds = _time_op(fn, (q, k, v), iters, timer)
    flops = 4.0 * b * h * s * s * d * 0.5
    hbm = 4.0 * b * s * h * d * dt.itemsize
    return CellResult(
        cell, STATUS_OK, value=seconds, seconds=seconds,
        flops=flops, bytes_accessed=hbm,
    )


def _run_moe(cell: CellSpec, iters: int, timer) -> CellResult:
    import jax
    import jax.numpy as jnp

    from activemonitor_tpu.ops.moe import init_moe_params, moe_ffn_expert_parallel

    mesh = _cell_mesh(cell)
    n = dict(cell.mesh)["ep"]
    dt = jnp.dtype(cell.dtype)
    d_model, d_ff, tokens = 32, 64, 8 * n
    params = init_moe_params(jax.random.key(2), d_model, d_ff, n_experts=n)
    x = jax.random.normal(jax.random.key(3), (tokens, d_model), dt)
    fn = jax.jit(lambda p, xx: moe_ffn_expert_parallel(p, xx, mesh, axis="ep"))
    seconds = _time_op(fn, (params, x), iters, timer)
    payload = tokens * d_model * dt.itemsize
    flops = 4.0 * tokens * d_model * d_ff + 2.0 * tokens * d_model * n
    hbm = (
        float(sum(leaf.size for leaf in jax.tree.leaves(params))) * 4
        + 2.0 * tokens * d_model * dt.itemsize
    )
    return CellResult(
        cell, STATUS_OK, value=seconds, seconds=seconds,
        flops=flops, bytes_accessed=hbm,
        # the token gather is autotune.all_gather("auto") inside the
        # op: stamp the ALLGATHER table's decision, the one that ran
        schedule=_resolve_schedule(cell, n, payload, dt, "allgather"),
    )


def _run_pipeline(cell: CellSpec, iters: int, timer) -> CellResult:
    import jax
    import jax.numpy as jnp

    from activemonitor_tpu.models.probe_model import ProbeModelConfig, init_params
    from activemonitor_tpu.ops.pipeline import (
        pipeline_forward_blocks,
        stack_layer_params,
    )

    mesh = _cell_mesh(cell)
    n = dict(cell.mesh)["pp"]
    dt = jnp.dtype(cell.dtype)
    cfg = ProbeModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_layers=n, d_ff=64,
        max_seq_len=32, dtype=dt,
    )
    stacked = stack_layer_params(init_params(jax.random.key(4), cfg)["layers"])
    b, s = n, 16
    x = jax.random.normal(jax.random.key(5), (b, s, cfg.d_model), dt)
    schedule = _resolve_schedule(
        cell, n, b * s * cfg.d_model * dt.itemsize, dt
    )
    fn = jax.jit(
        lambda layers, xx: pipeline_forward_blocks(
            layers, xx, cfg, mesh, axis="pp",
            allreduce_schedule=schedule or "auto",
        )
    )
    seconds = _time_op(fn, (stacked, x), iters, timer)
    flops = 32.0 * cfg.n_layers * b * s * cfg.d_model * cfg.d_model
    hbm = (
        float(sum(leaf.size for leaf in jax.tree.leaves(stacked))) * 4
        + 2.0 * b * s * cfg.d_model * dt.itemsize
    )
    return CellResult(
        cell, STATUS_OK, value=seconds, seconds=seconds,
        flops=flops, bytes_accessed=hbm, schedule=schedule,
    )


def _run_decode(cell: CellSpec, iters: int, timer) -> CellResult:
    import jax
    import jax.numpy as jnp

    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        decode_step,
        init_kv_cache,
        init_params,
    )

    dt = jnp.dtype(cell.dtype)
    cfg = ProbeModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, max_seq_len=16, dtype=dt,
    )
    params = init_params(jax.random.key(6), cfg)
    batch, steps = 2, 4
    tokens = jax.random.randint(
        jax.random.key(7), (batch, steps), 0, cfg.vocab_size
    )

    def run(p, toks):
        cache = init_kv_cache(cfg, batch, 8)
        logits = None
        for pos in range(steps):
            logits, cache = decode_step(
                p, cache, toks[:, pos], jnp.int32(pos), cfg, use_flash=True
            )
        return logits

    fn = jax.jit(run)
    seconds = _time_op(fn, (params, tokens), iters, timer)
    n_params = float(sum(leaf.size for leaf in jax.tree.leaves(params)))
    flops = 2.0 * n_params * batch * steps
    hbm = n_params * 4 * steps
    return CellResult(
        cell, STATUS_OK, value=seconds, seconds=seconds,
        flops=flops, bytes_accessed=hbm,
    )


def _run_training_step(cell: CellSpec, iters: int, timer) -> CellResult:
    import dataclasses

    import jax
    import jax.numpy as jnp

    from activemonitor_tpu.models.probe_model import tiny_config
    from activemonitor_tpu.probes.training_step import (
        build_sharded_train_step,
        grad_sync_plan,
        resolve_grad_sync,
    )

    mesh = _cell_mesh(cell)
    dt = jnp.dtype(cell.dtype)
    cfg = dataclasses.replace(tiny_config(), dtype=dt)
    requested = cell.schedule if cell.schedule != "-" else "auto"
    # stamp what actually RAN: the explicit tuned sync only engages on
    # a data-only mesh (resolve_grad_sync gates everything else back to
    # the XLA-inserted reduction) — reporting the tuned token on a mesh
    # where it never dispatched would misstate the evidence
    sync_mode, sync_reason = resolve_grad_sync(mesh, "dense", requested)
    if sync_mode == "explicit":
        plan = grad_sync_plan(cfg, mesh)
        schedule = (
            plan["schedule"]
            if requested == "auto"
            else _resolve_schedule(
                cell, plan["axis_n"], plan["largest_leaf_bytes"], dt
            )
        )
        details = {"grad_sync": {"mode": sync_mode, "axis_n": plan["axis_n"]}}
    else:
        schedule = "xla(implicit)"
        details = {"grad_sync": {"mode": sync_mode, "reason": sync_reason}}
    step, params, opt, data_sh = build_sharded_train_step(
        cfg, mesh, grad_sync=requested
    )
    batch = 2 * dict(cell.mesh)["data"]
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(8), (batch, 17), 0, cfg.vocab_size),
        data_sh,
    )
    # the step donates params/opt: thread the new state through each
    # timed iteration instead of re-passing deleted buffers
    params, opt, loss = step(params, opt, tokens)
    jax.block_until_ready(loss)
    seconds = math.inf
    for _ in range(max(1, iters)):
        start = timer()
        params, opt, loss = step(params, opt, tokens)
        jax.block_until_ready(loss)
        seconds = min(seconds, timer() - start)
    seconds = max(seconds, 1e-9)
    n_params = float(sum(leaf.size for leaf in jax.tree.leaves(params)))
    flops = 6.0 * n_params * batch * 16
    hbm = 3.0 * n_params * 4
    return CellResult(
        cell, STATUS_OK, value=seconds, seconds=seconds,
        flops=flops, bytes_accessed=hbm, schedule=schedule,
        details=details,
    )


def _run_hier_allreduce(cell: CellSpec, iters: int, timer) -> CellResult:
    import jax
    import jax.numpy as jnp

    from activemonitor_tpu.parallel import autotune
    from activemonitor_tpu.parallel.partition import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = _cell_mesh(cell)
    sizes = dict(cell.mesh)
    n_dcn, n_ici = sizes["dcn"], sizes["ici"]
    n = n_dcn * n_ici
    dt = jnp.dtype(cell.dtype)
    payload_kb = cell.payload_kb or DEFAULT_PAYLOADS_KB[0]
    # per-shard payload ≈ the cell's octave; rows divide n so the
    # two-level chunking stays static-shaped
    cols = 8
    rows = max(n, (payload_kb * 1024 // dt.itemsize) // cols)
    rows -= rows % n
    shard_payload = rows * cols * dt.itemsize
    plan = autotune.hier_plan("allreduce", n_dcn, n_ici, shard_payload, dt)
    x = jnp.ones((rows * n, cols), dt)

    fn = jax.jit(
        shard_map(
            lambda v: autotune.all_reduce(
                v, ("dcn", "ici"), schedule="auto", n=(n_dcn, n_ici)
            ),
            mesh=mesh,
            in_specs=P(("dcn", "ici"), None),
            out_specs=P(("dcn", "ici"), None),
            check_vma=False,
        )
    )
    seconds = _time_op(fn, (x,), iters, timer)
    # one spelling with the probe's stdout evidence (hier_plan_label)
    schedule = autotune.hier_plan_label(plan)
    # analytic cost model: one add per element per tier pass plus the
    # wire bytes in and out of HBM — comm-shaped, so the roofline stamp
    # reads memory-bound (the honest verdict for a collective cell)
    flops = float(x.size)
    hbm = 2.0 * x.size * dt.itemsize
    return CellResult(
        cell, STATUS_OK, value=seconds, seconds=seconds,
        flops=flops, bytes_accessed=hbm, schedule=schedule,
        details={"hier_plan": plan},
    )


def _run_serving(cell: CellSpec, _iters: int, timer) -> CellResult:
    # _iters: the soak already repeats its decode step many times, so
    # the shared per-runner repeat knob has nothing further to add
    import jax.numpy as jnp

    from activemonitor_tpu.models.probe_model import ProbeModelConfig
    from activemonitor_tpu.probes import serving as serving_probe
    from activemonitor_tpu.scheduler.serving import open_loop_requests

    mesh = _cell_mesh(cell)
    tp = dict(cell.mesh)["model"]
    dt = jnp.dtype(cell.dtype)
    cfg = ProbeModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, max_seq_len=32, dtype=dt,
    )
    batch = cell.batch or DEFAULT_BATCH_CEILINGS[0]
    # a saturating arrival burst (rate far above service): the cell
    # measures steady decode-step seconds under a full batch, and the
    # kv partition rules re-mesh the paged storage over "model" (a
    # wrong layout raises into the visible error path)
    requests = open_loop_requests(
        2 * batch, 1e6, seed=9,
        prompt_len_choices=(4, 8), output_choices=(3, 4),
    )
    soak = serving_probe.run_soak(
        cfg, requests, max_batch=batch, block_size=8, timer=timer,
        mesh=mesh, tp_axis="model",
    )
    # ONE analytic cost model, the probe's own (serving_probe.
    # roofline_inputs — measured occupancy + banked-KV footprint): the
    # roofline stamp under a confirmed regression must be the same
    # model the probe exports, not a hand-copied twin
    cost = serving_probe.roofline_inputs(soak, cfg, batch)
    seconds = max(cost["seconds"], 1e-9)
    flops = cost["flops"]
    hbm = cost["bytes"]
    cons = soak.scheduler.conservation()
    return CellResult(
        cell, STATUS_OK, value=seconds, seconds=seconds,
        flops=flops, bytes_accessed=hbm,
        details={
            "serving": {
                "tokens_per_s": round(soak.tokens_per_second, 2),
                "occupancy": round(soak.occupancy, 4),
                "conserved": bool(cons["ok"]),
                "tp_axis_n": tp,
            }
        },
    )


def _run_serving_disagg(cell: CellSpec, _iters: int, timer) -> CellResult:
    # _iters: the soak repeats its decode step per generated token.
    # One cell per topology variant, all under the SAME seeded mixed
    # hot-prefix workload — colo is the PR 14 engine verbatim, split*
    # the disaggregated pools (scheduler/pools.py), so a perf delta
    # between adjacent rows is the topology, not the workload.
    import jax.numpy as jnp

    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        param_count,
    )
    from activemonitor_tpu.ops.kv_cache import kv_bytes_per_token
    from activemonitor_tpu.probes import serving as serving_probe
    from activemonitor_tpu.scheduler.serving import mixed_open_loop_requests

    _cell_mesh(cell)  # infeasible pool shapes -> structured device skip
    dt = jnp.dtype(cell.dtype)
    cfg = ProbeModelConfig(
        vocab_size=64, d_model=32, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=64, max_seq_len=32, dtype=dt,
    )
    variant = cell.variant or "colo"
    # saturating burst (rate far above service) with a hot shared
    # prefix, so the prefix-cache variants actually hit
    requests = mixed_open_loop_requests(
        6, 1e6, seed=9, prefix_len=4,
        prompt_len_choices=(8, 12), output_choices=(2, 3),
        vocab=cfg.vocab_size,
    )
    param_bytes = param_count(cfg) * dt.itemsize
    if variant == "colo":
        soak = serving_probe.run_soak(
            cfg, requests, max_batch=4, block_size=4, timer=timer,
        )
        cost = serving_probe.roofline_inputs(soak, cfg, 4)
        seconds = max(cost["seconds"], 1e-9)
        flops, hbm = cost["flops"], cost["bytes"]
        conserved = bool(soak.scheduler.conservation()["ok"])
        block = {"mode": "colocated", "conserved": conserved}
    else:
        soak = serving_probe.run_disagg_soak(
            cfg, requests, prefill_slots=2, decode_slots=4, block_size=4,
            prefix_cache=variant in ("split-prefix", "split-spec"),
            speculate=2 if variant == "split-spec" else 0,
            timer=timer,
        )
        seconds = max(soak.decode_busy / max(1, soak.decode_steps), 1e-9)
        steps = max(1, soak.decode_steps)
        mean_width = (
            len(soak.intertoken_ms) / steps if soak.intertoken_ms else 1.0
        )
        mean_banked = (
            sum(soak.banked_samples) / len(soak.banked_samples)
            if soak.banked_samples
            else 0.0
        )
        flops = 2.0 * param_count(cfg) * max(1.0, mean_width)
        hbm = float(param_bytes + mean_banked * kv_bytes_per_token(cfg))
        migration = soak.scheduler.migration_ledger()
        conserved = bool(
            soak.scheduler.conservation()["ok"] and migration["ok"]
        )
        cache = soak.scheduler.prefix_cache
        block = {
            "mode": "disaggregated",
            "conserved": conserved,
            "migration_transfers": migration["transfers"],
            "migration_bytes": migration["bytes_total"],
            "prefix_hit_ratio": (
                cache.stats()["hit_ratio"] if cache is not None else None
            ),
            "spec_acceptance": soak.scheduler.speculation()["acceptance"],
        }
    if not conserved:
        return CellResult(
            cell,
            STATUS_ERROR,
            reason="token conservation violated across the pool boundary",
        )
    return CellResult(
        cell, STATUS_OK, value=seconds, seconds=seconds,
        flops=flops, bytes_accessed=hbm,
        details={"serving_disagg": block},
    )


# canonical seeded workload for a frontdoor-replay cell with no
# recorded trace wired: a record→replay round trip over this schedule,
# so the cell still measures the replay machinery deterministically
REPLAY_CANON_REQUESTS = 64
REPLAY_CANON_RATE_RPS = 200.0
REPLAY_CANON_SEED = 17
REPLAY_CANON_CHECKS = ("bench/hc-a", "bench/hc-b", "bench/hc-c")


def _run_frontdoor_replay(cell: CellSpec, _iters: int, timer) -> CellResult:
    # _iters: the schedule already carries its own request count.
    # jax-free on purpose: the workload is the front door's pure-python
    # admission + coalescing path, so the cell runs on any platform.
    import asyncio
    import os

    from activemonitor_tpu.frontdoor.traffic import (
        open_loop_checks,
        replayed_checks,
    )
    from activemonitor_tpu.obs.replay import (
        RecordedArrivals,
        drive_requests,
        load_trace,
    )

    trace_dir = os.environ.get("ACTIVEMONITOR_REPLAY_TRACE", "")
    if trace_dir:
        schedule, warnings = load_trace(trace_dir)
        if warnings:
            raise _CellSkip(
                SKIP_NO_TRACE,
                f"trace at {trace_dir} restored fresh: "
                f"{warnings[0].get('reason')}",
            )
        if not len(schedule):
            raise _CellSkip(
                SKIP_NO_TRACE, f"no arrival events journaled in {trace_dir}"
            )
        source = trace_dir
    else:
        # no recorded trace: a canonical seeded schedule recorded into
        # an in-memory trace and replayed — the same round trip, so the
        # baseline tracks the replay machinery either way
        seeded = open_loop_checks(
            REPLAY_CANON_REQUESTS,
            REPLAY_CANON_RATE_RPS,
            seed=REPLAY_CANON_SEED,
            checks=REPLAY_CANON_CHECKS,
        )
        events = []
        prev = 0.0
        for req in seeded:
            events.append(
                {
                    "tenant": req.tenant,
                    "check": req.check,
                    "gap": req.arrival - prev,
                    "freshness": req.freshness,
                }
            )
            prev = req.arrival
        schedule = RecordedArrivals(events)
        source = "canonical-seeded"
    requests = replayed_checks(schedule)
    started = timer()
    summary = asyncio.run(drive_requests(requests))
    elapsed = max(timer() - started, 1e-9)
    seconds = elapsed / len(requests)
    if not summary["conservation_ok"]:
        return CellResult(
            cell,
            STATUS_ERROR,
            reason="per-tenant conservation violated during replay",
        )
    # no analytic FLOP/byte model: the roofline entry reports its
    # structured no-cost-model reason, same as any costless cell
    return CellResult(
        cell,
        STATUS_OK,
        value=seconds,
        seconds=seconds,
        details={
            "replay": {
                "source": source,
                "requests": summary["requests"],
                "tenant_mix": summary["tenant_mix"],
                "outcomes": summary["outcome_counts"],
                "conserved": True,
            }
        },
    )


_RUNNERS: Dict[str, Callable] = {
    "flash": _run_flash,
    "ring": _run_ring,
    "moe": _run_moe,
    "pipeline": _run_pipeline,
    "decode": _run_decode,
    "training-step": _run_training_step,
    "hier-allreduce": _run_hier_allreduce,
    "serving": _run_serving,
    "serving-disagg": _run_serving_disagg,
    "frontdoor-replay": _run_frontdoor_replay,
}


def execute_cell(
    cell: CellSpec,
    *,
    iters: int = 2,
    timer: Callable[[], float] = time.monotonic,
) -> CellResult:
    """Run one cell with the real ops. Never raises: a runner bug is a
    visible ``error`` cell in the matrix, a device deficit a structured
    ``skipped`` one. The timer is injectable (PhaseTimings idiom) so
    the module keeps the analysis/ no-wall-clock-call contract."""
    runner = _RUNNERS.get(cell.op)
    if runner is None:
        return skipped_result(
            cell, SKIP_UNKNOWN_OP, f"no runner for op {cell.op!r}"
        )
    try:
        return runner(cell, iters, timer)
    except _CellSkip as skip:
        return skipped_result(cell, skip.code, skip.detail)
    except Exception as exc:  # a cell bug must not sink the matrix
        log.exception("matrix cell %s failed", cell.cell_id)
        return CellResult(cell, STATUS_ERROR, reason=repr(exc)[:200])


def make_executor(
    *, iters: int = 2, timer: Callable[[], float] = time.monotonic
) -> Callable[[CellSpec], CellResult]:
    """The executor the observatory re-runs bisects through."""
    return lambda cell: execute_cell(cell, iters=iters, timer=timer)


# ---------------------------------------------------------------------
# the observatory: baselines + hysteresis + roofline + bisect + bundle
# ---------------------------------------------------------------------


class MatrixObservatory:
    """Per-(cell, metric) rolling baselines, hysteresis verdicts, and
    the regression loop, persisted to the durable sidecar.

    Evidence sinks are wired post-construction like the flight
    recorder's sources: ``metrics`` (MetricsCollector — the pinned
    ``healthcheck_matrix_*`` families) and ``flightrec``
    (FlightRecorder — one ``matrix-regression`` bundle per confirmed
    regression). Either may stay None.
    """

    def __init__(
        self,
        *,
        clock: Optional[Clock] = None,
        path: str = "",
        warmup_runs: int = 3,
        confirm_runs: int = 2,
        calm_runs: int = 3,
        config: Optional[DetectorConfig] = None,
        rated_spec=None,
        metrics=None,
        flightrec=None,
    ):
        self.clock = clock or Clock()
        self.path = path
        self.warmup_runs = max(1, warmup_runs)
        self.confirm_runs = max(1, confirm_runs)
        self.calm_runs = max(1, calm_runs)
        self.config = config or DetectorConfig()
        # the rated roofline the cells' analytic cost models are judged
        # against (probes/rated.RatedSpec). None — unknown silicon /
        # interpret mode — stamps a structured skip instead of a
        # verdict: model numbers are never compared against a TPU bar.
        self.rated_spec = rated_spec
        self.metrics = metrics
        self.flightrec = flightrec
        self.detectors = default_detectors()
        self.baselines = CheckBaselines(self.clock, self.warmup_runs)
        self.hysteresis: Dict[str, Hysteresis] = {}
        self.last_round: Optional[dict] = None
        self.restore_warning: Optional[dict] = None
        if path:
            self._restore(path)

    # -- persistence (analysis/baseline.py blob helpers) ----------------
    def _restore(self, path: str) -> None:
        doc, warning = baseline_store.load_blob(path)
        if warning is not None:
            # defensive restore: fresh baselines + a structured warning
            # that rides every subsequent round summary (the
            # .status.analysis discipline applied to the sidecar)
            self.restore_warning = warning
            log.warning(
                "matrix sidecar %s restored fresh: %s (%s)",
                path,
                warning.get("reason"),
                warning.get("detail"),
            )
            return
        if doc is None:
            return  # first round: nothing durable yet
        self.baselines = CheckBaselines.from_dict(
            doc.get("baselines") or {}, self.clock, self.warmup_runs
        )
        hysteresis = doc.get("hysteresis")
        if isinstance(hysteresis, dict):
            for key, entry in hysteresis.items():
                if isinstance(key, str) and isinstance(entry, dict):
                    self.hysteresis[key] = Hysteresis.from_dict(
                        entry, self.confirm_runs, self.calm_runs,
                        jump_to_raw=True,
                    )
        last_round = doc.get("last_round")
        if isinstance(last_round, dict):
            self.last_round = last_round

    def save(self) -> Optional[dict]:
        if not self.path:
            return None
        return baseline_store.save_blob(
            self.path,
            {
                "updated_at": self.clock.now().isoformat(),
                "baselines": self.baselines.to_dict(),
                "hysteresis": {
                    key: state.to_dict()
                    for key, state in self.hysteresis.items()
                },
                "last_round": self.last_round,
            },
        )

    def snapshot(self) -> Optional[dict]:
        """The /statusz ``matrix`` block: the latest observed round."""
        return self.last_round

    # -- the round loop --------------------------------------------------
    def observe_round(
        self,
        results: List[CellResult],
        *,
        executor: Optional[Callable[[CellSpec], CellResult]] = None,
        interpret_mode: bool = False,
        fallback_reason: str = "",
    ) -> dict:
        """Fold one round of cell results through the evidence stack
        and return the round summary (the bench ``matrix_summary``
        block, the /statusz ``matrix`` block, and the sidecar's
        ``last_round`` are all this one dict)."""
        cells: Dict[str, dict] = {}
        counts = {STATUS_OK: 0, STATUS_SKIPPED: 0, STATUS_ERROR: 0}
        regressions: List[dict] = []
        bisects: List[dict] = []
        prior_cells = (self.last_round or {}).get("cells") or {}
        for result in results:
            cell_id = result.cell.cell_id
            if cell_id in cells:
                # defensive vs colliding scripted results: one cell id,
                # one row, one count — the counts header and the table
                # must never disagree
                continue
            entry = self._cell_entry(result, interpret_mode, fallback_reason)
            counts[result.status] = counts.get(result.status, 0) + 1
            cells[cell_id] = entry
            if result.status != STATUS_OK:
                continue
            transitions = self._evaluate(cell_id, entry, result, interpret_mode)
            cell_fired = False
            cell_bisect: Optional[dict] = None
            for metric, old, new in transitions:
                entry.setdefault("transitions", []).append([metric, old, new])
                if new != level_name(LEVEL_DEGRADED):
                    continue
                # confirmed regression: name the moved ceiling, bisect
                # exactly ONCE PER CELL per round (a real slowdown moves
                # seconds and the roofline fraction in tandem — both
                # metrics confirming together is one regression, not
                # two re-runs and two bundles), and ship the postmortem
                # bundle carrying BOTH artifacts' evidence
                prior = prior_cells.get(cell_id)
                roofline = entry.get("roofline") or {}
                regression = {
                    "cell": cell_id,
                    "metric": metric,
                    "transition": [old, new],
                    "ceiling": roofline.get("bound"),
                    "cost_source": roofline.get("cost_source"),
                }
                if not cell_fired:
                    cell_fired = True
                    cell_bisect = self._bisect(
                        result, prior, executor, interpret_mode
                    )
                    if cell_bisect is not None:
                        bisects.append(cell_bisect)
                    if self.flightrec is not None:
                        from activemonitor_tpu.obs.flightrec import KIND_MATRIX

                        self.flightrec.record(
                            KIND_MATRIX,
                            f"matrix/{cell_id}",
                            cell=dict(entry),
                            prior_cell=prior,
                            bisect=cell_bisect,
                            regression=dict(regression),
                        )
                if cell_bisect is not None:
                    regression["bisect_outcome"] = cell_bisect["outcome"]
                regressions.append(regression)
        summary: dict = {
            "matrix_version": MATRIX_VERSION,
            "generated_at": self.clock.now().isoformat(),
            "interpret_mode": interpret_mode,
            "fallback_reason": fallback_reason,
            "cells": cells,
            "counts": counts,
            "regressions": regressions,
            "bisects": bisects,
        }
        if self.restore_warning is not None:
            summary["restore_warning"] = dict(self.restore_warning)
        self.last_round = summary
        persist_error = self.save()
        if persist_error is not None:
            summary["persist_error"] = persist_error
        if self.metrics is not None:
            try:
                self.metrics.record_matrix_round(summary)
            except Exception:
                log.exception("matrix metrics export failed")
        return summary

    # -- internals -------------------------------------------------------
    def _cell_entry(
        self, result: CellResult, interpret_mode: bool, fallback_reason: str
    ) -> dict:
        cell = result.cell
        entry: dict = {
            "op": cell.op,
            "mesh": {axis: size for axis, size in cell.mesh},
            "dtype": cell.dtype,
            "schedule_requested": cell.schedule,
            "schedule": result.schedule,
            "status": result.status,
            "metric": result.metric,
            "unit": result.unit,
            # interpret-mode/fallback labeling rides EVERY cell (the
            # r02–r05 lesson: degraded rounds must carry their cause in
            # the evidence itself, not in lost stderr scrollback)
            "interpret_mode": interpret_mode,
        }
        if cell.payload_kb is not None:
            entry["payload_kb"] = cell.payload_kb
        if cell.batch is not None:
            entry["batch"] = cell.batch
        if fallback_reason:
            entry["fallback_reason"] = fallback_reason
        if result.status != STATUS_OK:
            entry["reason"] = result.reason
            return entry
        entry["value"] = result.value
        entry["roofline"] = self._roofline_entry(result)
        return entry

    def _roofline_entry(self, result: CellResult) -> dict:
        """The cell's roofline stamp (obs/roofline.py): an analytic
        cost-model verdict against the configured rated spec, or a
        structured skip — never a silent omission."""
        from activemonitor_tpu.obs import roofline as roofline_model

        if self.rated_spec is None:
            return {"skipped": "no rated roofline (interpret mode / unknown silicon)"}
        if result.flops <= 0 or result.bytes_accessed <= 0 or result.seconds <= 0:
            return {
                "skipped": (
                    f"degenerate cost model (flops={result.flops}, "
                    f"bytes={result.bytes_accessed}, seconds={result.seconds})"
                )
            }
        verdict = roofline_model.classify(
            flops=result.flops,
            hbm_bytes=result.bytes_accessed,
            seconds=result.seconds,
            spec=self.rated_spec,
            cost_source=roofline_model.COST_SOURCE_MODEL,
        )
        if verdict is None:
            return {"skipped": "classification rejected the cost model"}
        return verdict.to_dict()

    @staticmethod
    def _metric_key(cell_id: str, metric: str, interpret_mode: bool) -> str:
        """Baselines and hysteresis are PER PLATFORM MODE: a
        CPU-fallback round judged against TPU-learned seconds (or vice
        versa) would confirm-degrade every cell with platform noise —
        the r02–r05 wedge scenario again, this time self-inflicted.
        Interpret rounds compare only against prior interpret rounds
        (the `_prior_cpu_mesh_value` discipline bench.py already
        applies to its headline metric)."""
        mode = "cpu" if interpret_mode else "tpu"
        return f"{mode}:{cell_id}|{metric}"

    def _samples(
        self, entry: dict, result: CellResult, interpret_mode: bool
    ) -> Dict[str, float]:
        samples: Dict[str, float] = {}
        value = finite(result.value)
        if value is not None:
            samples[result.metric] = value
        roofline = entry.get("roofline") or {}
        fraction = finite(roofline.get("fraction"))
        if fraction is not None and not interpret_mode:
            # named so the rated-floor detector recognizes it as an
            # absolute health fraction (judged from round one). Gated
            # off in interpret mode: a model-sourced fraction on the
            # CPU mesh is evidence (it rides the stamp and the gauges,
            # labeled) but must never be COMPARED against a TPU bar —
            # the headline metric still gets the baseline-relative
            # zscore/trend detectors either way.
            samples["roofline-fraction"] = fraction
        return samples

    def _evaluate(
        self, cell_id: str, entry: dict, result: CellResult,
        interpret_mode: bool,
    ) -> List[Tuple[str, str, str]]:
        """One cell's detector chain + hysteresis, the engine's
        discipline: warm-up always feeds the baseline, post-warm-up
        anomalous samples are quarantined from it, the reported verdict
        is the worst metric's hysteresis state."""
        transitions: List[Tuple[str, str, str]] = []
        worst = LEVEL_OK
        for metric, value in self._samples(entry, result, interpret_mode).items():
            key = self._metric_key(cell_id, metric, interpret_mode)
            baseline = self.baselines.baseline(key)
            warmed = self.baselines.warmed(key)
            levels = []
            for detector in self.detectors:
                if detector.needs_baseline and not warmed:
                    continue
                levels.append(
                    detector.evaluate(metric, value, baseline, self.config)
                )
            raw_level = combine_raw_levels(levels)
            if metric == result.metric and warmed and baseline.median > 0:
                entry["vs_baseline"] = round(value / baseline.median, 4)
            state = self.hysteresis.get(key)
            if state is None:
                state = self.hysteresis[key] = Hysteresis(
                    self.confirm_runs, self.calm_runs, jump_to_raw=True
                )
            moved = state.update(raw_level)
            if moved is not None:
                transitions.append(
                    (metric, level_name(moved[0]), level_name(moved[1]))
                )
            if not warmed or raw_level == LEVEL_OK:
                self.baselines.observe(key, value)
            worst = max(worst, state.level)
        entry["verdict"] = level_name(worst)
        return transitions

    def _raw_level(
        self, cell_id: str, entry: dict, result: CellResult,
        interpret_mode: bool,
    ) -> int:
        """The detector chain's opinion of one measurement WITHOUT
        feeding baselines or hysteresis — how a bisect re-run is
        judged."""
        worst = LEVEL_OK
        for metric, value in self._samples(entry, result, interpret_mode).items():
            key = self._metric_key(cell_id, metric, interpret_mode)
            baseline = self.baselines.peek(key)
            warmed = self.baselines.warmed(key)
            levels = []
            for detector in self.detectors:
                if detector.needs_baseline and not warmed:
                    continue
                levels.append(
                    detector.evaluate(metric, value, baseline, self.config)
                )
            worst = max(worst, combine_raw_levels(levels))
        return worst

    def _bisect(
        self,
        result: CellResult,
        prior: Optional[dict],
        executor: Optional[Callable[[CellSpec], CellResult]],
        interpret_mode: bool,
    ) -> Optional[dict]:
        """Exactly one re-run of the regressing cell, judged against
        the live baseline and compared with the prior artifact's value.
        None when no executor is wired (a read-only observer — e.g. a
        controller replaying the sidecar — cannot re-run cells)."""
        if executor is None:
            return None
        cell_id = result.cell.cell_id
        prior = prior or {}
        record: dict = {
            "cell": cell_id,
            "metric": result.metric,
            "round_value": result.value,
            # comparable only within one platform mode: a TPU round's
            # seconds are not the baseline for a CPU-fallback re-run
            "prior_value": (
                prior.get("value")
                if prior.get("interpret_mode") == interpret_mode
                else None
            ),
        }
        try:
            rerun = executor(result.cell)
        except Exception as exc:  # executor bug: a visible error record
            record.update(outcome=BISECT_ERROR, reason=repr(exc)[:200])
            return record
        if rerun.status != STATUS_OK:
            record.update(outcome=BISECT_ERROR, reason=rerun.reason)
            return record
        record["rerun_value"] = rerun.value
        entry = {"roofline": self._roofline_entry(rerun)}
        raw = self._raw_level(cell_id, entry, rerun, interpret_mode)
        record["outcome"] = (
            BISECT_REPRODUCED if raw > LEVEL_OK else BISECT_RECOVERED
        )
        return record


class SidecarView:
    """Read-only /statusz source over the durable sidecar — the
    controller (``am-tpu run --matrix-state``) serves the matrix block
    without having run the round. Defensive like every restore path:
    a corrupt or version-skewed sidecar is a structured warning block,
    never a crash in the statusz handler. The parsed snapshot is
    cached on (mtime, size): the blob carries every cell's rolling
    baseline ring and changes at most once per bench round, so only
    the first read after a round pays the parse."""

    def __init__(self, path: str):
        self.path = path
        self._stamp: Optional[Tuple[float, int]] = None
        self._cached: Optional[dict] = None

    def snapshot(self) -> Optional[dict]:
        import os

        try:
            stat = os.stat(self.path)
            stamp: Optional[Tuple[float, int]] = (stat.st_mtime, stat.st_size)
        except OSError:
            stamp = None
        if stamp is not None and stamp == self._stamp:
            return self._cached
        doc, warning = baseline_store.load_blob(self.path)
        if warning is not None:
            snapshot: Optional[dict] = {
                "matrix_version": MATRIX_VERSION,
                "cells": {},
                "counts": {},
                "regressions": [],
                "bisects": [],
                "restore_warning": warning,
            }
        elif doc is None:
            snapshot = None
        else:
            last_round = doc.get("last_round")
            snapshot = last_round if isinstance(last_round, dict) else None
        self._stamp = stamp
        self._cached = snapshot
        return snapshot
