"""Collective algorithm zoo + autotuner tests (ISSUE 8).

Every zoo schedule must be numerically equivalent to the
``jax.lax.psum`` / ``all_gather`` reference across mesh sizes
n ∈ {2, 3, 4, 8} (odd-row shards included, bf16 and f32), send exactly
its theoretical hop count (the PR-5 ``_HOP_LOG`` contract), and the
autotuner must demonstrably flip its decision across a scripted
crossover — all on the virtual 8-device CPU mesh."""

import collections
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import activemonitor_tpu.parallel.schedules as schedules
from activemonitor_tpu.parallel import autotune
from activemonitor_tpu.parallel.schedules import (
    all_gather_recdouble,
    all_gather_recdouble_bandwidth,
    all_gather_ring,
    all_gather_ring_bandwidth,
    all_reduce_recdouble,
    all_reduce_recdouble_bandwidth,
    all_reduce_rsag,
    all_reduce_rsag_bandwidth,
    all_reduce_tree,
    all_reduce_tree_bandwidth,
    theoretical_hops,
)
from activemonitor_tpu.parallel.partition import shard_map

AXIS = "zoo"

ALL_REDUCE_FNS = {
    "rsag": all_reduce_rsag,
    "recdouble": all_reduce_recdouble,
    "tree": all_reduce_tree,
}
ALL_GATHER_FNS = {
    "ring": all_gather_ring,
    "ag-recdouble": all_gather_recdouble,
}


def submesh(n):
    return Mesh(np.array(jax.devices()[:n]), (AXIS,))


def apply_sharded(mesh, fn, x, gathered=False):
    """Run ``fn(shard)`` under shard_map; gathered=True means fn's
    output is already the full (replicated-content) array."""
    out_specs = P(None) if gathered else P(AXIS)
    run = shard_map(
        fn, mesh=mesh, in_specs=P(AXIS), out_specs=out_specs, check_vma=False
    )
    return run(x)


@pytest.mark.parametrize("sched", sorted(ALL_REDUCE_FNS))
@pytest.mark.parametrize(
    "n", [2, 3, 4, pytest.param(8, marks=pytest.mark.slow)]
)
def test_all_reduce_schedules_match_psum(sched, n):
    """allclose equivalence vs the XLA reference, odd-row shards (5
    rows/shard exercise the rsag padding path on every non-divisible
    n), f32."""
    mesh = submesh(n)
    fn = ALL_REDUCE_FNS[sched]
    rows = 5  # odd: 5 % n != 0 for n in {2,3,4,8}
    x = jax.random.normal(jax.random.key(n), (n * rows, 3), jnp.float32)
    got = apply_sharded(mesh, lambda v: fn(v, AXIS), x)
    want = apply_sharded(mesh, lambda v: jax.lax.psum(v, AXIS), x)
    assert jnp.allclose(got, want, atol=1e-5), (
        sched, n, float(jnp.max(jnp.abs(got - want)))
    )


@pytest.mark.parametrize("sched", sorted(ALL_REDUCE_FNS))
def test_all_reduce_schedules_match_psum_bf16(sched):
    """bf16 shards: integer-valued payloads keep every partial sum
    exactly representable, so the schedules must agree with psum
    BITWISE — any extra rounding (an upcast the reference doesn't do,
    a lost chunk) shows as a hard mismatch."""
    n = 4
    mesh = submesh(n)
    fn = ALL_REDUCE_FNS[sched]
    x = jnp.arange(n * 4 * 2, dtype=jnp.bfloat16).reshape(n * 4, 2) % 7
    got = apply_sharded(mesh, lambda v: fn(v, AXIS), x)
    want = apply_sharded(mesh, lambda v: jax.lax.psum(v, AXIS), x)
    assert got.dtype == jnp.bfloat16
    assert bool((got == want).all()), (sched, got - want)


@pytest.mark.parametrize("sched", sorted(ALL_GATHER_FNS))
@pytest.mark.parametrize(
    "n", [2, 3, 4, pytest.param(8, marks=pytest.mark.slow)]
)
def test_all_gather_schedules_match_reference_bitwise(sched, n):
    """The gather schedules only MOVE data — bitwise equality with
    ``lax.all_gather(tiled=True)`` is the contract, odd rows included."""
    mesh = submesh(n)
    fn = ALL_GATHER_FNS[sched]

    @partial(
        shard_map, mesh=mesh, in_specs=P(AXIS), out_specs=P(None),
        check_vma=False,
    )
    def diff(v):
        got = fn(v, AXIS)
        want = jax.lax.all_gather(v, AXIS, tiled=True)
        return jnp.max(jnp.abs(got - want))[None]

    x = jax.random.normal(jax.random.key(10 + n), (n * 5, 3), jnp.float32)
    assert float(diff(x)[0]) == 0.0


@pytest.mark.parametrize(
    "sched,n",
    [
        ("rsag", 2), ("rsag", 3),
        pytest.param("rsag", 8, marks=pytest.mark.slow),
        ("recdouble", 2), ("recdouble", 3), ("recdouble", 8),
        ("tree", 2), ("tree", 3), ("tree", 8),
    ],
)
def test_all_reduce_hop_budget(sched, n):
    """Traced-hop contract: each schedule issues exactly its
    theoretical round count (rsag 2(n−1); recdouble log2(p) + 2-round
    non-pow2 fold/unfold; tree 2·ceil(log2 n)). The schedules unroll
    python loops, so one traced application logs every ppermute."""
    mesh = submesh(n)
    fn = ALL_REDUCE_FNS[sched]
    # unique shape per case so cached traces can't swallow the log
    x = jnp.ones((n * 4, 2 + n), jnp.float32)
    schedules._HOP_LOG = log = []
    try:
        apply_sharded(mesh, lambda v: fn(v, AXIS), x)
    finally:
        schedules._HOP_LOG = None
    assert len(log) == theoretical_hops(sched, n), (sched, n, log)


@pytest.mark.parametrize(
    "n", [2, 3, pytest.param(8, marks=pytest.mark.slow)]
)
def test_all_gather_hop_budget(n):
    mesh = submesh(n)
    for sched, fn in ALL_GATHER_FNS.items():
        x = jnp.ones((n * 2, 1 + n), jnp.float32)

        @partial(
            shard_map, mesh=mesh, in_specs=P(AXIS), out_specs=P(None),
            check_vma=False,
        )
        def gathered(v):
            return fn(v, AXIS)

        schedules._HOP_LOG = log = []
        try:
            gathered(x)
        finally:
            schedules._HOP_LOG = None
        assert len(log) == theoretical_hops(sched, n), (sched, n, log)


def test_recdouble_non_pow2_fold_unfold_tags():
    """n=3 recursive doubling: one fold, log2(2)=1 exchange, one
    unfold — the hop tags prove the non-pow2 path really folds the
    remainder rank instead of silently falling back to another
    schedule."""
    n = 3
    mesh = submesh(n)
    x = jnp.ones((n * 2, 9), jnp.float32)
    schedules._HOP_LOG = log = []
    try:
        apply_sharded(mesh, lambda v: all_reduce_recdouble(v, AXIS), x)
    finally:
        schedules._HOP_LOG = None
    tags = collections.Counter(tag for tag, _step in log)
    assert tags == {
        "recdouble-fold": 1, "recdouble-xchg": 1, "recdouble-unfold": 1
    }


def test_ag_recdouble_falls_back_to_ring_off_pow2():
    n = 3
    mesh = submesh(n)
    x = jnp.ones((n * 2, 11), jnp.float32)

    @partial(
        shard_map, mesh=mesh, in_specs=P(AXIS), out_specs=P(None),
        check_vma=False,
    )
    def gathered(v):
        return all_gather_recdouble(v, AXIS)

    schedules._HOP_LOG = log = []
    try:
        gathered(x)
    finally:
        schedules._HOP_LOG = None
    assert all(tag == "ag-ring" for tag, _step in log), log
    assert len(log) == n - 1


def test_bandwidth_wrappers_report_conventions():
    """Zoo benches share the XLA benches' CollectiveResult/busbw
    accounting: allreduce busbw = algbw·2(n−1)/n, allgather payload is
    the gathered total with busbw = algbw·(n−1)/n."""
    from activemonitor_tpu.parallel.mesh import make_1d_mesh

    n = 4  # half the virtual mesh: conventions don't need all 8
    mesh = make_1d_mesh(devices=jax.devices()[:n])
    for bench in (
        all_reduce_rsag_bandwidth,
        all_reduce_recdouble_bandwidth,
        all_reduce_tree_bandwidth,
    ):
        r = bench(mesh, size_mb=0.25, iters=2)
        assert r.n_devices == n
        assert r.algbw_gbps > 0
        assert r.busbw_gbps == pytest.approx(r.algbw_gbps * 2 * (n - 1) / n)
    for bench in (all_gather_ring_bandwidth, all_gather_recdouble_bandwidth):
        r = bench(mesh, size_mb=0.25, iters=2)
        assert r.busbw_gbps == pytest.approx(r.algbw_gbps * (n - 1) / n)
        assert r.algbw_gbps > 0


def test_theoretical_hops_table():
    assert theoretical_hops("rsag", 8) == 14
    assert theoretical_hops("recdouble", 8) == 3
    assert theoretical_hops("recdouble", 3) == 3  # fold + 1 xchg + unfold
    assert theoretical_hops("tree", 8) == 6
    assert theoretical_hops("tree", 3) == 4
    assert theoretical_hops("ring", 8) == 7
    assert theoretical_hops("ag-recdouble", 8) == 3
    assert theoretical_hops("ag-recdouble", 3) == 2  # ring fallback
    assert theoretical_hops("rsag", 1) == 0
    with pytest.raises(ValueError, match="unknown schedule"):
        theoretical_hops("bogus", 8)
    # the public "recdouble" token names a DIFFERENT algorithm per
    # family: the gather variant's non-pow2 fallback is the ring
    assert theoretical_hops("recdouble", 6, collective="allgather") == 5
    assert theoretical_hops("recdouble", 8, collective="allgather") == 3
    assert theoretical_hops("recdouble", 6) == 4  # allreduce fold/unfold


# ---------------------------------------------------------------------------
# autotuner
# ---------------------------------------------------------------------------


class _FakeResult:
    def __init__(self, busbw_gbps, payload_bytes):
        self.busbw_gbps = busbw_gbps
        self.payload_bytes = payload_bytes


def _regime_bench(alpha_us, beta_by_schedule):
    """Scripted alpha-beta timings: time = alpha·rounds + bytes/beta.
    Latency-optimal schedules (few rounds, low effective beta) win
    small payloads; bandwidth-optimal ones win large — the NCCL
    crossover in miniature, no hardware involved."""

    def bench(_collective, schedule, mesh, axis, size_mb, _dtype, _iters):
        n = mesh.shape[axis]
        payload = int(size_mb * 1e6)
        rounds, beta_gbps = beta_by_schedule[schedule]
        seconds = alpha_us * 1e-6 * rounds + payload / (beta_gbps * 1e9)
        algbw = payload / seconds / 1e9
        busbw = algbw * 2 * (n - 1) / n
        return _FakeResult(busbw, payload)

    return bench


def test_autotuner_decision_flips_across_the_crossover():
    """The acceptance-criterion unit test: with scripted timings where
    recdouble has few rounds but low bandwidth and rsag many rounds
    but high bandwidth, the winner must flip from recdouble (small
    payloads) to rsag (large payloads), and lookup() must serve each
    regime its own schedule."""
    from activemonitor_tpu.parallel.mesh import make_1d_mesh

    autotune.clear()
    mesh = make_1d_mesh()  # fake bench: no collective actually runs
    # (rounds, effective beta GB/s): recdouble pays 3 rounds at 1 GB/s,
    # rsag pays 14 rounds at 10 GB/s — crossover ~a few hundred KB
    bench = _regime_bench(
        alpha_us=200.0,
        beta_by_schedule={
            "xla": (14, 5.0),
            "rsag": (14, 10.0),
            "recdouble": (3, 1.0),
            "tree": (6, 0.5),
        },
    )
    tuned = autotune.tune(
        mesh,
        collectives=("allreduce",),
        sizes_mb=(0.01, 100.0),
        dtype=jnp.bfloat16,
        iters=1,
        bench=bench,
    )
    raw = tuned.results
    assert len(tuned.keys) == 2  # one recorded cell per swept size
    small = raw["allreduce"][0.01]
    large = raw["allreduce"][100.0]
    assert max(small, key=small.get) == "recdouble"
    assert max(large, key=large.get) == "rsag"
    # the table serves each regime its winner
    assert autotune.lookup("allreduce", 8, int(0.01 * 1e6), jnp.bfloat16) == "recdouble"
    assert autotune.lookup("allreduce", 8, int(100 * 1e6), jnp.bfloat16) == "rsag"
    # crossover detection sees exactly one flip
    points = [
        (mb, max(bw, key=bw.get)) for mb, bw in raw["allreduce"].items()
    ]
    flips = autotune.crossover_points(points)
    assert len(flips) == 1
    assert flips[0]["from"] == "recdouble" and flips[0]["to"] == "rsag"
    autotune.clear()


def test_autotune_lookup_nearest_bucket_and_serialization():
    autotune.clear()
    decision = autotune.record(
        "allreduce", 8, 64 * 2**20, jnp.float32,
        {"xla": 5.0, "rsag": 8.0, "tree": 1.0},
    )
    assert decision.schedule == "rsag"
    assert decision.runner_up == "xla"
    assert decision.margin == pytest.approx(8.0 / 5.0)
    # a nearby (untuned) payload rides the nearest tuned octave
    assert autotune.lookup("allreduce", 8, 48 * 2**20, jnp.float32) == "rsag"
    # other axis sizes / dtypes are NOT served by this entry
    assert autotune.lookup("allreduce", 4, 64 * 2**20, jnp.float32) is None
    assert autotune.lookup("allreduce", 8, 64 * 2**20, jnp.bfloat16) is None
    table = autotune.table_as_dict()
    (key,) = table
    assert table[key]["schedule"] == "rsag"
    assert table[key]["per_schedule_busbw_gbps"]["tree"] == 1.0
    # keyed snapshots exclude cells other runs recorded
    other = autotune.TuneKey("allgather", 4, 10, "float32")
    assert autotune.table_as_dict(keys=[other]) == {}
    with pytest.raises(ValueError, match="no schedules"):
        autotune.record("allreduce", 8, 1, jnp.float32, {})
    autotune.clear()


def test_tune_rejects_unknown_collectives():
    from activemonitor_tpu.parallel.mesh import make_1d_mesh

    with pytest.raises(ValueError, match="unknown collectives"):
        autotune.tune(make_1d_mesh(), collectives=("reducescatter",))


def test_tuned_all_reduce_surface_consults_the_table():
    """all_reduce(x, schedule="auto") must dispatch to the tuned
    winner — proven by hop signature: after recording tree as the
    winner for this (n, payload octave, dtype), the auto path issues
    tree hops; after clear() it falls back to XLA psum (zero zoo
    hops)."""
    n = 4
    mesh = submesh(n)
    x = jnp.ones((n * 2, 13), jnp.float32)
    payload = (x.size // n) * x.dtype.itemsize

    autotune.clear()
    autotune.record("allreduce", n, payload, jnp.float32, {"tree": 2.0, "xla": 1.0})

    def auto(v):
        return autotune.all_reduce(v, AXIS, schedule="auto")

    schedules._HOP_LOG = log = []
    try:
        got = apply_sharded(mesh, auto, x)
    finally:
        schedules._HOP_LOG = None
    assert {tag for tag, _s in log} == {"tree-reduce", "tree-bcast"}
    want = apply_sharded(mesh, lambda v: jax.lax.psum(v, AXIS), x)
    assert jnp.allclose(got, want)

    autotune.clear()
    schedules._HOP_LOG = log = []
    try:
        # fresh shape: the previous trace must not be replayed
        apply_sharded(mesh, auto, jnp.ones((n * 2, 17), jnp.float32))
    finally:
        schedules._HOP_LOG = None
    assert log == []  # untuned → XLA builtin, no explicit hops


def test_tuned_surfaces_reject_unknown_schedules():
    n = 2
    mesh = submesh(n)
    x = jnp.ones((n * 2, 3), jnp.float32)
    with pytest.raises(ValueError, match="unknown all-reduce schedule"):
        apply_sharded(mesh, lambda v: autotune.all_reduce(v, AXIS, "bogus"), x)

    @partial(
        shard_map, mesh=mesh, in_specs=P(AXIS), out_specs=P(None),
        check_vma=False,
    )
    def bad_gather(v):
        return autotune.all_gather(v, AXIS, "bogus")

    with pytest.raises(ValueError, match="unknown all-gather schedule"):
        bad_gather(x)


def test_tuned_all_gather_explicit_schedule():
    n = 4
    mesh = submesh(n)
    x = jnp.arange(n * 2 * 3, dtype=jnp.float32).reshape(n * 2, 3)

    @partial(
        shard_map, mesh=mesh, in_specs=P(AXIS), out_specs=P(None),
        check_vma=False,
    )
    def diff(v):
        got = autotune.all_gather(v, AXIS, "ring")
        want = jax.lax.all_gather(v, AXIS, tiled=True)
        return jnp.max(jnp.abs(got - want))[None]

    assert float(diff(x)[0]) == 0.0


def test_auto_dispatch_is_safe_for_scalars_and_distant_payloads():
    """A tuned 64 MB cell must not crash (or even steer) a scalar
    psum: 0-d inputs always ride the builtin, and the nearest-octave
    fallback is bounded so a 4 KB payload on the wrong side of the
    crossover falls back to XLA instead of riding the 64 MB rsag
    decision."""
    n = 4
    mesh = submesh(n)
    autotune.clear()
    try:
        autotune.record(
            "allreduce", n, 64 * 2**20, jnp.float32, {"rsag": 10.0, "xla": 5.0}
        )
        # bounded fallback: 4 KB is ~14 octaves away — no decision
        assert autotune.lookup("allreduce", n, 4096, jnp.float32) is None
        # ...but 48 MB (1 octave) still rides the 64 MB cell
        assert autotune.lookup("allreduce", n, 48 * 2**20, jnp.float32) == "rsag"

        @partial(
            shard_map, mesh=mesh, in_specs=P(AXIS), out_specs=P(None),
            check_vma=False,
        )
        def scalar_auto(v):
            return autotune.all_reduce(jnp.sum(v), AXIS, schedule="auto")[None]

        got = scalar_auto(jnp.ones((n * 2, 3), jnp.float32))
        assert float(got[0]) == n * 2 * 3
    finally:
        autotune.clear()


def test_lookup_equidistant_octaves_tie_break_toward_smaller():
    """Two tuned octaves at equal distance must not crash (TuneKeys
    are unorderable) and resolve to the smaller payload's decision —
    the latency-safe side of the crossover."""
    autotune.clear()
    try:
        autotune.record("allreduce", 8, 2**20, jnp.float32, {"recdouble": 2.0})
        autotune.record("allreduce", 8, 2**24, jnp.float32, {"rsag": 2.0})
        # bucket 22: exactly two octaves from both tuned entries
        assert (
            autotune.lookup("allreduce", 8, 2**22, jnp.float32) == "recdouble"
        )
    finally:
        autotune.clear()


def test_payload_bucket_octaves():
    assert autotune.payload_bucket(1) == 0
    assert autotune.payload_bucket(2**20) == 20
    assert autotune.payload_bucket(2**20 + 1) == 20
    assert autotune.payload_bucket(2**21 - 1) == 20
    assert autotune.payload_bucket(2**21) == 21
