import sys

from activemonitor_tpu.probes.cli import main

sys.exit(main())
