"""Golden-equivalence suite for the partition-rule refactor.

Pins that rule-resolved PartitionSpecs are identical to the
hand-threaded layouts they replaced, and that rule-driven op outputs
(forward AND gradients) bitwise-match reconstructions of the
pre-refactor hand-threaded paths — for ring attention
(serial/overlap/bidir), the pipeline (overlap on/off), MoE, and the
composed DP×TP×PP step — on meshes n ∈ {2, 4, 8}. The hand layouts
live HERE as snapshots: the production code only has rules now, and
this suite is what licensed deleting the hand-threaded call sites.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

from activemonitor_tpu.parallel import autotune, partition
from activemonitor_tpu.parallel.mesh import make_1d_mesh, make_mesh

# the output-level goldens re-run every schedule twice (hand + rules)
# with gradients — n=2 carries the tier-1 gate and the wider meshes
# ride the slow tier (the test_graft_entry / test_schedules precedent:
# tier-1 keeps the 870s budget, the soak tiers run the full matrix).
# Correctness-vs-oracle at n=8 stays tier-1 in the per-op suites.
MESH_SIZES = (
    2,
    pytest.param(4, marks=pytest.mark.slow),
    pytest.param(8, marks=pytest.mark.slow),
)


@pytest.fixture(autouse=True)
def _untuned_table():
    # golden runs pin the UNTUNED dispatch (schedule="auto" → builtin)
    autotune.clear()
    yield
    autotune.clear()


def _spec_trees_equal(got, want):
    same = jax.tree.map(
        lambda a, b: a == b, got, want, is_leaf=lambda x: isinstance(x, P)
    )
    return all(jax.tree.leaves(same))


def _mesh(n, axis):
    return make_mesh((axis,), (n,), devices=jax.devices()[:n])


# ---------------------------------------------------------------------------
# spec-level golden: rules resolve to the exact hand-threaded layouts
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("gqa", [False, True])
def test_param_specs_match_hand_threaded_megatron_layout(gqa):
    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        param_specs,
    )

    cfg = ProbeModelConfig(n_kv_heads=2 if gqa else None)
    if gqa:
        attn = {
            "wq": P(None, "model", None),
            "wkv": P(None, None, "model", None),
        }
    else:
        attn = {"wqkv": P(None, None, "model", None)}
    layer = {
        "ln1": {"scale": P()},
        **attn,
        "wo": P("model", None, None),
        "ln2": {"scale": P()},
        "w_up": P(None, "model"),
        "w_down": P("model", None),
    }
    hand = {
        "embed": P(None, None),
        "layers": [layer] * cfg.n_layers,
        "final_ln": {"scale": P()},
    }
    assert _spec_trees_equal(param_specs(cfg), hand)


def test_stacked_layer_specs_match_hand_threaded_layout():
    from activemonitor_tpu.ops.pipeline import stacked_layer_specs

    hand = {
        "ln1": {"scale": P("pp", None)},
        "wqkv": P("pp", None, None, "model", None),
        "wo": P("pp", "model", None, None),
        "ln2": {"scale": P("pp", None)},
        "w_up": P("pp", None, "model"),
        "w_down": P("pp", "model", None),
    }
    assert _spec_trees_equal(stacked_layer_specs("pp", "model"), hand)


@pytest.mark.parametrize(
    "shape", [(2, 1, 1), (1, 2, 2), (2, 2, 2)], ids=["n2", "n4", "n8"]
)
def test_composed_param_rules_match_hand_threaded_layout(shape):
    from activemonitor_tpu.models.probe_model import init_params, tiny_config
    from activemonitor_tpu.ops.pipeline import (
        stack_layer_params,
        stacked_layer_specs,
    )
    from activemonitor_tpu.probes.training_step import composed_param_rules

    n = shape[0] * shape[1] * shape[2]
    mesh = make_mesh(
        ("data", "model", "pp"), shape, devices=jax.devices()[:n]
    )
    cfg = tiny_config()
    raw = init_params(jax.random.key(0), cfg)
    stacked = {
        "embed": raw["embed"],
        "layers": stack_layer_params(raw["layers"]),
        "final_ln": raw["final_ln"],
    }
    hand = {
        "embed": P(None, None),
        "layers": stacked_layer_specs("pp", "model"),
        "final_ln": {"scale": P()},
    }
    got = partition.match_partition_rules(
        composed_param_rules("pp", "model"), stacked, mesh=mesh
    )
    assert _spec_trees_equal(got, hand)


def test_moe_rules_match_hand_threaded_specs():
    from activemonitor_tpu.ops.moe import (
        init_moe_params,
        moe_partition_rules,
    )

    params = init_moe_params(jax.random.key(0), 16, 32, 8)
    x = jnp.zeros((32, 16))
    got = partition.match_partition_rules(
        moe_partition_rules("ep"), {**params, "x": x}
    )
    # the pre-refactor hand-threaded in_specs, verbatim
    assert got["router"] == P(None, None)
    assert got["w_up"] == P("ep", None, None)
    assert got["w_down"] == P("ep", None, None)
    assert got["x"] == P("ep", None)


def test_ring_rules_match_hand_threaded_spec():
    from activemonitor_tpu.ops.ring_attention import ring_partition_rules

    q = jnp.zeros((1, 8, 2, 4))
    got = partition.match_partition_rules(
        ring_partition_rules("sp"), {"q": q, "k": q, "v": q}
    )
    for name in ("q", "k", "v"):
        assert got[name] == P(None, "sp", None, None)
    composed = partition.match_partition_rules(
        ring_partition_rules("sp", batch_axis="data", heads_axis="model"),
        {"q": q, "k": q, "v": q},
    )
    assert composed["q"] == P("data", "sp", "model", None)


# ---------------------------------------------------------------------------
# output-level golden: rule-driven == hand-threaded reconstruction, bitwise
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("n", MESH_SIZES)
@pytest.mark.parametrize("variant", ["serial", "overlap", "bidir"])
def test_ring_attention_golden_fwd_and_grads(n, variant):
    """Rule-resolved ring attention bitwise-matches the pre-refactor
    hand-threaded shard_map call (reconstructed here with the exact
    old spec), forward and gradients, for every schedule variant."""
    from activemonitor_tpu.ops import ring_attention as ra

    mesh = _mesh(n, "sp")
    keys = jax.random.split(jax.random.key(n), 3)
    q, k, v = (
        jax.random.normal(kk, (1, 4 * n, 2, 8), jnp.float32) for kk in keys
    )

    def hand_path(q, k, v):
        # the pre-refactor call: one hand-built spec threaded straight
        # into shard_map around the same differentiable body
        spec = P(None, "sp", None, None)
        fn = partition.shard_map(
            lambda a, b, c: ra._ring_diff(
                a, b, c, "sp", n, True, False, variant, False
            ),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)

    def rules_path(q, k, v):
        return ra.ring_attention(q, k, v, mesh, "sp", variant=variant)

    want = jax.jit(hand_path)(q, k, v)
    got = jax.jit(rules_path)(q, k, v)
    assert (got == want).all(), float(jnp.max(jnp.abs(got - want)))

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32) ** 2)

    g_hand = jax.jit(jax.grad(loss(hand_path), argnums=(0, 1, 2)))(q, k, v)
    g_rules = jax.jit(jax.grad(loss(rules_path), argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_rules, g_hand):
        assert (a == b).all()


@pytest.fixture(scope="module")
def pipeline_setup():
    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        init_params,
    )
    from activemonitor_tpu.ops.pipeline import stack_layer_params

    cfg = ProbeModelConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=8, d_ff=32,
        max_seq_len=16, dtype=jnp.float32,
    )
    params = init_params(jax.random.key(0), cfg)
    stacked = stack_layer_params(params["layers"])
    x = jax.random.normal(jax.random.key(1), (8, 8, cfg.d_model), jnp.float32)
    return cfg, stacked, x


@pytest.mark.parametrize("n", MESH_SIZES)
@pytest.mark.parametrize("overlap", [False, True], ids=["serial", "overlap"])
def test_pipeline_golden_fwd_and_grads(pipeline_setup, n, overlap):
    """Rule-resolved pipeline bitwise-matches the hand-threaded
    boundary (the exact pre-refactor in_specs passed as explicit
    rules, with the builtin psum pinned), forward and gradients."""
    from activemonitor_tpu.ops.pipeline import pipeline_forward_blocks

    cfg, stacked, x = pipeline_setup
    mesh = _mesh(n, "pp")
    hand_rules = (
        (r"^layers(/|$)", P("pp")),
        (r"^(micro|out)$", P(None, None, None, None)),
    )

    def hand_path(stacked, x):
        return pipeline_forward_blocks(
            stacked, x, cfg, mesh, "pp", overlap=overlap,
            rules=hand_rules, allreduce_schedule="xla",
        )

    def rules_path(stacked, x):
        return pipeline_forward_blocks(
            stacked, x, cfg, mesh, "pp", overlap=overlap
        )

    want = jax.jit(hand_path)(stacked, x)
    got = jax.jit(rules_path)(stacked, x)
    assert (got == want).all()

    def loss(fn):
        return lambda layers, x: jnp.sum(fn(layers, x) ** 2)

    try:
        g_rules = jax.jit(jax.grad(loss(rules_path)))(stacked, x)
    except NotImplementedError:
        # lax.optimization_barrier has no differentiation rule on this
        # runtime vintage, so the OVERLAPPED schedule's backward never
        # existed pre-refactor either — forward bitwise above is the
        # whole hand-threaded surface for that cell
        assert overlap
        return
    g_hand = jax.jit(jax.grad(loss(hand_path)))(stacked, x)
    same = jax.tree.map(lambda a, b: bool((a == b).all()), g_rules, g_hand)
    assert all(jax.tree.leaves(same)), same


@pytest.mark.parametrize("n", MESH_SIZES)
def test_moe_golden_fwd_and_grads(n):
    """Rule-driven MoE bitwise-matches the pre-refactor hand-threaded
    body (hand in_specs, `lax.all_gather`, `scatter_dimension=0`
    hard-coded), forward and gradients."""
    from functools import partial as fpartial

    from activemonitor_tpu.ops.moe import (
        init_moe_params,
        moe_ffn_expert_parallel,
    )

    mesh = _mesh(n, "ep")
    params = init_moe_params(jax.random.key(0), d_model=16, d_ff=32, n_experts=8)
    x = jax.random.normal(jax.random.key(1), (32, 16), jnp.float32)
    e_local = 8 // n

    def hand_path(params, x):
        # the pre-refactor body, verbatim (dense top-1 dispatch with
        # hand specs and the hard-coded dim-0 scatter)
        @fpartial(
            partition.shard_map,
            mesh=mesh,
            in_specs=(
                P(None, None), P("ep", None, None), P("ep", None, None),
                P("ep", None),
            ),
            out_specs=P("ep", None),
            check_vma=False,
        )
        def run(router, w_up, w_down, x_shard):
            my_rank = jax.lax.axis_index("ep")
            tokens = jax.lax.all_gather(x_shard, "ep", tiled=True)
            logits = tokens @ router
            expert = jnp.argmax(logits, axis=-1)
            gate = jax.nn.softmax(logits, axis=-1)
            gate = jnp.take_along_axis(gate, expert[:, None], axis=-1)
            out = jnp.zeros_like(tokens)
            for e in range(e_local):
                eid = my_rank * e_local + e
                mask = (expert == eid)[:, None].astype(tokens.dtype)
                h = jax.nn.gelu(tokens @ w_up[e])
                out = out + mask * gate * (h @ w_down[e])
            return jax.lax.psum_scatter(out, "ep", scatter_dimension=0, tiled=True)

        return run(params["router"], params["w_up"], params["w_down"], x)

    def rules_path(params, x):
        return moe_ffn_expert_parallel(params, x, mesh, "ep")

    want = jax.jit(hand_path)(params, x)
    got = jax.jit(rules_path)(params, x)
    assert (got == want).all()

    def loss(fn):
        return lambda p, x: jnp.sum(fn(p, x) ** 2)

    g_hand = jax.jit(jax.grad(loss(hand_path), argnums=(0, 1)))(params, x)
    g_rules = jax.jit(jax.grad(loss(rules_path), argnums=(0, 1)))(params, x)
    same = jax.tree.map(lambda a, b: bool((a == b).all()), g_rules, g_hand)
    assert all(jax.tree.leaves(same)), same


def test_moe_re_meshed_layout_scatters_the_derived_axis():
    """The satellite fix: a re-meshed token layout (leading replicated
    group dim, tokens sharded on dim 1) gathers/scatters the RIGHT
    axis — derived from the resolved spec, never the hard-coded 0 —
    and still matches the dense oracle."""
    from activemonitor_tpu.ops.moe import (
        init_moe_params,
        moe_ffn_expert_parallel,
        moe_ffn_reference,
        moe_partition_rules,
    )

    mesh = make_1d_mesh("ep")
    params = init_moe_params(jax.random.key(0), d_model=16, d_ff=32, n_experts=8)
    x = jax.random.normal(jax.random.key(1), (3, 32, 16), jnp.float32)
    rules = (
        ("^router$", P(None, None)),
        (r"^w_(up|down)$", P("ep", None, None)),
        ("^x$", P(None, "ep", None)),  # tokens on dim 1, groups replicated
    )
    got = jax.jit(
        lambda p, x: moe_ffn_expert_parallel(p, x, mesh, "ep", rules=rules)
    )(params, x)
    want = moe_ffn_reference(params, x)
    assert got.shape == x.shape
    assert jnp.max(jnp.abs(got - want)) < 1e-5
    # a layout that does not shard tokens over the axis is a clear error
    bad = moe_partition_rules("ep")[:-1] + (("^x$", P(None, None)),)
    with pytest.raises(ValueError, match="does not shard over"):
        moe_ffn_expert_parallel(
            params, x[0], mesh, "ep", rules=bad
        )
    # rules leaving the expert weights unsharded would silently reuse
    # the first local-expert block on every shard — hard error instead
    with pytest.raises(ValueError, match="leading \\(expert\\) dim"):
        moe_ffn_expert_parallel(
            params, x[0], mesh, "ep", rules=(("^x$", P("ep", None)),)
        )
    # a sharded router would route differently per shard — same gate
    sharded_router = (("^router$", P("ep", None)),) + moe_partition_rules("ep")[1:]
    with pytest.raises(ValueError, match="router"):
        moe_ffn_expert_parallel(
            params, x[0], mesh, "ep", rules=sharded_router
        )


@pytest.mark.parametrize(
    "shape", [(2, 1, 1), (1, 2, 2), (2, 2, 2)], ids=["n2", "n4", "n8"]
)
def test_composed_train_step_golden(shape):
    """The composed DP×TP×PP step under rule-resolved specs: the
    resolved sharding tree equals the hand-threaded one (asserted for
    every mesh above), and a step executes to a finite loss — bitwise
    identity of the program follows from spec identity, which is the
    part the legacy runtime can also check."""
    from activemonitor_tpu.models.probe_model import tiny_config
    from activemonitor_tpu.probes.training_step import (
        build_composed_train_step,
    )
    from activemonitor_tpu.utils.compat import SUPPORTS_PARTIAL_MANUAL

    if not SUPPORTS_PARTIAL_MANUAL:
        pytest.skip("legacy shard_map: no partial-manual composed mode")
    n = shape[0] * shape[1] * shape[2]
    mesh = make_mesh(("data", "model", "pp"), shape, devices=jax.devices()[:n])
    cfg = tiny_config()
    step, params, opt, data_sh = build_composed_train_step(cfg, mesh)
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(7), (4, 17), 0, cfg.vocab_size),
        data_sh,
    )
    _, _, loss = step(params, opt, tokens)
    assert bool(jnp.isfinite(loss))
