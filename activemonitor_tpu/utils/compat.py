"""JAX API compatibility — one import site for symbols that moved.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` export, and the kwargs moved with it: the
replication check was renamed ``check_rep`` → ``check_vma`` and
partially-manual meshes flipped polarity from ``auto`` (the axes that
STAY compiler-managed) to ``axis_names`` (the axes that become manual).
Installed containers carry either vintage, so every shard_map in this
tree imports from here and writes the NEW calling convention; this
adapter translates for legacy installs.
"""

from __future__ import annotations

try:  # modern export (jax >= 0.6-era API)
    from jax import shard_map as _shard_map_impl

    _LEGACY = False
except ImportError:  # legacy home, legacy kwargs
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _LEGACY = True

# Partially-manual shard_map (manual over a subset of mesh axes, the
# rest compiler-managed — ops/pipeline.py composed mode) is broken on
# the legacy lowering: lax.axis_index becomes a PartitionId the SPMD
# partitioner rejects, and the data-carried workaround trips a hard
# CHECK in hlo_sharding_util once a scan is involved. Callers gate
# composed-mode paths on this instead of discovering it as a crash.
SUPPORTS_PARTIAL_MANUAL = not _LEGACY

# True when the modern jax.shard_map export is missing — the same
# vintage boundary behind every capability flag below. Exposed for
# skip-gates that guard against legacy-runtime crashes (a tier-1 test
# that SIGSEGVs the interpreter takes the whole suite down with it).
LEGACY_JAX = _LEGACY

# Legacy jaxlib's CPU backend rejects cross-process collectives
# ("Multiprocess computations aren't implemented on the CPU backend"),
# so the two-process DCN tests can only run on the modern runtime (or
# on real TPU, where the capability has always existed).
SUPPORTS_CPU_MULTIPROCESS = not _LEGACY


def axis_size(axis_name) -> int:
    """Static size of a named mesh axis from inside a shard_map body.

    Modern JAX exposes ``jax.lax.axis_size``; the legacy runtime keeps
    the size on the axis frame (``jax.core.axis_frame`` returns the
    bare int there). Schedules in parallel/schedules.py unroll python
    loops over ring/tree rounds, so the size must be a concrete int at
    trace time — a traced ``psum(1, axis)`` would not do.
    """
    import jax

    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(axis_name))
    from jax.core import axis_frame

    frame = axis_frame(axis_name)
    return int(getattr(frame, "size", frame))


def compiled_cost_analysis(compiled):
    """Normalize an ALREADY-compiled executable's ``cost_analysis()``
    to ``{"flops": float, "bytes_accessed": float, "output_bytes":
    float}``, or None when any vintage boundary gets in the way.

    The raw API moved twice: it returns a one-dict LIST on older
    jaxlibs and a bare dict on newer ones, and the keys are XLA's
    space-separated spellings ("bytes accessed", "bytes
    accessedout{}"). Roofline classification (obs/roofline.py) must
    not care, and a backend without the analysis (some plugin
    runtimes) must read as "unavailable", never as a crash inside a
    health probe. Callers holding a compiled object (AOT probes that
    time the very executable they analyze) come here directly;
    :func:`compile_cost_analysis` wraps the lower-and-compile step for
    everyone else.
    """
    try:
        raw = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    try:
        flops = float(raw.get("flops", 0.0) or 0.0)
        bytes_accessed = float(raw.get("bytes accessed", 0.0) or 0.0)
        output_bytes = float(raw.get("bytes accessedout{}", 0.0) or 0.0)
    except (TypeError, ValueError):
        return None
    if flops <= 0 or bytes_accessed <= 0:
        # an analysis missing either half is no analysis: some plugin
        # backends report flops with zero bytes (or vice versa), and
        # handing that downstream would discard the caller's analytic
        # fallback in favor of a degenerate-cost skip
        return None
    return {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "output_bytes": output_bytes,
    }


def compile_cost_analysis(fn, *args, **kwargs):
    """XLA's compile-time cost analysis for ``fn(*args)`` — lower +
    compile + :func:`compiled_cost_analysis`, never raising."""
    import jax

    try:
        jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
        compiled = jitted.lower(*args, **kwargs).compile()
    except Exception:
        return None
    return compiled_cost_analysis(compiled)


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    check_vma: bool = True,
    axis_names=frozenset(),
):
    """``jax.shard_map`` calling convention on any installed JAX.

    ``axis_names`` is the NEW polarity: the mesh axes the body is
    manual over; empty means all of them (fully manual, the default).
    On legacy installs it is translated to ``auto`` (its complement)
    and ``check_vma`` to ``check_rep``.
    """
    if not _LEGACY:
        kwargs = {}
        if axis_names:
            kwargs["axis_names"] = frozenset(axis_names)
        return _shard_map_impl(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=check_vma,
            **kwargs,
        )
    auto = (
        frozenset(mesh.axis_names) - frozenset(axis_names)
        if axis_names
        else frozenset()
    )
    return _shard_map_impl(
        f,
        mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=check_vma,
        auto=auto,
    )
