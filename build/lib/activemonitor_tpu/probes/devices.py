"""Device inventory probe.

Asserts the TPU slice is fully visible: device count matches the
expected topology (e.g. 8 for a v5e-8) and the platform is what the
check demands. The BASELINE.md device-inventory target:
``len(jax.devices()) == 8`` on a v5e-8, platform ``tpu``.
"""

from __future__ import annotations

from typing import Optional

from activemonitor_tpu.parallel.mesh import device_info
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult


def run(expect_devices: Optional[int] = None, require_platform: str = "") -> ProbeResult:
    info = device_info()
    ok = True
    problems = []
    if expect_devices is not None and info["count"] != expect_devices:
        ok = False
        problems.append(f"expected {expect_devices} devices, found {info['count']}")
    if require_platform and info["platform"] != require_platform:
        ok = False
        problems.append(
            f"expected platform {require_platform!r}, found {info['platform']!r}"
        )
    summary = (
        f"{info['count']}x {info['device_kind']} ({info['platform']})"
        if ok
        else "; ".join(problems)
    )
    return ProbeResult(
        ok=ok,
        summary=summary,
        metrics=[
            ProbeMetric(
                "tpu-device-count",
                info["count"],
                help="Number of accelerator devices visible to the probe",
            ),
            ProbeMetric(
                "tpu-device-healthy",
                1.0 if ok else 0.0,
                help="1 when the device inventory matches expectations",
            ),
        ],
        details=info,
    )
