"""The front door: high-QPS async ingestion for probe-as-a-service.

ROADMAP item 3. Checks used to arrive only as CRs through the
apiserver watch, so the fleet's throughput ceiling was the control
plane's. The front door is the FlowMesh-style fabric in front of the
sharded fleet: tenants submit one-shot check requests (or whole probe
DAGs) at high QPS *without touching the apiserver* — a request either
rides a cached result, fans in on an in-flight run, or triggers
exactly one run through the existing Manager enqueue path (so
sharding, tracing, attribution, and SLO accounting apply unchanged).

One request's path, in order:

1. **admission** (frontdoor/admission.py): the tenant's token bucket
   pays one token or the request is a structured ``quota`` refusal.
2. **coalescing cache** (frontdoor/coalesce.py): fresh ring result ⇒
   ``cache_hit`` (served immediately — even in degraded mode: cached
   answers are exactly what a wounded control plane can still afford);
   in-flight run ⇒ ``joined`` (fans in, fans out on completion).
3. **miss**: degraded mode (breaker open) PARKS the request in a
   bounded lot instead of dropping it — the pump replays it when the
   breaker closes; healthy mode triggers one probe run via the bound
   backend (Manager.enqueue) and registers the in-flight entry every
   duplicate joins.

The decision path is synchronous (``submit`` returns a
:class:`Ticket`; ``Ticket.wait()`` awaits the fanned-out result), so
admission latency is pure policy arithmetic — the 10k-requests/s soak
measures it without event-loop scheduling noise. Accounting is
conservation-by-construction, the serving scheduler's discipline
applied per tenant: every submitted request lands in EXACTLY one of
{cache_hit, joined, run, parked, refused}, and :meth:`FrontDoor.
conservation` cross-checks the admission ledger against the outcome
ledger so a tenant-attribution bug cannot hide behind balanced global
totals.

Everything timed runs on the injectable Clock (``hack/lint.py`` bans
wall-clock reads in this package); state is single-owner on the event
loop like the manager's queue sets.
"""

from __future__ import annotations

import asyncio
import logging
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

from activemonitor_tpu.frontdoor.admission import (
    PRE_ADMISSION_REASONS,
    REFUSE_ABANDONED,
    REFUSE_PARKED_FULL,
    REFUSE_UNROUTED,
    AdmissionController,
)
from activemonitor_tpu.frontdoor.coalesce import (
    LOOKUP_HIT,
    LOOKUP_INFLIGHT,
    CoalescingCache,
)
from activemonitor_tpu.frontdoor.dag import ProbeDag
from activemonitor_tpu.obs.history import CheckResult, ResultHistory
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.frontdoor")

# one-of-exactly-one outcome vocabulary (the conservation ledger's
# columns and the healthcheck_frontdoor_requests_total{outcome} label)
OUTCOME_HIT = "cache_hit"
OUTCOME_JOINED = "joined"
OUTCOME_RUN = "run"
OUTCOME_PARKED = "parked"
OUTCOME_REFUSED = "refused"

# degraded-mode parking lot bound: beyond this the refusal is
# structured (parked_full), never an unbounded queue
DEFAULT_PARK_CAPACITY = 1024

# QPS is reported over rotating buckets of this many seconds
QPS_WINDOW_SECONDS = 5.0

# an in-flight run older than this is reaped (waiters cancelled): the
# reconciler's synthesized-timeout path records SOMETHING for every
# owned check, so only an unroutable key (deleted check, disowned
# shard) can strand an entry this long
DEFAULT_REAP_SECONDS = 600.0


@dataclass
class Ticket:
    """One submitted request's decision + (eventually) its result."""

    rid: int
    tenant: str
    check: str
    outcome: str  # decision-time outcome (vocabulary above)
    shard: int = 0
    reason: str = ""  # refusal reason; "" otherwise
    result: Optional[CheckResult] = None  # immediate for cache hits
    future: Optional[asyncio.Future] = None  # joined / run / parked
    # the two-ceiling freshness decision (CoalescingCache.clamp) the
    # lookup ran under — structured, so a narrowed window is visible to
    # the caller instead of silent; None for pre-lookup refusals
    clamp: Optional[dict] = None
    # the decision's lifecycle on the door's monotonic clock —
    # ("admit"|"coalesce-join"|"demand-fire"|"enqueue"|"parked", t) in
    # order; the critical-path waterfall's front-door evidence
    lifecycle: List[Tuple[str, float]] = field(default_factory=list)

    @property
    def trace_id(self) -> str:
        """The underlying run's trace id (joins the N fanned-out
        responses to the ONE reconcile cycle at /debug/traces)."""
        return self.result.trace_id if self.result is not None else ""

    async def wait(self) -> Optional[CheckResult]:
        """The fanned-out result (immediately for hits/refusals)."""
        if self.result is None and self.future is not None:
            self.result = await self.future
        return self.result


@dataclass
class _Parked:
    """A degraded-mode request awaiting the pump."""

    tenant: str  # the ledger (booked) name
    check: str
    freshness: Optional[float]
    future: asyncio.Future
    shard: int
    parked_at: float


@dataclass
class _Tally:
    """One tenant's outcome ledger (admission keeps its own)."""

    submitted: int = 0
    cache_hits: int = 0
    joins: int = 0
    runs: int = 0
    parked: int = 0  # currently parked (decrements when pumped)
    # requests whose asked freshness exceeded the ceiling in force and
    # was narrowed (the two-ceiling rule) — informational, orthogonal
    # to the one-of-exactly-one outcome columns
    clamped: int = 0

    def to_dict(self) -> dict:
        return {
            "submitted": self.submitted,
            "cache_hits": self.cache_hits,
            "coalesced_joins": self.joins,
            "probe_runs": self.runs,
            "parked": self.parked,
            "clamped": self.clamped,
        }


class FrontDoor:
    """Admission + coalescing + DAG execution over a bound backend."""

    def __init__(
        self,
        history: ResultHistory,
        admission: AdmissionController,
        *,
        clock: Optional[Clock] = None,
        metrics=None,  # MetricsCollector (duck-typed; optional)
        resilience=None,  # ResilienceCoordinator: .degraded drives parking
        default_freshness: float = 30.0,
        park_capacity: int = DEFAULT_PARK_CAPACITY,
    ):
        self.clock = clock or Clock()
        self.admission = admission
        self.cache = CoalescingCache(
            history, clock=self.clock, default_freshness=default_freshness
        )
        self.metrics = metrics
        self.resilience = resilience
        self.park_capacity = max(0, int(park_capacity))
        self._parked: Deque[_Parked] = deque()
        # shard -> trigger(namespace, name); None key = default backend
        self._backends: Dict[Optional[int], Callable[[str, str], None]] = {}
        # sharded fleet: the live ownership predicate (Manager wires
        # coordinator.owns_key). A miss for an unowned key is a
        # structured `unrouted` refusal naming its shard — this
        # replica's rings never see the owner's results, so triggering
        # (or parking) here would strand the waiters until reap
        self.owns: Optional[Callable[[str], bool]] = None
        self._rid = 0
        # durable workload trace (obs/journal.py), wired by the Manager
        # post-construction like the backend/ownership hooks: every
        # submit's decision is journaled as one `arrival` event from
        # _account — the single point every outcome passes through
        self.journal = None
        # span tracer (obs/trace.py), wired by the Manager: the door's
        # admission decision is recorded as an `admission` span INTO
        # the triggered cycle's trace, so the critical-path waterfall
        # sees the front-door hop. None: lifecycle-only evidence.
        self.tracer = None
        # check -> the trace id of its most recently triggered run, so
        # coalesce-joins attach their admission spans to the run they
        # actually ride (bounded by the fleet's check count)
        self._inflight_trace: Dict[str, str] = {}
        self._last_arrival: Optional[float] = None
        # the DAG shape note for arrival events submitted via run_dag
        self._dag_shape: Optional[dict] = None
        self._tallies: Dict[str, _Tally] = {}
        # fleet-wide running totals in lockstep with the per-tenant
        # tallies, so the per-submit gauge refresh is O(1), not a walk
        self._totals = _Tally()
        self.reaped_runs = 0
        # QPS over rotating buckets on the injected clock
        self._qps_bucket_start: Optional[float] = None
        self._qps_bucket_count = 0
        self._qps_last = 0.0

    # -- wiring ----------------------------------------------------------
    def bind(self, trigger: Callable[[str, str], None]) -> None:
        """The default backend — Manager.enqueue's (namespace, name)
        signature, so a triggered run IS a normal workqueue cycle."""
        self._backends[None] = trigger

    def bind_shard(self, shard: int, trigger: Callable[[str, str], None]) -> None:
        """Per-shard backends for a fleet where this front door fans
        out to several replicas; keys route via the admission router."""
        self._backends[shard] = trigger

    @property
    def degraded(self) -> bool:
        return bool(self.resilience is not None and self.resilience.degraded)

    # -- adaptive degraded mode (resilience/adapt.py) --------------------
    def widen_freshness(self, factor: float) -> float:
        """Engage the degraded-mode staleness ceiling at ``factor`` ×
        the operator default (clamped to widen-only), so cached answers
        absorb demand under a confirmed control-plane burn. Returns the
        ceiling now in force."""
        self.cache.set_degraded_ceiling(
            self.cache.default_freshness * max(1.0, float(factor))
        )
        return self.cache.freshness_ceiling()

    def restore_freshness(self) -> None:
        """Release the degraded-mode ceiling: back to the operator
        default. Parked requests keep the freshness they asked for —
        the pump re-decides them under the restored ceiling."""
        self.cache.set_degraded_ceiling(None)

    # -- the submit path -------------------------------------------------
    def submit(
        self,
        tenant: str,
        check: str,
        freshness: Optional[float] = None,
    ) -> Ticket:
        """One request, decided synchronously. ``check`` is the check
        identity (``namespace/name``); ``freshness`` the seconds a
        cached result stays acceptable (None: the door's default)."""
        if "/" not in check:
            raise ValueError(
                f"check identity must be namespace/name, got {check!r}"
            )
        started = self.clock.monotonic()
        self._rid += 1
        rid = self._rid
        self._note_qps(started)
        decision = self.admission.admit(tenant, check)
        # ledger rows are keyed by the BOOKED name (never-seen tenants
        # share the overflow row), so open-endpoint traffic cannot mint
        # unbounded tallies or metric series
        booked = decision.booked
        tally = self._tallies.setdefault(booked, _Tally())
        tally.submitted += 1
        self._totals.submitted += 1
        if not decision.admitted:
            ticket = Ticket(
                rid=rid,
                tenant=tenant,
                check=check,
                outcome=OUTCOME_REFUSED,
                reason=decision.reason,
            )
            self._account(ticket, started, booked)
            return ticket
        if self.owns is not None and not self.owns(check):
            # sharded fleet, another replica's key: this replica's
            # rings never receive the owner's results, so a run or a
            # parked wait here would strand every waiter until reap.
            # Refuse with the shard id so a fronting router re-aims.
            refusal = self.admission.refuse(
                tenant, REFUSE_UNROUTED, booked=booked
            )
            ticket = Ticket(
                rid=rid,
                tenant=tenant,
                check=check,
                outcome=OUTCOME_REFUSED,
                shard=decision.shard,
                reason=refusal.reason,
            )
            self._account(ticket, started, booked)
            return ticket
        # the two-ceiling freshness rule, decided ONCE and surfaced on
        # the ticket + ledger: a request asking for more staleness than
        # the ceiling in force narrows audibly, never silently
        clamp = self.cache.clamp(freshness)
        if clamp["clamped"]:
            tally.clamped += 1
            self._totals.clamped += 1
            if self.metrics is not None:
                self.metrics.record_frontdoor_clamp(booked, clamp["mode"])
        lifecycle: List[Tuple[str, float]] = [("admit", started)]
        outcome, fresh = self.cache.lookup(check, freshness)
        if outcome == LOOKUP_HIT:
            tally.cache_hits += 1
            self._totals.cache_hits += 1
            ticket = Ticket(
                rid=rid,
                tenant=tenant,
                check=check,
                outcome=OUTCOME_HIT,
                shard=decision.shard,
                result=fresh,
                lifecycle=lifecycle,
            )
        elif outcome == LOOKUP_INFLIGHT:
            tally.joins += 1
            self._totals.joins += 1
            lifecycle.append(("coalesce-join", self.clock.monotonic()))
            # the join rides an in-flight run: its admission decision
            # is front-door time ON that run's critical path too
            self._record_admission(
                self._inflight_trace.get(check, ""), started
            )
            ticket = Ticket(
                rid=rid,
                tenant=tenant,
                check=check,
                outcome=OUTCOME_JOINED,
                shard=decision.shard,
                future=self.cache.join(check),
                lifecycle=lifecycle,
            )
        elif self.degraded:
            # breaker open: PARK, never drop — the cache already served
            # what it could; a miss is real demand the pump replays the
            # moment the control plane recovers (docs/resilience.md)
            if len(self._parked) >= self.park_capacity:
                refusal = self.admission.refuse(
                    tenant, REFUSE_PARKED_FULL, booked=booked
                )
                ticket = Ticket(
                    rid=rid,
                    tenant=tenant,
                    check=check,
                    outcome=OUTCOME_REFUSED,
                    shard=decision.shard,
                    reason=refusal.reason,
                )
            else:
                tally.parked += 1
                self._totals.parked += 1
                lifecycle.append(("parked", self.clock.monotonic()))
                fut: asyncio.Future = (
                    asyncio.get_running_loop().create_future()
                )
                self._parked.append(
                    _Parked(
                        tenant=booked,
                        check=check,
                        freshness=freshness,
                        future=fut,
                        shard=decision.shard,
                        parked_at=started,
                    )
                )
                ticket = Ticket(
                    rid=rid,
                    tenant=tenant,
                    check=check,
                    outcome=OUTCOME_PARKED,
                    shard=decision.shard,
                    future=fut,
                    lifecycle=lifecycle,
                )
        else:
            tally.runs += 1
            self._totals.runs += 1
            self.cache.begin(check)
            lifecycle.append(("demand-fire", self.clock.monotonic()))
            run_trace = self._trigger(check, decision.shard)
            lifecycle.append(("enqueue", self.clock.monotonic()))
            if run_trace:
                self._inflight_trace[check] = run_trace
            self._record_admission(run_trace, started)
            ticket = Ticket(
                rid=rid,
                tenant=tenant,
                check=check,
                outcome=OUTCOME_RUN,
                shard=decision.shard,
                future=self.cache.join(check),
                lifecycle=lifecycle,
            )
        ticket.clamp = clamp
        self._account(ticket, started, booked)
        return ticket

    # -- DAG execution ---------------------------------------------------
    async def run_dag(
        self, tenant: str, dag: ProbeDag
    ) -> Dict[str, Ticket]:
        """Execute a probe DAG stage by stage: each step is a normal
        front-door submission (quota paid per step, coalescing per
        step), and a stage starts only when every step of the previous
        stage has its result — downstream steps therefore reuse
        upstream results through the cache instead of re-probing. A
        refused or result-less step (cancelled waiter) stops the DAG:
        its downstream steps are never submitted (reported absent in
        the returned map, so the caller sees exactly how far it got)."""
        tickets: Dict[str, Ticket] = {}
        stages = dag.stages()
        # stamp the DAG shape on every arrival event this execution
        # journals (the workload trace records the *structure* of the
        # demand, not just its flat request stream)
        self._dag_shape = {
            "name": getattr(dag, "name", ""),
            "steps": sum(len(stage) for stage in stages),
            "stages": len(stages),
        }
        try:
            for stage in stages:
                stage_tickets = [
                    (step, self.submit(tenant, step.check, step.freshness))
                    for step in stage
                ]
                for step, ticket in stage_tickets:
                    tickets[step.name] = ticket
                results = await asyncio.gather(
                    *(t.wait() for _s, t in stage_tickets),
                    return_exceptions=True,
                )
                for (step, ticket), outcome in zip(stage_tickets, results):
                    if ticket.outcome == OUTCOME_REFUSED or isinstance(
                        outcome, BaseException
                    ):
                        return tickets  # stop: downstream is meaningless
            return tickets
        finally:
            self._dag_shape = None

    # -- degraded-mode pump ---------------------------------------------
    def pump(self) -> int:
        """Replay parked requests once the controller is healthy again:
        each re-decides against the cache (the outage may have left a
        fresh result or an in-flight run to ride) and otherwise
        triggers its run. Returns how many were resolved; stops the
        moment degraded mode re-trips mid-replay. Driven by the
        manager's resilience sweep next to the status-write replay."""
        pumped = 0
        while self._parked and not self.degraded:
            parked = self._parked.popleft()
            tally = self._tallies.setdefault(parked.tenant, _Tally())
            tally.parked -= 1
            self._totals.parked -= 1
            if parked.future.done():
                # waiter gave up while parked (cancelled wait): booked
                # as a structured post-admission refusal so the ledger
                # stays exact
                self._refuse_parked(parked, REFUSE_ABANDONED)
                pumped += 1
                continue
            if self.owns is not None and not self.owns(parked.check):
                # the shard was handed off while this request sat
                # parked: same verdict the submit path gives — a
                # structured unrouted refusal, never a run this
                # replica's rings could not resolve
                self._refuse_parked(parked, REFUSE_UNROUTED)
                parked.future.cancel()
                pumped += 1
                continue
            outcome, fresh = self.cache.lookup(parked.check, parked.freshness)
            if outcome == LOOKUP_HIT:
                tally.cache_hits += 1
                self._totals.cache_hits += 1
                parked.future.set_result(fresh)
            elif outcome == LOOKUP_INFLIGHT:
                tally.joins += 1
                self._totals.joins += 1
                self._chain(self.cache.join(parked.check), parked.future)
            else:
                tally.runs += 1
                self._totals.runs += 1
                self.cache.begin(parked.check)
                run_trace = self._trigger(parked.check, parked.shard)
                if run_trace:
                    self._inflight_trace[parked.check] = run_trace
                # the pumped run's admission span covers the whole
                # parked wait — that IS where the request's time went
                self._record_admission(run_trace, parked.parked_at)
                self._chain(self.cache.join(parked.check), parked.future)
            pumped += 1
        self._refresh_gauges()
        return pumped

    def reap(self, max_age_seconds: float = DEFAULT_REAP_SECONDS) -> int:
        """Cancel waiters of in-flight entries older than ``max_age``.
        A deleted, quarantined, or stopped check's demanded run records
        no result (the reconciler consumes the demand unserved); the
        synthesized-timeout path covers every other owned run. Counted,
        driven by the same resilience sweep as the pump."""
        stale = self.cache.stale_inflight(
            self.clock.monotonic() - max_age_seconds
        )
        for key in stale:
            self.cache.forget(key)
            self._inflight_trace.pop(key, None)
            self.reaped_runs += 1
        if stale:
            self._refresh_gauges()
        return len(stale)

    # -- internals -------------------------------------------------------
    def _refuse_parked(self, parked: _Parked, reason: str) -> None:
        """A parked request refused at pump time: the ledger AND the
        refusal counter both record it (the submit-path counters fire
        from _account, which pump-time refusals never pass through)."""
        self.admission.refuse(parked.tenant, reason)
        if self.metrics is not None:
            self.metrics.record_frontdoor_refusal(parked.tenant, reason)

    @staticmethod
    def _chain(source: asyncio.Future, target: asyncio.Future) -> None:
        """Resolve ``target`` from ``source`` (a parked request's
        pre-existing future joined onto a live run)."""

        def _copy(fut: asyncio.Future) -> None:
            if target.done():
                return
            if fut.cancelled():
                target.cancel()
            else:
                target.set_result(fut.result())

        source.add_done_callback(_copy)

    def _trigger(self, check: str, shard: int) -> Optional[str]:
        trigger = self._backends.get(shard, self._backends.get(None))
        if trigger is None:
            raise RuntimeError(
                "front door has no backend bound (FrontDoor.bind)"
            )
        namespace, _, name = check.partition("/")
        # Manager.enqueue returns the cycle's (pending) trace id so the
        # admission span lands on the run it triggered; a plain
        # backend returning None costs the span, never the trigger
        return trigger(namespace, name)

    def _record_admission(self, trace_id: Optional[str], started: float) -> None:
        """Book the admission decision as a span on the triggered (or
        joined) run's trace — the waterfall's ``admission`` stage.
        Best-effort: no tracer / no trace id / a recording error costs
        the span, never the submit."""
        if self.tracer is None or not trace_id:
            return
        try:
            self.tracer.record_span(
                "admission",
                start=started,
                end=self.clock.monotonic(),
                trace_id=trace_id,
            )
        except Exception:
            log.exception("admission span recording failed")

    def _note_qps(self, now: float) -> None:
        if self._qps_bucket_start is None:
            self._qps_bucket_start = now
        elif now - self._qps_bucket_start >= QPS_WINDOW_SECONDS:
            elapsed = now - self._qps_bucket_start
            self._qps_last = self._qps_bucket_count / elapsed
            self._qps_bucket_start = now
            self._qps_bucket_count = 0
        self._qps_bucket_count += 1

    def qps(self) -> float:
        """Submissions/second: the live bucket once it holds ≥1s of
        data, else the last completed bucket's rate."""
        if self._qps_bucket_start is not None:
            elapsed = self.clock.monotonic() - self._qps_bucket_start
            if elapsed >= 1.0:
                return self._qps_bucket_count / elapsed
        return self._qps_last

    def _account(self, ticket: Ticket, started: float, booked: str) -> None:
        if self.journal is not None:
            gap = (
                started - self._last_arrival
                if self._last_arrival is not None
                else 0.0
            )
            self._last_arrival = started
            # never raises by the journal's own contract, but the
            # submit path tolerates a hostile duck-typed journal too
            try:
                self.journal.record_arrival(
                    tenant=booked,
                    check=ticket.check,
                    outcome=ticket.outcome,
                    gap=gap,
                    reason=ticket.reason,
                    shard=ticket.shard,
                    dag=self._dag_shape,
                )
            except Exception:
                log.exception("arrival journaling failed")
        # metric labels carry the BOOKED name — bounded by the
        # admission config even on an open endpoint
        if self.metrics is not None:
            self.metrics.record_frontdoor_request(booked, ticket.outcome)
            if ticket.outcome == OUTCOME_REFUSED:
                self.metrics.record_frontdoor_refusal(booked, ticket.reason)
            self.metrics.observe_frontdoor_admission(
                max(0.0, self.clock.monotonic() - started)
            )
        self._refresh_gauges()

    def coalesce_ratios(self) -> dict:
        """hit / miss / join fractions over every admitted lookup (the
        pinned healthcheck_frontdoor_coalesce_ratio{kind} gauges), from
        the O(1) running totals. ``miss`` counts requests that became
        runs or parked — demand the cache could not absorb."""
        hits = self._totals.cache_hits
        joins = self._totals.joins
        misses = self._totals.runs + self._totals.parked
        total = hits + joins + misses
        if not total:
            return {"hit": 0.0, "miss": 0.0, "join": 0.0, "lookups": 0}
        return {
            "hit": hits / total,
            "miss": misses / total,
            "join": joins / total,
            "lookups": total,
        }

    def queue_depth(self) -> int:
        """Parked requests + waiters fanned in on in-flight runs — the
        demand the door is currently holding open."""
        return len(self._parked) + self.cache.waiter_count()

    def _refresh_gauges(self) -> None:
        if self.metrics is None:
            return
        self.metrics.set_frontdoor_queue_depth(self.queue_depth())
        ratios = self.coalesce_ratios()
        self.metrics.set_frontdoor_coalesce(
            hit=ratios["hit"], miss=ratios["miss"], join=ratios["join"]
        )

    # -- accounting ------------------------------------------------------
    def conservation(self) -> dict:
        """The exact per-tenant ledger: every submitted request lands in
        exactly one of {cache_hit, join, run, parked, refused}, so

            submitted == cache_hits + joins + runs + parked + refused

        per tenant AND in total — and the admission controller's
        independent event-time ledger must agree (admitted == the four
        non-refused outcomes + post-admission parked_full refusals),
        so a tenant-attribution bug cannot hide behind balanced global
        totals. ``ok`` is the AND of every equality — the property
        test's and the stress soak's gate."""
        tenants = sorted(
            set(self._tallies)
            | set(self.admission.admitted)
            | set(self.admission.refused)
        )
        rows: Dict[str, dict] = {}
        all_ok = True
        for tenant in tenants:
            tally = self._tallies.get(tenant, _Tally())
            refused = dict(self.admission.refused.get(tenant, {}))
            refused_total = sum(refused.values())
            admitted = self.admission.admitted.get(tenant, 0)
            # quota/unknown_tenant refuse BEFORE the bucket admits;
            # parked_full/abandoned refuse an already-admitted request
            pre = sum(refused.get(r, 0) for r in PRE_ADMISSION_REASONS)
            post = refused_total - pre
            row = tally.to_dict()
            row["admitted"] = admitted
            row["refused"] = refused
            row["refused_total"] = refused_total
            outcomes = (
                tally.cache_hits + tally.joins + tally.runs + tally.parked
            )
            row["ok"] = (
                tally.submitted == outcomes + refused_total
                and tally.submitted == admitted + pre
                and admitted == outcomes + post
            )
            all_ok = all_ok and row["ok"]
            rows[tenant] = row
        return {
            "tenants": rows,
            "submitted": sum(r["submitted"] for r in rows.values()),
            "refused": sum(r["refused_total"] for r in rows.values()),
            "cache_hits": sum(r["cache_hits"] for r in rows.values()),
            "coalesced_joins": sum(
                r["coalesced_joins"] for r in rows.values()
            ),
            "probe_runs": sum(r["probe_runs"] for r in rows.values()),
            "parked": sum(r["parked"] for r in rows.values()),
            "ok": all_ok,
        }

    def snapshot(self) -> dict:
        """The /statusz fleet block (schema pinned by the contract
        test; rollup_statusz merges these across replicas)."""
        conservation = self.conservation()
        return {
            "qps": self.qps(),
            "coalescing": self.coalesce_ratios(),
            "queue_depth": self.queue_depth(),
            "parked": len(self._parked),
            "inflight_runs": len(self.cache.inflight_keys()),
            "reaped_runs": self.reaped_runs,
            "degraded": self.degraded,
            "conservation_ok": conservation["ok"],
            "freshness": {
                "default": self.cache.default_freshness,
                "ceiling": self.cache.freshness_ceiling(),
                "widened": self.cache.degraded_ceiling is not None,
                "clamped": self._totals.clamped,
            },
            "requests": {
                "submitted": conservation["submitted"],
                "refused": conservation["refused"],
                "cache_hits": conservation["cache_hits"],
                "coalesced_joins": conservation["coalesced_joins"],
                "probe_runs": conservation["probe_runs"],
            },
            "tenants": {
                tenant: {
                    "submitted": row["submitted"],
                    "refused": row["refused_total"],
                    "refusals": row["refused"],
                    "clamped": row.get("clamped", 0),
                }
                for tenant, row in conservation["tenants"].items()
            },
        }
