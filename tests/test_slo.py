"""Fleet SLO layer tests: the result-history ring, the rolling-window
SLO math, the /statusz contract, and the acceptance slice of ISSUE 2 —
a FakeEngine + fake-clock scripted pass/fail sequence yielding exact
availability / p95 / error-budget values via both /statusz and
``sample_value()``, with the cycle's trace id riding the
``healthcheck_phase_seconds`` histogram as an OpenMetrics exemplar.
"""

import asyncio
import collections
import datetime
import json
import re

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine
from activemonitor_tpu.engine.base import PHASE_FAILED, PHASE_SUCCEEDED
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.obs import FleetStatus, ResultHistory, SLOConfig
from activemonitor_tpu.obs.slo import (
    DEFAULT_WINDOW_SECONDS,
    evaluate,
    fleet_goodput,
    quantile,
    rollup_statusz,
    slo_config_from_spec,
    window_results,
)
from activemonitor_tpu.utils.clock import FakeClock

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"


def make_hc(name="hc-slo", repeat=60, slo=None):
    spec = {
        "repeatAfterSec": repeat,
        "level": "cluster",
        # backoffMin == backoffMax == 1 makes the poll pacer sleep
        # exactly 1 s per step, so scripted poll counts translate to
        # exact latencies on the fake clock
        "backoffMax": 1,
        "backoffMin": 1,
        "workflow": {
            "generateName": f"{name}-",
            "workflowtimeout": 30,
            "resource": {
                "namespace": "health",
                "serviceAccount": "sa",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if slo is not None:
        spec["slo"] = slo
    return HealthCheck.from_dict(
        {"metadata": {"name": name, "namespace": "health"}, "spec": spec}
    )


# ---------------------------------------------------------------------
# history ring
# ---------------------------------------------------------------------


def test_history_eviction_order_under_wraparound():
    clock = FakeClock()
    history = ResultHistory(clock, capacity=5)
    for i in range(12):
        history.record("ns/hc", ok=True, latency=float(i), workflow=f"wf-{i}")
    results = history.results("ns/hc")
    assert len(results) == 5
    # oldest evicted first; survivors keep insertion order
    assert [r.workflow for r in results] == [f"wf-{i}" for i in range(7, 12)]
    assert [r.latency for r in results] == [7.0, 8.0, 9.0, 10.0, 11.0]
    assert history.last("ns/hc").workflow == "wf-11"


def test_history_per_check_isolation():
    history = ResultHistory(FakeClock(), capacity=3)
    for i in range(5):
        history.record("ns/a", ok=True, latency=1.0, workflow=f"a-{i}")
    history.record("ns/b", ok=False, latency=2.0, workflow="b-0")
    assert len(history.results("ns/a")) == 3  # a wrapped
    assert len(history.results("ns/b")) == 1  # b untouched by a's churn
    assert history.results("ns/b")[0].ok is False
    assert sorted(history.checks()) == ["ns/a", "ns/b"]
    history.forget("ns/a")
    assert history.results("ns/a") == []
    assert len(history.results("ns/b")) == 1


def test_history_tail_and_timestamps_come_from_injected_clock():
    clock = FakeClock()
    history = ResultHistory(clock)

    async def drive():
        history.record("ns/hc", ok=True, latency=0.0, workflow="w1")
        await clock.advance(10.0)
        history.record("ns/hc", ok=True, latency=0.0, workflow="w2")

    asyncio.run(drive())
    first, second = history.results("ns/hc")
    assert (second.ts - first.ts).total_seconds() == 10.0
    assert [r.workflow for r in history.tail("ns/hc", 1)] == ["w2"]
    assert history.tail("ns/hc", 0) == []
    assert history.tail("ns/none") == []


# ---------------------------------------------------------------------
# SLO math (pure functions, exact values)
# ---------------------------------------------------------------------


def scripted_history(clock, verdicts_latencies, key="ns/hc"):
    history = ResultHistory(clock)
    for ok, latency in verdicts_latencies:
        history.record(key, ok=ok, latency=latency, workflow="wf")
    return history


def test_quantiles_are_nearest_rank_exact():
    latencies = [0.0, 1.0, 1.0, 2.0, 2.0, 3.0, 3.0, 4.0, 4.0, 9.0]
    assert quantile(latencies, 0.50) == 2.0
    assert quantile(latencies, 0.95) == 9.0
    assert quantile(latencies, 0.99) == 9.0
    assert quantile([5.0], 0.95) == 5.0
    assert quantile([], 0.95) is None


def test_evaluate_exact_budget_math():
    clock = FakeClock()
    # 8 passes, 2 failures; objective 0.8 allows a 0.2 failure ratio
    history = scripted_history(
        clock, [(True, 1.0)] * 8 + [(False, 1.0)] * 2
    )
    state = evaluate(
        history.results("ns/hc"),
        SLOConfig(objective=0.8, window_seconds=3600),
        clock.now(),
    )
    assert state.availability == 0.8
    assert state.burn_rate == pytest.approx(1.0)
    assert state.error_budget_remaining == pytest.approx(0.0)
    # a blown budget goes negative — the overdraft is the signal
    history.record("ns/hc", ok=False, latency=1.0, workflow="wf")
    state = evaluate(
        history.results("ns/hc"),
        SLOConfig(objective=0.8, window_seconds=3600),
        clock.now(),
    )
    assert state.error_budget_remaining < 0


def test_results_age_out_of_the_window():
    clock = FakeClock()
    history = ResultHistory(clock)

    async def drive():
        history.record("ns/hc", ok=False, latency=1.0, workflow="old")
        await clock.advance(120.0)
        history.record("ns/hc", ok=True, latency=1.0, workflow="new")

    asyncio.run(drive())
    config = SLOConfig(objective=0.9, window_seconds=60)
    windowed = window_results(history.results("ns/hc"), clock.now(), 60)
    assert [r.workflow for r in windowed] == ["new"]
    state = evaluate(history.results("ns/hc"), config, clock.now())
    # the old failure aged out: a clean window, full budget
    assert state.availability == 1.0
    assert state.error_budget_remaining == 1.0
    assert state.burn_rate == 0.0


def test_window_left_boundary_is_exclusive():
    """The window is (now - windowSeconds, now]: a result EXACTLY one
    window old has aged out."""
    clock = FakeClock()
    history = ResultHistory(clock)

    async def drive():
        history.record("ns/hc", ok=False, latency=1.0, workflow="boundary")
        await clock.advance(60.0)
        history.record("ns/hc", ok=True, latency=1.0, workflow="fresh")

    asyncio.run(drive())
    windowed = window_results(history.results("ns/hc"), clock.now(), 60.0)
    assert [r.workflow for r in windowed] == ["fresh"]


def test_evaluate_empty_window_reports_none():
    clock = FakeClock()
    state = evaluate([], SLOConfig(objective=0.9, window_seconds=60), clock.now())
    assert state.availability is None
    assert state.error_budget_remaining is None
    assert state.burn_rate is None


def test_slo_config_from_spec_defaults_off():
    assert slo_config_from_spec(make_hc().spec) is None
    config = slo_config_from_spec(
        make_hc(slo={"objective": 0.99, "windowSeconds": 600}).spec
    )
    assert config == SLOConfig(objective=0.99, window_seconds=600.0)


def test_fleet_goodput_is_run_weighted():
    clock = FakeClock()
    history = ResultHistory(clock)
    for _ in range(9):
        history.record("ns/flappy", ok=False, latency=1.0, workflow="wf")
    history.record("ns/flappy", ok=True, latency=1.0, workflow="wf")
    history.record("ns/steady", ok=True, latency=1.0, workflow="wf")
    ratio = fleet_goodput(history, {}, clock.now())
    assert ratio == pytest.approx(2 / 11)
    assert fleet_goodput(ResultHistory(clock), {}, clock.now()) is None


# ---------------------------------------------------------------------
# FleetStatus: gauges + /statusz payload
# ---------------------------------------------------------------------


SLO_LABELS = {"healthcheck_name": "hc-slo", "namespace": "health"}


def test_fleet_status_updates_slo_gauges_and_forget_clears_them():
    clock = FakeClock()
    metrics = MetricsCollector()
    fleet = FleetStatus(clock, metrics)
    hc = make_hc(slo={"objective": 0.8, "windowSeconds": 3600})
    for ok in (True, True, True, False):
        fleet.record(hc, ok=ok, latency=1.0, workflow="wf")
    assert (
        metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
        == 0.75
    )
    assert metrics.sample_value(
        "healthcheck_error_budget_remaining", SLO_LABELS
    ) == pytest.approx(1.0 - 0.25 / 0.2)
    assert metrics.sample_value(
        "healthcheck_slo_burn_rate", SLO_LABELS
    ) == pytest.approx(0.25 / 0.2)
    # the fleet rollup is refreshed off the record path (manager loop /
    # statusz), not per run
    assert fleet.refresh_fleet_goodput() == 0.75
    assert metrics.sample_value("healthcheck_fleet_goodput_ratio", {}) == 0.75
    fleet.forget(hc.key, hc.metadata.name, hc.metadata.namespace)
    assert (
        metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
        is None
    )
    assert fleet.history.results(hc.key) == []


def test_fleet_status_without_slo_block_sets_no_slo_series():
    clock = FakeClock()
    metrics = MetricsCollector()
    fleet = FleetStatus(clock, metrics)
    fleet.record(make_hc(), ok=True, latency=1.0, workflow="wf")
    assert (
        metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
        is None
    )
    # fleet goodput still counts the run
    assert fleet.refresh_fleet_goodput() == 1.0
    assert metrics.sample_value("healthcheck_fleet_goodput_ratio", {}) == 1.0


def test_removing_the_slo_block_clears_the_series():
    """Editing spec.slo off a live check must stop its gauges from
    advertising the last pre-edit budget forever."""
    clock = FakeClock()
    metrics = MetricsCollector()
    fleet = FleetStatus(clock, metrics)
    with_slo = make_hc(slo={"objective": 0.9, "windowSeconds": 600})
    fleet.record(with_slo, ok=True, latency=1.0, workflow="wf")
    assert (
        metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
        == 1.0
    )
    edited = make_hc()  # same check, slo block removed
    fleet.record(edited, ok=True, latency=1.0, workflow="wf")
    assert (
        metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
        is None
    )


def test_same_name_checks_in_different_namespaces_keep_separate_series():
    clock = FakeClock()
    metrics = MetricsCollector()
    fleet = FleetStatus(clock, metrics)
    a = make_hc(slo={"objective": 0.9, "windowSeconds": 600})
    b = make_hc(slo={"objective": 0.9, "windowSeconds": 600})
    b.metadata.namespace = "staging"
    fleet.record(a, ok=True, latency=1.0, workflow="wf")
    fleet.record(b, ok=False, latency=1.0, workflow="wf")
    assert (
        metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
        == 1.0
    )
    assert (
        metrics.sample_value(
            "healthcheck_slo_availability_ratio",
            {"healthcheck_name": "hc-slo", "namespace": "staging"},
        )
        == 0.0
    )
    # deleting one namespace's check leaves the other's series alone
    fleet.forget(b.key, b.metadata.name, b.metadata.namespace)
    assert (
        metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
        == 1.0
    )


# the /statusz schema, locked field-by-field like the exposition test:
# renaming or retyping any of these breaks dashboards and `am-tpu
# status` alike, so it must be a deliberate, test-visible change
FLEET_FIELDS = {
    "checks": int,
    "window_runs": int,
    "goodput_ratio": (int, float, type(None)),
    # lost-goodput attribution block (ISSUE 7): the decomposition that
    # sums to 1 - goodput_ratio (obs/attribution.py)
    "goodput": dict,
    "generated_at": str,
    # resilience block (ISSUE 3): degraded mode, breaker verdict,
    # replay backlog, fleet-wide remedy budget
    "degraded": bool,
    "breaker": (dict, type(None)),
    "status_writes_queued": int,
    "remedy_tokens": (int, float, type(None)),
    # anomaly rollup (ISSUE 4): checks per non-ok analysis state
    "anomalies": dict,
    # sharded-fleet ownership (ISSUE 6): this replica's owned shards
    # and per-shard check counts; None when unsharded
    "sharding": (dict, type(None)),
    # scenario-matrix round summary (ISSUE 12): the latest observed
    # round's per-cell verdicts; None until a matrix source is wired
    "matrix": (dict, type(None)),
    # front-door ingestion summary (ISSUE 15): QPS, coalescing ratios,
    # queue depth, per-tenant refusals; None when no front door is wired
    "frontdoor": (dict, type(None)),
    # durable telemetry journal (ISSUE 16): segment table, per-stream
    # counts, lag; None when no --journal-dir is wired
    "journal": (dict, type(None)),
    # critical-path latency decomposition (ISSUE 17): run-weighted
    # merge of the per-check blocks; None until a windowed run still
    # has spans in the ring
    "critical_path": (dict, type(None)),
    # closed-loop adaptive control (ISSUE 18): engaged levers, cadence
    # episodes, front-door degraded state, recent decisions; None when
    # no AdaptiveController is wired
    "adaptive": (dict, type(None)),
    # multi-cluster federation (ISSUE 19): cluster registry states,
    # routing, global front-door ledger; None when this controller is
    # not federating (--federation-config unset)
    "federation": (dict, type(None)),
}
CHECK_FIELDS = {
    "key": str,
    "healthcheck": str,
    "namespace": str,
    "state": str,  # healthy | flapping | quarantined
    # baseline-analysis verdict (ISSUE 4): None without an analysis: block
    "analysis": (dict, type(None)),
    # lost-goodput attribution over the check's window (ISSUE 7): None
    # while the window is empty
    "attribution": (dict, type(None)),
    # latest roofline snapshot (ISSUE 9): None until a run ships the
    # contract's roofline block
    "roofline": (dict, type(None)),
    "remedy_budget_remaining": (int, type(None)),
    "last_status": str,
    "last_trace_id": str,
    "runs_recorded": int,
    "window": dict,
    "slo": (dict, type(None)),
    "history": list,
    # per-stage p50/p95/p99 waterfall aggregation (ISSUE 17): None
    # while no windowed run still has spans in the ring
    "critical_path": (dict, type(None)),
    # this check's adaptation episode (ISSUE 18): None unless the
    # adaptive controller currently holds a lever on the check
    "adapt": (dict, type(None)),
}
WINDOW_FIELDS = {
    "seconds": (int, float),
    "results": int,
    "availability": (int, float, type(None)),
    "p50_seconds": (int, float, type(None)),
    "p95_seconds": (int, float, type(None)),
    "p99_seconds": (int, float, type(None)),
}
SLO_FIELDS = {
    "objective": (int, float),
    "window_seconds": (int, float),
    "availability": (int, float, type(None)),
    "error_budget_remaining": (int, float, type(None)),
    "burn_rate": (int, float, type(None)),
}
HISTORY_FIELDS = {
    "ts": str,
    "ok": bool,
    "latency_seconds": (int, float),
    "workflow": str,
    "trace_id": str,
    # the run's numeric metric samples (ISSUE 4: detectors and /debug
    # endpoints read them from the ring)
    "metrics": dict,
    # the run's phase timings + record-time attribution (ISSUE 7)
    "timings": dict,
    # the run's roofline verdicts (ISSUE 9: the contract's roofline
    # block riding the ring into every surface)
    "roofline": dict,
    "bucket": str,
    "why": str,
}
# the fleet.goodput / per-check attribution blocks (ISSUE 7), locked
# like everything else here: the conservation dashboards stack these
GOODPUT_FIELDS = {
    "ratio": (int, float, type(None)),
    "window_runs": int,
    "lost_ratio": (int, float),
    "lost_runs": dict,
    "attribution": dict,
    "top": (str, type(None)),
    "version": int,
}
ATTRIBUTION_FIELDS = {
    "window_runs": int,
    "lost_runs": int,
    "lost_ratio": (int, float),
    "buckets": dict,
    "counts": dict,
    "top": (str, type(None)),
    "why": str,
}
# one flight-recorder bundle (obs/flightrec.py), as served at
# /debug/flightrec and written to --flight-dir JSONL
BUNDLE_FIELDS = {
    "id": str,
    "kind": str,
    "check": str,
    "ts": str,
    "trace_id": str,
    "spans": list,
    "results": list,
    "baselines": (dict, type(None)),
    "resilience": (dict, type(None)),
    "sharding": (dict, type(None)),
    "attribution": (dict, type(None)),
    # the check's latest roofline snapshot (ISSUE 9)
    "roofline": (dict, type(None)),
    # the triggering run's critical-path waterfall (ISSUE 17): None
    # when the bundle's trace has no finished spans in the ring
    "waterfall": (dict, type(None)),
    "extra": dict,
}
BREAKER_FIELDS = {
    "name": str,
    "state": str,
    "recent_failures": int,
    "retry_after_seconds": (int, float),
    "trips": int,
}
# the critical_path block (ISSUE 17, obs/criticalpath.py): served
# per check, merged into the fleet block, and rollup-merged across
# replicas — one schema for all three surfaces
CRITICAL_PATH_FIELDS = {
    "runs": int,
    # runs from version-skewed (old-binary) replicas whose whole
    # latency is booked under untracked
    "skewed_runs": int,
    "wall": dict,
    "stages": dict,
    "dominant_stage": str,
    "last": (dict, type(None)),
}
WATERFALL_FIELDS = {
    "trace_id": str,
    "wall_seconds": (int, float),
    "stages": dict,
    "dominant_stage": str,
    "segments": list,
}


def assert_schema(doc: dict, fields: dict, where: str):
    assert set(doc.keys()) == set(fields.keys()), f"{where}: {sorted(doc)}"
    for field_name, types in fields.items():
        assert isinstance(doc[field_name], types), (
            f"{where}.{field_name} is {type(doc[field_name]).__name__}"
        )


def test_statusz_schema_contract():
    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    with_slo = make_hc(slo={"objective": 0.9, "windowSeconds": 600})
    without = make_hc(name="hc-plain")
    fleet.record(with_slo, ok=True, latency=2.0, workflow="wf-1")
    fleet.record(with_slo, ok=False, latency=4.0, workflow="wf-2")
    # JSON round-trip: the contract is what a client parses, not the
    # Python objects
    payload = json.loads(json.dumps(fleet.statusz([with_slo, without])))
    assert_schema(payload["fleet"], FLEET_FIELDS, "fleet")
    assert_schema(payload["fleet"]["goodput"], GOODPUT_FIELDS, "goodput")
    assert len(payload["checks"]) == 2
    for check in payload["checks"]:
        assert_schema(check, CHECK_FIELDS, "check")
        assert_schema(check["window"], WINDOW_FIELDS, "window")
        if check["attribution"] is not None:
            assert_schema(
                check["attribution"], ATTRIBUTION_FIELDS, "attribution"
            )
        for entry in check["history"]:
            assert_schema(entry, HISTORY_FIELDS, "history")
    slo_check = payload["checks"][0]
    assert_schema(slo_check["slo"], SLO_FIELDS, "slo")
    assert slo_check["slo"]["availability"] == 0.5
    assert slo_check["window"]["p95_seconds"] == 4.0
    assert slo_check["history"][-1]["workflow"] == "wf-2"
    assert payload["checks"][1]["slo"] is None
    assert payload["checks"][1]["window"]["seconds"] == DEFAULT_WINDOW_SECONDS
    # standalone FleetStatus (no coordinator): a healthy controller
    assert payload["fleet"]["degraded"] is False
    assert payload["fleet"]["breaker"] is None
    for check in payload["checks"]:
        assert check["state"] == "healthy"
        assert check["remedy_budget_remaining"] is None
    # with the reconciler's coordinator attached, the fleet block
    # carries the breaker snapshot and the fleet remedy budget
    from activemonitor_tpu.resilience import ResilienceCoordinator

    fleet.resilience = ResilienceCoordinator(clock, None, remedy_rate=2.0)
    payload = json.loads(json.dumps(fleet.statusz([with_slo, without])))
    assert_schema(payload["fleet"], FLEET_FIELDS, "fleet")
    assert_schema(payload["fleet"]["breaker"], BREAKER_FIELDS, "breaker")
    assert payload["fleet"]["degraded"] is False
    assert payload["fleet"]["remedy_tokens"] == 2.0
    assert payload["fleet"]["status_writes_queued"] == 0


def test_flight_bundle_schema_contract(tmp_path):
    """The flight-recorder bundle schema (ISSUE 7), locked like the
    statusz payload: /debug/flightrec clients and offline JSONL readers
    parse the same shape, so renaming a field must be deliberate."""
    from activemonitor_tpu.analysis import AnalysisEngine
    from activemonitor_tpu.obs import FlightRecorder, Tracer
    from activemonitor_tpu.resilience import ResilienceCoordinator

    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    hc = make_hc()
    fleet.record(hc, ok=False, latency=2.0, workflow="wf-1")
    recorder = FlightRecorder(clock, flight_dir=str(tmp_path))
    recorder.tracer = Tracer(clock)
    recorder.history = fleet.history
    recorder.fleet = fleet
    recorder.resilience = ResilienceCoordinator(clock, None)
    recorder.analysis = AnalysisEngine(clock)
    bundle = recorder.record(
        "degraded-transition", key=hc.key, transition=("ok", "degraded")
    )
    # the contract is what a client parses: JSON round-trip first
    doc = json.loads(json.dumps(bundle))
    assert_schema(doc, BUNDLE_FIELDS, "bundle")
    assert doc["kind"] == "degraded-transition"
    assert doc["check"] == hc.key
    for entry in doc["results"]:
        assert_schema(entry, HISTORY_FIELDS, "bundle.results")
    assert_schema(doc["attribution"], ATTRIBUTION_FIELDS, "bundle.attribution")
    # tuples in extra were normalized to JSON shapes at record time:
    # the in-memory ring serves exactly what the JSONL sink holds
    assert doc["extra"] == {"transition": ["ok", "degraded"]}
    # the durable JSONL line is the same document
    [line] = list(FlightRecorder.read_jsonl(str(tmp_path / "flightrec.jsonl")))
    assert_schema(line, BUNDLE_FIELDS, "jsonl bundle")
    assert line["id"] == doc["id"]


def _traced_fleet(clock, hc, span_plan, *, latency):
    """A FleetStatus whose one recorded run still has live spans in the
    tracer ring — the precondition for a non-None critical_path block.
    ``span_plan`` is (name, start, end) triples on the fake monotonic
    timeline; the run's probe timings carve 1s of probe_phase out of
    its poll stage."""
    from activemonitor_tpu.obs import Tracer

    fleet = FleetStatus(clock, MetricsCollector())
    tracer = Tracer(clock)
    fleet.tracer = tracer
    with tracer.trace("reconcile"):
        for name, start, end in span_plan:
            tracer.record_span(name, start=start, end=end)
        fleet.record(
            hc,
            ok=True,
            latency=latency,
            workflow="wf",
            timings={"calibrate": 1.0},
        )
    return fleet


def test_statusz_critical_path_block_and_rollup():
    """Satellite 3 (ISSUE 17): the critical_path block rides /statusz
    per check AND per fleet, and the 3-replica rollup run-weights the
    percentiles — an old-binary replica (no block at all) merges with
    its whole windowed latency booked under untracked instead of
    silently vanishing from the fleet view."""
    clock = FakeClock()
    hc = make_hc()
    # replica A: a healthy path — poll dominates (4s window, 1s of it
    # carved into probe_phase by the run's timings)
    fleet_a = _traced_fleet(
        clock, hc, [("dequeue", 0.0, 1.0), ("poll", 1.0, 5.0)], latency=5.0
    )
    # replica B: queue-wait degraded — 4 of its 5 seconds in the queue
    fleet_b = _traced_fleet(
        clock, hc, [("dequeue", 0.0, 4.0), ("poll", 4.0, 5.0)], latency=5.0
    )
    # replica C: an old binary — records runs but serves no block
    fleet_c = FleetStatus(clock, MetricsCollector())
    fleet_c.record(hc, ok=True, latency=3.0, workflow="wf")

    p_a = json.loads(json.dumps(fleet_a.statusz([hc])))
    p_b = json.loads(json.dumps(fleet_b.statusz([hc])))
    p_c = json.loads(json.dumps(fleet_c.statusz([hc])))
    for payload in (p_a, p_b):
        [entry] = payload["checks"]
        assert_schema(
            entry["critical_path"], CRITICAL_PATH_FIELDS, "critical_path"
        )
        assert_schema(
            entry["critical_path"]["last"], WATERFALL_FIELDS, "last waterfall"
        )
        assert_schema(
            payload["fleet"]["critical_path"],
            CRITICAL_PATH_FIELDS,
            "fleet.critical_path",
        )
        # single-run conservation survives serialization: the per-stage
        # p95s sum back to the wall p95
        block = entry["critical_path"]
        assert sum(
            q["p95"] for q in block["stages"].values()
        ) == pytest.approx(block["wall"]["p95"], abs=1e-9)
    assert p_a["fleet"]["critical_path"]["dominant_stage"] == "poll"
    assert p_b["fleet"]["critical_path"]["dominant_stage"] == "queue_wait"
    # probe_phase was carved out of poll, not double-booked
    assert p_a["checks"][0]["critical_path"]["stages"]["probe_phase"][
        "p95"
    ] == pytest.approx(1.0)
    assert p_a["checks"][0]["critical_path"]["stages"]["poll"][
        "p95"
    ] == pytest.approx(3.0)

    # simulate the old binary: the key is absent, not null
    p_c["fleet"].pop("critical_path")
    for entry in p_c["checks"]:
        entry.pop("critical_path")

    merged = rollup_statusz([p_a, p_b, p_c])
    block = merged["fleet"]["critical_path"]
    assert_schema(block, CRITICAL_PATH_FIELDS, "rollup.critical_path")
    assert block["runs"] == 3
    assert block["skewed_runs"] == 1
    # run-weighted means: A(qw=1) B(qw=4) C(qw=0) -> 5/3, and the old
    # binary's 3s window lands entirely under untracked -> 1.0
    assert block["stages"]["queue_wait"]["p95"] == pytest.approx(5.0 / 3.0)
    assert block["stages"]["untracked"]["p95"] == pytest.approx(1.0)
    assert block["stages"]["poll"]["p95"] == pytest.approx(1.0)
    assert block["wall"]["p95"] == pytest.approx(13.0 / 3.0)
    assert block["dominant_stage"] == "queue_wait"
    # the newest measured run's waterfall survives the merge for the
    # CLI's ASCII rendering (first-seen-wins, like the check dedupe)
    assert_schema(block["last"], WATERFALL_FIELDS, "rollup last")


def test_statusz_history_is_a_bounded_tail():
    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    hc = make_hc()
    for i in range(25):
        fleet.record(hc, ok=True, latency=float(i), workflow=f"wf-{i}")
    [entry] = fleet.statusz([hc])["checks"]
    assert len(entry["history"]) == FleetStatus.HISTORY_TAIL
    assert entry["history"][-1]["workflow"] == "wf-24"
    assert entry["runs_recorded"] == 25


# ---------------------------------------------------------------------
# acceptance: FakeEngine + fake clock scripted sequence
# ---------------------------------------------------------------------

# (polls-until-terminal, verdict): latency is exactly polls-1 seconds
# with the 1 s constant backoff the spec pins. Sorted latencies
# [0,1,1,2,2,3,3,4,4,9] -> p50=2.0, p95=9.0; 9/10 ok with objective 0.8
# -> availability 0.9, burn 0.5, budget remaining 0.5.
SCRIPT = [
    (1, True),
    (2, True),
    (2, True),
    (3, True),
    (3, True),
    (4, True),
    (4, True),
    (5, True),
    (5, True),
    (10, False),
]
EXPECTED_AVAILABILITY = 0.9
EXPECTED_P50 = 2.0
EXPECTED_P95 = 9.0
EXPECTED_BUDGET_REMAINING = 0.5
EXPECTED_BURN = 0.5

CONTRACT_DOC = json.dumps(
    {
        "metrics": [
            {"name": "probe-bw-gbps", "value": 123.0, "metrictype": "gauge"}
        ],
        "timings": {"allreduce": 2.5, "compile": 30.0},
    }
)
OUTPUTS = {"parameters": [{"name": "metrics", "value": CONTRACT_DOC}]}


def scripted_engine(script):
    """FakeEngine whose Nth submitted workflow follows the Nth script
    entry: pending until the scripted poll count, then the scripted
    verdict (successes carry the metrics+timings contract)."""
    engine = FakeWorkflowEngine()
    queue = collections.deque(script)
    assigned = {}

    def completer(wf, count):
        name = wf["metadata"]["name"]
        if name not in assigned:
            if not queue:
                return None  # off-script: stays pending
            assigned[name] = queue.popleft()
        polls, ok = assigned[name]
        if count < polls:
            return None
        if ok:
            return {"phase": PHASE_SUCCEEDED, "outputs": OUTPUTS}
        return {"phase": PHASE_FAILED, "message": "scripted failure"}

    engine._default_completer = completer
    return engine


async def settle():
    for _ in range(50):
        await asyncio.sleep(0)


@pytest.mark.asyncio
async def test_scripted_sequence_yields_exact_slo_values(tmp_path):
    import aiohttp

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=scripted_engine(SCRIPT),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=2)
    manager._health_addr = "127.0.0.1:0"
    await manager.start()
    try:
        hc = make_hc(slo={"objective": 0.8, "windowSeconds": 3600})
        await client.apply(hc)
        first = True
        for polls, _ok in SCRIPT:
            if not first:
                # fire the reschedule timer for the next run
                await clock.advance(60.0)
            first = False
            await settle()
            for _ in range(polls):
                await clock.advance(1.0)
            await settle()

        key = "health/hc-slo"
        results = reconciler.fleet.history.results(key)
        assert [r.ok for r in results] == [ok for _p, ok in SCRIPT]
        assert [r.latency for r in results] == [
            float(p - 1) for p, _ok in SCRIPT
        ]

        # exact values through the registry...
        assert (
            metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
            == EXPECTED_AVAILABILITY
        )
        assert metrics.sample_value(
            "healthcheck_error_budget_remaining", SLO_LABELS
        ) == pytest.approx(EXPECTED_BUDGET_REMAINING)
        assert metrics.sample_value(
            "healthcheck_slo_burn_rate", SLO_LABELS
        ) == pytest.approx(EXPECTED_BURN)
        # phase timings flowed from the stdout contract of each of the
        # 9 successful runs
        assert metrics.sample_value(
            "healthcheck_phase_seconds_sum",
            {"healthcheck_name": "hc-slo", "phase": "allreduce"},
        ) == pytest.approx(9 * 2.5)

        # ... and the same exact values through /statusz
        port = manager._http_runners[0].addresses[0][1]
        async with aiohttp.ClientSession() as session:
            async with session.get(f"http://127.0.0.1:{port}/statusz") as r:
                assert r.status == 200
                payload = await r.json()
        [entry] = payload["checks"]
        assert entry["key"] == key
        assert entry["window"]["availability"] == EXPECTED_AVAILABILITY
        assert entry["window"]["p50_seconds"] == EXPECTED_P50
        assert entry["window"]["p95_seconds"] == EXPECTED_P95
        assert entry["slo"]["error_budget_remaining"] == pytest.approx(
            EXPECTED_BUDGET_REMAINING
        )
        assert entry["slo"]["burn_rate"] == pytest.approx(EXPECTED_BURN)
        assert payload["fleet"]["goodput_ratio"] == EXPECTED_AVAILABILITY
        # serving /statusz refreshed the fleet gauge to the same number
        assert (
            metrics.sample_value("healthcheck_fleet_goodput_ratio", {})
            == EXPECTED_AVAILABILITY
        )
        assert entry["last_status"] == "Failed"
        assert entry["last_trace_id"]

        # every recorded run is joinable to a retained trace
        trace_ids = {t["trace_id"] for t in reconciler.tracer.traces()}
        for result in results:
            assert result.trace_id in trace_ids

        # the phase histogram carries the cycle's trace id as an
        # OpenMetrics exemplar, resolvable in /debug/traces
        om_text = metrics.exposition(openmetrics=True).decode()
        match = re.search(
            r'healthcheck_phase_seconds_bucket\{[^}]*phase="allreduce"[^}]*\}'
            r' [0-9.e+-]+ # \{trace_id="([0-9a-f]+)"\}',
            om_text,
        )
        assert match, "no trace_id exemplar on healthcheck_phase_seconds"
        exemplar_trace = match.group(1)
        assert exemplar_trace in trace_ids
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{port}/debug/traces",
                params={"trace_id": exemplar_trace},
            ) as r:
                traces = (await r.json())["traces"]
        assert traces and traces[0]["trace_id"] == exemplar_trace
        # the runtime histogram is exemplar-stamped too
        assert re.search(
            r'healthcheck_runtime_histogram_seconds_bucket\{[^}]*\}'
            r' [0-9.e+-]+ # \{trace_id="[0-9a-f]+"\}',
            om_text,
        )
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_deleted_check_drops_out_of_statusz_and_gauges():
    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=scripted_engine([(1, True)]),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=1)
    await manager.start()
    try:
        hc = make_hc(slo={"objective": 0.9, "windowSeconds": 600})
        await client.apply(hc)
        await settle()
        await clock.advance(1.0)
        await settle()
        assert (
            metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
            == 1.0
        )
        await client.delete("health", "hc-slo")
        await settle()
        assert (
            metrics.sample_value("healthcheck_slo_availability_ratio", SLO_LABELS)
            is None
        )
        assert reconciler.fleet.history.results("health/hc-slo") == []
        assert reconciler.fleet.statusz(await client.list())["checks"] == []
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_metrics_accept_negotiation_serves_openmetrics():
    """Default scrapes keep the reference's exact text format; a
    scraper asking for OpenMetrics gets the exemplar-bearing format."""
    import aiohttp

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=scripted_engine([]),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
        clock=clock,
    )
    manager = Manager(
        client=client,
        reconciler=reconciler,
        max_parallel=1,
        metrics_bind_address="127.0.0.1:0",
        metrics_secure=False,
    )
    await manager.start()
    try:
        port = manager._http_runners[0].addresses[0][1]
        async with aiohttp.ClientSession() as session:
            async with session.get(f"http://127.0.0.1:{port}/metrics") as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith("text/plain")
                assert not (await r.text()).endswith("# EOF\n")
            async with session.get(
                f"http://127.0.0.1:{port}/metrics",
                headers={"Accept": "application/openmetrics-text"},
            ) as r:
                assert r.status == 200
                assert r.headers["Content-Type"].startswith(
                    "application/openmetrics-text"
                )
                assert (await r.text()).endswith("# EOF\n")
    finally:
        await manager.stop()


# ---------------------------------------------------------------------
# am-tpu status CLI
# ---------------------------------------------------------------------


def test_status_cli_flags_parse():
    from activemonitor_tpu.__main__ import build_parser

    args = build_parser().parse_args(["status"])
    # --url is repeatable for sharded fleets; None means the default
    # health-probe endpoint (resolved in _status)
    assert args.url is None
    assert args.output == "table"
    args = build_parser().parse_args(
        ["status", "--url", "http://x:1/statusz", "-o", "json"]
    )
    assert args.url == ["http://x:1/statusz"]
    assert args.output == "json"
    args = build_parser().parse_args(
        ["status", "--url", "http://x:1/statusz", "--url", "http://y:1/statusz"]
    )
    assert len(args.url) == 2


def test_render_status_table_shapes_rows():
    from activemonitor_tpu.__main__ import render_status_table

    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    hc = make_hc(slo={"objective": 0.8, "windowSeconds": 3600})
    fleet.record(hc, ok=True, latency=2.0, workflow="wf-1")
    fleet.record(hc, ok=False, latency=6.0, workflow="wf-2")
    payload = json.loads(json.dumps(fleet.statusz([hc])))
    text = render_status_table(payload)
    lines = text.splitlines()
    assert lines[0].startswith("FLEET  checks=1")
    assert "goodput=50.0%" in lines[0]
    header, row = lines[1], lines[2]
    assert header.split() == [
        "NAME", "NAMESPACE", "STATUS", "STATE", "ANOMALY", "RUNS", "AVAIL",
        "P50", "P95", "P99", "BUDGET", "BURN", "REMEDY", "ADAPT", "WHY",
        "LAST", "TRACE",
    ]
    cells = row.split()
    assert cells[0] == "hc-slo"
    assert "50.0%" in row  # availability
    assert "6.00s" in row  # p95/p99
    # budget: f=0.5, allowed=0.2 -> remaining 1 - 2.5 = -150%
    assert "-150.0%" in row
    # the WHY column carries the attribution headline: one failed run
    # of two, no evidence -> unknown:50%
    assert "unknown:50%" in row


def test_render_status_table_empty_fleet():
    from activemonitor_tpu.__main__ import render_status_table

    text = render_status_table(
        {
            "fleet": {
                "checks": 0,
                "window_runs": 0,
                "goodput_ratio": None,
                "generated_at": "",
            },
            "checks": [],
        }
    )
    assert "No HealthChecks found." in text
    assert "goodput=-" in text


@pytest.mark.asyncio
async def test_status_cli_fetches_statusz(capsys):
    from activemonitor_tpu.__main__ import _status, build_parser

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=scripted_engine([(1, True)]),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=1)
    manager._health_addr = "127.0.0.1:0"
    await manager.start()
    try:
        await client.apply(make_hc())
        await settle()
        await clock.advance(1.0)
        await settle()
        port = manager._http_runners[0].addresses[0][1]
        args = build_parser().parse_args(
            ["status", "--url", f"http://127.0.0.1:{port}/statusz"]
        )
        assert await _status(args) == 0
        out = capsys.readouterr().out
        assert out.startswith("FLEET  checks=1")
        assert "hc-slo" in out
        assert "100.0%" in out  # availability of the one passing run
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_status_cli_partial_fleet_renders_survivors(capsys):
    """During a failover one replica URL is dead — exactly when the
    operator is running `am-tpu status` to watch the handoff. A dead
    replica must degrade to a stderr warning, not abort the whole
    rollup (all-or-nothing would blind the CLI for the entire runbook
    window)."""
    import socket

    from activemonitor_tpu.__main__ import _status, build_parser

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=scripted_engine([(1, True)]),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=1)
    manager._health_addr = "127.0.0.1:0"
    await manager.start()
    try:
        await client.apply(make_hc())
        await settle()
        await clock.advance(1.0)
        await settle()
        port = manager._http_runners[0].addresses[0][1]
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        args = build_parser().parse_args(
            [
                "status",
                "--url", f"http://127.0.0.1:{port}/statusz",
                "--url", f"http://127.0.0.1:{dead_port}/statusz",
            ]
        )
        assert await _status(args) == 0
        captured = capsys.readouterr()
        assert "hc-slo" in captured.out  # the survivor's checks rendered
        assert "cannot reach" in captured.err
        assert "partial fleet view (1/2 replicas reporting)" in captured.err
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_status_cli_unreachable_controller_is_a_clean_error(capsys):
    from activemonitor_tpu.__main__ import _status, build_parser

    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    args = build_parser().parse_args(
        ["status", "--url", f"http://127.0.0.1:{port}/statusz"]
    )
    assert await _status(args) == 1
    assert "cannot reach" in capsys.readouterr().err


# ---------------------------------------------------------------------
# probe phase telemetry (the payload side of the contract)
# ---------------------------------------------------------------------


def test_phase_timings_context_manager_accumulates():
    from activemonitor_tpu.probes.base import PhaseTimings

    t = [0.0]

    def monotonic():
        return t[0]

    timings = PhaseTimings(monotonic)
    with timings.phase("compile"):
        t[0] += 3.0
    with timings.phase("execute"):
        t[0] += 1.5
    with timings.phase("execute"):  # re-entry accumulates
        t[0] += 0.5
    assert timings == {"compile": 3.0, "execute": 2.0}


def test_phase_recorded_even_when_the_block_raises():
    from activemonitor_tpu.probes.base import PhaseTimings

    t = [0.0]
    timings = PhaseTimings(lambda: t[0])
    with pytest.raises(RuntimeError):
        with timings.phase("boom"):
            t[0] += 2.0
            raise RuntimeError("x")
    assert timings["boom"] == 2.0


def test_contract_line_carries_timings():
    from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult

    result = ProbeResult(
        ok=True,
        summary="fine",
        metrics=[ProbeMetric("bw", 1.0)],
        timings={"compile": 3.25},
    )
    doc = json.loads(result.contract_line())
    assert doc["timings"] == {"compile": 3.25}
    # no timings -> the field is absent, keeping the pre-timings
    # contract byte-compatible
    bare = ProbeResult(ok=True, summary="fine")
    assert "timings" not in json.loads(bare.contract_line())


def test_emitted_contract_roundtrips_through_the_collector(capsys):
    """stdout contract -> workflow outputs -> collector: the timings a
    probe measures are the phases the controller exports."""
    from activemonitor_tpu.probes.base import ProbeResult

    result = ProbeResult(
        ok=True, summary="fine", timings={"allreduce": 2.0, "all-gather": 1.0}
    )
    assert result.emit() == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    collector = MetricsCollector()
    status = {"outputs": {"parameters": [{"name": "m", "value": line}]}}
    collector.record_custom_metrics("hc", status)
    assert collector.sample_value(
        "healthcheck_phase_seconds_sum",
        {"healthcheck_name": "hc", "phase": "allreduce"},
    ) == 2.0
    # phase names are sanitized into exposition-legal form
    assert collector.sample_value(
        "healthcheck_phase_seconds_sum",
        {"healthcheck_name": "hc", "phase": "all_gather"},
    ) == 1.0


def test_statusz_generated_at_tracks_clock():
    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    payload = fleet.statusz([])
    assert payload["fleet"]["generated_at"] == clock.now().isoformat()
    assert datetime.datetime.fromisoformat(payload["fleet"]["generated_at"])
