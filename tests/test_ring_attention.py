"""Ring attention tests — sequence parallelism on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import pytest

from activemonitor_tpu.ops.ring_attention import reference_attention, ring_attention
from activemonitor_tpu.parallel.mesh import make_1d_mesh
from activemonitor_tpu.probes import ring as ring_probe


@pytest.fixture(scope="module")
def mesh():
    return make_1d_mesh("sp")


def qkv(seq=64, batch=2, heads=4, head_dim=16, dtype=jnp.float32):
    keys = jax.random.split(jax.random.key(0), 3)
    return tuple(
        jax.random.normal(k, (batch, seq, heads, head_dim), dtype) for k in keys
    )


@pytest.mark.parametrize("causal", [True, False])
def test_matches_reference(mesh, causal):
    q, k, v = qkv()
    got = ring_attention(q, k, v, mesh, "sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    assert jnp.max(jnp.abs(got - want)) < 1e-5


@pytest.mark.parametrize("causal", [True, False])
def test_flash_block_compute_matches_reference(mesh, causal):
    # the fused per-step block compute (flash_attention_partial under
    # the ring's lax.switch) must agree with both the XLA path and the
    # single-device reference
    q, k, v = qkv(seq=128)
    flash = ring_attention(q, k, v, mesh, "sp", causal=causal, use_flash=True)
    plain = ring_attention(q, k, v, mesh, "sp", causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    assert float(jnp.max(jnp.abs(flash - want))) < 1e-5
    assert float(jnp.max(jnp.abs(flash - plain))) < 1e-5


def test_probe_flash_mode(mesh):
    result = ring_probe.run(
        batch=1, seq_per_device=16, heads=2, head_dim=16, iters=2, use_flash=True
    )
    assert result.ok
    assert result.details["block_compute"] == "flash"


def test_matches_reference_bf16(mesh):
    q, k, v = qkv(dtype=jnp.bfloat16)
    got = ring_attention(q, k, v, mesh, "sp")
    want = reference_attention(q, k, v)
    assert (
        jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))) < 2e-2
    )


def test_jit_compatible(mesh):
    q, k, v = qkv()
    fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh, "sp"))
    out = fn(q, k, v)
    assert out.shape == q.shape
    assert jnp.isfinite(out).all()


def test_single_query_block_first_row(mesh):
    """Causality: token 0 attends only to itself — output equals v[0]."""
    q, k, v = qkv()
    out = ring_attention(q, k, v, mesh, "sp", causal=True)
    assert jnp.allclose(out[:, 0], v[:, 0], atol=1e-5)


def test_probe_runs_and_reports(mesh):
    result = ring_probe.run(seq_per_device=16, heads=2, head_dim=8, iters=2)
    assert result.ok
    names = {m.name for m in result.metrics}
    assert names == {
        "ring-attention-max-error",
        "ring-attention-tokens-per-second",
        "ring-attention-tflops",
    }
    assert result.details["devices"] == 8
    assert result.details["seq"] == 16 * 8


def test_distributed_detection(monkeypatch):
    from activemonitor_tpu.parallel.distributed import detect_multihost_env

    monkeypatch.delenv("ACTIVEMONITOR_DISTRIBUTED", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    assert not detect_multihost_env()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a")
    assert not detect_multihost_env()
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host-a,host-b")
    assert detect_multihost_env()
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES")
    monkeypatch.setenv("ACTIVEMONITOR_DISTRIBUTED", "1")
    assert detect_multihost_env()


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("use_flash", [False, True])
def test_gradients_match_reference(mesh, causal, use_flash):
    """The custom-VJP backward (second K/V ring pass against the saved
    global logsumexp) must agree with autodiff through single-device
    attention — for the XLA einsum blocks AND the fused kernel blocks."""
    q, k, v = qkv()

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g_ring = jax.grad(
        loss(lambda a, b, c: ring_attention(
            a, b, c, mesh, "sp", causal=causal, use_flash=use_flash
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for got, want in zip(g_ring, g_ref):
        assert float(jnp.max(jnp.abs(got - want))) < 1e-5


def test_gradients_bf16(mesh):
    """bf16 inputs keep bf16 on the wire in BOTH ring passes; gradients
    still track the float32 reference within bf16 rounding."""
    q, k, v = qkv(dtype=jnp.bfloat16)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    g_ring = jax.grad(
        loss(lambda a, b, c: ring_attention(a, b, c, mesh, "sp")),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(
            a.astype(jnp.float32), b.astype(jnp.float32), c.astype(jnp.float32)
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    for got, want in zip(g_ring, g_ref):
        norm = max(1e-9, float(jnp.max(jnp.abs(want))))
        rel = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want))) / norm
        assert rel < 5e-2


def test_train_step_ring_attention():
    """attention="ring" trains: a dp×tp×sp composed step through ring
    attention's custom VJP produces a finite loss that decreases."""
    from activemonitor_tpu.models.probe_model import tiny_config
    from activemonitor_tpu.parallel.mesh import make_mesh
    from activemonitor_tpu.probes.training_step import build_sharded_train_step

    sp_mesh = make_mesh(("data", "model", "sp"), (2, 2, 2))
    cfg = tiny_config()
    step, params, opt, data_sh = build_sharded_train_step(
        cfg, sp_mesh, attention="ring"
    )
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(3), (4, 17), 0, cfg.vocab_size),
        data_sh,
    )
    losses = []
    for _ in range(3):
        params, opt, loss = step(params, opt, tokens)
        losses.append(float(loss))
    assert all(l == l for l in losses), losses
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("use_flash", [False, True])
def test_gqa_matches_reference(mesh, causal, use_flash):
    """Grouped K/V heads ride the ring with the NARROW head count on
    the wire (the GQA bandwidth win applies to ICI traffic too);
    gradients come back group-summed in K/V's own shape."""
    keys = jax.random.split(jax.random.key(4), 3)
    q = jax.random.normal(keys[0], (2, 64, 4, 16), jnp.float32)
    k = jax.random.normal(keys[1], (2, 64, 2, 16), jnp.float32)
    v = jax.random.normal(keys[2], (2, 64, 2, 16), jnp.float32)
    got = ring_attention(q, k, v, mesh, "sp", causal=causal, use_flash=use_flash)
    want = reference_attention(q, k, v, causal=causal)
    assert got.shape == q.shape
    assert float(jnp.max(jnp.abs(got - want))) < 1e-5

    def loss(fn):
        return lambda a, b, c: jnp.sum(fn(a, b, c).astype(jnp.float32) ** 2)

    g_ring = jax.grad(
        loss(lambda a, b, c: ring_attention(
            a, b, c, mesh, "sp", causal=causal, use_flash=use_flash
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        loss(lambda a, b, c: reference_attention(a, b, c, causal=causal)),
        argnums=(0, 1, 2),
    )(q, k, v)
    assert g_ring[1].shape == k.shape  # group already summed
    for a, b in zip(g_ring, g_ref):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-4


def test_train_step_ring_attention_gqa():
    """A GQA config trains through sequence-parallel ring attention."""
    from activemonitor_tpu.models.probe_model import ProbeModelConfig
    from activemonitor_tpu.parallel.mesh import make_mesh
    from activemonitor_tpu.probes.training_step import build_sharded_train_step

    cfg = ProbeModelConfig(
        vocab_size=256, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
        d_ff=128, max_seq_len=64,
    )
    sp_mesh = make_mesh(("data", "model", "sp"), (2, 2, 2))
    step, params, opt, data_sh = build_sharded_train_step(
        cfg, sp_mesh, attention="ring"
    )
    tokens = jax.device_put(
        jax.random.randint(jax.random.key(5), (4, 33), 0, cfg.vocab_size),
        data_sh,
    )
    _, _, loss = step(params, opt, tokens)
    value = float(loss)
    assert value == value and 0 < value < 10


def test_ring_attention_fn_validates_axes():
    from activemonitor_tpu.models.probe_model import ring_attention_fn, tiny_config
    from activemonitor_tpu.parallel.mesh import make_mesh

    cfg = tiny_config()
    with pytest.raises(ValueError, match="'sp' mesh axis"):
        ring_attention_fn(cfg, make_mesh(("data", "model"), (2, 4)))
    with pytest.raises(ValueError, match="divisible"):
        # tiny_config has 4 heads; tp axis of 8 cannot split them
        ring_attention_fn(cfg, make_mesh(("model", "sp"), (8, 1)))


def test_context_parallel_forward_matches_dense(mesh):
    """The long-context model path (seq sharded + ring attention) must
    agree with the dense single-device forward."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from activemonitor_tpu.models.probe_model import (
        forward,
        forward_context_parallel,
        init_params,
        tiny_config,
    )

    cfg = tiny_config()
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg.vocab_size)
    sharded = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))
    got = forward_context_parallel(params, sharded, cfg, mesh)
    want = forward(params, tokens, cfg)
    assert jnp.max(jnp.abs(got - want)) < 3e-2  # bf16 compute tolerance
