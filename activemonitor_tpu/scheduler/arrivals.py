"""Seeded Poisson arrival process — the one open-loop traffic contract.

Both open-loop generators in the tree — the serving probe's request
schedule (:func:`scheduler.serving.open_loop_requests`) and the front
door's check-request schedule (:func:`frontdoor.traffic.
open_loop_checks`) — draw their arrival times from this process, so
"same seed ⇒ byte-identical schedule" is ONE contract with one
implementation, not two generators that can drift apart.

The determinism contract is the *draw order* against a single
``random.Random(seed)``: one ``expovariate`` per arrival, with any
payload draws (prompt lengths, tenants, check identities) interleaved
by the caller through :meth:`PoissonArrivals.choice` on the SAME rng.
Callers must keep their draw order stable across refactors — the
serving scheduler-trace tests pin it byte-for-byte.

Open-loop on purpose (the FlowMesh serving framing): the schedule is
generated up front and never adapts to service latency, so overload
shows up as queueing delay instead of a coordinated-omission slowdown.
No wall clock anywhere — arrival times are plain floats on the
caller's timeline (``hack/lint.py`` bans ``time.time()`` here like the
other clock-disciplined modules).
"""

from __future__ import annotations

import random
from typing import Sequence, TypeVar

T = TypeVar("T")


class PoissonArrivals:
    """Seeded exponential inter-arrival generator plus the rng the
    caller interleaves payload draws on.

    ``next()`` advances the cumulative arrival time by one
    ``expovariate(rate_per_s)`` draw and returns it; ``choice(seq)``
    draws a payload attribute from the same rng (tuple-normalized, so
    list vs tuple spellings of a choice set cannot change the draw).
    """

    def __init__(self, rate_per_s: float, seed: int):
        if rate_per_s <= 0:
            raise ValueError(f"rate_per_s must be > 0, got {rate_per_s}")
        self.rate_per_s = rate_per_s
        self.rng = random.Random(seed)
        self.now = 0.0

    def next(self) -> float:
        """The next arrival's time (seconds since schedule start)."""
        self.now += self.rng.expovariate(self.rate_per_s)
        return self.now

    def choice(self, seq: Sequence[T]) -> T:
        """One payload draw from the shared rng (draw-order is part of
        the determinism contract — see module docstring)."""
        return self.rng.choice(tuple(seq))
