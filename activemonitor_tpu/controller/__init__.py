"""Controller: reconciler state machine, clients, RBAC, events."""

from activemonitor_tpu.controller.client import (
    ConflictError,
    HealthCheckClient,
    InMemoryHealthCheckClient,
    NotFoundError,
    ShardFilteredClient,
    WatchEvent,
    retry_on_conflict,
)
from activemonitor_tpu.controller.sharding import (
    ShardCoordinator,
    ShardFencedError,
    ShardRouter,
    ShardSet,
)
from activemonitor_tpu.controller.events import (
    EVENT_NORMAL,
    EVENT_WARNING,
    Event,
    EventRecorder,
)
from activemonitor_tpu.controller.rbac import (
    DEFAULT_HEALTHCHECK_RULES,
    DEFAULT_REMEDY_RULES,
    InMemoryRBACBackend,
    KubernetesRBACBackend,
    MANAGED_BY_LABEL_KEY,
    MANAGED_BY_VALUE,
    RBACError,
    RBACObject,
    RBACProvisioner,
    resolve_rbac_rules,
)
from activemonitor_tpu.controller.reconciler import HealthCheckReconciler
from activemonitor_tpu.controller.workflow_spec import (
    WF_INSTANCE_ID,
    WF_INSTANCE_ID_LABEL_KEY,
    WorkflowSpecError,
    parse_remedy_workflow_from_healthcheck,
    parse_workflow_from_healthcheck,
)

__all__ = [
    "ConflictError",
    "DEFAULT_HEALTHCHECK_RULES",
    "DEFAULT_REMEDY_RULES",
    "EVENT_NORMAL",
    "EVENT_WARNING",
    "Event",
    "EventRecorder",
    "HealthCheckClient",
    "HealthCheckReconciler",
    "InMemoryHealthCheckClient",
    "InMemoryRBACBackend",
    "KubernetesRBACBackend",
    "MANAGED_BY_LABEL_KEY",
    "MANAGED_BY_VALUE",
    "NotFoundError",
    "RBACError",
    "RBACObject",
    "RBACProvisioner",
    "ShardCoordinator",
    "ShardFencedError",
    "ShardFilteredClient",
    "ShardRouter",
    "ShardSet",
    "WF_INSTANCE_ID",
    "WF_INSTANCE_ID_LABEL_KEY",
    "WatchEvent",
    "WorkflowSpecError",
    "parse_remedy_workflow_from_healthcheck",
    "parse_workflow_from_healthcheck",
    "resolve_rbac_rules",
    "retry_on_conflict",
]
