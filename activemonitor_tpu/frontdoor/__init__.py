"""Probe-as-a-service front door (ROADMAP item 3).

High-QPS async ingestion in front of the sharded fleet: per-tenant
admission quotas riding the storm token bucket, a request-coalescing
cache over the result rings (N identical tenant questions share ONE
probe run), composable probe DAGs compiled into the Manager enqueue
path, and degraded-mode parking instead of drops. docs/operations.md
"Probe-as-a-service front door" is the operator contract.
"""

from activemonitor_tpu.frontdoor.admission import (
    AdmissionController,
    AdmissionDecision,
    OVERFLOW_TENANT,
    REFUSE_ABANDONED,
    REFUSE_PARKED_FULL,
    REFUSE_QUOTA,
    REFUSE_TENANT_CAPACITY,
    REFUSE_UNKNOWN_TENANT,
    REFUSE_UNROUTED,
    TenantQuota,
)
from activemonitor_tpu.frontdoor.coalesce import (
    CoalescingCache,
    DEFAULT_FRESHNESS_SECONDS,
)
from activemonitor_tpu.frontdoor.dag import DagStep, ProbeDag, parse_dag
from activemonitor_tpu.frontdoor.service import (
    FrontDoor,
    OUTCOME_HIT,
    OUTCOME_JOINED,
    OUTCOME_PARKED,
    OUTCOME_REFUSED,
    OUTCOME_RUN,
    Ticket,
)
from activemonitor_tpu.frontdoor.traffic import CheckRequest, open_loop_checks

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "CheckRequest",
    "CoalescingCache",
    "DEFAULT_FRESHNESS_SECONDS",
    "DagStep",
    "FrontDoor",
    "OUTCOME_HIT",
    "OUTCOME_JOINED",
    "OUTCOME_PARKED",
    "OUTCOME_REFUSED",
    "OUTCOME_RUN",
    "OVERFLOW_TENANT",
    "ProbeDag",
    "REFUSE_ABANDONED",
    "REFUSE_PARKED_FULL",
    "REFUSE_QUOTA",
    "REFUSE_TENANT_CAPACITY",
    "REFUSE_UNKNOWN_TENANT",
    "REFUSE_UNROUTED",
    "TenantQuota",
    "Ticket",
    "open_loop_checks",
    "parse_dag",
]
