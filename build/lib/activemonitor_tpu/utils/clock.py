"""Clock abstraction so scheduler/backoff/timer code is deterministic in tests.

The reference tests real timing with short cadences in envtest
(SURVEY.md §4); we do better by injecting a fake clock and advancing it
manually, so backoff/cron/timer tests run in milliseconds.
"""

from __future__ import annotations

import asyncio
import datetime
import heapq
import time
from typing import List, Tuple


class Clock:
    """Real wall/monotonic clock."""

    def now(self) -> datetime.datetime:
        return datetime.datetime.now(datetime.timezone.utc)

    def monotonic(self) -> float:
        return time.monotonic()

    async def sleep(self, seconds: float) -> None:
        await asyncio.sleep(seconds)


class FakeClock(Clock):
    """Manually-advanced clock for tests.

    ``sleep`` blocks until ``advance`` moves time past the wake point.
    """

    def __init__(self, start: float = 0.0, epoch: datetime.datetime | None = None):
        self._t = start
        self._epoch = epoch or datetime.datetime(2026, 1, 1, tzinfo=datetime.timezone.utc)
        self._start = start
        self._sleepers: List[Tuple[float, int, asyncio.Future]] = []
        self._seq = 0

    def now(self) -> datetime.datetime:
        return self._epoch + datetime.timedelta(seconds=self._t - self._start)

    def monotonic(self) -> float:
        return self._t

    async def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            await asyncio.sleep(0)
            return
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._seq += 1
        heapq.heappush(self._sleepers, (self._t + seconds, self._seq, fut))
        await fut

    async def advance(self, seconds: float) -> None:
        """Move time forward, waking sleepers in wake-time order."""
        # Let tasks spawned-but-not-yet-started register their sleeps at
        # the current time before it moves.
        for _ in range(10):
            await asyncio.sleep(0)
        target = self._t + seconds
        while self._sleepers and self._sleepers[0][0] <= target:
            wake, _, fut = heapq.heappop(self._sleepers)
            self._t = max(self._t, wake)
            if not fut.done():
                fut.set_result(None)
            # Let the woken coroutine (and anything it spawns) run before
            # advancing further, so causality matches real time.
            for _ in range(10):
                await asyncio.sleep(0)
        self._t = target
        for _ in range(10):
            await asyncio.sleep(0)


def micro_time(dt: datetime.datetime) -> str:
    """Kubernetes ``MicroTime`` canonical wire format: RFC3339 with
    EXACTLY six fractional digits (``2026-07-30T04:10:11.000123Z``) —
    what client-go always writes.

    ``datetime.isoformat()`` omits the fraction entirely when
    ``microsecond == 0``. Older apiservers parsed MicroTime with the
    strict RFC3339Micro layout (fraction REQUIRED → a flaky 400 on
    lease renewal); current apimachinery falls back to lenient RFC3339,
    but the canonical six-digit form is valid against every version and
    is what fixed-epoch FakeClock tests (microsecond ALWAYS 0) would
    otherwise silently diverge from. Documented in docs/conformance.md;
    every MicroTime field (Lease renewTime/acquireTime) goes through
    here. Naive datetimes are interpreted as UTC — the repo convention
    — never as host-local time."""
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return (
        dt.astimezone(datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%f")
        + "Z"
    )
