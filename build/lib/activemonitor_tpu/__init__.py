"""activemonitor_tpu — a TPU-native monitoring-and-self-healing framework.

A brand-new framework with the capabilities of keikoproj/active-monitor
(reference: /root/reference): a controller that runs user-defined
``HealthCheck`` specs as periodic probe workflows (interval or cron
scheduled, with inverse-exponential status polling, per-check
least-privilege RBAC, pause semantics, Prometheus/event/status
observability) and, on failure, triggers bounded ``RemedyWorkflow``
self-healing with run limits and reset-interval hysteresis.

Unlike the Go reference, probe payloads are first-class TPU workloads:
JAX programs that verify device inventory, measure ICI all-reduce
bandwidth against rated throughput, and smoke-test XLA compilation of a
sharded training step — exported through the same custom-metrics
contract the reference defines (reference: internal/metrics/collector.go:68-115).

Layout (see SURVEY.md §7 for the build plan):

- ``api``        — HealthCheck spec/status types + CRD generation
                   (reference: api/v1alpha1/healthcheck_types.go)
- ``store``      — artifact readers: inline / URL / file
                   (reference: internal/store/)
- ``scheduler``  — cron parsing, inverse-exponential backoff, timer wheel
                   (reference: healthcheck_controller.go:251-263,575-605,745-754)
- ``engine``     — workflow execution backends: fake (tests), local
                   process (single host), Argo (Kubernetes)
                   (reference boundary: healthcheck_controller.go:502-534,617)
- ``controller`` — reconciler state machine, RBAC provisioner, events
                   (reference: internal/controllers/healthcheck_controller.go)
- ``metrics``    — Prometheus collectors incl. dynamic custom gauges
                   (reference: internal/metrics/collector.go)
- ``probes``     — the TPU-native probe payload library (new)
- ``models``     — the probe transformer used by the training-step probe (new)
- ``parallel``   — device mesh + timed-collective helpers (new)
- ``ops``        — TPU kernels (Pallas) used by probes (new)
"""

__version__ = "0.1.0"

GROUP = "activemonitor.keikoproj.io"
VERSION = "v1alpha1"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "HealthCheck"
