"""Controller manager — the process shell around the reconciler.

The reference's manager (reference: cmd/main.go:68-133) provides: watch
→ workqueue → bounded concurrent reconciles, leader election, metrics
server with optional auth, health/readiness probes. Equivalent here:

- watch events from the client feed an asyncio queue; ``max_parallel``
  workers drain it (reference: MaxConcurrentReconciles,
  healthcheck_controller.go:298 / cmd/main.go:144 default 10)
- keys are deduplicated while queued (a queued key absorbs new events,
  like controller-runtime's workqueue)
- on start, all existing HealthChecks are enqueued (boot resync — the
  checkpoint/resume path, SURVEY.md §5.4)
- an aiohttp server exposes /metrics, /healthz, /readyz
  (reference: cmd/main.go:74-81,121-126)
- leadership is acquired before reconciling (reference: cmd/main.go:87-88)
"""

from __future__ import annotations

import asyncio
import logging
import os
from typing import Dict, Optional, Set, Tuple

from activemonitor_tpu.controller.client import HealthCheckClient
from activemonitor_tpu.controller.leader import AlwaysLeader, LeaderElector
from activemonitor_tpu.controller.reconciler import HealthCheckReconciler
from activemonitor_tpu.metrics.collector import (
    RECONCILE_ERROR,
    RECONCILE_REQUEUE_AFTER,
    RECONCILE_SUCCESS,
)

log = logging.getLogger("activemonitor.manager")

DEFAULT_MAX_PARALLEL = 10  # reference: cmd/main.go:144

WILDCARD_HOSTS = {"", "0.0.0.0", "::", "[::]", "*"}


def _norm_host(host: str) -> str:
    return "127.0.0.1" if host == "localhost" else host


def addr_conflict(a: str, b: str) -> bool:
    """Same port with overlapping hosts — ':8081' equals
    '0.0.0.0:8081', localhost equals 127.0.0.1, and any wildcard
    (v4 or v6) overlaps every host."""
    if not a or not b:
        return False
    host_a, _, port_a = a.rpartition(":")
    host_b, _, port_b = b.rpartition(":")
    if port_a != port_b:
        return False
    host_a, host_b = _norm_host(host_a), _norm_host(host_b)
    return (
        host_a == host_b or host_a in WILDCARD_HOSTS or host_b in WILDCARD_HOSTS
    )


def addr_same(a: str, b: str) -> bool:
    """Exactly the same socket (normalized host + port) — the only
    overlap that can be served as one merged site without changing
    either endpoint's exposure."""
    host_a, _, port_a = a.rpartition(":")
    host_b, _, port_b = b.rpartition(":")
    host_a, host_b = _norm_host(host_a), _norm_host(host_b)
    if host_a in WILDCARD_HOSTS and host_b in WILDCARD_HOSTS:
        host_a = host_b = "0.0.0.0"
    return port_a == port_b and host_a == host_b


def _jax_profiler_trace(path: str):
    """The default capture backend — imported lazily so a controller
    that never arms a capture never pays the jax import."""
    import jax

    return jax.profiler.trace(path)


class ProfileOnAnomaly:
    """One bounded ``jax.profiler.trace`` capture per confirmed anomaly
    (``--profile-on-anomaly DIR``; off by default).

    The trigger sites — attribution confirming ok→degraded
    (reconciler ``_note_analysis``) and a run pushing its SLO burn rate
    past 1.0 (``FleetStatus._record``) — call :meth:`arm`; the NEXT
    reconcile of that check then runs inside a profiler capture
    (:meth:`capture`, wrapped around the worker's reconcile call).
    Profiling the *next* run rather than the one that fired keeps the
    trigger path free of profiler overhead and captures a run end to
    end instead of from mid-flight.

    Bounded three ways: a per-check cooldown (a flapping check cannot
    fill the disk with captures), an armed-dedupe (N triggers between
    runs arm ONE capture), and a directory byte cap — oldest capture
    dirs prune beyond ``max_bytes``, and the ``captures.jsonl`` index
    rotates through the shared ``rotate_capped`` like the flight
    recorder's sink. Empty capture dirs (a probe that died before the
    first device event) are swept, never shipped. Every landed capture
    bumps ``healthcheck_profile_captures_total{reason}`` and records a
    ``profile-capture`` flight bundle carrying the capture path and the
    profiled run's waterfall. Never raises into the reconcile it wraps.
    """

    DEFAULT_COOLDOWN_SECONDS = 600.0
    DEFAULT_MAX_BYTES = 256 << 20
    CAPTURE_INDEX = "captures.jsonl"
    INDEX_MAX_BYTES = 1 << 20

    def __init__(
        self,
        clock,
        directory: str = "",
        cooldown: float = DEFAULT_COOLDOWN_SECONDS,
        max_bytes: int = DEFAULT_MAX_BYTES,
        metrics=None,
        flightrec=None,
        capture_factory=None,  # (path) -> context manager; tests inject
    ):
        self.clock = clock
        self.directory = directory
        self.cooldown = max(0.0, float(cooldown))
        self.max_bytes = max(0, int(max_bytes))
        self.metrics = metrics
        self.flightrec = flightrec
        self.capture_factory = capture_factory or _jax_profiler_trace
        self._armed: Dict[str, str] = {}  # key -> trigger reason
        self._last_capture: Dict[str, float] = {}
        self._capture_paths: list = []  # oldest first, for the byte cap
        self._seq = 0

    @property
    def enabled(self) -> bool:
        return bool(self.directory)

    def arm(self, key: str, reason: str) -> bool:
        """Request one capture of ``key``'s next run. Returns whether it
        armed (False: disabled, already armed, or inside the per-check
        cooldown). Never raises — trigger sites sit on the record path."""
        try:
            if not self.enabled or key in self._armed:
                return False
            last = self._last_capture.get(key)
            if last is not None and (
                self.clock.monotonic() - last < self.cooldown
            ):
                return False
            self._armed[key] = reason
            log.info("profile-on-anomaly armed for %s (%s)", key, reason)
            return True
        except Exception:
            log.exception("profile arm failed for %s", key)
            return False

    def capture(self, key: str):
        """The context manager the reconciler wraps one watch (probe
        run) in: a real profiler capture when ``key`` is armed, a no-op
        otherwise."""
        reason = self._armed.pop(key, None)
        if reason is None:
            import contextlib

            return contextlib.nullcontext()
        return _ProfileCapture(self, key, reason)

    # -- internals (driven by _ProfileCapture) -------------------------
    def _begin(self, key: str) -> str:
        # the cooldown stamps at CAPTURE time: the armed run's own
        # record may re-fire the trigger (its burn rate is still hot),
        # and that re-arm must land inside the cooldown, not restart it
        self._last_capture[key] = self.clock.monotonic()
        self._seq += 1
        safe = key.replace("/", "_").replace(os.sep, "_")
        return os.path.join(self.directory, f"{safe}-{self._seq:06d}")

    def _finish(self, key: str, reason: str, path: str) -> None:
        from activemonitor_tpu.obs.journal import prune_empty_dirs, rotate_capped

        # a capture that produced no device events leaves an empty dir
        # tree — sweep it rather than shipping an empty artifact
        prune_empty_dirs(path)
        captured = os.path.isdir(path)
        if captured:
            self._capture_paths.append(path)
            self._enforce_cap()
            try:
                os.makedirs(self.directory, exist_ok=True)
                index = os.path.join(self.directory, self.CAPTURE_INDEX)
                rotate_capped(index, self.INDEX_MAX_BYTES)
                import json

                with open(index, "a") as f:
                    f.write(
                        json.dumps(
                            {
                                "ts": self.clock.now().isoformat(),
                                "check": key,
                                "reason": reason,
                                "path": path,
                            }
                        )
                        + "\n"
                    )
            except OSError:
                log.exception("capture index append failed")
        if self.metrics is not None:
            self.metrics.record_profile_capture(reason)
        if self.flightrec is not None:
            from activemonitor_tpu.obs.flightrec import KIND_PROFILE

            self.flightrec.record(
                KIND_PROFILE,
                key=key,
                reason=reason,
                capture_path=path if captured else "",
                captured=captured,
            )
        log.warning(
            "profile capture for %s (%s): %s",
            key,
            reason,
            path if captured else "no device events (dir swept)",
        )

    def _enforce_cap(self) -> None:
        """Prune oldest capture dirs beyond the byte cap (the newest
        always survives — a cap smaller than one capture still keeps
        the evidence that was just paid for)."""
        if self.max_bytes <= 0:
            return

        def _tree_bytes(root: str) -> int:
            total = 0
            for dirpath, _dirs, files in os.walk(root):
                for name in files:
                    try:
                        total += os.path.getsize(os.path.join(dirpath, name))
                    except OSError:
                        pass
            return total

        sizes = {p: _tree_bytes(p) for p in self._capture_paths}
        while len(self._capture_paths) > 1 and (
            sum(sizes[p] for p in self._capture_paths) > self.max_bytes
        ):
            import shutil

            oldest = self._capture_paths.pop(0)
            sizes.pop(oldest, None)
            try:
                shutil.rmtree(oldest)
            except OSError:
                log.exception("capture prune failed for %s", oldest)
                break


class _ProfileCapture:
    """One armed capture's lifecycle around a reconcile. Both edges are
    best-effort: a profiler that fails to start (no jax, no devices)
    still books the attempt — cooldown, counter, bundle — so a broken
    profiler cannot re-arm itself into a tight capture loop."""

    def __init__(self, profiler: ProfileOnAnomaly, key: str, reason: str):
        self.profiler = profiler
        self.key = key
        self.reason = reason
        self.path = ""
        self._cm = None

    def __enter__(self):
        prof = self.profiler
        try:
            self.path = prof._begin(self.key)
            self._cm = prof.capture_factory(self.path)
            self._cm.__enter__()
        except Exception:
            log.exception("profiler capture start failed for %s", self.key)
            self._cm = None
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        try:
            if self._cm is not None:
                self._cm.__exit__(exc_type, exc, tb)
        except Exception:
            log.exception("profiler capture stop failed for %s", self.key)
        try:
            self.profiler._finish(self.key, self.reason, self.path)
        except Exception:
            log.exception("profiler capture finish failed for %s", self.key)
        return False  # never swallow the reconcile's own exception


class Manager:
    def __init__(
        self,
        client: HealthCheckClient,
        reconciler: HealthCheckReconciler,
        max_parallel: int = DEFAULT_MAX_PARALLEL,
        metrics_bind_address: str = "",  # "host:port" or "" to disable
        health_probe_bind_address: str = "",
        leader_elector: Optional[LeaderElector] = None,
        metrics_secure: bool = False,  # TLS on the metrics endpoint
        metrics_cert_file: str = "",  # self-signed fallback when empty
        metrics_key_file: str = "",
        metrics_auth_token: str = "",  # static bearer token; "" = open
        metrics_auth_token_file: str = "",  # re-read with a TTL (rotation)
        metrics_authorizer=None,  # KubeScrapeAuthorizer: TokenReview+SAR
        remedy_rate: float = 0.0,  # fleet-wide remedies/min; 0 = no cap
        shard_coordinator=None,  # ShardCoordinator: sharded-fleet mode
        goodput_interval: float = 30.0,  # rollup cadence; big fleets raise it
        flight_dir: str = "",  # durable flight-bundle JSONL dir; "" = memory only
        frontdoor=None,  # FrontDoor: probe-as-a-service ingestion surface
        journal_dir: str = "",  # durable telemetry journal dir; "" = no journal
        journal_max_bytes: int = 0,  # per-segment byte cap; 0 = journal default
        profile_on_anomaly_dir: str = "",  # capture dir; "" = profiling off
        profile_cooldown: float = ProfileOnAnomaly.DEFAULT_COOLDOWN_SECONDS,
        profile_max_bytes: int = 0,  # capture-dir byte cap; 0 = default
        federation=None,  # FederationPlane: multi-cluster control plane
    ):
        self.client = client
        self.reconciler = reconciler
        # sharded fleet (controller/sharding.py): ownership filters the
        # workqueue, shard handoffs resync/release their keys, and the
        # write fence rides the reconciler. None = classic single-owner
        # mode behind the leader elector.
        self._shards = shard_coordinator
        # shards whose adoption resync failed (transient list error):
        # retried by the shard loop until it lands — a one-shot resync
        # would silently stop monitoring the adopted shard's existing
        # checks (the watch only covers FUTURE events)
        self._resync_pending: Set[int] = set()
        self._boot_resynced = False
        # home-shard losses seen so far: a re-acquisition (losses > 0)
        # may never skip its adoption resync, even during boot
        self._home_losses = 0
        if shard_coordinator is not None:
            reconciler.shards = shard_coordinator
            reconciler.fleet.sharding = shard_coordinator
            # flight bundles on a sharded fleet carry the ownership
            # snapshot of the moment — who held what when it degraded
            reconciler.flightrec.sharding = shard_coordinator
        # --flight-dir: every bundle also lands as one JSONL line on
        # disk, so a postmortem survives the controller that wrote it
        if flight_dir:
            reconciler.flightrec.flight_dir = flight_dir
        # --frontdoor (frontdoor/service.py): triggered runs ride THIS
        # manager's enqueue (same workqueue, sharding, tracing, SLO
        # accounting as watch-path runs), the snapshot rides /statusz,
        # and the resilience sweep pumps degraded-mode parked requests
        self._frontdoor = frontdoor
        if frontdoor is not None:
            frontdoor.bind(self._frontdoor_trigger)
            reconciler.fleet.frontdoor = frontdoor
            # the door's admission decisions land as spans on the runs
            # they trigger/join — the waterfall's `admission` stage
            frontdoor.tracer = reconciler.tracer
            if shard_coordinator is not None:
                # sharded fleet: a miss for a key another replica owns
                # must refuse `unrouted` (naming its shard) instead of
                # triggering locally — enqueue would drop the unowned
                # key and this replica's rings never see the owner's
                # results, so the waiters would hang until reap
                frontdoor.owns = shard_coordinator.owns_key
            # adaptive lever 4 (resilience/adapt.py): a confirmed
            # control-plane burn widens the door's freshness ceiling
            # and sheds low-priority tenants before the breaker trips
            reconciler.adapt.frontdoor = frontdoor
        # --journal-dir (obs/journal.py): the durable telemetry journal.
        # Replay-then-subscribe via attach_journal restores the SLO /
        # goodput windows the restart would otherwise lose, the front
        # door records its arrival stream (the workload trace), the
        # goodput loop exports the gauges + compacts aged segments, and
        # the snapshot rides /statusz.
        self._journal = None
        if journal_dir:
            from activemonitor_tpu.obs.journal import (
                DEFAULT_MAX_BYTES,
                TelemetryJournal,
            )

            journal = TelemetryJournal(
                journal_dir,
                clock=reconciler.clock,
                max_bytes=journal_max_bytes or DEFAULT_MAX_BYTES,
                metrics=reconciler.metrics,
            )
            self._journal = journal
            reconciler.fleet.attach_journal(journal)
            if frontdoor is not None:
                frontdoor.journal = journal
        # --federation-config (federation/plane.py): the multi-cluster
        # control plane. Cluster-transition flight bundles ride THIS
        # controller's recorder, the registry gauges its collector, the
        # /statusz federation block the fleet, and transport stays out
        # of the package: the plane polls through the aiohttp hook
        # below. The goodput loop drives the poll/sweep cadence.
        self._federation = federation
        if federation is not None:
            reconciler.fleet.federation = federation
            federation.registry.flightrec = reconciler.flightrec
            if federation.registry.metrics is None:
                federation.registry.metrics = reconciler.metrics
            if federation.router.metrics is None:
                federation.router.metrics = reconciler.metrics
            if federation.fetch is None:
                federation.fetch = self._fetch_cluster_statusz
        # fleet-wide remedy storm control (--remedy-rate) lives in the
        # reconciler's resilience coordinator. Sharded fleets apportion
        # the FLEET rate by owned shards (rate × owned/N, re-applied on
        # every handoff by _apportion_remedy_rate) so the per-replica
        # buckets always sum to the configured cap — a static rate/N
        # split silently halves the fleet budget whenever survivors run
        # with adopted shards (replicas < shards). Boot value is the
        # home-shard share; the acquire hook corrects it immediately.
        self._remedy_rate = remedy_rate
        if shard_coordinator is not None and remedy_rate > 0:
            reconciler.resilience.configure_remedy_rate(
                remedy_rate / shard_coordinator.shards
            )
        else:
            reconciler.resilience.configure_remedy_rate(remedy_rate)
        # --profile-on-anomaly (ProfileOnAnomaly above): a confirmed
        # degradation or a burn-rate crossing arms ONE bounded profiler
        # capture of the check's next run; both trigger sites are wired
        # here so a standalone reconciler/fleet never profiles
        self._profiler = ProfileOnAnomaly(
            clock=reconciler.clock,
            directory=profile_on_anomaly_dir,
            cooldown=profile_cooldown,
            max_bytes=profile_max_bytes or ProfileOnAnomaly.DEFAULT_MAX_BYTES,
            metrics=reconciler.metrics,
            flightrec=reconciler.flightrec,
        )
        if self._profiler.enabled:
            reconciler.profile_hook = self._profiler.arm
            reconciler.fleet.profile_hook = self._profiler.arm
            # the capture itself wraps the WATCH task (the probe run),
            # not the scheduling reconcile — a no-op reconcile must not
            # consume an armed capture
            reconciler.profile_capture = self._profiler.capture
        # failed-run requeues ride this manager's workqueue: per-key
        # serialized, stop-aware, re-rate-limited on crash — never a
        # loop inside a dying watch/timer task
        reconciler.requeue_hook = self.enqueue
        self.max_parallel = max_parallel
        self._metrics_addr = metrics_bind_address
        self._health_addr = health_probe_bind_address
        # the goodput/shard-count rollup walks the whole (owned) check
        # list — at 50k-check scale an operator stretches this cadence
        self._goodput_interval = goodput_interval
        self._metrics_secure = metrics_secure
        self._metrics_cert_file = metrics_cert_file
        self._metrics_key_file = metrics_key_file
        from activemonitor_tpu.utils.tokenfile import FileToken

        # on_error="clear": a deleted/unmounted token file means access
        # was revoked — the gate fails closed, never "last token wins"
        self._metrics_token = FileToken(
            path=metrics_auth_token_file,
            initial=metrics_auth_token,
            on_error="clear",
        )
        self._metrics_authorizer = metrics_authorizer
        from activemonitor_tpu.errors import ConfigurationError

        # one overlap decision drives both the secure refusal and the
        # plaintext single-site merge — a string-equality merge would
        # double-bind ':9090' vs '0.0.0.0:9090' (EADDRINUSE mid-start)
        conflict = addr_conflict(metrics_bind_address, health_probe_bind_address)
        self._shared_addr = conflict and addr_same(
            metrics_bind_address, health_probe_bind_address
        )
        if conflict and not self._shared_addr:
            # same port, DIFFERENT hosts (one a wildcard): a merge would
            # silently widen or narrow one endpoint's exposure — refuse,
            # whether secure or not
            raise ConfigurationError(
                "metrics and health probe addresses overlap on one port "
                "with different hosts "
                f"({metrics_bind_address!r} vs {health_probe_bind_address!r}); "
                "use identical addresses to share the port, or different ports"
            )
        if metrics_secure and self._shared_addr:
            # health probes must stay plaintext for the kubelet's default
            # httpGet scheme; a shared TLS port would restart-loop the
            # pod. Refuse at construction, before any side effects.
            raise ConfigurationError(
                "metrics and health probes cannot share an address when "
                "--metrics-secure is on; use separate ports or "
                "--no-metrics-secure"
            )
        if bool(metrics_cert_file) != bool(metrics_key_file):
            # also a construction-time usage error: failing later at
            # bind time would come after -f manifests were applied
            raise ConfigurationError(
                "metrics TLS needs BOTH --metrics-cert-file and "
                "--metrics-key-file (got only one)"
            )
        # build the TLS context NOW so a missing/malformed PEM is a
        # usage error before any side effects, not a bind-time traceback
        self._metrics_ssl = None
        # rotation baseline, stat'ed BEFORE the chain loads: a rotation
        # landing in the stat→load window then costs one harmless extra
        # reload at the first tick, whereas stat-after-load would adopt
        # it silently and never reload the stale chain
        self._cert_baseline = None
        if metrics_secure and metrics_bind_address:
            import ssl as _ssl

            from activemonitor_tpu.utils.tls import server_ssl_context

            if metrics_cert_file:
                try:
                    self._cert_baseline = (
                        os.stat(metrics_cert_file).st_mtime_ns,
                        os.stat(metrics_key_file).st_mtime_ns,
                    )
                except OSError:
                    pass
            try:
                self._metrics_ssl = server_ssl_context(
                    metrics_cert_file, metrics_key_file
                )
            except (OSError, _ssl.SSLError) as e:
                raise ConfigurationError(
                    f"metrics TLS certificate unusable: {e}"
                ) from e
        self._elector = leader_elector or AlwaysLeader()
        self._queue: asyncio.Queue = asyncio.Queue()
        self._queued: Set[str] = set()
        self._processing: Set[str] = set()
        self._dirty: Set[str] = set()
        # per queued key: (pre-minted trace id, enqueue monotonic) — the
        # one hop contextvars cannot cross is the workqueue (enqueue and
        # dequeue happen on different tasks), so the trace rides here
        # and the worker roots the cycle's span on it; the enqueue time
        # feeds the workqueue_queue_duration histogram and the trace's
        # "dequeue" (queue wait) span
        self._pending_trace: Dict[str, Tuple[str, float]] = {}
        self._active_workers = 0
        self._ready = asyncio.Event()
        self._stopping = asyncio.Event()
        self._tasks: list = []
        self._requeue_tasks: Set[asyncio.Task] = set()
        self._http_runners: list = []
        self.reconciler.metrics.set_max_concurrent(self.max_parallel)

    async def _fetch_cluster_statusz(self, url: str) -> Optional[dict]:
        """The federation plane's transport hook: one member cluster's
        /statusz, fetched under the same connect/read-gap timeouts as
        the CLI's multi-URL fetch (a total cap would misreport a slow-
        streaming healthy cluster as dead). Any failure returns None —
        absence of movement, which the liveness window judges; the
        error itself never decides health."""
        import aiohttp

        timeout = aiohttp.ClientTimeout(
            connect=5, sock_connect=5, sock_read=15
        )
        try:
            async with aiohttp.ClientSession(timeout=timeout) as session:
                async with session.get(url) as resp:
                    if resp.status != 200:
                        return None
                    return await resp.json()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.warning("federation statusz fetch failed for %s", url)
            return None

    def _frontdoor_trigger(self, namespace: str, name: str) -> Optional[str]:
        """The front door's run trigger: mark the cycle demand-driven
        (the schedule-current dedupe must not swallow it — the tenant
        asked for a fresher answer than the rings hold) and ride the
        ordinary workqueue, so sharding/tracing/attribution/SLO
        accounting apply to the triggered run unchanged. Returns the
        cycle's trace id (enqueue pre-mints it) so the door can book
        its admission span on the run it just triggered."""
        self.reconciler.demand(namespace, name)
        return self.enqueue(namespace, name)

    # -- queue ----------------------------------------------------------
    # controller-runtime workqueue semantics: a queued key coalesces new
    # events; a key being PROCESSED is marked dirty and re-queued after
    # its reconcile finishes, so one key never reconciles concurrently.
    def enqueue(self, namespace: str, name: str) -> Optional[str]:
        """Queue one reconcile; returns the cycle's pre-minted trace id
        (the pending one when the key coalesced, None when the key is
        unowned or deferred dirty) — the front door attaches its
        admission span to the trace this returns."""
        key = f"{namespace}/{name}"
        metrics = self.reconciler.metrics
        if self._shards is not None and not self._shards.owns_key(key):
            return None  # another shard's owner reconciles this key
        if key in self._processing:
            self._dirty.add(key)
            # client-go counts EVERY Add() — coalesced and dirty-deferred
            # included — so rate(workqueue_adds_total) reads true event
            # pressure even when the queue absorbs it
            metrics.record_queue_add(self._queue.qsize())
            return None
        if key in self._queued:
            metrics.record_queue_add(self._queue.qsize())
            pending = self._pending_trace.get(key)
            return pending[0] if pending else None  # coalesce: already pending
        self._queued.add(key)
        # the trace starts HERE — the cycle's invisible window opens at
        # enqueue, and queue wait must be attributable like every other
        # phase
        trace_id = self.reconciler.tracer.new_trace_id()
        self._pending_trace[key] = (
            trace_id,
            self.reconciler.clock.monotonic(),
        )
        self._queue.put_nowait((namespace, name))
        metrics.record_queue_add(self._queue.qsize())
        return trace_id

    async def _watch_loop(self, iterator) -> None:
        async for event in iterator:
            self.enqueue(event.namespace, event.name)

    async def _worker(self, index: int) -> None:
        metrics = self.reconciler.metrics
        tracer = self.reconciler.tracer
        clock = self.reconciler.clock
        while True:
            namespace, name = await self._queue.get()
            key = f"{namespace}/{name}"
            self._queued.discard(key)
            if self._shards is not None and not self._shards.owns_key(key):
                # the shard was handed off while the key sat queued: its
                # new owner reconciles it — processing here would submit
                # a duplicate run behind the fence
                self._pending_trace.pop(key, None)
                self._dirty.discard(key)
                self._queue.task_done()
                continue
            self._processing.add(key)
            trace_id, enqueued_at = self._pending_trace.pop(
                key, (None, clock.monotonic())
            )
            dequeued_at = clock.monotonic()
            metrics.record_queue_get(
                self._queue.qsize(), dequeued_at - enqueued_at
            )
            self._active_workers += 1
            metrics.set_active_workers(self._active_workers)
            result = RECONCILE_SUCCESS
            # a ROOT span per dequeue (never inherited: this task's
            # contextvar still holds the previous iteration's context);
            # the detached watch task the reconcile spawns inherits it,
            # so poll/status-write spans land in the same trace
            with tracer.trace(
                "reconcile", trace_id=trace_id, healthcheck=key, worker=index
            ):
                tracer.record_span("dequeue", start=enqueued_at, healthcheck=key)
                try:
                    requeue_after = await self.reconciler.reconcile(
                        namespace, name
                    )
                except asyncio.CancelledError:
                    raise
                except Exception:
                    log.exception("reconcile %s/%s crashed", namespace, name)
                    requeue_after = 1.0
                    result = RECONCILE_ERROR
                finally:
                    self._processing.discard(key)
                    work_seconds = clock.monotonic() - dequeued_at
                    self._active_workers -= 1
                    metrics.set_active_workers(self._active_workers)
                    metrics.record_work_duration(work_seconds)
            if result is not RECONCILE_ERROR and requeue_after:
                result = RECONCILE_REQUEUE_AFTER
            metrics.record_reconcile(result, work_seconds)
            if key in self._dirty:
                self._dirty.discard(key)
                self.enqueue(namespace, name)
            if requeue_after:
                task = asyncio.create_task(
                    self._requeue_later(namespace, name, requeue_after)
                )
                # hold a strong reference: the loop keeps only a weakref
                # and an unreferenced sleeper can be GC'd before firing
                self._requeue_tasks.add(task)
                task.add_done_callback(self._requeue_tasks.discard)
            self._queue.task_done()

    async def _requeue_later(self, namespace: str, name: str, delay: float) -> None:
        await self.reconciler.clock.sleep(delay)
        if not self._stopping.is_set():
            self.enqueue(namespace, name)

    # -- lifecycle --------------------------------------------------------
    async def start(self) -> None:
        """Acquire leadership (or the home shard), start HTTP endpoints,
        resync, serve."""
        await self._start_http()
        if self._shards is not None:
            # sharded fleet: per-shard Leases replace the single lock.
            # Losing ONE shard releases its keys and keeps serving; the
            # shard set keeps standing by for every shard forever.
            self._shards.on_acquired = self._shard_acquired
            self._shards.on_lost = self._shard_lost
            self._shards.pre_shed = self._shard_pre_shed
            log.info(
                "waiting for a shard (%d shards, home %d)",
                self._shards.shards, self._shards.shard_id,
            )
            await self._shards.start()
            log.info(
                "shard(s) %s acquired; starting %d workers",
                self._shards.owned_shards(), self.max_parallel,
            )
            self._tasks.append(asyncio.create_task(self._shard_loop()))
        else:
            log.info("waiting for leadership (%s)", type(self._elector).__name__)
            await self._elector.acquire()
            log.info("leadership acquired; starting %d workers", self.max_parallel)

            # a lost election must stop reconciling immediately — the
            # other replica is already active (reference:
            # controller-runtime terminates the process on lost
            # leadership)
            lost = getattr(self._elector, "lost", None)
            if isinstance(lost, asyncio.Event):
                self._tasks.append(
                    asyncio.create_task(self._leadership_watch(lost))
                )

        # watch FIRST, resync list second. No-lost-events rests on one of
        # two client guarantees: in-memory/file watches register
        # synchronously at call time; the k8s watch starts without a
        # resourceVersion, so the server replays the full current state
        # as synthetic ADDED events once the stream connects. Either way
        # nothing can fall between watch() and the list below.
        watch_iterator = self.client.watch()
        self._tasks.append(asyncio.create_task(self._watch_loop(watch_iterator)))
        for i in range(self.max_parallel):
            self._tasks.append(asyncio.create_task(self._worker(i)))
        self._tasks.append(
            asyncio.create_task(self._goodput_loop(self._goodput_interval))
        )
        self._tasks.append(asyncio.create_task(self._resilience_loop()))
        # boot resync: reconcile everything that already exists
        for hc in await self.client.list():
            self.enqueue(hc.metadata.namespace, hc.metadata.name)
        # from here on, adopted shards resync themselves (the home
        # shard's acquisition during start() rode this boot list)
        self._boot_resynced = True
        self._ready.set()

    async def _cert_reload_loop(self, interval: float = 60.0) -> None:
        """Poll the metrics TLS PEM files' mtimes and reload the serving
        chain when they change. ``SSLContext.load_cert_chain`` on the
        live context applies to NEW handshakes (established connections
        keep their session), which is exactly rotation semantics. A
        half-written pair mid-rotation fails the DRY-RUN load into a
        throwaway context, so the live chain is untouched until a
        coherent pair appears — load_cert_chain installs the cert
        before checking the key, so validating directly on the live
        context would leave a torn new-cert/old-key pair behind."""
        import ssl as _ssl

        clock = self.reconciler.clock

        def mtimes():
            return (
                os.stat(self._metrics_cert_file).st_mtime_ns,
                os.stat(self._metrics_key_file).st_mtime_ns,
            )

        # baseline from __init__ (when the chain actually loaded), so a
        # rotation in the window before this task's first tick is seen
        # as a change rather than silently adopted as the baseline
        last = self._cert_baseline
        while True:
            await clock.sleep(interval)
            try:
                now = mtimes()
            except OSError as e:
                log.warning("metrics TLS files unreadable (%s); keeping "
                            "the current chain", e)
                continue
            if now == last:
                continue
            try:
                # dry-run first: prove the pair is coherent in a
                # throwaway context before touching the live one
                probe_ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
                probe_ctx.load_cert_chain(
                    self._metrics_cert_file, self._metrics_key_file
                )
                self._metrics_ssl.load_cert_chain(
                    self._metrics_cert_file, self._metrics_key_file
                )
            except (OSError, ValueError) as e:
                log.warning(
                    "metrics TLS reload failed (%s); keeping the current "
                    "chain until the next attempt", e,
                )
                continue  # retry; mtime stays != last so we re-attempt
            last = now
            log.info("metrics TLS certificate reloaded (rotation detected)")

    async def _goodput_loop(self, interval: float = 30.0) -> None:
        """Periodically roll up fleet health: the fraction of scheduled
        checks whose latest run succeeded within 2x their cadence."""
        from activemonitor_tpu.scheduler import parse_cron

        clock = self.reconciler.clock
        while True:
            try:
                checks = await self.client.list()
                scheduled = 0
                good = 0
                now = clock.now()
                for hc in checks:
                    interval_s = hc.spec.repeat_after_sec
                    if interval_s <= 0 and not hc.spec.schedule.cron:
                        continue  # paused checks don't count either way
                    scheduled += 1
                    if hc.status.status != "Succeeded" or hc.status.finished_at is None:
                        continue
                    # cadence precedence mirrors the reconciler's
                    # _effective_repeat_after: a cron schedule wins even
                    # when repeatAfterSec is also set
                    if hc.spec.schedule.cron:
                        # cron period around now (handles non-uniform crons
                        # approximately: the gap between the next two fires)
                        try:
                            sched = parse_cron(hc.spec.schedule.cron)
                            fire1 = sched.next(now)
                            interval_s = (sched.next(fire1) - fire1).total_seconds()
                        except Exception:
                            continue
                    if (now - hc.status.finished_at).total_seconds() <= 2 * interval_s:
                        good += 1
                # an empty fleet is vacuously healthy — and the gauge
                # must not freeze at a stale fraction
                self.reconciler.metrics.cadence_goodput.set(
                    good / scheduled if scheduled else 1.0
                )
                # the run-weighted SLO goodput refreshes on the same
                # cadence — it walks every check's result ring, which
                # is rollup work, not reconcile-path work
                self.reconciler.fleet.refresh_fleet_goodput()
                # scenario-matrix gauges (--matrix-state): export the
                # sidecar's latest round into the healthcheck_matrix_*
                # families, once per new round
                self.reconciler.fleet.refresh_matrix_metrics()
                # critical-path stage gauges: walks every check's
                # windowed traces — rollup-cadence work, never
                # reconcile-path work (obs/criticalpath.py)
                self.reconciler.fleet.refresh_critical_path_metrics(checks)
                # journal level gauges (--journal-dir) + compaction of
                # aged-out segments — rollup-cadence work like the rest
                self.reconciler.fleet.refresh_journal_metrics()
                if self._journal is not None:
                    self._journal.compact()
                if self._shards is not None:
                    # per-shard ownership counts for /statusz and the
                    # healthcheck_shard_checks gauge (rollup work too)
                    self._shards.update_check_counts(checks)
                if self._federation is not None:
                    # federation round (--federation-config): poll every
                    # member cluster's /statusz (observed movement IS
                    # the liveness signal), sweep health transitions,
                    # refresh the federation gauges — rollup-cadence
                    # work riding the same loop as the other rollups
                    await self._federation.poll()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("goodput rollup failed")
            await clock.sleep(interval)

    async def _resilience_loop(self, interval: float = 5.0) -> None:
        """Drive time-based resilience state even while traffic is
        quiet: the breaker's open → half-open transition happens on
        state reads, the degraded gauge must follow it, and status
        writes queued during degraded mode need a replay driver that
        doesn't depend on new runs finishing (docs/resilience.md)."""
        clock = self.reconciler.clock
        while True:
            await clock.sleep(interval)
            try:
                self.reconciler.resilience.refresh()
                await self.reconciler.replay_status_writes()
                # adaptive-control sweep (resilience/adapt.py): refresh
                # the contention-placement lever from the cohort index,
                # the derived front-door degraded mode, and the lever
                # gauges — never raises by its own contract
                self.reconciler.adapt.sweep()
                if self._frontdoor is not None:
                    # degraded-mode parked requests replay next to the
                    # queued status writes (same recovery signal), and
                    # stranded in-flight entries (deleted check,
                    # disowned shard) are reaped on the same sweep
                    self._frontdoor.pump()
                    self._frontdoor.reap()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("resilience sweep failed")

    # -- sharded fleet ---------------------------------------------------
    async def _shard_acquired(self, shard: int) -> None:
        """Adopt a shard: reconcile every check it routes. The restart-
        resume path (reconciler divergence 10) rebuilds each TimerWheel
        entry from durable ``.status`` — current checks re-arm for the
        remaining interval, checks whose fire passed while the shard was
        orphaned run immediately — so the dead owner's owed runs fire
        exactly once, here. A failed resync is parked for the shard
        loop to retry: the watch stream only yields FUTURE events, so
        giving up would silently stop monitoring the shard's existing
        checks."""
        self._apportion_remedy_rate()
        from activemonitor_tpu.obs.flightrec import KIND_HANDOFF

        self.reconciler.flightrec.record(
            KIND_HANDOFF, shard=shard, event="acquired"
        )
        if (
            shard == self._shards.shard_id
            and not self._boot_resynced
            and self._home_losses == 0
        ):
            # the home shard is acquired while start() is still waiting
            # on the shard set, and start()'s boot resync — which always
            # follows — lists the whole owned slice anyway: a second
            # full LIST here would double the O(fleet/N) boot cost for
            # zero extra coverage. Only the FIRST acquisition may skip:
            # a home shard lost and re-acquired while the boot list was
            # in flight had its keys filtered out of that list (owns was
            # False at enqueue time), so the re-acquisition must resync
            # like any adoption or the shard's existing checks stay
            # unmonitored until an unrelated watch event
            return
        if not await self._adopt_resync({shard}):
            self._resync_pending.add(shard)

    def _apportion_remedy_rate(self) -> None:
        """This replica's share of the fleet --remedy-rate follows its
        owned-shard count: rate × owned/N. Summed over the fleet the
        buckets equal the configured cap exactly whenever every shard
        has one owner — including survivors carrying adopted shards.
        (A shardless standby gets rate/N rather than zero: a bucket
        must exist for the fence-adjacent window where a just-lost
        shard's in-flight run still reaches the remedy gate.)"""
        if self._shards is None or self._remedy_rate <= 0:
            return
        owned = max(1, len(self._shards.set.owned))
        self.reconciler.resilience.configure_remedy_rate(
            self._remedy_rate * owned / self._shards.shards
        )

    async def _adopt_resync(self, shards: Set[int]) -> bool:
        """Resync every check routed to ``shards`` — ONE list serves
        the whole batch (a burst adoption of k shards must not cost k
        identical O(owned-slice) LISTs)."""
        try:
            checks = await self.client.list()
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception(
                "adoption resync list for shard(s) %s failed; retrying "
                "from the shard loop", sorted(shards),
            )
            return False
        adopted = 0
        for hc in checks:
            if self._shards.shard_for(hc.key) in shards:
                self.enqueue(hc.metadata.namespace, hc.metadata.name)
                adopted += 1
        log.info(
            "shard(s) %s adopted: %d checks resynced", sorted(shards), adopted
        )
        return True

    async def _shard_pre_shed(self, shard: int) -> bool:
        """A voluntary shed must hand the adopter durable truth: defer
        (try again next sweep) while any of the shard's work is still in
        flight — a reconcile being processed, a watch tracking a
        submitted workflow, or a queued status write. Shedding under any
        of those drops the run's record at the fence and the adopter
        re-submits the very cycle this replica already ran (the crash
        path has no such choice; the voluntary path does)."""

        def in_shard(key: str) -> bool:
            return self._shards.shard_for(key) == shard

        def defer() -> bool:
            # the shard was DRAINING while this gate ran: any timer fire
            # or dequeue in that window was dropped unsubmitted, and an
            # aborted shed keeps ownership — so a resync must re-arm
            # whatever the drain swallowed (it runs on the next sweep,
            # after the coordinator lifts the draining mark)
            self._resync_pending.add(shard)
            return False

        if any(in_shard(key) for key in self._processing):
            return defer()
        if self.reconciler.has_inflight(in_shard):
            return defer()
        res = self.reconciler.resilience
        if res.pending_status_writes():
            await self.reconciler.replay_status_writes()
        if any(in_shard(key) for key in res.queued_status_keys()):
            return defer()
        return True

    async def _shard_lost(self, shard: int) -> None:
        """Handoff cleanup: every pending timer, in-flight watch, and
        queued status write for the shard's keys dies HERE — whatever
        survived would either double-fire against the new owner's
        schedule or be rejected by the write fence."""
        if shard == self._shards.shard_id:
            self._home_losses += 1
        self._apportion_remedy_rate()
        from activemonitor_tpu.obs.flightrec import KIND_HANDOFF

        self.reconciler.flightrec.record(
            KIND_HANDOFF, shard=shard, event="lost"
        )
        self._resync_pending.discard(shard)
        released = self.reconciler.release_keys(
            lambda key: self._shards.shard_for(key) == shard
        )
        log.warning(
            "shard %d handed off: released %d timers/watches", shard, released
        )

    async def _shard_loop(self, interval: float = 10.0) -> None:
        """Publish this replica's workqueue depth (rides the shard lease
        renewals) and run the work-stealing policy: shed an adopted
        shard when our depth diverges above the fleet median."""
        clock = self.reconciler.clock
        while True:
            await clock.sleep(interval)
            try:
                # retry adoption resyncs that failed at acquisition time
                # (or were owed by an aborted shed) — still-owned shards
                # only, batched behind one list. Subtract exactly what
                # was attempted: a shard adopted DURING the awaited list
                # may park its own failed resync concurrently, and a
                # blanket clear() would silently drop it
                self._resync_pending &= set(self._shards.set.owned)
                attempted = set(self._resync_pending)
                if attempted and await self._adopt_resync(attempted):
                    self._resync_pending -= attempted
                depth = self._queue.qsize()
                shed = await self._shards.rebalance(depth)
                if shed is not None:
                    log.info("work-stealing shed shard %d", shed)
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("shard rebalance sweep failed")

    async def _leadership_watch(self, lost: asyncio.Event) -> None:
        await lost.wait()
        log.critical("leadership lost; stopping reconcile workers")
        # flip the stop signal (run_forever / the CLI observe it) and
        # halt all work without awaiting our own cancellation
        self._stopping.set()
        for t in self._tasks:
            if t is not asyncio.current_task():
                t.cancel()
        for t in self._requeue_tasks:
            t.cancel()

    @property
    def stopping(self) -> asyncio.Event:
        """Set when the manager is shutting down (or has lost leadership)."""
        return self._stopping

    async def run_forever(self) -> None:
        await self.start()
        await self._stopping.wait()

    async def stop(self) -> None:
        self._stopping.set()
        for t in list(self._tasks) + list(self._requeue_tasks):
            t.cancel()
        await asyncio.gather(
            *self._tasks, *self._requeue_tasks, return_exceptions=True
        )
        self._tasks.clear()
        self._requeue_tasks.clear()
        await self.reconciler.shutdown()
        # drain queued event posts (bounded) before closing the recorder,
        # so the final transitions recorded during shutdown still reach
        # the Events API
        flush = getattr(self.reconciler.recorder, "flush", None)
        if flush is not None:
            try:
                await asyncio.wait_for(flush(), timeout=5.0)
            except Exception:
                # best-effort: a hung API server must not stall stop()
                log.debug("event flush failed during stop", exc_info=True)
        self.reconciler.recorder.close()
        for runner in self._http_runners:
            await runner.cleanup()
        self._http_runners.clear()
        # awaitable release guarantees the lease handoff completes before
        # the caller tears down the shared API session
        if self._shards is not None:
            await self._shards.stop()
        release_async = getattr(self._elector, "release_async", None)
        if release_async is not None:
            await release_async()
        else:
            self._elector.release()

    # -- HTTP endpoints ---------------------------------------------------
    async def _start_http(self) -> None:
        if not self._metrics_addr and not self._health_addr:
            return
        if self._metrics_ssl is not None and self._metrics_cert_file:
            # cert-manager-style rotation: the PEM files on disk are
            # renewed under the controller; without a reload loop the
            # endpoint serves the ORIGINAL chain until restart and
            # scrapes start failing at its expiry (controller-runtime
            # ships a certwatcher for exactly this). Started HERE, not
            # after leadership: a STANDBY replica serves TLS metrics
            # too, and it may wait in acquire() across many rotations.
            self._tasks.append(asyncio.create_task(self._cert_reload_loop()))
        from aiohttp import web

        def static_token_matches(request) -> Optional[bool]:
            """True/False against the static bearer token; None when no
            static token is configured at all."""
            token = self._metrics_token.get()
            if self._metrics_token.path and not token:
                # a token file was configured but yields nothing (not
                # mounted yet / wrong path): FAIL CLOSED — the operator
                # asked for auth, so an empty token must not mean "open"
                return False
            if not token:
                return None
            import hmac

            auth = request.headers.get("Authorization", "")
            # bytes compare: compare_digest on str raises for
            # non-ASCII headers (fuzzed input would 500, not 401)
            return hmac.compare_digest(
                auth.encode("utf-8", "surrogateescape"),
                f"Bearer {token}".encode(),
            )

        async def denial(request) -> Optional["web.Response"]:
            """The metrics auth filter (reference: authn/z-filtered
            :8443, cmd/main.go:74-81) as a reusable gate: None when the
            request may proceed, an error response otherwise. Health
            probes stay open for the kubelet; /debug reuses this gate
            when it is forced onto the same socket as /metrics."""
            if self._metrics_authorizer is not None:
                # K8s-native path (TokenReview + SubjectAccessReview):
                # the CLUSTER decides who scrapes, per identity, via
                # RBAC — exactly the reference's filter. The static
                # token (if also configured) stays honored as the
                # documented migration/fallback credential.
                auth = request.headers.get("Authorization", "")
                bearer = auth[7:] if auth.startswith("Bearer ") else ""
                verdict = await self._metrics_authorizer.allowed(bearer)
                if verdict is not True:
                    static = static_token_matches(request)
                    if static is not True:
                        if verdict is None:
                            # authorizer infra failure and the fallback
                            # credential (if any) didn't match: fail
                            # closed, but tell the scraper it is US,
                            # not them — a 401 here would send the
                            # operator chasing good credentials
                            return web.Response(
                                status=503, text="authorization unavailable"
                            )
                        return web.Response(status=401, text="unauthorized")
            else:
                static = static_token_matches(request)
                if static is False:
                    return web.Response(status=401, text="unauthorized")
            return None

        async def metrics(request):
            denied = await denial(request)
            if denied is not None:
                return denied
            collector = self.reconciler.metrics
            # content negotiation: OpenMetrics is the format that
            # carries the trace-id exemplars on the latency histograms;
            # the default text format stays the reference's exact
            # scrape contract
            if "application/openmetrics-text" in request.headers.get(
                "Accept", ""
            ):
                return web.Response(
                    body=collector.exposition(openmetrics=True),
                    headers={
                        "Content-Type": collector.OPENMETRICS_CONTENT_TYPE
                    },
                )
            data = collector.exposition()
            return web.Response(
                body=data, content_type="text/plain", charset="utf-8"
            )

        async def healthz(_request):
            return web.Response(text="ok")

        async def readyz(_request):
            if self._ready.is_set():
                return web.Response(text="ok")
            return web.Response(status=503, text="not ready")

        async def debug_traces(request):
            # completed reconcile-cycle traces, newest last; ?trace_id=
            # narrows to one (the id a correlated log line / event
            # carries), ?check= to one check's cycles — the deep links
            # `am-tpu why` and the flight recorder hand out, so a
            # single cycle is addressable without client-side filtering
            traces = self.reconciler.tracer.traces()
            wanted = request.query.get("trace_id")
            if wanted:
                traces = [t for t in traces if t["trace_id"] == wanted]
            check = request.query.get("check")
            if check:
                traces = [
                    t
                    for t in traces
                    if any(
                        s["attrs"].get("healthcheck") == check
                        for s in t["spans"]
                    )
                ]
            return web.json_response({"traces": traces})

        async def debug_flightrec(request):
            # degradation flight bundles, oldest first; ?kind= / ?check=
            # narrow (docs/operations.md "Reading a flight recording")
            bundles = self.reconciler.flightrec.bundles(
                kind=request.query.get("kind"),
                check=request.query.get("check"),
            )
            return web.json_response({"bundles": bundles})

        async def debug_events(request):
            events = self.reconciler.recorder.all
            wanted = request.query.get("trace_id")
            if wanted:
                events = [e for e in events if e.trace_id == wanted]
            return web.json_response({"events": [e.to_dict() for e in events]})

        async def statusz(_request):
            # fleet SLO summary: the client's live check list joined
            # with the reconciler's result history and budget state
            # (obs/slo.py owns the schema; a contract test pins it)
            checks = await self.client.list()
            return web.json_response(self.reconciler.fleet.statusz(checks))

        async def frontdoor_submit(request):
            # the async ingestion surface (frontdoor/service.py):
            # tenants POST one-shot check requests at high QPS without
            # touching the apiserver. wait=false returns the admission
            # decision immediately; the default awaits the fanned-out
            # result (cache hit, coalesced join, or the triggered run)
            door = self._frontdoor
            if door is None:
                return web.Response(status=404, text="no front door configured")
            try:
                body = await request.json()
                tenant = str(body["tenant"])
                check = str(body["check"])
                freshness = body.get("freshness")
                freshness = None if freshness is None else float(freshness)
                wait = bool(body.get("wait", True))
                dag_spec = body.get("dag")
            except (KeyError, TypeError, ValueError) as e:
                return web.Response(status=400, text=f"bad request: {e}")

            def ticket_doc(ticket) -> dict:
                result = ticket.result
                return {
                    "outcome": ticket.outcome,
                    "reason": ticket.reason,
                    "tenant": ticket.tenant,
                    "check": ticket.check,
                    "shard": ticket.shard,
                    "trace_id": ticket.trace_id,
                    "result": result.to_dict() if result is not None else None,
                }

            ticket = None
            try:
                if dag_spec:
                    # composable probe DAG: the check field names the
                    # DAG, the dag field carries the arrow syntax
                    # (docs/operations.md "Probe DAGs")
                    from activemonitor_tpu.frontdoor.dag import parse_dag

                    dag = parse_dag(check, str(dag_spec), freshness)
                    if not wait:
                        # fire-and-forget: the DAG executes in the
                        # background (results land in the rings and the
                        # metric families); 202 acknowledges admission
                        # of the request, not its outcome
                        task = asyncio.create_task(
                            door.run_dag(tenant, dag)
                        )
                        self._requeue_tasks.add(task)
                        task.add_done_callback(self._requeue_tasks.discard)
                        return web.json_response(
                            {
                                "dag": check,
                                "accepted": True,
                                "steps": [s.name for s in dag.steps],
                            },
                            status=202,
                        )
                    tickets = await door.run_dag(tenant, dag)
                    return web.json_response(
                        {
                            "dag": check,
                            "steps": {
                                name: ticket_doc(t)
                                for name, t in tickets.items()
                            },
                        }
                    )
                ticket = door.submit(tenant, check, freshness)
                if wait and ticket.future is not None:
                    # shield: a handler-task cancellation (client gone,
                    # server stopping) must NOT cancel the shared
                    # fan-in future other waiters ride — and it keeps
                    # the two cancellation sources distinguishable
                    await asyncio.shield(ticket.future)
                    ticket.result = ticket.future.result()
            except ValueError as e:
                return web.Response(status=400, text=f"bad request: {e}")
            except asyncio.CancelledError:
                # the reap sweep cancels waiters of stranded runs
                # (deleted/quarantined/stopped checks record no
                # result) — that is a gateway timeout for THIS
                # request, not a dying server: the shield above means
                # the ticket's future is cancelled ONLY on reap, so
                # re-raise for a genuine handler-task cancellation
                if (
                    ticket is None
                    or ticket.future is None
                    or not ticket.future.cancelled()
                ):
                    raise
                return web.Response(
                    status=504,
                    text="probe run recorded no result (check deleted, "
                    "quarantined, or stopped); request reaped",
                )
            return web.json_response(ticket_doc(ticket))

        async def frontdoor_status(_request):
            if self._frontdoor is None:
                return web.Response(status=404, text="no front door configured")
            return web.json_response(self._frontdoor.snapshot())

        # /debug and /statusz ride the health-probe site (plaintext,
        # kubelet-open) — trace/event/fleet payloads are operator
        # diagnostics like /healthz, not scrape data behind the metrics
        # auth filter. The front door rides the same site: its tenants
        # are the cluster's own workloads, and the admission layer IS
        # its protection (quota refusals, not transport auth).
        debug_routes = [
            web.get("/debug/traces", debug_traces),
            web.get("/debug/events", debug_events),
            web.get("/debug/flightrec", debug_flightrec),
            web.get("/statusz", statusz),
            web.post("/frontdoor/submit", frontdoor_submit),
            web.get("/frontdoor", frontdoor_status),
        ]

        def guarded(handler):
            """On the MERGED site /debug shares a socket with the
            auth-filtered /metrics — an operator who put a token in
            front of that port meant all its operational data, so the
            debug endpoints enforce the same gate there."""

            async def wrapped(request):
                denied = await denial(request)
                if denied is not None:
                    return denied
                return await handler(request)

            return wrapped

        guarded_debug_routes = [
            web.get("/debug/traces", guarded(debug_traces)),
            web.get("/debug/events", guarded(debug_events)),
            web.get("/debug/flightrec", guarded(debug_flightrec)),
            web.get("/statusz", guarded(statusz)),
            web.post("/frontdoor/submit", guarded(frontdoor_submit)),
            web.get("/frontdoor", guarded(frontdoor_status)),
        ]

        async def bind_site(addr: str, routes, secure: bool = False) -> None:
            host, _, port = addr.rpartition(":")
            app = web.Application()
            app.add_routes(routes)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(
                runner,
                host or "0.0.0.0",
                int(port),
                ssl_context=self._metrics_ssl if secure else None,
            )
            await site.start()
            self._http_runners.append(runner)

        if self._metrics_addr and self._shared_addr:
            # identical sockets only (addr_same in __init__); overlapping
            # -but-different hosts were refused there, so this merge
            # cannot change either endpoint's exposure
            await bind_site(
                self._metrics_addr,
                [
                    web.get("/metrics", metrics),
                    web.get("/healthz", healthz),
                    web.get("/readyz", readyz),
                ]
                + guarded_debug_routes,
            )
            return
        if self._metrics_addr:
            await bind_site(
                self._metrics_addr,
                [web.get("/metrics", metrics)],
                secure=self._metrics_secure,
            )
        if self._health_addr:
            await bind_site(
                self._health_addr,
                [web.get("/healthz", healthz), web.get("/readyz", readyz)]
                + debug_routes,
            )

    @property
    def ready(self) -> bool:
        return self._ready.is_set()
