"""Remedy storm control — a fleet-wide token bucket.

The per-check gates (``remedyRunsLimit`` / ``remedyResetInterval``,
reference: healthcheck_controller.go:677-721) bound how often ONE check
self-heals. They compose multiplicatively across a fleet: a bad rollout
that fails 200 checks at once launches 200 remedy workflows in the same
minute, each within its own per-check budget — a self-inflicted storm
against the very cluster the remedies are supposed to heal. The token
bucket is the fleet-wide cap layered on top (``--remedy-rate``): tokens
refill continuously at ``rate_per_minute``; every admitted remedy takes
one; when the bucket is dry the remedy is *suppressed* — evented and
counted under ``healthcheck_remedy_runs_total{result="suppressed"}`` —
and the next failure after refill runs it.

Refill is computed lazily from the injected clock's monotonic time (no
background task, no wall clock — hack/lint.py bans ``time.time()`` in
this package), so fake-clock tests script exhaustion and refill exactly.
"""

from __future__ import annotations

from typing import Optional

from activemonitor_tpu.utils.clock import Clock


class TokenBucket:
    """Continuous-refill token bucket on an injectable monotonic clock.

    ``rate_per_minute`` tokens accrue per minute up to ``burst``
    (default: ``max(1, rate_per_minute)``, so a configured cap always
    admits at least one remedy immediately after a quiet period).
    """

    def __init__(
        self,
        rate_per_minute: float,
        burst: Optional[float] = None,
        clock: Optional[Clock] = None,
    ):
        if rate_per_minute <= 0:
            raise ValueError("rate_per_minute must be > 0 (omit the bucket for 'no cap')")
        self.rate_per_second = rate_per_minute / 60.0
        self.burst = float(burst) if burst is not None else max(1.0, rate_per_minute)
        self.clock = clock or Clock()
        self._tokens = self.burst  # start full: the cap bounds rate, not startup
        self._stamp = self.clock.monotonic()

    def _refill(self) -> None:
        now = self.clock.monotonic()
        elapsed = max(0.0, now - self._stamp)
        self._stamp = now
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate_per_second)

    def set_rate(self, rate_per_minute: float) -> None:
        """Re-rate a LIVE bucket without granting a fresh burst: tokens
        accrued so far are settled at the old rate, then the refill rate
        and default burst change in place. Sharded fleets re-apportion
        each replica's share of the fleet remedy cap as shard ownership
        moves — replacing the bucket instead would refill it to burst on
        every handoff, and a flapping shard could mint remedy budget."""
        if rate_per_minute <= 0:
            raise ValueError("rate_per_minute must be > 0 (omit the bucket for 'no cap')")
        self._refill()
        self.rate_per_second = rate_per_minute / 60.0
        self.burst = max(1.0, rate_per_minute)
        self._tokens = min(self._tokens, self.burst)

    def try_take(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available; False (nothing taken) when
        the bucket cannot cover them."""
        self._refill()
        if self._tokens + 1e-9 < n:
            return False
        self._tokens -= n
        return True

    def available(self) -> float:
        """Tokens on hand right now (refilled to now)."""
        self._refill()
        return self._tokens

    def seconds_until(self, n: float = 1.0) -> float:
        """How long until ``n`` tokens are on hand (0 when already)."""
        self._refill()
        deficit = n - self._tokens
        if deficit <= 0:
            return 0.0
        return deficit / self.rate_per_second
