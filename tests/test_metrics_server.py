"""Secure metrics serving — TLS + bearer-token auth.

Reference parity: metrics on :8443 secure-by-default with an authn/z
filter, self-signed fallback when no cert is supplied
(reference: cmd/main.go:74-81, flags :138-144). Health probes stay
plaintext and unauthenticated for the kubelet.
"""

import ssl

import pytest

from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.utils.tls import generate_self_signed_cert

try:  # the TLS tests mint certs; gate on the optional dependency
    import cryptography

    _HAS_CRYPTO = cryptography is not None
except ImportError:
    _HAS_CRYPTO = False

needs_cryptography = pytest.mark.skipif(
    not _HAS_CRYPTO, reason="cryptography not installed in this container"
)


def free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def make_manager(**kwargs):
    client = InMemoryHealthCheckClient()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=FakeWorkflowEngine(),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
    )
    return Manager(client=client, reconciler=reconciler, max_parallel=1, **kwargs)


async def fetch(url, token=None, ca_pem=None):
    import aiohttp

    if url.startswith("https"):
        if ca_pem is not None:
            ctx = ssl.create_default_context(cadata=ca_pem.decode())
            ctx.check_hostname = False  # IP connect vs DNS SAN
        else:
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            ctx.verify_mode = ssl.CERT_NONE
    else:
        ctx = None
    headers = {"Authorization": f"Bearer {token}"} if token else {}
    async with aiohttp.ClientSession() as session:
        async with session.get(url, ssl=ctx, headers=headers) as resp:
            return resp.status, await resp.text()


@needs_cryptography
@pytest.mark.asyncio
async def test_metrics_tls_self_signed_by_default():
    port = free_port()
    manager = make_manager(
        metrics_bind_address=f"127.0.0.1:{port}", metrics_secure=True
    )
    await manager.start()
    try:
        # https works (self-signed, so no verification)
        status, text = await fetch(f"https://127.0.0.1:{port}/metrics")
        assert status == 200
        assert "healthcheck_success_count" in text
        # plaintext scrape against the TLS port fails
        with pytest.raises(Exception):
            await fetch(f"http://127.0.0.1:{port}/metrics")
    finally:
        await manager.stop()


@needs_cryptography
@pytest.mark.asyncio
async def test_metrics_tls_with_supplied_certificate(tmp_path):
    cert_pem, key_pem = generate_self_signed_cert("metrics.test")
    cert_file = tmp_path / "tls.crt"
    key_file = tmp_path / "tls.key"
    cert_file.write_bytes(cert_pem)
    key_file.write_bytes(key_pem)

    port = free_port()
    manager = make_manager(
        metrics_bind_address=f"127.0.0.1:{port}",
        metrics_secure=True,
        metrics_cert_file=str(cert_file),
        metrics_key_file=str(key_file),
    )
    await manager.start()
    try:
        # the client VERIFIES against the supplied cert — proof the
        # server actually serves it, not an ephemeral one
        status, _ = await fetch(f"https://127.0.0.1:{port}/metrics", ca_pem=cert_pem)
        assert status == 200
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_metrics_bearer_auth():
    port_metrics, port_health = free_port(), free_port()
    manager = make_manager(
        metrics_bind_address=f"127.0.0.1:{port_metrics}",
        health_probe_bind_address=f"127.0.0.1:{port_health}",
        metrics_auth_token="scrape-me",
    )
    await manager.start()
    try:
        status, _ = await fetch(f"http://127.0.0.1:{port_metrics}/metrics")
        assert status == 401
        status, _ = await fetch(
            f"http://127.0.0.1:{port_metrics}/metrics", token="wrong"
        )
        assert status == 401
        status, text = await fetch(
            f"http://127.0.0.1:{port_metrics}/metrics", token="scrape-me"
        )
        assert status == 200 and "healthcheck" in text
        # health probes stay open (kubelet has no tokens)
        status, _ = await fetch(f"http://127.0.0.1:{port_health}/healthz")
        assert status == 200
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_metrics_token_rotation_from_file(tmp_path):
    """A rotated scrape-token Secret must be picked up without a
    restart (TTL re-read)."""
    token_file = tmp_path / "token"
    token_file.write_text("first\n")
    port = free_port()
    manager = make_manager(
        metrics_bind_address=f"127.0.0.1:{port}",
        metrics_auth_token_file=str(token_file),
    )
    await manager.start()
    try:
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics", token="first")
        assert status == 200
        token_file.write_text("second\n")
        manager._metrics_token.expire()  # TTL elapsed
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics", token="first")
        assert status == 401
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics", token="second")
        assert status == 200
        # fuzzed non-ASCII header is a 401, not a 500
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics", token="tök€n")
        assert status == 401
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_metrics_auth_fails_closed_when_token_file_deleted(tmp_path):
    """Revoking the token by deleting the file must 401 the old token
    after the TTL — fail closed, not last-token-wins."""
    token_file = tmp_path / "token"
    token_file.write_text("live-token\n")
    port = free_port()
    manager = make_manager(
        metrics_bind_address=f"127.0.0.1:{port}",
        metrics_auth_token_file=str(token_file),
    )
    await manager.start()
    try:
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics", token="live-token")
        assert status == 200
        token_file.unlink()  # operator revokes access
        manager._metrics_token.expire()
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics", token="live-token")
        assert status == 401
    finally:
        await manager.stop()


def test_plaintext_overlapping_addresses_merge_instead_of_double_binding():
    """':P' and '0.0.0.0:P' are the same socket — the manager must
    serve one combined site, not crash with EADDRINUSE mid-start."""
    from activemonitor_tpu.controller.manager import addr_conflict, addr_same

    assert addr_conflict(":9090", "0.0.0.0:9090")
    assert addr_conflict("localhost:9090", "127.0.0.1:9090")
    assert not addr_conflict(":9090", ":9091")
    assert not addr_conflict("", ":9090")
    assert addr_same(":9090", "0.0.0.0:9090")
    assert not addr_same("127.0.0.1:9090", "0.0.0.0:9090")
    m = make_manager(
        metrics_bind_address=":9090",
        health_probe_bind_address="0.0.0.0:9090",
        metrics_secure=False,
    )
    assert m._shared_addr


def test_same_port_different_hosts_is_refused():
    """Merging '127.0.0.1:P' onto '0.0.0.0:P' would silently widen (or
    narrow) an endpoint's exposure — refused, secure or not."""
    with pytest.raises(ValueError, match="different hosts"):
        make_manager(
            metrics_bind_address="127.0.0.1:9090",
            health_probe_bind_address="0.0.0.0:9090",
            metrics_secure=False,
        )


@pytest.mark.asyncio
async def test_metrics_auth_fails_closed_on_unreadable_token_file():
    """--metrics-auth-token-file pointing at a missing file (Secret not
    mounted) must DENY, not silently serve unauthenticated."""
    port = free_port()
    manager = make_manager(
        metrics_bind_address=f"127.0.0.1:{port}",
        metrics_auth_token_file="/nonexistent/scrape-token",
    )
    await manager.start()
    try:
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics")
        assert status == 401
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics", token="anything")
        assert status == 401
    finally:
        await manager.stop()


def test_half_supplied_cert_pair_is_refused(tmp_path):
    from activemonitor_tpu.utils.tls import server_ssl_context

    with pytest.raises(ValueError, match="BOTH"):
        server_ssl_context(cert_file=str(tmp_path / "only.crt"))


def test_unusable_cert_is_a_construction_time_usage_error(tmp_path):
    """Missing or malformed PEM files fail at Manager construction (as
    ConfigurationError → clean CLI exit), not at bind time after
    manifests were applied."""
    with pytest.raises(ValueError, match="certificate unusable"):
        make_manager(
            metrics_bind_address="127.0.0.1:9443",
            metrics_secure=True,
            metrics_cert_file=str(tmp_path / "missing.crt"),
            metrics_key_file=str(tmp_path / "missing.key"),
        )
    bad = tmp_path / "bad.pem"
    bad.write_text("not a pem")
    with pytest.raises(ValueError, match="certificate unusable"):
        make_manager(
            metrics_bind_address="127.0.0.1:9443",
            metrics_secure=True,
            metrics_cert_file=str(bad),
            metrics_key_file=str(bad),
        )


@pytest.mark.asyncio
async def test_metrics_plaintext_when_explicitly_insecure():
    port = free_port()
    manager = make_manager(
        metrics_bind_address=f"127.0.0.1:{port}", metrics_secure=False
    )
    await manager.start()
    try:
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics")
        assert status == 200
    finally:
        await manager.stop()


def test_shared_secure_address_is_refused():
    """TLS on a port shared with health probes would break kubelet
    httpGet probes — refused at construction, before any side effects."""
    with pytest.raises(ValueError, match="share an address"):
        make_manager(
            metrics_bind_address="127.0.0.1:9999",
            health_probe_bind_address="127.0.0.1:9999",
            metrics_secure=True,
        )


def test_cli_defaults_secure():
    from activemonitor_tpu.__main__ import build_parser

    args = build_parser().parse_args(["run"])
    assert args.metrics_secure is True
    assert args.metrics_bind_address == ":8443"
    assert args.metrics_k8s_auth == "auto"
    args = build_parser().parse_args(["run", "--no-metrics-secure"])
    assert args.metrics_secure is False


# -- k8s-native scrape authn/z (TokenReview + SubjectAccessReview) -----
# reference: cmd/main.go:74-81 WithAuthenticationAndAuthorization


async def k8s_auth_manager(port, **kwargs):
    """Manager wired to a stub apiserver playing the review APIs."""
    from activemonitor_tpu.kube import KubeApi, KubeConfig
    from activemonitor_tpu.kube.authn import KubeScrapeAuthorizer
    from activemonitor_tpu.kube.stub import StubApiServer

    server = StubApiServer()
    await server.start()
    server.scrape_tokens["prom-token"] = "system:serviceaccount:monitoring:prometheus"
    server.metrics_allowed_users.add("system:serviceaccount:monitoring:prometheus")
    server.scrape_tokens["peon-token"] = "peon"  # authenticates, no RBAC
    api = KubeApi(KubeConfig(server=server.url))
    manager = make_manager(
        metrics_bind_address=f"127.0.0.1:{port}",
        metrics_authorizer=KubeScrapeAuthorizer(api),
        **kwargs,
    )
    return server, api, manager


@pytest.mark.asyncio
async def test_k8s_auth_allows_rbac_authorized_identity():
    port = free_port()
    server, api, manager = await k8s_auth_manager(port)
    await manager.start()
    try:
        # cluster-authorized identity scrapes
        status, text = await fetch(
            f"http://127.0.0.1:{port}/metrics", token="prom-token"
        )
        assert status == 200 and "healthcheck" in text
        # authenticated but not RBAC-authorized for /metrics: denied
        status, _ = await fetch(
            f"http://127.0.0.1:{port}/metrics", token="peon-token"
        )
        assert status == 401
        # unauthenticated / unknown token: denied
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics", token="junk")
        assert status == 401
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics")
        assert status == 401
    finally:
        await manager.stop()
        await api.close()
        await server.stop()


@pytest.mark.asyncio
async def test_k8s_auth_static_token_stays_honored_as_fallback():
    port = free_port()
    server, api, manager = await k8s_auth_manager(
        port, metrics_auth_token="legacy-scraper"
    )
    await manager.start()
    try:
        status, _ = await fetch(
            f"http://127.0.0.1:{port}/metrics", token="legacy-scraper"
        )
        assert status == 200
        status, _ = await fetch(f"http://127.0.0.1:{port}/metrics", token="junk")
        assert status == 401
    finally:
        await manager.stop()
        await api.close()
        await server.stop()


@pytest.mark.asyncio
async def test_k8s_auth_fails_closed_when_apiserver_down():
    """TokenReview infra failure + no fallback credential: 503, never
    an open endpoint."""
    port = free_port()
    server, api, manager = await k8s_auth_manager(port)
    await server.stop()  # apiserver gone before the first scrape
    await manager.start()
    try:
        status, _ = await fetch(
            f"http://127.0.0.1:{port}/metrics", token="prom-token"
        )
        assert status == 503
    finally:
        await manager.stop()
        await api.close()


@pytest.mark.asyncio
async def test_k8s_auth_decision_is_cached():
    port = free_port()
    server, api, manager = await k8s_auth_manager(port)
    await manager.start()
    try:
        for _ in range(3):
            status, _ = await fetch(
                f"http://127.0.0.1:{port}/metrics", token="prom-token"
            )
            assert status == 200
        reviews = [p for _m, p in server.requests if "tokenreviews" in p]
        assert len(reviews) == 1  # one TokenReview for three scrapes
    finally:
        await manager.stop()
        await api.close()
        await server.stop()


class _FakeReviewApi:
    """Plays just the TokenReview/SAR endpoints for cache unit tests."""

    def __init__(self):
        self.token_reviews = 0

    async def create(self, path, body):
        if "tokenreviews" in path:
            self.token_reviews += 1
            token = body["spec"]["token"]
            if token.startswith(("good", "norbac")):
                return {
                    "status": {
                        "authenticated": True,
                        "user": {"username": token},
                    }
                }
            return {"status": {"authenticated": False}}
        return {"status": {"allowed": body["spec"]["user"].startswith("good")}}


@pytest.mark.asyncio
async def test_k8s_auth_cache_never_stores_raw_tokens():
    from activemonitor_tpu.kube.authn import KubeScrapeAuthorizer

    auth = KubeScrapeAuthorizer(_FakeReviewApi())
    assert await auth.allowed("good-secret-bearer") is True
    assert "good-secret-bearer" not in auth._cache  # only sha256 keys
    assert all(len(k) == 64 for k in auth._cache)


@pytest.mark.asyncio
async def test_k8s_auth_negative_verdicts_age_out_faster():
    """A denial cached at provisioning time must not outlive the short
    negative TTL — the scraper whose RBAC just landed recovers in
    seconds, not a full positive TTL."""
    from activemonitor_tpu.kube.authn import KubeScrapeAuthorizer

    clock = [0.0]
    api = _FakeReviewApi()
    auth = KubeScrapeAuthorizer(
        api, cache_ttl=60.0, negative_ttl=10.0, monotonic=lambda: clock[0]
    )
    assert await auth.allowed("norbac-scraper") is False
    assert await auth.allowed("good-scraper") is True
    reviews = api.token_reviews
    clock[0] = 11.0  # past the negative TTL, inside the positive one
    assert await auth.allowed("norbac-scraper") is False
    assert api.token_reviews == reviews + 1  # denial re-evaluated
    assert await auth.allowed("good-scraper") is True
    assert api.token_reviews == reviews + 1  # positive still cached


@pytest.mark.asyncio
async def test_k8s_auth_junk_spam_cannot_evict_live_verdict():
    """Per-entry eviction: junk-token churn drops its own (soonest-to-
    expire) entries, never the legitimate scraper's fresh verdict."""
    from activemonitor_tpu.kube.authn import KubeScrapeAuthorizer

    clock = [0.0]
    api = _FakeReviewApi()
    auth = KubeScrapeAuthorizer(
        api, cache_ttl=60.0, negative_ttl=10.0,
        monotonic=lambda: clock[0], max_entries=4,
    )
    assert await auth.allowed("good-scraper") is True
    reviews = api.token_reviews
    for i in range(20):  # spam well past max_entries
        clock[0] += 0.01
        assert await auth.allowed(f"junk-{i}") is False
    assert len(auth._cache) <= 4
    assert await auth.allowed("good-scraper") is True
    assert api.token_reviews == reviews + 20  # no re-review of the scraper


@pytest.mark.asyncio
async def test_k8s_auth_expiry_heap_stays_bounded():
    """Re-remembering the same tokens leaves stale heap entries behind;
    compaction must keep the heap O(max_entries) under refresh churn,
    and lazy invalidation must never evict a key via a stale entry."""
    from activemonitor_tpu.kube.authn import KubeScrapeAuthorizer

    clock = [0.0]
    api = _FakeReviewApi()
    auth = KubeScrapeAuthorizer(
        api, cache_ttl=60.0, negative_ttl=10.0,
        monotonic=lambda: clock[0], max_entries=4,
    )
    for round_ in range(50):  # each re-review pushes a fresh heap entry
        clock[0] = round_ * 61.0  # past the positive TTL: re-evaluated
        for i in range(3):
            assert await auth.allowed(f"norbac-{i}") is False
    assert len(auth._expiries) <= 2 * 4
    assert len(auth._cache) <= 4
    # a live verdict inserted now survives junk churn at capacity
    assert await auth.allowed("good-scraper") is True
    reviews = api.token_reviews
    for i in range(10):
        clock[0] += 0.01
        assert await auth.allowed(f"junk-{i}") is False
    assert await auth.allowed("good-scraper") is True
    assert api.token_reviews == reviews + 10


def test_cli_k8s_auth_on_requires_cluster_credentials():
    import asyncio as aio

    from activemonitor_tpu.__main__ import _run_controller, build_parser
    from activemonitor_tpu.errors import ConfigurationError

    args = build_parser().parse_args(
        ["run", "--engine", "local", "--metrics-k8s-auth", "on"]
    )
    with pytest.raises(ConfigurationError, match="cluster credentials"):
        aio.run(_run_controller(args, "file", None, None))


@needs_cryptography
@pytest.mark.asyncio
async def test_metrics_tls_certificate_rotation_reloads(tmp_path):
    """cert-manager-style rotation: the PEM files are renewed under the
    running controller; new handshakes must serve the NEW chain without
    a restart (controller-runtime's certwatcher behavior). Old chain
    before the poll tick, new chain after — verified by which CA each
    fetch trusts."""
    import asyncio

    from activemonitor_tpu.utils.clock import FakeClock

    old_cert, old_key = generate_self_signed_cert("metrics.test")
    cert_file = tmp_path / "tls.crt"
    key_file = tmp_path / "tls.key"
    cert_file.write_bytes(old_cert)
    key_file.write_bytes(old_key)

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=FakeWorkflowEngine(),
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
        clock=clock,
    )
    port = free_port()
    manager = Manager(
        client=client,
        reconciler=reconciler,
        max_parallel=1,
        metrics_bind_address=f"127.0.0.1:{port}",
        metrics_secure=True,
        metrics_cert_file=str(cert_file),
        metrics_key_file=str(key_file),
    )
    await manager.start()
    try:
        status, _ = await fetch(
            f"https://127.0.0.1:{port}/metrics", ca_pem=old_cert
        )
        assert status == 200

        new_cert, new_key = generate_self_signed_cert("metrics.test")
        assert new_cert != old_cert
        import os

        # a TORN rotation first: new cert, old key. The dry-run load
        # must reject the pair and leave the LIVE chain untouched —
        # load_cert_chain on the live context would strand a broken
        # new-cert/old-key hybrid and fail every new handshake
        cert_file.write_bytes(new_cert)
        os.utime(cert_file, ns=(1, 1))
        await clock.advance(61)
        await asyncio.sleep(0.05)
        status, _ = await fetch(
            f"https://127.0.0.1:{port}/metrics", ca_pem=old_cert
        )
        assert status == 200  # old chain still serving

        key_file.write_bytes(new_key)  # rotation completes
        os.utime(cert_file, ns=(2, 2))
        await clock.advance(61)  # one reload-poll tick
        await asyncio.sleep(0.05)

        status, _ = await fetch(
            f"https://127.0.0.1:{port}/metrics", ca_pem=new_cert
        )
        assert status == 200  # new chain served to new handshakes
        with pytest.raises(Exception):
            await fetch(f"https://127.0.0.1:{port}/metrics", ca_pem=old_cert)
    finally:
        await manager.stop()
