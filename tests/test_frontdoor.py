"""Probe-as-a-service front door (ISSUE 15).

Units (admission quotas, freshness windows, fan-in/fan-out, DAG
validation), the per-tenant conservation property under concurrent
submission, degraded-mode parking, and the scripted FakeClock
acceptance: N duplicate requests → ONE probe run through the Manager
enqueue path → N fanned-out results joinable by trace_id, visible in
/statusz, the gauges, and the `am-tpu status` FRONTDOOR block.
"""

import asyncio
import random

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.controller.sharding import ShardRouter
from activemonitor_tpu.engine import FakeWorkflowEngine, succeed_after
from activemonitor_tpu.frontdoor import (
    AdmissionController,
    FrontDoor,
    OUTCOME_HIT,
    OUTCOME_JOINED,
    OUTCOME_PARKED,
    OUTCOME_REFUSED,
    OUTCOME_RUN,
    REFUSE_PARKED_FULL,
    REFUSE_QUOTA,
    REFUSE_UNKNOWN_TENANT,
    TenantQuota,
    open_loop_checks,
    parse_dag,
)
from activemonitor_tpu.frontdoor.dag import DagStep, ProbeDag
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.obs.history import ResultHistory
from activemonitor_tpu.obs.slo import merge_frontdoor_blocks, rollup_statusz
from activemonitor_tpu.utils.clock import FakeClock

WF_INLINE = (
    "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"
)


def make_door(
    clock,
    *,
    quotas=None,
    default_quota=TenantQuota(rate_per_minute=600.0),
    router=None,
    resilience=None,
    metrics=None,
    freshness=30.0,
    park_capacity=8,
):
    history = ResultHistory(clock)
    door = FrontDoor(
        history,
        AdmissionController(
            quotas, default_quota=default_quota, router=router, clock=clock
        ),
        clock=clock,
        metrics=metrics,
        resilience=resilience,
        default_freshness=freshness,
        park_capacity=park_capacity,
    )
    triggered = []
    door.bind(lambda ns, name: triggered.append(f"{ns}/{name}"))
    return door, history, triggered


class FakeResilience:
    """Just the .degraded bit the front door reads."""

    def __init__(self):
        self.degraded = False


# -- admission ---------------------------------------------------------


@pytest.mark.asyncio
async def test_quota_refusal_is_structured_and_refills():
    clock = FakeClock()
    door, _history, triggered = make_door(
        clock,
        quotas={"t-a": TenantQuota(rate_per_minute=2.0, burst=2.0)},
        default_quota=None,
    )
    first = door.submit("t-a", "health/x")
    second = door.submit("t-a", "health/y")
    third = door.submit("t-a", "health/z")
    assert (first.outcome, second.outcome) == (OUTCOME_RUN, OUTCOME_RUN)
    assert third.outcome == OUTCOME_REFUSED
    assert third.reason == REFUSE_QUOTA
    assert door.admission.refused["t-a"] == {REFUSE_QUOTA: 1}
    assert triggered == ["health/x", "health/y"]
    # 2/min refills one token every 30 s — the next submit admits
    await clock.advance(30.0)
    assert door.submit("t-a", "health/z").outcome == OUTCOME_RUN
    assert door.conservation()["ok"]


@pytest.mark.asyncio
async def test_unknown_tenant_refused_without_default_quota():
    clock = FakeClock()
    door, _history, triggered = make_door(
        clock, quotas={"known": TenantQuota(60.0)}, default_quota=None
    )
    ticket = door.submit("stranger", "health/x")
    assert ticket.outcome == OUTCOME_REFUSED
    assert ticket.reason == REFUSE_UNKNOWN_TENANT
    assert triggered == []
    # with a default quota the same stranger is admitted lazily
    open_door, _h, _t = make_door(clock)
    assert open_door.submit("stranger", "health/x").outcome == OUTCOME_RUN
    assert door.conservation()["ok"] and open_door.conservation()["ok"]


@pytest.mark.asyncio
async def test_front_door_routes_through_the_fleet_shard_router():
    """A front-door request for check X lands on the SAME shard the
    watch path would route X's reconcile to — per-shard backends get
    exactly their own keys."""
    clock = FakeClock()
    router = ShardRouter(3)
    history = ResultHistory(clock)
    door = FrontDoor(
        history,
        AdmissionController(
            default_quota=TenantQuota(6000.0), router=router, clock=clock
        ),
        clock=clock,
    )
    by_shard = {shard: [] for shard in range(3)}
    for shard in range(3):
        door.bind_shard(
            shard,
            lambda ns, name, s=shard: by_shard[s].append(f"{ns}/{name}"),
        )
    keys = [f"health/check-{i:03d}" for i in range(60)]
    for key in keys:
        ticket = door.submit("t", key)
        assert ticket.outcome == OUTCOME_RUN
        assert ticket.shard == router.shard_for(key)
    for shard in range(3):
        assert by_shard[shard] == [
            k for k in keys if router.shard_for(k) == shard
        ]
    assert sum(len(v) for v in by_shard.values()) == len(keys)


@pytest.mark.asyncio
async def test_tenant_cardinality_is_bounded_by_max_tenants():
    """An open endpoint cannot mint unbounded per-tenant state: beyond
    max_tenants, new names refuse `tenant_capacity` booked under the
    shared (overflow) row — one ledger row and one metric series for
    ANY number of sprayed tenant strings."""
    from activemonitor_tpu.frontdoor import (
        OVERFLOW_TENANT,
        REFUSE_TENANT_CAPACITY,
    )

    clock = FakeClock()
    metrics = MetricsCollector()
    history = ResultHistory(clock)
    door = FrontDoor(
        history,
        AdmissionController(
            default_quota=TenantQuota(6000.0), clock=clock, max_tenants=2
        ),
        clock=clock,
        metrics=metrics,
    )
    door.bind(lambda ns, name: None)
    assert door.submit("t-1", "health/a").outcome == OUTCOME_RUN
    assert door.submit("t-2", "health/b").outcome == OUTCOME_RUN
    for i in range(50):  # 50 sprayed names, ONE overflow row
        ticket = door.submit(f"sprayed-{i}", "health/c")
        assert ticket.outcome == OUTCOME_REFUSED
        assert ticket.reason == REFUSE_TENANT_CAPACITY
    # known tenants keep being admitted
    assert door.submit("t-1", "health/d").outcome == OUTCOME_RUN
    conservation = door.conservation()
    assert conservation["ok"]
    assert set(conservation["tenants"]) == {"t-1", "t-2", OVERFLOW_TENANT}
    overflow = conservation["tenants"][OVERFLOW_TENANT]
    assert overflow["refused"] == {REFUSE_TENANT_CAPACITY: 50}
    assert (
        metrics.sample_value(
            "healthcheck_frontdoor_refusals_total",
            {"tenant": OVERFLOW_TENANT, "reason": REFUSE_TENANT_CAPACITY},
        )
        == 50
    )
    # unknown-tenant refusals on a closed fleet share the row too
    closed, _h, _t = make_door(clock, quotas={}, default_quota=None)
    for i in range(10):
        assert closed.submit(f"x-{i}", "health/a").reason == (
            REFUSE_UNKNOWN_TENANT
        )
    assert set(closed.conservation()["tenants"]) == {OVERFLOW_TENANT}


@pytest.mark.asyncio
async def test_unowned_key_is_a_structured_unrouted_refusal():
    """Sharded fleet: a miss for a key another replica owns refuses
    `unrouted` (with the owning shard id) instead of triggering a run
    this replica's rings would never resolve."""
    from activemonitor_tpu.frontdoor import REFUSE_UNROUTED

    clock = FakeClock()
    router = ShardRouter(3)
    door, history, triggered = make_door(clock, router=router)
    door.owns = lambda key: router.shard_for(key) == 0
    owned = next(
        f"health/c-{i}" for i in range(50)
        if router.shard_for(f"health/c-{i}") == 0
    )
    unowned = next(
        f"health/c-{i}" for i in range(50)
        if router.shard_for(f"health/c-{i}") != 0
    )
    assert door.submit("t", owned).outcome == OUTCOME_RUN
    ticket = door.submit("t", unowned)
    assert ticket.outcome == OUTCOME_REFUSED
    assert ticket.reason == REFUSE_UNROUTED
    assert ticket.shard == router.shard_for(unowned)  # re-aim target
    assert triggered == [owned]  # never triggered locally
    assert door.cache.inflight_keys() == [owned]  # nothing stranded
    # a fresh ring result still serves even for an unowned key? No —
    # the owns gate runs before the lookup, so ownership is authoritative
    history.record(unowned, ok=True, latency=1.0, workflow="wf", trace_id="t")
    assert door.submit("t", unowned).outcome == OUTCOME_REFUSED
    assert door.conservation()["ok"]


# -- coalescing --------------------------------------------------------


@pytest.mark.asyncio
async def test_freshness_window_edges_and_per_request_override():
    clock = FakeClock()
    door, history, triggered = make_door(clock, freshness=30.0)
    history.record("health/x", ok=True, latency=1.0, workflow="wf", trace_id="t0")
    await clock.advance(29.0)
    assert door.submit("a", "health/x").outcome == OUTCOME_HIT
    # a stricter per-request window misses where the default hits
    strict = door.submit("a", "health/x", freshness=10.0)
    assert strict.outcome == OUTCOME_RUN
    # resolve that run so the expiry probe below starts clean
    history.record("health/x", ok=True, latency=1.0, workflow="wf", trace_id="t1")
    await clock.advance(30.0)  # 30 s past the newest result: aged out
    # a WIDER per-request window clamps down to the operator's default
    # — the default is the staleness ceiling, not a suggestion
    assert door.cache.fresh_result("health/x", 86400.0) is None
    assert door.submit("a", "health/x").outcome == OUTCOME_RUN
    assert triggered == ["health/x", "health/x"]
    assert door.conservation()["ok"]


@pytest.mark.asyncio
async def test_duplicates_fan_in_on_one_run_and_share_the_trace_id():
    clock = FakeClock()
    door, history, triggered = make_door(clock)
    tickets = [door.submit(f"tenant-{i}", "health/x") for i in range(5)]
    assert [t.outcome for t in tickets] == [OUTCOME_RUN] + [OUTCOME_JOINED] * 4
    assert triggered == ["health/x"]  # ONE trigger for five requests
    recorded = history.record(
        "health/x", ok=True, latency=2.0, workflow="wf-9", trace_id="trace-9"
    )
    results = await asyncio.gather(*(t.wait() for t in tickets))
    assert all(r is recorded for r in results)
    assert {t.trace_id for t in tickets} == {"trace-9"}
    ratios = door.coalesce_ratios()
    assert ratios["join"] == pytest.approx(0.8)
    assert ratios["miss"] == pytest.approx(0.2)
    assert door.conservation()["ok"]


@pytest.mark.asyncio
async def test_scheduled_run_coalesces_front_door_traffic():
    """An in-flight entry resolves on ANY recorded result for the key —
    including one the check's own schedule produced — so the watch
    path's run absorbs front-door demand too."""
    clock = FakeClock()
    door, history, _triggered = make_door(clock)
    ticket = door.submit("a", "health/x")
    assert ticket.outcome == OUTCOME_RUN
    # the SCHEDULED run records first; the front door's waiter rides it
    scheduled = history.record(
        "health/x", ok=False, latency=3.0, workflow="wf-sched", trace_id="ts"
    )
    assert await ticket.wait() is scheduled


# -- degraded mode -----------------------------------------------------


@pytest.mark.asyncio
async def test_degraded_misses_park_and_pump_replays_them():
    clock = FakeClock()
    resilience = FakeResilience()
    door, history, triggered = make_door(clock, resilience=resilience)
    history.record("health/y", ok=True, latency=1.0, workflow="wf", trace_id="ty")
    resilience.degraded = True
    # cache hits still serve while degraded — that's the point of the
    # cache in an outage
    assert door.submit("a", "health/y").outcome == OUTCOME_HIT
    parked = door.submit("a", "health/x")
    assert parked.outcome == OUTCOME_PARKED
    assert triggered == []  # parked, never triggered
    assert door.queue_depth() == 1
    # pump during degraded is a no-op
    assert door.pump() == 0
    resilience.degraded = False
    assert door.pump() == 1
    assert triggered == ["health/x"]  # replayed, not dropped
    recorded = history.record(
        "health/x", ok=True, latency=1.0, workflow="wf2", trace_id="tx"
    )
    assert await parked.wait() is recorded
    conservation = door.conservation()
    assert conservation["ok"]
    assert conservation["tenants"]["a"]["parked"] == 0
    assert conservation["tenants"]["a"]["probe_runs"] == 1


@pytest.mark.asyncio
async def test_deleted_check_cancels_waiters_at_reconcile_speed():
    """A typo'd or just-deleted check must fail its front-door waiters
    the moment the reconciler notices (fleet.forget), not at the reap
    sweep's 600s bound."""
    from activemonitor_tpu.obs.slo import FleetStatus

    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    door = FrontDoor(
        fleet.history,
        AdmissionController(default_quota=TenantQuota(600.0), clock=clock),
        clock=clock,
    )
    door.bind(lambda ns, name: None)
    fleet.frontdoor = door
    ticket = door.submit("t", "health/typo")
    assert ticket.outcome == OUTCOME_RUN
    fleet.forget("health/typo")  # the reconciler's deleted path
    with pytest.raises(asyncio.CancelledError):
        await ticket.wait()
    assert door.cache.inflight_keys() == []
    assert door.conservation()["ok"]


@pytest.mark.asyncio
async def test_pump_rechecks_ownership_and_records_refusal_metrics():
    """A request parked before a shard handoff must get the same
    structured `unrouted` verdict the submit path gives — and pump-time
    refusals (unrouted, abandoned) reach the Prometheus counter, not
    just the in-memory ledger."""
    from activemonitor_tpu.frontdoor import REFUSE_ABANDONED, REFUSE_UNROUTED

    clock = FakeClock()
    resilience = FakeResilience()
    resilience.degraded = True
    metrics = MetricsCollector()
    history = ResultHistory(clock)
    door = FrontDoor(
        history,
        AdmissionController(default_quota=TenantQuota(600.0), clock=clock),
        clock=clock,
        metrics=metrics,
        resilience=resilience,
    )
    triggered = []
    door.bind(lambda ns, name: triggered.append(f"{ns}/{name}"))
    handed_off = door.submit("t", "health/a")
    abandoned = door.submit("t", "health/b")
    live = door.submit("t", "health/c")
    assert [
        handed_off.outcome, abandoned.outcome, live.outcome
    ] == [OUTCOME_PARKED] * 3
    # the shard moves away while all three sit parked; one waiter gives up
    door.owns = lambda key: key != "health/a"
    abandoned.future.cancel()
    resilience.degraded = False
    assert door.pump() == 3
    assert triggered == ["health/c"]  # only the live, still-owned key ran
    with pytest.raises(asyncio.CancelledError):
        await handed_off.wait()
    for reason in (REFUSE_UNROUTED, REFUSE_ABANDONED):
        assert (
            metrics.sample_value(
                "healthcheck_frontdoor_refusals_total",
                {"tenant": "t", "reason": reason},
            )
            == 1.0
        ), reason
    assert door.conservation()["ok"]


@pytest.mark.asyncio
async def test_park_capacity_overflow_is_a_structured_refusal():
    clock = FakeClock()
    resilience = FakeResilience()
    resilience.degraded = True
    door, _history, _triggered = make_door(
        clock, resilience=resilience, park_capacity=1
    )
    assert door.submit("a", "health/x").outcome == OUTCOME_PARKED
    overflow = door.submit("a", "health/z")
    assert overflow.outcome == OUTCOME_REFUSED
    assert overflow.reason == REFUSE_PARKED_FULL
    assert door.conservation()["ok"]


@pytest.mark.asyncio
async def test_reap_cancels_stranded_inflight_waiters():
    """An in-flight entry whose run never records (deleted check,
    disowned shard) is reaped after the age bound: waiters are
    cancelled — a visible outcome, not an eternal hang — and the
    counter records it."""
    clock = FakeClock()
    door, _history, _triggered = make_door(clock)
    ticket = door.submit("a", "health/ghost")
    assert ticket.outcome == OUTCOME_RUN
    assert door.reap(max_age_seconds=600.0) == 0  # too young
    await clock.advance(601.0)
    assert door.reap(max_age_seconds=600.0) == 1
    assert door.reaped_runs == 1
    assert door.cache.inflight_keys() == []
    with pytest.raises(asyncio.CancelledError):
        await ticket.wait()
    # outcome-counted at decision time, so the ledger stays exact
    assert door.conservation()["ok"]


# -- DAGs --------------------------------------------------------------


def test_dag_parse_stages_and_validation():
    dag = parse_dag(
        "readiness",
        "health/compile -> health/ici, health/hbm -> health/train",
    )
    stages = dag.stages()
    assert [[s.name for s in stage] for stage in stages] == [
        ["health/compile"],
        ["health/ici", "health/hbm"],
        ["health/train"],
    ]
    # every second-stage step waits on the whole first stage, etc.
    assert stages[1][0].after == ("health/compile",)
    assert stages[2][0].after == ("health/ici", "health/hbm")
    with pytest.raises(ValueError, match="empty spec"):
        parse_dag("nothing", " -> ")
    with pytest.raises(ValueError, match="repeats step name"):
        parse_dag("dup", "health/a -> health/a")
    # malformed tokens reject at PARSE time — before any earlier stage
    # could pay quota or launch a run
    with pytest.raises(ValueError, match="badtoken"):
        parse_dag("typo", "health/a -> badtoken")
    with pytest.raises(ValueError, match="unknown step"):
        ProbeDag("bad", (DagStep(name="a", check="h/a", after=("ghost",)),))
    with pytest.raises(ValueError, match="cycle"):
        ProbeDag(
            "loop",
            (
                DagStep(name="a", check="h/a", after=("b",)),
                DagStep(name="b", check="h/b", after=("a",)),
            ),
        )


@pytest.mark.asyncio
async def test_dag_executes_in_stages_and_reuses_upstream_results():
    clock = FakeClock()
    door, history, triggered = make_door(clock, freshness=300.0)

    async def resolve_runs():
        # play the backend: every triggered run records a result
        while True:
            await asyncio.sleep(0)
            for key in list(door.cache.inflight_keys()):
                history.record(
                    key, ok=True, latency=1.0, workflow="wf", trace_id=f"t-{key}"
                )

    player = asyncio.create_task(resolve_runs())
    try:
        dag = parse_dag(
            "readiness", "health/compile -> health/ici -> health/train"
        )
        tickets = await door.run_dag("tenant-a", dag)
        assert [t.outcome for t in tickets.values()] == [OUTCOME_RUN] * 3
        # stage order reached the backend in dependency order
        assert triggered == ["health/compile", "health/ici", "health/train"]
        # a second tenant running the SAME dag inside the freshness
        # window re-probes NOTHING — every step serves from the rings
        again = await door.run_dag("tenant-b", dag)
        assert [t.outcome for t in again.values()] == [OUTCOME_HIT] * 3
        assert triggered == ["health/compile", "health/ici", "health/train"]
        # per-step trace ids join each step to its one underlying run
        assert again["health/ici"].trace_id == "t-health/ici"
    finally:
        player.cancel()
        await asyncio.gather(player, return_exceptions=True)
    assert door.conservation()["ok"]


@pytest.mark.asyncio
async def test_dag_stops_at_a_refused_step():
    clock = FakeClock()
    door, _history, triggered = make_door(
        clock,
        quotas={"t": TenantQuota(rate_per_minute=60.0, burst=1.0)},
        default_quota=None,
    )
    async def resolve_runs():
        while True:
            await asyncio.sleep(0)
            for key in list(door.cache.inflight_keys()):
                _history.record(
                    key, ok=True, latency=1.0, workflow="wf", trace_id="t"
                )

    player = asyncio.create_task(resolve_runs())
    try:
        dag = parse_dag("readiness", "health/compile -> health/train")
        tickets = await door.run_dag("t", dag)
    finally:
        player.cancel()
        await asyncio.gather(player, return_exceptions=True)
    # the single-token bucket admits the first step; the second stage
    # refuses on quota and the DAG reports exactly how far it got
    assert tickets["health/compile"].outcome == OUTCOME_RUN
    assert triggered == ["health/compile"]
    assert (
        "health/train" not in tickets
        or tickets["health/train"].outcome == OUTCOME_REFUSED
    )
    assert door.conservation()["ok"]


# -- conservation property under concurrent submission -----------------


@pytest.mark.asyncio
@pytest.mark.parametrize("seed", [3, 11, 42, 1337])
async def test_per_tenant_conservation_property(seed):
    """Property: whatever the interleaving of concurrent submissions,
    scheduled result recordings, degraded flips, pumps, and quota
    refusals, every tenant's ledger stays EXACT —
    submitted == cache_hits + joins + runs + parked + refused — and
    the admission controller's independent tally agrees."""
    clock = FakeClock()
    rng = random.Random(seed)
    resilience = FakeResilience()
    door, history, _triggered = make_door(
        clock,
        quotas={"t-throttled": TenantQuota(rate_per_minute=60.0, burst=3.0)},
        default_quota=TenantQuota(rate_per_minute=100_000.0),
        resilience=resilience,
        freshness=20.0,
        park_capacity=16,
    )
    checks = [f"health/chk-{i:02d}" for i in range(6)]
    tenants = ["t-a", "t-b", "t-c", "t-throttled"]
    requests = open_loop_checks(
        300, rate_rps=50.0, seed=seed, checks=checks, tenants=tenants
    )
    tickets = []

    async def submit_slice(slice_requests):
        for req in slice_requests:
            tickets.append(door.submit(req.tenant, req.check))
            if rng.random() < 0.2:
                await asyncio.sleep(0)  # yield mid-slice: interleave

    i = 0
    while i < len(requests):
        width = rng.randrange(1, 5)
        batch = requests[i : i + 40]
        i += 40
        # concurrent submitters over interleaved slices of the batch
        await asyncio.gather(
            *(submit_slice(batch[w::width]) for w in range(width))
        )
        event = rng.random()
        if event < 0.3:
            resilience.degraded = not resilience.degraded
        if event < 0.5:
            for key in list(door.cache.inflight_keys()):
                if rng.random() < 0.7:
                    history.record(
                        key, ok=True, latency=0.5, workflow="wf", trace_id="t"
                    )
        door.pump()
        await clock.advance(rng.uniform(0.0, 10.0))
        # mid-storm: the ledger is already exact, parked and all
        assert door.conservation()["ok"]
    # quiesce: recover, pump everything, resolve every in-flight run
    resilience.degraded = False
    door.pump()
    for key in list(door.cache.inflight_keys()):
        history.record(key, ok=True, latency=0.5, workflow="wf", trace_id="t")
    conservation = door.conservation()
    assert conservation["ok"]
    assert conservation["submitted"] == len(requests)
    assert conservation["parked"] == 0
    # the throttled tenant really was throttled, and every refusal is
    # on its ledger, not vanished
    throttled = conservation["tenants"]["t-throttled"]
    assert throttled["refused"].get(REFUSE_QUOTA, 0) > 0
    assert throttled["submitted"] == throttled["admitted"] + sum(
        throttled["refused"].values()
    ) - throttled["refused"].get(REFUSE_PARKED_FULL, 0)
    # every ticket eventually resolved or was refused/parked-resolved
    for ticket in tickets:
        if ticket.outcome not in (OUTCOME_REFUSED,):
            assert await ticket.wait() is not None


# -- traffic generator -------------------------------------------------


def test_open_loop_checks_seeded_determinism():
    checks = ["health/a", "health/b", "health/c"]
    first = open_loop_checks(32, 8.0, seed=7, checks=checks)
    second = open_loop_checks(32, 8.0, seed=7, checks=checks)
    assert first == second
    assert first != open_loop_checks(32, 8.0, seed=8, checks=checks)
    arrivals = [r.arrival for r in first]
    assert arrivals == sorted(arrivals)
    assert {r.tenant for r in first} == {"tenant-a", "tenant-b"}
    with pytest.raises(ValueError):
        open_loop_checks(0, 8.0, seed=7, checks=checks)
    with pytest.raises(ValueError):
        open_loop_checks(4, 8.0, seed=7, checks=[])


# -- rollup + CLI ------------------------------------------------------


def test_rollup_merges_frontdoor_blocks_lookup_weighted():
    def payload(frontdoor):
        return {
            "fleet": {
                "checks": 0,
                "window_runs": 0,
                "goodput_ratio": None,
                "goodput": {},
                "generated_at": "",
                "degraded": False,
                "breaker": None,
                "status_writes_queued": 0,
                "remedy_tokens": None,
                "anomalies": {"warning": 0, "degraded": 0},
                "sharding": None,
                "matrix": None,
                "frontdoor": frontdoor,
            },
            "checks": [],
        }

    a = {
        "qps": 100.0,
        "coalescing": {"hit": 0.5, "miss": 0.5, "join": 0.0, "lookups": 20},
        "queue_depth": 2,
        "parked": 1,
        "inflight_runs": 1,
        "reaped_runs": 0,
        "degraded": False,
        "conservation_ok": True,
        "requests": {
            "submitted": 22,
            "refused": 2,
            "cache_hits": 10,
            "coalesced_joins": 0,
            "probe_runs": 9,
        },
        "tenants": {
            "t-a": {"submitted": 22, "refused": 2, "refusals": {"quota": 2}}
        },
    }
    b = {
        "qps": 50.0,
        "coalescing": {"hit": 0.0, "miss": 0.0, "join": 1.0, "lookups": 10},
        "queue_depth": 0,
        "parked": 0,
        "inflight_runs": 0,
        "reaped_runs": 1,
        "degraded": True,
        "conservation_ok": True,
        "requests": {
            "submitted": 10,
            "refused": 0,
            "cache_hits": 0,
            "coalesced_joins": 10,
            "probe_runs": 0,
        },
        "tenants": {
            "t-a": {"submitted": 4, "refused": 0, "refusals": {}},
            "t-b": {"submitted": 6, "refused": 0, "refusals": {}},
        },
    }
    rollup = rollup_statusz([payload(a), payload(b)])
    merged = rollup["fleet"]["frontdoor"]
    assert merged["qps"] == pytest.approx(150.0)
    assert merged["degraded"] is True
    assert merged["queue_depth"] == 2
    assert merged["requests"]["submitted"] == 32
    assert merged["tenants"]["t-a"]["submitted"] == 26
    assert merged["tenants"]["t-a"]["refusals"] == {"quota": 2}
    # lookup-weighted: 10 hits + 10 joins + (9 runs + 1 parked) = 30
    assert merged["coalescing"]["lookups"] == 30
    assert merged["coalescing"]["hit"] == pytest.approx(10 / 30)
    assert merged["coalescing"]["join"] == pytest.approx(10 / 30)
    # replicas without a front door roll up to null, like matrix
    assert rollup_statusz([payload(None)])["fleet"]["frontdoor"] is None
    assert merge_frontdoor_blocks([]) is None


def test_status_table_renders_the_frontdoor_block():
    from activemonitor_tpu.__main__ import render_status_table

    payload = {
        "fleet": {
            "checks": 1,
            "window_runs": 4,
            "goodput_ratio": 1.0,
            "frontdoor": {
                "qps": 1234.5,
                "coalescing": {
                    "hit": 0.75,
                    "miss": 0.05,
                    "join": 0.20,
                    "lookups": 400,
                },
                "queue_depth": 3,
                "parked": 0,
                "inflight_runs": 1,
                "reaped_runs": 0,
                "degraded": False,
                "conservation_ok": True,
                "requests": {
                    "submitted": 420,
                    "refused": 20,
                    "cache_hits": 300,
                    "coalesced_joins": 80,
                    "probe_runs": 20,
                },
                "tenants": {
                    "t-noisy": {
                        "submitted": 100,
                        "refused": 20,
                        "refusals": {"quota": 20},
                    },
                    "t-quiet": {"submitted": 320, "refused": 0, "refusals": {}},
                },
            },
        },
        "checks": [],
    }
    text = render_status_table(payload)
    assert "FRONTDOOR" in text
    assert "qps=1234.5" in text
    assert "hit=75.0%" in text
    assert "join=20.0%" in text
    assert "queue_depth=3" in text
    assert "refusals={t-noisy: 20}" in text
    # a payload without a front door renders no FRONTDOOR line
    assert "FRONTDOOR" not in render_status_table(
        {"fleet": {"checks": 0}, "checks": []}
    )


# -- the scripted FakeClock acceptance ---------------------------------


def make_hc(name, repeat=3600):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": "health"},
            "spec": {
                "repeatAfterSec": repeat,
                "level": "cluster",
                "workflow": {
                    "generateName": f"{name}-",
                    "workflowtimeout": 30,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "sa",
                        "source": {"inline": WF_INLINE},
                    },
                },
            },
        }
    )


async def settle():
    for _ in range(50):
        await asyncio.sleep(0)


@pytest.mark.asyncio
async def test_acceptance_n_duplicates_one_run_n_fanned_results():
    """The fast-tier acceptance (ISSUE 15): N duplicate requests → 1
    probe run through the Manager enqueue path → N fanned-out results
    joinable by trace_id at /debug/traces — with the evidence visible
    in /statusz, the pinned gauges, and the status table."""
    import aiohttp

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    engine = FakeWorkflowEngine(succeed_after(1))
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    door = FrontDoor(
        reconciler.fleet.history,
        AdmissionController(
            default_quota=TenantQuota(rate_per_minute=6000.0), clock=clock
        ),
        clock=clock,
        metrics=metrics,
        resilience=reconciler.resilience,
        default_freshness=30.0,
    )
    manager = Manager(
        client=client, reconciler=reconciler, max_parallel=2, frontdoor=door
    )
    manager._health_addr = "127.0.0.1:0"
    await manager.start()
    try:
        await client.apply(make_hc("hc-slice"))
        # boot run: the watch-path reconcile records the first result
        await settle()
        await clock.advance(1.0)
        await settle()
        boot = reconciler.fleet.history.last("health/hc-slice")
        assert boot is not None and boot.ok
        boot_workflows = len(engine.submitted)

        # inside the freshness window: every tenant is a cache hit on
        # the SCHEDULED run's result — zero new workflows
        for i in range(3):
            ticket = door.submit(f"tenant-{i}", "health/hc-slice")
            assert ticket.outcome == OUTCOME_HIT
            assert ticket.trace_id == boot.trace_id
        assert len(engine.submitted) == boot_workflows

        # age the result out, then storm N duplicate requests
        await clock.advance(31.0)
        n = 6
        tickets = [
            door.submit(f"tenant-{i}", "health/hc-slice") for i in range(n)
        ]
        assert [t.outcome for t in tickets] == (
            [OUTCOME_RUN] + [OUTCOME_JOINED] * (n - 1)
        )
        # drive the ONE triggered reconcile to completion
        await settle()
        await clock.advance(1.0)
        await settle()
        results = await asyncio.gather(*(t.wait() for t in tickets))
        assert len(engine.submitted) == boot_workflows + 1  # ONE run
        trace_ids = {t.trace_id for t in tickets}
        assert len(trace_ids) == 1 and results[0].trace_id in trace_ids
        assert all(r is results[0] for r in results)

        # the fanned-out trace_id joins to the one reconcile cycle
        trace_id = tickets[0].trace_id
        traces = [
            t
            for t in reconciler.tracer.traces()
            if t["trace_id"] == trace_id
        ]
        assert len(traces) == 1
        assert any(
            s["attrs"].get("healthcheck") == "health/hc-slice"
            for s in traces[0]["spans"]
        )

        # /statusz carries the frontdoor block; HTTP ingestion works
        port = manager._http_runners[0].addresses[0][1]
        async with aiohttp.ClientSession() as session:
            async with session.get(
                f"http://127.0.0.1:{port}/statusz"
            ) as resp:
                payload = await resp.json()
            frontdoor = payload["fleet"]["frontdoor"]
            assert frontdoor["conservation_ok"] is True
            assert frontdoor["requests"]["cache_hits"] == 3
            assert frontdoor["requests"]["coalesced_joins"] == n - 1
            assert frontdoor["requests"]["probe_runs"] == 1
            # POST /frontdoor/submit: the HTTP surface serves a hit
            # for the just-recorded run without touching the engine
            async with session.post(
                f"http://127.0.0.1:{port}/frontdoor/submit",
                json={"tenant": "tenant-http", "check": "health/hc-slice"},
            ) as resp:
                assert resp.status == 200
                doc = await resp.json()
            assert doc["outcome"] == OUTCOME_HIT
            assert doc["trace_id"] == trace_id
            assert doc["result"]["ok"] is True
            # malformed body is a 400, not a traceback
            async with session.post(
                f"http://127.0.0.1:{port}/frontdoor/submit",
                json={"tenant": "t"},
            ) as resp:
                assert resp.status == 400
            # a malformed DAG token rejects before any stage runs
            async with session.post(
                f"http://127.0.0.1:{port}/frontdoor/submit",
                json={
                    "tenant": "t",
                    "check": "readiness",
                    "dag": "health/hc-slice -> badtoken",
                },
            ) as resp:
                assert resp.status == 400
            # wait=false on a DAG is fire-and-forget: 202 accepted
            async with session.post(
                f"http://127.0.0.1:{port}/frontdoor/submit",
                json={
                    "tenant": "tenant-dag",
                    "check": "readiness",
                    "dag": "health/hc-slice",
                    "wait": False,
                },
            ) as resp:
                assert resp.status == 202
                accepted = await resp.json()
            assert accepted["accepted"] is True
        assert len(engine.submitted) == boot_workflows + 1

        # pinned gauges populated from the same ledger
        assert (
            metrics.sample_value(
                "healthcheck_frontdoor_requests_total",
                {"tenant": "tenant-0", "outcome": "cache_hit"},
            )
            == 1.0
        )
        assert (
            metrics.sample_value(
                "healthcheck_frontdoor_requests_total",
                {"tenant": "tenant-1", "outcome": "joined"},
            )
            == 1.0
        )
        assert (
            metrics.sample_value(
                "healthcheck_frontdoor_queue_depth", {}
            )
            == 0.0
        )
        hit = metrics.sample_value(
            "healthcheck_frontdoor_coalesce_ratio", {"kind": "hit"}
        )
        join = metrics.sample_value(
            "healthcheck_frontdoor_coalesce_ratio", {"kind": "join"}
        )
        assert hit and hit > 0 and join and join > 0

        # the status table leads with the same evidence
        from activemonitor_tpu.__main__ import render_status_table

        assert "FRONTDOOR" in render_status_table(payload)
    finally:
        await manager.stop()
