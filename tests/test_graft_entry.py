"""Driver-artifact guards: __graft_entry__ and bench must keep working.

The driver compile-checks entry() single-chip and runs
dryrun_multichip(N) on a virtual CPU platform; breaking either breaks
the round's evaluation, so CI pins them.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import pytest

import __graft_entry__  # conftest puts the repo root on sys.path

REPO = Path(__file__).resolve().parent.parent


def test_entry_returns_jittable_fn():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (4, 128, 4096)
    assert out.dtype.name == "float32"


def test_entry_lowers_without_execution():
    """The driver's compile check only needs lowering to succeed."""
    fn, args = __graft_entry__.entry()
    lowered = jax.jit(fn).lower(*args)
    assert "func" in lowered.as_text()[:2000]


@pytest.mark.parametrize(
    "n_devices",
    [2, pytest.param(4, marks=pytest.mark.slow), 8],
)
def test_dryrun_multichip_full_matrix(n_devices):
    """Mesh-shape edge cases stay covered as the parallelism code
    evolves: 2 (degenerate 1x2) and 8 (the driver's non-square 2x4) in
    tier-1; the square 2x2 and the 16-device subprocess ride the slow
    tier."""
    # conftest already forces the 8-device virtual CPU platform
    __graft_entry__.dryrun_multichip(n_devices)


@pytest.mark.slow  # fresh-interpreter 16-device compile, ~30 s alone
def test_dryrun_multichip_16_devices_subprocess():
    """16 devices exceeds this process's virtual platform — exercise the
    larger mesh (4x4, deeper pipeline staging) in a fresh interpreter."""
    result = subprocess.run(
        [
            sys.executable,
            "-c",
            "import __graft_entry__ as g; g.dryrun_multichip(16); print('ok16')",
        ],
        cwd=str(REPO),
        capture_output=True,
        text=True,
        timeout=560,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=16",
        },
    )
    assert result.returncode == 0, result.stderr[-1500:]
    assert "ok16" in result.stdout


def test_dryrun_insufficient_devices_errors():
    with pytest.raises(RuntimeError, match="need 64 devices"):
        __graft_entry__.dryrun_multichip(64)


def test_bench_emits_single_json_line():
    """bench.py on whatever platform CI has must print exactly one JSON
    object with the required keys."""
    result = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=str(REPO),
        capture_output=True,
        text=True,
        timeout=560,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        },
    )
    assert result.returncode == 0, result.stderr[-800:]
    lines = [l for l in result.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, lines
    doc = json.loads(lines[0])
    # required driver contract keys; extra context (platform, secondary
    # kernel metrics on TPU) rides along in the same line
    assert {"metric", "value", "unit", "vs_baseline"} <= set(doc)
    assert isinstance(doc["value"], (int, float))
    assert doc["platform"] == "cpu"
    assert doc["n_devices"] == 8
    # honesty contract (VERDICT r3 weak #1): a CPU artifact must not
    # read as "meets the TPU bar" — vs_baseline is either null or the
    # CPU-vs-prior-CPU trajectory ratio, explicitly labeled as such
    if doc["vs_baseline"] is not None:
        assert "baseline_source" in doc
        assert "cpu-mesh" in doc["baseline_source"]
        assert doc["vs_baseline"] > 0
    # ...and must still evidence the kernels run
    assert "flash_fwd_max_error_interpret" in doc["secondary"]
    assert doc["secondary"]["flash_fwd_max_error_interpret"] < 2e-2
    assert "flash_grad_rel_error_interpret" in doc["secondary"]
    assert "decode_fused_vs_dense_interpret" in doc["secondary"], doc[
        "secondary"
    ].get("decode_interpret_error", doc["secondary"])
    assert doc["secondary"]["decode_fused_vs_dense_interpret"] < 1e-3
    # the overlap layer's evidence: bit-compat overlapped schedule and
    # the bidirectional ring within tolerance
    assert doc["secondary"]["ring_overlap_vs_serial_max_error"] == 0.0
    assert doc["secondary"]["ring_bidir_max_error_interpret"] < 1e-3
    # the autotune evidence block (ISSUE 8): interpret-mode table,
    # labeled so it can never be read against a TPU bar
    autotune = doc["collective_autotune"]
    assert autotune["interpret_mode"] is True
    assert autotune["table"]  # winners actually recorded
    for entry in autotune["table"].values():
        assert entry["schedule"] in ("xla", "rsag", "recdouble", "tree")
    from activemonitor_tpu.utils.compat import SUPPORTS_PARTIAL_MANUAL

    if SUPPORTS_PARTIAL_MANUAL:
        assert doc["secondary"]["composed_dp_tp_pp_loss"] > 0
    else:
        # legacy lowering cannot run the partially-manual composed step;
        # the guarded secondary records the real diagnostic instead
        assert "composed_step_error" in doc["secondary"]
    # the serving evidence block (ISSUE 14): continuous batching ran,
    # labeled interpret-mode, with the correctness gate and the exact
    # token-conservation ledger in the artifact itself
    serving = doc["serving_summary"]
    assert serving["interpret_mode"] is True
    assert serving["ok"] is True and serving["consistency"] is True
    assert serving["conservation"]["ok"] is True
    assert serving["tokens_per_s"] > 0
    assert serving["ttft_p99_ms"] >= serving["ttft_p50_ms"] >= 0
    # off-TPU the roofline is a structured skip, never a silent hole
    assert serving["roofline"] is not None


def test_device_probe_watchdog_fails_fast_on_consecutive_hangs(monkeypatch):
    """ISSUE-6 satellite: r02–r05 silently wedged on the device probe.
    Two consecutive full-timeout hangs must end the probe ladder
    immediately (a wedged tunnel is not a transient blip) with a reason
    string for the artifact — not burn the remaining ~10-minute retry
    window before the inevitable CPU fallback."""
    sys.path.insert(0, str(REPO))
    import subprocess as sp

    import bench

    calls = {"run": 0, "slept": 0.0}

    def hang(*_a, **_kw):
        calls["run"] += 1
        raise sp.TimeoutExpired(cmd="probe", timeout=bench._PROBE_TIMEOUT)

    monkeypatch.setattr(bench.subprocess, "run", hang)
    monkeypatch.setattr(
        bench.time, "sleep", lambda s: calls.__setitem__("slept", calls["slept"] + s)
    )
    reachable, reason = bench._device_reachable()
    assert reachable is False
    assert calls["run"] == bench._PROBE_HANG_FAIL_FAST  # fail fast, no ladder
    assert "hung past" in reason and "failing fast" in reason


def test_device_probe_watchdog_retries_clean_exits_and_reports_reason(
    monkeypatch,
):
    """Non-hang failures (libtpu init error, plugin mismatch) stay on
    the full retry ladder — they really are transient on this tunnel —
    and the LAST diagnostic becomes the fallback_reason."""
    sys.path.insert(0, str(REPO))
    import bench

    class Proc:
        returncode = 1
        stdout = b""
        stderr = b"RuntimeError: libtpu init failed\n"

    calls = {"run": 0}

    def fail(*_a, **_kw):
        calls["run"] += 1
        return Proc()

    monkeypatch.setattr(bench.subprocess, "run", fail)
    monkeypatch.setattr(bench.time, "sleep", lambda _s: None)
    reachable, reason = bench._device_reachable()
    assert reachable is False
    assert calls["run"] == bench._PROBE_ATTEMPTS
    assert "exited with 1" in reason and "libtpu init failed" in reason

    # a success anywhere on the ladder reports reachable with no reason
    class Good(Proc):
        returncode = 0

    outcomes = [Proc(), Good()]
    monkeypatch.setattr(
        bench.subprocess, "run", lambda *_a, **_kw: outcomes.pop(0)
    )
    assert bench._device_reachable() == (True, "")


def test_last_known_good_tpu_block(tmp_path):
    """The CPU fallback embeds the opportunistic harness's capture,
    trimmed to the summary keys, with its timestamp."""
    sys.path.insert(0, str(REPO))
    import bench

    capture = {
        "metric": "mxu_bf16_fraction_of_rated",
        "value": 0.93,
        "unit": "fraction",
        "vs_baseline": 1.03,
        "platform": "tpu",
        "n_devices": 1,
        "device_kind": "TPU v5e",
        "secondary": {"flash_attention_tflops": 90.0},
        "captured_at": "2026-07-29T12:00:00+00:00",
        "flash_sweep": {"summary": "best fwd 90 TFLOP/s", "details": {"x": 1}},
    }
    path = tmp_path / "BENCH_TPU.json"
    path.write_text(json.dumps(capture))
    block = bench._last_known_good_tpu(str(path))
    assert block["value"] == 0.93
    assert block["captured_at"] == "2026-07-29T12:00:00+00:00"
    assert block["flash_sweep_summary"] == "best fwd 90 TFLOP/s"
    assert "details" not in str(block.get("flash_sweep", ""))
    assert bench._last_known_good_tpu(str(tmp_path / "missing.json")) is None
