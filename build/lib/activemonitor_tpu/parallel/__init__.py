"""Device mesh + timed collective helpers."""

from activemonitor_tpu.parallel.collectives import (
    CollectiveResult,
    all_gather_bandwidth,
    all_reduce_bandwidth,
    all_to_all_bandwidth,
    ppermute_ring_bandwidth,
    reduce_scatter_bandwidth,
)
from activemonitor_tpu.parallel.mesh import (
    best_2d_shape,
    device_info,
    make_1d_mesh,
    make_2d_mesh,
)

__all__ = [
    "CollectiveResult",
    "all_gather_bandwidth",
    "all_reduce_bandwidth",
    "all_to_all_bandwidth",
    "best_2d_shape",
    "device_info",
    "make_1d_mesh",
    "make_2d_mesh",
    "ppermute_ring_bandwidth",
    "reduce_scatter_bandwidth",
]
