"""Collectives-sweep probe — the full XLA collective set over ICI,
plus the explicit-schedule zoo and its message-size autotune sweep.

The ici-allreduce probe answers the north-star question; this probe
characterizes the whole communication surface the parallelism code
relies on: all-reduce (dp gradient sync), all-gather (tp/weight
gather), reduce-scatter (ZeRO/psum_scatter), all-to-all (ep dispatch,
ops/moe.py) and single-hop ppermute (ring attention, ops/ring_attention
.py; pipeline, ops/pipeline.py). A degradation only one pattern hits —
e.g. a routing fault that halves the bisection but leaves neighbor
links intact — shows up here before it shows up as slow training.

On top of the XLA builtins, the zoo cases time the explicit ppermute
schedules (parallel/schedules.py): ring reduce-scatter+all-gather,
recursive doubling, tree reduce-broadcast for all-reduce; ring and
recursive-doubling all-gather. Each gets a **schedule-specific** rated
ceiling below (its own wire volume and direction usage), so a schedule
merely hitting its own algorithmic ceiling is distinguishable from a
degraded link.

Exports, per collective C in {allreduce, allgather, reducescatter,
alltoall, ringhop, ringhop-bidir} plus the zoo cases
{allreduce-rsag, allreduce-recdouble, allreduce-tree, allgather-ring,
allgather-recdouble} and the hierarchical cases {allreduce-hier,
allreduce-hier-latency} (two-tier compositions over a synthetic
(2, n/2) ("dcn", "ici") re-mesh of the flat device set; an odd or
<4-device set records a structured ``hier_skipped`` detail naming the
mesh it lacked) (prefix ``collective-``, distinct from the north-star
probe's ``ici-`` gauges so a merged battery contract never carries
duplicate names):

- ``collective-<C>-busbw-gbps`` — NCCL busbw convention
- ``collective-<C>-fraction-of-rated`` — busbw / schedule ceiling (TPU)

``sweep()`` is the message-size autotune entrypoint: every schedule
across a log-spaced payload grid (~256 KB → 256 MB), winners folded
into the parallel/autotune decision table, crossover points located,
and the whole table serialized into ``details`` as evidence. Sweep
headline gauges: ``collective-sweep-zoo-best-win`` (best zoo busbw /
XLA-builtin busbw over the grid — >1 means a zoo schedule measurably
beat the builtin somewhere) and ``collective-sweep-crossovers``
(winner flips along the grid). ``quick=True`` (2 payload sizes,
reduced iters) keeps CPU-interpret/tier-1 runs cheap.

Rated ceilings assume the same bidirectional-ring model as probes/ici:
2 x unidir link bw for the XLA ring collectives AND for the
bidirectional hop, 1 x for a single unidirectional hop; all-to-all is
bisection-bound (8*B*(n-1)/n^2); the zoo schedules carry their own
per-algorithm ceilings (see _rated_busbw).

Verdict: every collective's fraction must clear ``threshold`` (rated
hardware, >1 device); otherwise informational-pass, like the other
bandwidth probes. No reference counterpart (the reference has no
communication backend at all, SURVEY.md §5.8).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax

from activemonitor_tpu.parallel import autotune
from activemonitor_tpu.parallel.collectives import (
    CollectiveResult,
    all_gather_bandwidth,
    all_reduce_bandwidth,
    all_to_all_bandwidth,
    ppermute_bidir_bandwidth,
    ppermute_ring_bandwidth,
    reduce_scatter_bandwidth,
)
from activemonitor_tpu.parallel.mesh import (
    best_2d_shape,
    make_1d_mesh,
    make_2d_mesh,
    make_synthetic_two_tier_mesh,
)
from activemonitor_tpu.parallel.schedules import (
    all_gather_recdouble_bandwidth,
    all_gather_ring_bandwidth,
    all_reduce_rsag_bandwidth,
    all_reduce_recdouble_bandwidth,
    all_reduce_tree_bandwidth,
    theoretical_hops,
)
from activemonitor_tpu.probes.base import ProbeMetric, ProbeResult
from activemonitor_tpu.probes.rated import rated_for

# the XLA-builtin set (the default `run` sweep — cost-stable since PR 5)
ALL_CASES = (
    "allreduce", "allgather", "reducescatter", "alltoall", "ringhop",
    "ringhop-bidir",
)

# the explicit-schedule zoo (parallel/schedules.py) — opt-in cases for
# `run`, always raced by `sweep`
ZOO_CASES = (
    "allreduce-rsag", "allreduce-recdouble", "allreduce-tree",
    "allgather-ring", "allgather-recdouble",
)

# the hierarchical (DCN×ICI) compositions, measured over a SYNTHETIC
# two-tier re-mesh of the flat device set (2 × n/2 — the single-
# process stand-in for a real multislice topology; probes/dcn.py owns
# the real cross-host measurement). Opt-in like the zoo; an odd or
# <4-device set records a structured skip naming the mesh it lacked.
HIER_CASES = ("allreduce-hier", "allreduce-hier-latency")


def _hier_case_bench(variant: str) -> Callable:
    def bench(mesh, size_mb=64.0, dtype=None, iters=5, axis=""):
        if axis:
            # the re-mesh always spans ALL devices; a per-axis caller
            # reaching this bench is a bug, not a silent ignore
            raise ValueError(
                "hierarchical cases re-mesh the full device set; "
                f"per-axis restriction ({axis!r}) is not supported"
            )
        import jax.numpy as jnp

        from activemonitor_tpu.parallel.mesh import (
            make_synthetic_two_tier_mesh,
        )
        from activemonitor_tpu.parallel.schedules import (
            hier_all_reduce_bandwidth,
        )

        devices = list(mesh.devices.flat)
        hier_mesh = make_synthetic_two_tier_mesh(devices)
        if hier_mesh is None:  # callers pre-filter; bug if reached
            raise ValueError(
                f"{len(devices)} device(s) cannot form the synthetic "
                "(2, n/2) two-tier mesh"
            )
        return hier_all_reduce_bandwidth(
            hier_mesh, size_mb=size_mb, dtype=dtype or jnp.bfloat16,
            iters=iters, variant=variant,
        )

    return bench


_BENCH: Dict[str, Callable] = {
    "allreduce": all_reduce_bandwidth,
    "allgather": all_gather_bandwidth,
    "reducescatter": reduce_scatter_bandwidth,
    "alltoall": all_to_all_bandwidth,
    "ringhop": ppermute_ring_bandwidth,
    "ringhop-bidir": ppermute_bidir_bandwidth,
    "allreduce-rsag": all_reduce_rsag_bandwidth,
    "allreduce-recdouble": all_reduce_recdouble_bandwidth,
    "allreduce-tree": all_reduce_tree_bandwidth,
    "allgather-ring": all_gather_ring_bandwidth,
    "allgather-recdouble": all_gather_recdouble_bandwidth,
    "allreduce-hier": _hier_case_bench("bandwidth"),
    "allreduce-hier-latency": _hier_case_bench("latency"),
}

# sweep headline gauges — contract spelling (pinned by tests/test_lint)
SWEEP_ZOO_BEST_WIN_METRIC = "collective-sweep-zoo-best-win"
SWEEP_CROSSOVERS_METRIC = "collective-sweep-crossovers"


def _rated_busbw(name: str, unidir_gbps: float, n: int) -> float:
    """Achievable-busbw ceiling on a bidirectional ring of n devices
    with per-direction link bandwidth ``unidir_gbps``.

    XLA builtins keep the module-doc ring model. Zoo schedules get
    **per-algorithm** ceilings from their own wire volume and link
    usage ON THAT RING — non-neighbor exchanges pay ring contention,
    not just round count — so "losing to its own ceiling" (an
    algorithmic property) is distinguishable from a slow link:

    - ``allreduce-rsag``: unidirectional neighbor ring, 2(n−1)/n × S
      volume one way — busbw ceiling is ONE link direction (half the
      XLA bidir ring's 2x).
    - ``allreduce-recdouble``: round s exchanges full payloads with
      the partner 2^s ring-hops away, so every link carries 2^s
      concurrent flows: per-direction link time ≥ Σ 2^s · S/B =
      (p−1)·S/B (+ ~2 neighbor-ish rounds folding the non-pow2
      remainder in/out) ⇒ busbw ≤ 2(n−1)/n · B/(p−1+fold). The
      latency-optimal schedule's bandwidth ceiling collapses as n
      grows — by design, and now by routing too.
    - ``allreduce-tree``: 2·ceil(log2 n) rounds; each round's
      messages span disjoint ring segments and pipeline through
      intermediates, so a round costs ~S/B ⇒ busbw ≤
      2(n−1)/n · B/rounds.
    - ``allgather-ring``: per-device send volume is (n−1)/n of the
      gathered payload over neighbor links ⇒ one link direction.
    - ``allgather-recdouble``: block at round s is 2^s shards crossing
      2^s links ⇒ per-link (n−1)·shard/B both ways — same ceiling as
      the ring (its win is rounds/latency, never bandwidth).

    These are modeled ceilings (routing assumptions included), not
    rated-silicon guarantees — which is why zoo fractions are
    informational in ``_emit`` and never gate the verdict.
    """
    if name == "ringhop":
        return unidir_gbps
    if name == "ringhop-bidir":
        # both link directions active per hop — full-duplex ceiling,
        # the same 2x-unidir model as the ici probe's ring comparator
        return 2 * unidir_gbps
    if name == "alltoall":
        return 8 * unidir_gbps * (n - 1) / n**2
    if name in ("allreduce-rsag", "allgather-ring", "allgather-recdouble"):
        return unidir_gbps
    if name == "allreduce-hier":
        # bandwidth composition on the synthetic 2×(n/2) re-mesh: the
        # ICI rs/ag phases ride one ring direction (the rsag bound);
        # the halved-payload dcn exchange shares the same links here
        # (no real second tier on a flat device set), costing ~one
        # more chunk round ⇒ informational bar at the rsag ceiling
        return unidir_gbps
    if name == "allreduce-hier-latency":
        # full-payload few-round schedules per synthetic tier: the
        # recdouble collapse applied to each tier in sequence —
        # latency path wins rounds, never bandwidth (by design)
        ici_n = max(2, n // 2)
        p = 1 << (ici_n.bit_length() - 1)
        link_rounds = (p - 1) + (2 if ici_n - p else 0) + 1  # + dcn round
        return 2 * (n - 1) / n * unidir_gbps / link_rounds
    if name == "allreduce-recdouble":
        p = 1 << (max(2, n).bit_length() - 1)  # largest pow2 ≤ n
        fold = 2 if n - p else 0
        link_rounds = (p - 1) + fold  # Σ 2^s contention + fold/unfold
        return 2 * (n - 1) / n * unidir_gbps / link_rounds
    if name == "allreduce-tree":
        rounds = max(1, theoretical_hops("tree", n))
        return 2 * (n - 1) / n * unidir_gbps / rounds
    return 2 * unidir_gbps


def _emit(
    entries: List[Tuple[str, str, int, CollectiveResult]],
    threshold: float,
    context: str,
    details: Dict,
    roofline: bool = True,
) -> ProbeResult:
    """Shared emission scaffolding for the flat and per-axis sweeps.

    ``entries``: (label, base_case, ring_n, result) — the label is the
    metric suffix ("allreduce" or "allreduce-data"), the base case picks
    the rated comparator, ring_n its ring size. ``context`` names the
    measured surface in the summary.

    Zoo-schedule (and hierarchical-case) fractions are exported but
    NEVER gate the verdict: their denominators are modeled algorithmic
    ceilings (routing assumptions included, see _rated_busbw), and a
    modeling error must misread as an off gauge, not a failed
    HealthCheck. The XLA-builtin cases keep the rated-silicon
    comparison and the verdict."""
    informational = ZOO_CASES + HIER_CASES
    devices = jax.devices()
    rated = rated_for(devices[0].device_kind)
    on_tpu = devices[0].platform == "tpu"
    metrics: List[ProbeMetric] = []
    fractions: Dict[str, float] = {}
    verdict_fractions: Dict[str, float] = {}
    for label, base_case, ring_n, result in entries:
        key = label.replace("-", "_")
        metrics.append(
            ProbeMetric(
                f"collective-{label}-busbw-gbps",
                result.busbw_gbps,
                help=f"Measured {result.name} bus bandwidth (NCCL convention), GB/s",
            )
        )
        details[f"{key}_busbw_gbps"] = round(result.busbw_gbps, 2)
        if rated is not None and on_tpu:
            rated_busbw = _rated_busbw(base_case, rated.ici_unidir_gbps, ring_n)
            fraction = result.busbw_gbps / rated_busbw
            fractions[label] = fraction
            if base_case not in informational:
                verdict_fractions[label] = fraction
            metrics.append(
                ProbeMetric(
                    f"collective-{label}-fraction-of-rated",
                    fraction,
                    help=f"{result.name} busbw / schedule-specific ring ceiling"
                    + (" (informational)" if base_case in informational else ""),
                )
            )
            details[f"{key}_fraction_of_rated"] = round(fraction, 3)

    if fractions:
        # the verdict (and the summary's "worst") judge only the
        # rated-silicon comparisons; zoo ceilings are informational
        judged = verdict_fractions or fractions
        worst = min(judged, key=judged.get)
        ok = not verdict_fractions or verdict_fractions[worst] >= threshold
        summary = (
            f"{context}: worst {worst} at {judged[worst]:.0%} of "
            f"rated {rated.generation}"
            + ("" if ok else f" (< {threshold:.0%} threshold)")
            + ("" if verdict_fractions else " (zoo ceilings: informational)")
        )
    else:
        ok = True
        best = max(entries, key=lambda e: e[3].busbw_gbps)
        summary = (
            f"{context}: best {best[0]} {best[3].busbw_gbps:.1f} GB/s "
            "(no rated comparison)"
        )
    probe_result = ProbeResult(
        ok=ok, summary=summary, metrics=metrics, details=details
    )
    # ICI-roofline verdict per rated-silicon case (obs/roofline.py):
    # collectives live on the comm roofline — the ceiling is the
    # schedule's own rated busbw (the fraction's denominator) — so the
    # attribution layer can cite "0.62 of comm-bound ceiling" instead
    # of a bare number. Every case records a verdict OR a structured
    # skip (the silent-omission ban): zoo cases skip because their
    # ceilings are modeled algorithmic bars, not silicon; non-rated
    # hardware skips because there is no ICI roofline to stand on.
    from activemonitor_tpu.obs import roofline as roofline_model

    for label, base_case, ring_n, result in entries:
        prefix = f"collective-{label}"
        if not roofline:
            cap = roofline_model.skip_capture(prefix, "disabled (--no-roofline)")
        elif base_case in informational:
            cap = roofline_model.skip_capture(
                prefix,
                "zoo ceiling is a modeled algorithmic bar, not rated "
                "silicon (informational case)",
            )
        elif label in verdict_fractions:
            cap = roofline_model.comm_capture(
                prefix,
                busbw_gbps=result.busbw_gbps,
                rated_busbw_gbps=_rated_busbw(
                    base_case, rated.ici_unidir_gbps, ring_n
                ),
                payload_bytes=float(result.payload_bytes),
                # reduce-type collectives do one add per wire byte;
                # pure-movement patterns do none
                flops=(
                    float(result.payload_bytes) / 2.0
                    if base_case.startswith(("allreduce", "reducescatter"))
                    else 0.0
                ),
            )
        else:
            cap = roofline_model.skip_capture(
                prefix, "no rated ICI ceiling for this hardware"
            )
        roofline_model.apply(probe_result, cap)
    return probe_result


def _validate_cases(
    cases: Sequence[str], allow_hier: bool = True
) -> Tuple[str, ...]:
    cases = tuple(cases)
    unknown = [c for c in cases if c not in _BENCH]
    if unknown:
        raise ValueError(
            f"unknown collectives {unknown}; pick from "
            f"{ALL_CASES + ZOO_CASES + HIER_CASES}"
        )
    if not allow_hier:
        hier = [c for c in cases if c in HIER_CASES]
        if hier:
            raise ValueError(
                f"hierarchical cases {hier} re-mesh the FULL device set "
                "into a synthetic (dcn, ici) topology; they cannot be "
                "restricted to one axis — run them through the flat "
                "sweep (`collectives --cases ...`) instead"
            )
    return cases


def run_per_axis(
    size_mb: float = 64.0,
    iters: int = 5,
    threshold: float = 0.8,
    cases: Optional[Sequence[str]] = None,
    roofline: bool = True,
) -> ProbeResult:
    """Per-axis variant over the 2D mesh: the chosen collectives
    restricted to EACH mesh axis (default: all-reduce + single-hop
    ppermute; any ``_BENCH`` case — including zoo schedules — can be
    threaded through ``cases``). The mesh is built with
    physical-topology alignment (parallel/mesh.make_2d_mesh uses
    mesh_utils.create_device_mesh on TPU), so on a real slice the two
    axes ride different torus dimensions and a degradation confined to
    one link direction shows up as one axis's fraction dropping while
    the other stays healthy — `collectives` alone can only say "slow",
    this says "slow WHERE"."""
    cases = _validate_cases(cases or ("allreduce", "ringhop"), allow_hier=False)
    devices = jax.devices()
    n = len(devices)
    if n < 4:
        return ProbeResult(
            ok=True,
            summary=f"per-axis sweep skipped: {n} device(s), no 2D mesh",
            metrics=[],
            details={
                "devices": n,
                "skipped": True,
                # the shape a 2D mesh WOULD have taken — so a skip in a
                # fleet rollup still says what topology was absent
                "mesh": dict(zip(("data", "model"), best_2d_shape(n))),
            },
        )
    mesh = make_2d_mesh()
    entries = [
        (f"{name}-{axis}", name, mesh.shape[axis],
         _BENCH[name](mesh, size_mb=size_mb, iters=iters, axis=axis))
        for axis in mesh.axis_names
        if mesh.shape[axis] >= 2  # nothing to move along a singleton axis
        for name in cases
    ]
    details = {
        "devices": n,
        "device_kind": devices[0].device_kind,
        "mesh": dict(mesh.shape),
    }
    return _emit(
        entries, threshold, f"per-axis sweep over mesh {dict(mesh.shape)}",
        details, roofline=roofline,
    )


def run(
    size_mb: float = 64.0,
    iters: int = 5,
    threshold: float = 0.8,
    cases: Optional[Sequence[str]] = None,
    roofline: bool = True,
) -> ProbeResult:
    cases = _validate_cases(cases or ALL_CASES)
    devices = jax.devices()
    n = len(devices)
    if n < 2:
        return ProbeResult(
            ok=True,
            summary=f"collectives sweep skipped: {n} device(s), nothing to move",
            metrics=[],
            details={"devices": n, "skipped": True, "mesh": {"ici": n}},
        )

    mesh = make_1d_mesh()
    details: Dict = {"devices": n, "device_kind": devices[0].device_kind}
    # hierarchical cases need the synthetic (2, n/2) two-tier re-mesh
    # (one shared rule: parallel/mesh.make_synthetic_two_tier_mesh) —
    # an impossible expansion is a structured skip naming the mesh it
    # lacked, never a crash or a silent hole (the run_per_axis skip
    # contract)
    if make_synthetic_two_tier_mesh(devices) is None:
        impossible = [c for c in cases if c in HIER_CASES]
        if impossible:
            details["hier_skipped"] = {
                case: {
                    "reason": (
                        f"{n} device(s) cannot form the synthetic "
                        "(2, n/2) two-tier mesh (needs an even count "
                        ">= 4)"
                    ),
                    "mesh": {"dcn": 2, "ici": max(1, n // 2)},
                }
                for case in impossible
            }
            cases = tuple(c for c in cases if c not in HIER_CASES)
    entries = [
        (name, name, n, _BENCH[name](mesh, size_mb=size_mb, iters=iters))
        for name in cases
    ]
    if not entries:
        return ProbeResult(
            ok=True,
            summary=(
                f"collectives sweep: every requested case skipped on "
                f"{n} device(s)"
            ),
            metrics=[],
            details=details,
        )
    return _emit(
        entries, threshold, f"{len(entries)} collectives over {n} device(s)",
        details, roofline=roofline,
    )


# the full log-spaced payload grid lives with the tuner (single
# source of truth); quick mode keeps the endpoints' spirit at
# CPU-interpret-affordable sizes — the small end sits at the ~4KB
# latency-regime floor the full grid now reaches, so even quick
# tables carry a cell on the latency side of the crossover
SWEEP_SIZES_MB = autotune.DEFAULT_SWEEP_SIZES_MB
QUICK_SWEEP_SIZES_MB = (0.004, 2.0)


def sweep(
    sizes_mb: Optional[Sequence[float]] = None,
    iters: int = 3,
    quick: bool = False,
    collectives: Sequence[str] = ("allreduce", "allgather"),
    dtype=None,
    bench: Optional[Callable] = None,
) -> ProbeResult:
    """Message-size autotune sweep: race every schedule (XLA builtin +
    zoo) across the payload grid, fold winners into the
    parallel/autotune decision table, and report crossover points.

    ``quick=True``: 2 payload sizes, reduced iters — the tier-1 /
    CPU-interpret budget mode (the full grid at 256 MB × several
    schedules is a TPU-sized bill). ``bench`` is the injectable
    measurement hook (parallel/autotune.tune contract) — tests script
    fake timings through it."""
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.bfloat16
    if sizes_mb is None:
        sizes_mb = QUICK_SWEEP_SIZES_MB if quick else SWEEP_SIZES_MB
    if quick:
        iters = min(iters, 2)
    devices = jax.devices()
    n = len(devices)
    if n < 2:
        return ProbeResult(
            ok=True,
            summary=f"autotune sweep skipped: {n} device(s), nothing to tune",
            metrics=[],
            details={"devices": n, "skipped": True, "mesh": {"ici": n}},
        )
    mesh = make_1d_mesh()
    tuned = autotune.tune(
        mesh,
        collectives=tuple(collectives),
        sizes_mb=tuple(sizes_mb),
        dtype=dtype,
        iters=iters,
        bench=bench,
    )
    raw = tuned.results

    # crossovers + the zoo-vs-builtin headline, per collective family
    crossovers: Dict[str, list] = {}
    zoo_best_win = 0.0
    best_cell = None
    for family, by_size in raw.items():
        points = []
        for size_mb, busbw in by_size.items():
            winner = max(busbw, key=busbw.get)
            points.append((size_mb, winner))
            xla_bw = busbw.get("xla", 0.0)
            for schedule, bw in busbw.items():
                if schedule == "xla" or xla_bw <= 0:
                    continue
                win = bw / xla_bw
                if win > zoo_best_win:
                    zoo_best_win = win
                    best_cell = {
                        "collective": family,
                        "schedule": schedule,
                        "size_mb": size_mb,
                        "busbw_gbps": round(bw, 3),
                        "xla_busbw_gbps": round(xla_bw, 3),
                    }
        crossovers[family] = autotune.crossover_points(points)

    if zoo_best_win <= 1.0:
        # no zoo schedule actually beat the builtin anywhere — a
        # "best cell" naming a LOSING (schedule, payload) pair must
        # not sit in the artifact where the acceptance evidence goes
        best_cell = None
    n_crossovers = sum(len(v) for v in crossovers.values())
    metrics = [
        ProbeMetric(
            SWEEP_ZOO_BEST_WIN_METRIC,
            zoo_best_win,
            help="Best zoo-schedule busbw / XLA-builtin busbw over the "
            "sweep grid (>1: a zoo schedule measurably won a cell)",
        ),
        ProbeMetric(
            SWEEP_CROSSOVERS_METRIC,
            float(n_crossovers),
            help="Winner flips along the payload grid (per-topology "
            "crossover count)",
        ),
    ]
    details = {
        "devices": n,
        "device_kind": devices[0].device_kind,
        "dtype": jnp.dtype(dtype).name,
        "sizes_mb": list(sizes_mb),
        "quick": quick,
        "results_busbw_gbps": {
            family: {
                f"{size_mb}MB": {s: round(bw, 3) for s, bw in busbw.items()}
                for size_mb, busbw in by_size.items()
            }
            for family, by_size in raw.items()
        },
        # only the cells THIS run measured — a long-lived process's
        # earlier tunes are not this sweep's evidence
        "autotune_table": autotune.table_as_dict(keys=tuned.keys),
        "crossovers": crossovers,
        "zoo_best_win": round(zoo_best_win, 3),
        "zoo_best_cell": best_cell,
    }
    summary = (
        f"autotune sweep over {n} device(s), {len(sizes_mb)} sizes: "
        f"{n_crossovers} crossover(s), best zoo win "
        f"{zoo_best_win:.2f}x vs XLA"
        + (
            f" ({best_cell['schedule']} @ {best_cell['size_mb']}MB "
            f"{best_cell['collective']})"
            if best_cell and zoo_best_win > 1.0
            else ""
        )
    )
    # informational: the sweep produces evidence (the decision table),
    # not a pass/fail verdict — correctness is the equivalence suite's
    # job, regressions are the analysis layer's
    return ProbeResult(ok=True, summary=summary, metrics=metrics, details=details)
