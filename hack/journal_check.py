#!/usr/bin/env python
"""Telemetry-journal integrity checker — the CI gate for a journal dir.

Standalone: ``python hack/journal_check.py <journal-dir>``. Exit 0 when
the journal is internally consistent, 1 with one finding per line when
it is not. A tier-1 test (tests/test_journal.py) runs it as a
subprocess against a freshly recorded trace, so a regression in the
journal's on-disk format fails CI the same way a lint finding does.

Checks, in order:

- segment chain: the ``journal-NNNNNN.jsonl`` sequence numbers are
  contiguous — a gap means a segment was lost outside compaction's
  oldest-first discipline.
- per-segment header: the first line of every segment is a ``header``
  record carrying the schema version this checker understands
  (obs/journal.JOURNAL_VERSION).
- per-line validity: every event line is JSON with the versioned
  envelope (``v``, ``stream`` in the known stream set).
- conservation across streams: every ``result`` event whose payload
  carries a non-empty attribution bucket has EXACTLY one ``attribution``
  event (the journal writes both from the same append — a mismatch
  means torn writes or double-counting, the failure mode the restart
  acceptance test guards against).

The line checks deliberately reuse ``obs.journal.read_journal`` — the
checker must agree bit-for-bit with what a restarting controller would
accept, or CI would bless journals the boot path rejects.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from activemonitor_tpu.obs.journal import (  # noqa: E402
    STREAM_ATTRIBUTION,
    STREAM_RESULT,
    STREAMS,
    list_segments,
    read_journal,
)


def check_journal(journal_dir: str) -> list:
    """Every integrity finding for ``journal_dir`` as
    ``"<code>: <detail>"`` strings; empty = consistent. Pure so the
    tier-1 test can call it in-process too."""
    findings = []
    path = Path(journal_dir)
    if not path.is_dir():
        return [f"missing-dir: {journal_dir} is not a directory"]
    segments = list_segments(journal_dir)
    events, warnings = read_journal(journal_dir)
    # read_journal is all-or-nothing: ANY warning means a restarting
    # controller would restore fresh, so every warning is a finding
    for warning in warnings:
        findings.append(
            "{}: {}".format(
                warning.get("reason", "corrupt"), warning.get("detail", "")
            )
        )
    if not segments and not warnings:
        # an absent/empty journal is a clean first boot, not a finding
        return findings
    counts = {stream: 0 for stream in STREAMS}
    buckets = 0
    for event in events:
        stream = event.get("stream")
        if stream in counts:
            counts[stream] += 1
        if stream == STREAM_RESULT and event.get("bucket"):
            buckets += 1
    if not warnings and buckets != counts[STREAM_ATTRIBUTION]:
        findings.append(
            "conservation: {} result events carry an attribution bucket "
            "but {} attribution events were journaled".format(
                buckets, counts[STREAM_ATTRIBUTION]
            )
        )
    return findings


def main(argv) -> int:
    if len(argv) != 1:
        print(
            "usage: python hack/journal_check.py <journal-dir>",
            file=sys.stderr,
        )
        return 2
    journal_dir = argv[0]
    findings = check_journal(journal_dir)
    segments = list_segments(journal_dir)
    events, _warnings = read_journal(journal_dir)
    counts = {stream: 0 for stream in STREAMS}
    for event in events:
        if event.get("stream") in counts:
            counts[event.get("stream")] += 1
    summary = "  ".join(f"{stream}={counts[stream]}" for stream in STREAMS)
    print(f"{journal_dir}: {len(segments)} segment(s)  {summary}")
    for finding in findings:
        print(f"FINDING {finding}")
    if findings:
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
