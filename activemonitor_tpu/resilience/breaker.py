"""Shared circuit breaker — closed / open / half-open, injectable clock.

The controller's retry ladders (SURVEY.md §5.3: 1 s requeue,
RetryOnConflict, retry-in-place transients) make every individual
operation durable, but they are *local*: during a real apiserver or
Argo-path outage each check's ladder keeps hammering the same dead
endpoint at full cadence. The breaker is the *global* complement — it
watches the stream of transient outcomes crossing the process boundary
and, once failures run consecutive past a threshold, fails the
controller FAST into degraded mode (docs/resilience.md) instead of
letting a hundred ladders grind against a 500 storm.

State machine (the classic Nygard shape):

- **closed**: all traffic flows; ``failure_threshold`` transient
  failures within ``failure_window`` seconds trip to open. Rate-window
  counting, deliberately NOT consecutive: a status-write storm
  interleaves failing PATCHes with healthy GETs (every conflict-retried
  write re-reads first), so a consecutive counter would never trip on
  the exact outage this breaker exists for. Successes while closed
  therefore do not erase recent failures; only time does.
- **open**: mutating traffic is rejected instantly with
  :class:`BreakerOpenError` until ``recovery_seconds`` elapse on the
  injected clock. Outcomes recorded while open (stragglers from
  in-flight calls, ungated reads) change nothing.
- **half-open**: traffic flows again; the first success closes the
  breaker, the first transient failure re-opens it for another full
  recovery window. Deliberately no probe budget: every admitted call IS
  a probe, in-flight work is naturally bounded, and a budget counter
  that callers could leak (allow() without a recorded outcome) is a
  stuck-open bug waiting to happen.

Only *transient* outcomes count (5xx/429, connection errors, timeouts).
A 4xx proves the server is alive and answering — it resets the streak
rather than feeding it, so a single misconfigured check can never trip
the fleet into degraded mode.

Clock discipline: every deadline reads ``clock.monotonic()`` — never the
wall clock (hack/lint.py bans ``time.time()`` in this package) — so
fake-clock tests script the open window exactly.
"""

from __future__ import annotations

import asyncio
import collections
import logging
from typing import Callable, Optional

from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.resilience")

STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half-open"

DEFAULT_FAILURE_THRESHOLD = 5
DEFAULT_RECOVERY_SECONDS = 30.0


class BreakerOpenError(Exception):
    """Raised instead of attempting a call while the breaker is open.

    Carries ``status = 503`` so the reconciler's duck-typed transient
    classification (controller.client.is_transient) treats a rejected
    call exactly like a server-side 503: retry later, never a
    deterministic give-up. The breaker itself never counts this error
    as a failure — no call happened.
    """

    status = 503  # duck-typed transient for is_transient()

    def __init__(self, name: str, retry_after: float):
        super().__init__(
            f"circuit breaker {name!r} is open; retry in {retry_after:.1f}s"
        )
        self.breaker_name = name
        self.retry_after = retry_after


def is_transient_error(exc: BaseException) -> bool:
    """Transient = worth counting toward tripping the breaker: a
    server-side throttle/5xx status, a connection-level failure, or a
    timeout. BreakerOpenError is explicitly NOT transient here — the
    breaker must never feed on its own rejections."""
    if isinstance(exc, BreakerOpenError):
        return False
    status = getattr(exc, "status", None)
    if status is not None:
        # one source of truth for the retryable status set (imported
        # lazily: controller.client is higher in the layer stack)
        from activemonitor_tpu.controller.client import TRANSIENT_STATUSES

        return status in TRANSIENT_STATUSES
    return isinstance(exc, (OSError, asyncio.TimeoutError))


class CircuitBreaker:
    def __init__(
        self,
        name: str = "api",
        clock: Optional[Clock] = None,
        failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
        failure_window: Optional[float] = None,
        recovery_seconds: float = DEFAULT_RECOVERY_SECONDS,
        on_transition: Optional[Callable[[str, str], None]] = None,
    ):
        self.name = name
        self.clock = clock or Clock()
        self.failure_threshold = max(1, failure_threshold)
        self.recovery_seconds = max(0.0, recovery_seconds)
        # rate window for tripping: threshold failures inside this many
        # seconds open the circuit (default: the recovery window, but
        # never so tight that slow retry ladders can't accumulate)
        self.failure_window = (
            failure_window
            if failure_window is not None
            else max(self.recovery_seconds, 10.0)
        )
        self._on_transition = on_transition
        self._state = STATE_CLOSED
        # monotonic timestamps of the last `threshold` transient failures
        self._failures: collections.deque = collections.deque(
            maxlen=self.failure_threshold
        )
        self._opened_at = 0.0
        self._trip_count = 0  # lifetime opens, surfaced in snapshot()

    # -- state ----------------------------------------------------------
    def _transition(self, new_state: str) -> None:
        old = self._state
        if old == new_state:
            return
        self._state = new_state
        log.log(
            logging.WARNING if new_state == STATE_OPEN else logging.INFO,
            "circuit breaker %r: %s -> %s",
            self.name,
            old,
            new_state,
        )
        if self._on_transition is not None:
            try:
                self._on_transition(old, new_state)
            except Exception:  # observability must never break the breaker
                log.exception("breaker transition callback failed")

    @property
    def state(self) -> str:
        """Current state; reading it performs the time-driven
        open → half-open transition (no background task needed)."""
        if (
            self._state == STATE_OPEN
            and self.clock.monotonic() >= self._opened_at + self.recovery_seconds
        ):
            self._transition(STATE_HALF_OPEN)
        return self._state

    def retry_after(self) -> float:
        """Seconds until the open window elapses (0 when not open)."""
        if self.state != STATE_OPEN:
            return 0.0
        return max(
            0.0, self._opened_at + self.recovery_seconds - self.clock.monotonic()
        )

    def allow(self) -> bool:
        """May a call be attempted right now? Open rejects; closed and
        half-open admit (every half-open call is a recovery probe)."""
        return self.state != STATE_OPEN

    # -- outcomes -------------------------------------------------------
    def record_success(self) -> None:
        """A half-open success closes the circuit. A closed success
        changes nothing — recent failures age out by TIME, not by
        interleaved successes (see the rate-window rationale in the
        module docstring) — and an open success is a straggler from an
        in-flight call, ignored until the window elapses."""
        if self.state == STATE_HALF_OPEN:
            self._failures.clear()
            self._transition(STATE_CLOSED)

    def record_failure(self) -> None:
        """One transient failure. Classification is the caller's job —
        use :meth:`observe` to classify and record in one step."""
        state = self.state
        if state == STATE_HALF_OPEN:
            # the recovery probe failed: a full new open window
            self._trip()
            return
        if state == STATE_OPEN:
            return  # stragglers while open change nothing
        now = self.clock.monotonic()
        self._failures.append(now)
        if (
            len(self._failures) == self.failure_threshold
            and now - self._failures[0] <= self.failure_window
        ):
            self._trip()

    def _trip(self) -> None:
        self._opened_at = self.clock.monotonic()
        self._failures.clear()
        self._trip_count += 1
        self._transition(STATE_OPEN)

    def observe(self, exc: Optional[BaseException]) -> None:
        """Record one finished call: ``None`` is a success; a transient
        exception is a failure; a deterministic exception (4xx, a code
        bug) proves the far side is answering and counts as a success
        for circuit purposes. A :class:`BreakerOpenError` is NO outcome
        at all — no call happened — and is ignored so the breaker can
        neither feed on nor (worse) close itself off its own rejections."""
        if isinstance(exc, BreakerOpenError):
            return
        if exc is None or not is_transient_error(exc):
            self.record_success()
        else:
            self.record_failure()

    # -- introspection --------------------------------------------------
    def snapshot(self) -> dict:
        """The /statusz view of this breaker."""
        return {
            "name": self.name,
            "state": self.state,
            "recent_failures": len(self._failures),
            "retry_after_seconds": self.retry_after(),
            "trips": self._trip_count,
        }
