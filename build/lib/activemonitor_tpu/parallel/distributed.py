"""Multi-host (multi-slice) initialization — the DCN story.

Single-slice probes talk over ICI only. For multi-host slices and
multislice topologies, JAX's distributed runtime must be initialized
before any device access so all hosts join one global device set and
collectives can ride DCN between slices
(SURVEY.md §5.8: `jax.distributed.initialize` is the NCCL/MPI-backend
equivalent).

The probe CLI calls :func:`maybe_initialize_distributed` first thing;
it is a no-op unless the standard TPU/GKE environment variables (or
explicit arguments) indicate a multi-host run, so single-host probes
stay zero-config.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger(__name__)


def detect_multihost_env() -> bool:
    """True when the pod/VM environment announces a multi-host topology
    (GKE TPU injects these for multi-host node pools)."""
    if os.environ.get("ACTIVEMONITOR_DISTRIBUTED") == "1":
        return True
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return "," in hostnames  # more than one worker


def maybe_initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    force: bool = False,
) -> bool:
    """Initialize jax.distributed when the environment calls for it.

    Returns True if distributed mode was initialized. Explicit arguments
    (or ``force``) win; otherwise JAX's own TPU auto-detection fills
    everything in.
    """
    import jax

    if not (force or coordinator_address or detect_multihost_env()):
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        # double-init is fine ("distributed.initialize should only be
        # called once" in jax 0.9); anything else should surface
        if "once" in str(e) or "already" in str(e):
            return True
        raise
    log.info(
        "distributed initialized: process %d/%d, %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )
    return True


def distribute(array, sharding):
    """Place a host-resident (or local-device) array onto a sharding
    that may span PROCESSES.

    Single-process: plain ``device_put``. Multi-process: every process
    passes the same GLOBAL logical array (deterministic construction —
    same seed on every host) and contributes only its addressable
    shards via ``make_array_from_callback`` — the multi-host answer to
    "how does a global batch/parameter land on a DCN-spanning mesh"
    without any host ever holding another host's shard on device.
    """
    import jax
    import numpy as np

    if jax.process_count() == 1:
        return jax.device_put(array, sharding)
    host = np.asarray(array)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx]
    )


def distribute_tree(tree, sharding_tree):
    """:func:`distribute` over a pytree of arrays + matching shardings."""
    import jax

    return jax.tree.map(distribute, tree, sharding_tree)
