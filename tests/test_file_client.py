"""File-backed client tests: CRUD, status persistence across restarts, watch."""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller.client import ConflictError, NotFoundError
from activemonitor_tpu.controller.client_file import FileHealthCheckClient


def make_hc(name="hc-a", repeat=60):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": "health"},
            "spec": {"repeatAfterSec": repeat, "level": "cluster"},
        }
    )


@pytest.mark.asyncio
async def test_apply_get_list_delete(tmp_path):
    c = FileHealthCheckClient(str(tmp_path))
    await c.apply(make_hc("a"))
    await c.apply(make_hc("b"))
    assert len(await c.list()) == 2
    got = await c.get("health", "a")
    assert got.spec.repeat_after_sec == 60
    await c.delete("health", "a")
    assert await c.get("health", "a") is None
    with pytest.raises(NotFoundError):
        await c.delete("health", "a")


@pytest.mark.asyncio
async def test_user_authored_yaml_is_read(tmp_path):
    # the store is just files: a user can drop a manifest in directly
    (tmp_path / "mine.yaml").write_text(
        """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata: {name: dropped-in, namespace: health}
spec: {repeatAfterSec: 30, level: namespace}
"""
    )
    c = FileHealthCheckClient(str(tmp_path))
    got = await c.get("health", "dropped-in")
    assert got is not None
    assert got.spec.level == "namespace"


@pytest.mark.asyncio
async def test_status_persists_across_client_instances(tmp_path):
    """SURVEY.md §5.4 — the status sidecar is the durable checkpoint."""
    c1 = FileHealthCheckClient(str(tmp_path))
    await c1.apply(make_hc())
    hc = await c1.get("health", "hc-a")
    hc.status.success_count = 7
    hc.status.status = "Succeeded"
    await c1.update_status(hc)

    c2 = FileHealthCheckClient(str(tmp_path))  # "controller restart"
    got = await c2.get("health", "hc-a")
    assert got.status.success_count == 7
    assert got.status.status == "Succeeded"


@pytest.mark.asyncio
async def test_update_status_missing_raises(tmp_path):
    c = FileHealthCheckClient(str(tmp_path))
    with pytest.raises(NotFoundError):
        await c.update_status(make_hc())


@pytest.mark.asyncio
async def test_conflict_on_stale_resource_version(tmp_path):
    c = FileHealthCheckClient(str(tmp_path))
    await c.apply(make_hc())
    first = await c.get("health", "hc-a")
    updated = await c.update_status(first)
    stale = first.deepcopy()
    stale.metadata.resource_version = "does-not-match"
    stale.status.success_count = 9
    with pytest.raises(ConflictError):
        await c.update_status(stale)
    # the winning write is intact
    assert (await c.get("health", "hc-a")).metadata.resource_version == updated.metadata.resource_version


@pytest.mark.asyncio
async def test_delete_removes_status_sidecar(tmp_path):
    c = FileHealthCheckClient(str(tmp_path))
    await c.apply(make_hc())
    hc = await c.get("health", "hc-a")
    await c.update_status(hc)
    assert list((tmp_path / ".status").iterdir())
    await c.delete("health", "hc-a")
    assert not list((tmp_path / ".status").iterdir())


@pytest.mark.asyncio
async def test_corrupt_yaml_skipped(tmp_path, caplog):
    (tmp_path / "bad.yaml").write_text("{unclosed: [")
    c = FileHealthCheckClient(str(tmp_path))
    assert await c.list() == []


@pytest.mark.asyncio
async def test_watch_emits_lifecycle_events(tmp_path):
    c = FileHealthCheckClient(str(tmp_path), poll_seconds=0.05)
    events = []

    async def watcher():
        async for ev in c.watch():
            events.append((ev.type, ev.name))
            if len(events) >= 3:
                return

    task = asyncio.create_task(watcher())
    await asyncio.sleep(0.15)  # let the initial scan settle
    await c.apply(make_hc("w1"))
    await asyncio.sleep(0.15)
    changed = make_hc("w1", repeat=120)
    await c.apply(changed)
    await asyncio.sleep(0.15)
    await c.delete("health", "w1")
    await asyncio.wait_for(task, 5)
    assert events == [("ADDED", "w1"), ("MODIFIED", "w1"), ("DELETED", "w1")]


@pytest.mark.asyncio
async def test_status_update_emits_modified_like_other_clients(tmp_path):
    """Status writes emit MODIFIED — the in-memory client and a real
    apiserver both do (status-subresource writes are watch events), so
    the file backend must too or a manager reacting to MODIFIED
    behaves differently per store. The reconciler's dedupe absorbs the
    self-churn from its own status writes, exactly as in cluster mode
    (tests/test_e2e_local.py proves runs don't double)."""
    c = FileHealthCheckClient(str(tmp_path), poll_seconds=0.05)
    await c.apply(make_hc())
    events = []

    async def watcher():
        async for ev in c.watch():
            events.append((ev.type, ev.name))

    task = asyncio.create_task(watcher())
    await asyncio.sleep(0.15)
    hc = await c.get("health", "hc-a")
    hc.status.success_count = 1
    await c.update_status(hc)
    for _ in range(40):
        if ("MODIFIED", "hc-a") in events:
            break
        await asyncio.sleep(0.05)
    task.cancel()
    assert ("MODIFIED", "hc-a") in events, events


@pytest.mark.asyncio
async def test_one_invalid_check_does_not_break_store(tmp_path):
    (tmp_path / "bad-check.yaml").write_text(
        "kind: HealthCheck\nmetadata: {name: broken}\nspec: {repeatAfterSec: sixty}\n"
    )
    c = FileHealthCheckClient(str(tmp_path))
    await c.apply(make_hc("good"))
    names = [hc.metadata.name for hc in await c.list()]
    assert names == ["good"]  # bad one skipped, store still works


@pytest.mark.asyncio
async def test_apply_updates_user_named_file_in_place(tmp_path):
    user_file = tmp_path / "zz-mine.yaml"
    user_file.write_text(
        """
apiVersion: activemonitor.keikoproj.io/v1alpha1
kind: HealthCheck
metadata: {name: hc-a, namespace: health}
spec: {repeatAfterSec: 60, level: cluster}
"""
    )
    c = FileHealthCheckClient(str(tmp_path))
    updated = make_hc("hc-a", repeat=120)
    await c.apply(updated)
    got = await c.get("health", "hc-a")
    assert got.spec.repeat_after_sec == 120  # no stale duplicate wins
    assert not (tmp_path / "health__hc-a.yaml").exists()  # rewritten in place
