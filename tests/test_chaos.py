"""Chaos tier: fault injection against the stub API server.

The reference gets its resilience ladder (SURVEY.md §5.3 — panic
recover, 1s requeue, RetryOnConflict, synthesized failures) but never
tests it against a misbehaving API server. This tier does: 5xx storms,
conflict storms, dropped watch streams and a slow API server, asserting
the controller recovers every time — no dead schedules, no duplicate
state, no hung watches.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import RBACProvisioner
from activemonitor_tpu.controller.client_k8s import KubernetesHealthCheckClient
from activemonitor_tpu.controller.events import KubernetesEventRecorder
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.controller.rbac import KubernetesRBACBackend
from activemonitor_tpu.controller.reconciler import HealthCheckReconciler
from activemonitor_tpu.engine.argo import (
    WF_GROUP,
    WF_PLURAL,
    WF_VERSION,
    ArgoWorkflowEngine,
)
from activemonitor_tpu.kube import api_path
from activemonitor_tpu.metrics import MetricsCollector

from tests.kube_harness import stub_env

INLINE_HELLO = """
apiVersion: argoproj.io/v1alpha1
kind: Workflow
metadata:
  generateName: chaos-
spec:
  entrypoint: main
  templates:
    - name: main
      container:
        image: python:3.12-slim
        command: [python, -c, "print('hello')"]
"""


def chaos_check(name="chaos-check"):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": "health"},
            "spec": {
                "repeatAfterSec": 60,
                "level": "namespace",
                "workflow": {
                    "generateName": "chaos-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "chaos-sa",
                        "source": {"inline": INLINE_HELLO},
                    },
                },
            },
        }
    )


def build_controller(api, max_parallel=2):
    client = KubernetesHealthCheckClient(api)
    reconciler = HealthCheckReconciler(
        client=client,
        engine=ArgoWorkflowEngine(api),
        rbac=RBACProvisioner(KubernetesRBACBackend(api)),
        recorder=KubernetesEventRecorder(api),
        metrics=MetricsCollector(),
    )
    return client, Manager(
        client=client, reconciler=reconciler, max_parallel=max_parallel
    )


async def wait_for(predicate, timeout=15.0, interval=0.05):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        result = await predicate()
        if result:
            return result
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError("condition not met")
        await asyncio.sleep(interval)


def argo_player(server, api):
    """Background task playing the Argo controller: marks every
    submitted Workflow Succeeded, forever (survives resubmissions AND
    injected faults — the real Argo controller's workqueue retries a
    failed status write, so ours must too or a single chaos 500 would
    silently kill the player mid-test)."""
    from activemonitor_tpu.kube import ApiError

    async def play():
        done = set()
        while True:
            for wf in server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
                name = wf["metadata"]["name"]
                if name in done:
                    continue
                try:
                    await api.merge_patch(
                        api_path(
                            WF_GROUP, WF_VERSION, WF_PLURAL,
                            wf["metadata"]["namespace"], name, "status",
                        ),
                        {"status": {"phase": "Succeeded"}},
                    )
                    done.add(name)  # only after the write landed
                except ApiError:
                    continue  # chaos 500: retry on the next sweep
            await asyncio.sleep(0.05)

    return asyncio.create_task(play())


@pytest.mark.asyncio
async def test_watch_stream_drop_reconnects():
    """An abruptly closed watch stream must not lose later events."""
    async with stub_env() as (server, api):
        client = KubernetesHealthCheckClient(api)
        seen = []

        async def consume():
            async for event in client.watch():
                seen.append((event.type, event.name))

        task = asyncio.create_task(consume())
        try:
            await client.apply(chaos_check("first"))
            await wait_for(lambda: asyncio.sleep(0, ("ADDED", "first") in seen))

            assert server.drop_watches() >= 1
            # event created while the client is between streams: the
            # resume-from-last-rv reconnect must deliver it
            await client.apply(chaos_check("second"))
            await wait_for(lambda: asyncio.sleep(0, ("ADDED", "second") in seen))
        finally:
            task.cancel()


@pytest.mark.asyncio
async def test_degraded_workflow_watch_full_lifecycle():
    """The workflow watch stream (divergence 11) is storm-degraded —
    500s on every workflow read plus repeated stream drops — while a
    check runs. The engine must fall back to direct GETs/pacing sleeps
    and the check must still reach Succeeded; nothing may depend on the
    informer being alive."""
    async with stub_env() as (server, api):
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        dropper_running = True

        async def dropper():
            while dropper_running:
                server.drop_watches()
                await asyncio.sleep(0.05)

        drop_task = asyncio.create_task(dropper())
        # every workflow read (list, watch reconnect, fallback GET)
        # fails 20 times before the path clears
        server.inject_fault("/workflows", status=500, times=20, method="GET")
        try:
            await client.apply(chaos_check("degraded-watch"))

            async def succeeded():
                hc = await client.get("health", "degraded-watch")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded, timeout=30.0)
            assert hc.status.success_count == 1
            # transient poll errors ride out IN PLACE: the storm must
            # not have produced duplicate submissions for this one
            # scheduled fire
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 1
        finally:
            dropper_running = False
            drop_task.cancel()
            player.cancel()
            await manager.stop()


@pytest.mark.asyncio
async def test_workflow_submit_500_storm_recovers():
    """The first submits fail with 500s; the requeue ladder must retry
    until the API server heals, then the check completes normally."""
    async with stub_env() as (server, api):
        server.inject_fault(f"/{WF_PLURAL}", status=500, times=3, method="POST")
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        try:
            await client.apply(chaos_check())

            async def succeeded():
                hc = await client.get("health", "chaos-check")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded)
            assert hc.status.success_count == 1
            # all three injected faults were actually consumed
            assert all(f["remaining"] == 0 for f in server.faults)
        finally:
            player.cancel()
            await manager.stop()


@pytest.mark.asyncio
async def test_status_write_500_storm_does_not_kill_schedule():
    """A 5xx burst on the terminal status write outliving the conflict
    retries must requeue the check, not silently drop its schedule
    (reference requeues on any reconcile error, :204)."""
    async with stub_env() as (server, api):
        server.inject_fault(
            "/healthchecks/chaos-check/status", status=500, times=4, method="PATCH"
        )
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        try:
            await client.apply(chaos_check())

            async def succeeded():
                hc = await client.get("health", "chaos-check")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded)
            assert hc.status.success_count >= 1
            assert all(f["remaining"] == 0 for f in server.faults)
            # the schedule survived: the next run is on the books
            assert manager.reconciler.timers.exists("health/chaos-check")
        finally:
            player.cancel()
            await manager.stop()


@pytest.mark.asyncio
async def test_status_conflict_storm_retries_without_rerun():
    """409s within the RetryOnConflict budget are absorbed: exactly one
    workflow run, no requeue, status written."""
    async with stub_env() as (server, api):
        server.inject_fault(
            "/healthchecks/chaos-check/status", status=409, times=3, method="PATCH"
        )
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        try:
            await client.apply(chaos_check())

            async def succeeded():
                hc = await client.get("health", "chaos-check")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded)
            # conflicts were retried inside the write, not by re-running
            # the workflow
            assert hc.status.success_count == 1
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 1
        finally:
            player.cancel()
            await manager.stop()


@pytest.mark.asyncio
async def test_slow_apiserver_full_lifecycle():
    """Uniform API latency slows everything but breaks nothing."""
    async with stub_env() as (server, api):
        server.latency = 0.05
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        try:
            await client.apply(chaos_check())

            async def succeeded():
                hc = await client.get("health", "chaos-check")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded, timeout=30.0)
            assert hc.status.success_count == 1
        finally:
            player.cancel()
            await manager.stop()


@pytest.mark.asyncio
async def test_ha_failover_without_double_submission():
    """Two full controllers, lease election, one check: the standby must
    take over on leader shutdown, resume the schedule from durable
    status (divergence 10) WITHOUT resubmitting the recent run, and own
    the next fire."""
    from activemonitor_tpu.controller.leader import KubernetesLeaseElector
    from activemonitor_tpu.kube import KubeApi, KubeConfig
    from activemonitor_tpu.utils.clock import FakeClock

    from tests.kube_harness import advance, drive_until

    async with stub_env() as (server, api_a):
        clock = FakeClock()
        api_b = KubeApi(KubeConfig(server=server.url))

        def controller(api, identity):
            client = KubernetesHealthCheckClient(api)
            reconciler = HealthCheckReconciler(
                client=client,
                engine=ArgoWorkflowEngine(api),
                rbac=RBACProvisioner(KubernetesRBACBackend(api)),
                recorder=KubernetesEventRecorder(api),
                metrics=MetricsCollector(),
                clock=clock,
            )
            elector = KubernetesLeaseElector(
                api=api,
                namespace="health",
                identity=identity,
                lease_seconds=15.0,
                clock=clock,
            )
            return client, Manager(
                client=client,
                reconciler=reconciler,
                max_parallel=2,
                leader_elector=elector,
            )

        client_a, mgr_a = controller(api_a, "replica-a")
        client_b, mgr_b = controller(api_b, "replica-b")
        a_stopped = False
        b_start = None
        try:
            await mgr_a.start()
            b_start = asyncio.create_task(mgr_b.start())
            await asyncio.sleep(0.2)
            assert not b_start.done()  # B stands by while A leads

            await client_a.apply(chaos_check("ha-check"))
            workflows = await wait_for(
                lambda: asyncio.sleep(0, server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))
            )
            wf1 = workflows[0]["metadata"]["name"]
            await api_a.merge_patch(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, "health", wf1, "status"),
                {"status": {"phase": "Succeeded"}},
            )

            async def succeeded(count):
                async def check():
                    hc = await client_b.get("health", "ha-check")
                    return hc if hc and hc.status.success_count == count else None

                # the poll loop between submit and terminal phase runs on
                # the fake clock: drive it
                return await drive_until(clock, check)

            await succeeded(1)

            # graceful failover: A releases the lease, B acquires
            await mgr_a.stop()
            a_stopped = True
            await drive_until(
                clock, lambda: asyncio.sleep(0, b_start.done()), max_seconds=30
            )
            await b_start

            # B boot-resynced: the schedule must resume from status, not
            # resubmit the run that just finished
            await asyncio.sleep(0.3)
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 1
            await wait_for(
                lambda: asyncio.sleep(
                    0, mgr_b.reconciler.timers.exists("health/ha-check")
                )
            )

            # the next fire is B's: advance toward the 60s interval, but
            # STOP the moment wf2 appears — its (fake) workflowtimeout
            # starts at submission, and jumping fake time past it before
            # the test plays Argo would synthesize a timeout failure
            workflows = await drive_until(
                clock,
                lambda: asyncio.sleep(
                    0,
                    len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 2
                    and server.objs(WF_GROUP, WF_VERSION, WF_PLURAL),
                ),
                max_seconds=75,
            )
            wf2 = next(
                w["metadata"]["name"]
                for w in workflows
                if w["metadata"]["name"] != wf1
            )
            await api_b.merge_patch(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, "health", wf2, "status"),
                {"status": {"phase": "Succeeded"}},
            )
            hc = await succeeded(2)
            assert hc.status.total_healthcheck_runs == 2
            # exactly two runs ever: no duplicate across the failover
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 2
        finally:
            if not a_stopped:
                await mgr_a.stop()
            if b_start is not None and not b_start.done():
                b_start.cancel()
            await mgr_b.stop()
            await api_b.close()


@pytest.mark.asyncio
async def test_chaos_soak_sustained_faults_over_simulated_time():
    """The chaos scenarios above are one-shot; this tier sustains them:
    30 simulated minutes, 12 checks on a 300 s cadence, and EVERY
    simulated minute injects a fresh fault burst — 500s on workflow
    reads, 500s on status writes, dropped watch streams, with uniform
    latency for the middle third. Quantified recovery: every check
    keeps making scheduled progress (no dead schedule), nothing
    double-submits past the cadence ceiling, and the server's live
    watch connections stay bounded (reconnects replace, never
    accumulate)."""
    from activemonitor_tpu.utils.clock import FakeClock

    N = 12
    async with stub_env() as (server, api):
        clock = FakeClock()
        client = KubernetesHealthCheckClient(api)
        reconciler = HealthCheckReconciler(
            client=client,
            engine=ArgoWorkflowEngine(api),
            rbac=RBACProvisioner(KubernetesRBACBackend(api)),
            recorder=KubernetesEventRecorder(api),
            metrics=MetricsCollector(),
            clock=clock,
        )
        manager = Manager(client=client, reconciler=reconciler, max_parallel=6)
        await manager.start()
        player = argo_player(server, api)
        try:
            for i in range(N):
                hc = chaos_check(f"chaos-soak-{i:02d}")
                hc.spec.repeat_after_sec = 300
                hc.spec.workflow.generate_name = f"chaos-soak-{i:02d}-"
                hc.spec.workflow.timeout = 120  # chaos targets the API,
                # not Argo slowness — keep synthesized timeouts out
                await client.apply(hc)
            await asyncio.sleep(0.3)

            for minute in range(30):
                # a fresh storm every simulated minute — but only once
                # the last one was consumed. An unbounded fault backlog
                # is not "sustained chaos", it is a permanently-down
                # API for writes, which no controller (reference
                # included) can make durable progress against.
                if not any(f["remaining"] > 0 for f in server.faults):
                    server.faults.clear()
                    server.inject_fault(
                        "/workflows", status=500, times=2, method="GET"
                    )
                    server.inject_fault(
                        "/status", status=500, times=2, method="PATCH"
                    )
                    if minute % 3 == 0:
                        server.inject_fault(
                            f"/{WF_PLURAL}", status=500, times=2, method="POST"
                        )
                if minute % 5 == 0:
                    server.drop_watches()
                server.latency = 0.02 if 10 <= minute < 20 else 0.0
                # watch-recovery backoffs sleep in REAL seconds: each
                # simulated minute gets ~0.5 s of real air so recovery
                # ladders can climb between storms
                for _ in range(4):  # 4 x 15 s = one simulated minute
                    await clock.advance(15)
                    await asyncio.sleep(0.12)
            server.latency = 0.0
            server.faults.clear()
            # quiesce: let in-flight runs, retries, and real-time watch
            # reconnects complete
            for _ in range(10):
                await clock.advance(15)
                await asyncio.sleep(0.15)
            await reconciler.wait_watches()

            for i in range(N):
                name = f"chaos-soak-{i:02d}"
                hc = await client.get("health", name)
                runs = hc.status.total_healthcheck_runs
                # 300 s cadence over 1800 s: every check must have kept
                # its schedule alive through the storms (>=4 runs), and
                # the retry ladder must not have double-submitted (<=9)
                assert 4 <= runs <= 9, (name, runs, hc.status)
                assert hc.status.status == "Succeeded", (name, hc.status)
            assert server.live_watch_count() <= 4, server.live_watch_count()
        finally:
            player.cancel()
            await manager.stop()


@pytest.mark.asyncio
async def test_chaos_soak_breaker_degrades_and_recovers_without_duplicates():
    """ISSUE-3 acceptance: a seeded soak (injected 500s + watch drops +
    latency) in which the shared circuit breaker opens, the controller
    enters degraded mode (gauge + snapshot), the terminal status write
    queues for replay — and recovery closes the breaker, replays the
    queued write, with exactly ONE workflow ever created per scheduled
    fire (no duplicates through the whole storm)."""
    import random

    from activemonitor_tpu.kube import KubeApi, KubeConfig
    from activemonitor_tpu.resilience import (
        CircuitBreaker,
        ResilienceCoordinator,
        STATE_CLOSED,
        STATE_OPEN,
    )
    from activemonitor_tpu.utils.clock import FakeClock

    from tests.kube_harness import drive_until

    async with stub_env() as (server, api):
        clock = FakeClock()
        metrics = MetricsCollector()
        breaker = CircuitBreaker(
            "api", clock=clock, failure_threshold=5, recovery_seconds=30.0
        )
        resilience = ResilienceCoordinator(
            clock, metrics, breaker=breaker, rng=random.Random(42)
        )
        client = KubernetesHealthCheckClient(api)
        reconciler = HealthCheckReconciler(
            client=client,
            engine=ArgoWorkflowEngine(api),
            rbac=RBACProvisioner(KubernetesRBACBackend(api)),
            recorder=KubernetesEventRecorder(api),
            metrics=metrics,
            clock=clock,
            resilience=resilience,
        )
        # the breaker observes the controller's transport — NOT the
        # test scaffolding's (the Argo player gets its own session)
        api.set_breaker(breaker)
        player_api = KubeApi(KubeConfig(server=server.url))
        manager = Manager(client=client, reconciler=reconciler, max_parallel=2)
        await manager.start()
        player = argo_player(server, player_api)
        key = "health/chaos-breaker"
        try:
            hc = chaos_check("chaos-breaker")
            hc.spec.repeat_after_sec = 300
            hc.spec.workflow.timeout = 120
            await client.apply(hc)

            # ---- baseline: run 1 completes cleanly -------------------
            async def run_count(n):
                async def check():
                    got = await client.get("health", "chaos-breaker")
                    return (
                        got
                        if got and got.status.total_healthcheck_runs >= n
                        else None
                    )

                return check

            await drive_until(clock, await run_count(1), max_seconds=150)
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 1
            assert breaker.state == STATE_CLOSED
            flush = getattr(reconciler.recorder, "flush", None)
            if flush is not None:
                await flush()

            # ---- storm: every workflow read 500s, every healthcheck
            # status write 500s, watch streams drop, uniform latency ---
            server.inject_fault(
                "/workflows", status=500, times=500, method="GET"
            )
            server.inject_fault(
                "/healthchecks", status=500, times=500, method="PATCH"
            )
            server.latency = 0.01
            server.drop_watches()

            # the 300 s timer fires run 2: the submit (POST) lands — ONE
            # new workflow — but its polls hit the 500 storm and the
            # breaker opens
            async def breaker_open():
                server.drop_watches()
                return breaker.state == STATE_OPEN

            await drive_until(clock, breaker_open, max_seconds=400)
            assert breaker.state == STATE_OPEN
            # degraded mode is reported on the gauge and the snapshot
            assert (
                metrics.sample_value("healthcheck_controller_degraded", {})
                == 1.0
            )
            assert resilience.snapshot()["degraded"] is True
            # the degraded pacer stretches the retry cadence within the
            # breaker's recovery window
            assert 1.0 <= resilience.requeue_delay(1.0) <= 30.0
            # exactly one new workflow for the fire, despite the storm
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 2

            # ---- partial recovery: reads heal, writes stay broken ----
            server.latency = 0.0
            server.faults[:] = [
                f for f in server.faults if f["path_substr"] != "/workflows"
            ]
            # the open window elapses -> half-open -> a read succeeds and
            # closes the breaker -> the verdict (the player marked wf2
            # Succeeded long ago) lands -> the terminal status write hits
            # the PATCH storm, re-trips the breaker, and QUEUES
            async def write_parked():
                return resilience.pending_status_writes() >= 1

            await drive_until(clock, write_parked, max_seconds=400)
            assert resilience.pending_status_writes() == 1
            assert resilience.queued_status(key).total_healthcheck_runs == 2
            assert breaker.state == STATE_OPEN  # re-tripped by the writes
            assert (
                metrics.sample_value("healthcheck_controller_degraded", {})
                == 1.0
            )
            # the durable status still shows run 1 only...
            got = await client.get("health", "chaos-breaker")
            assert got.status.total_healthcheck_runs == 1
            # ...and a reconcile poked while the write is parked must NOT
            # double-submit (the queued status overlays the stale one)
            await reconciler.reconcile("health", "chaos-breaker")
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 2

            # ---- full recovery: writes heal, the replay sweep drains --
            server.faults.clear()

            async def replayed():
                got = await client.get("health", "chaos-breaker")
                return got if got.status.total_healthcheck_runs >= 2 else None

            await drive_until(clock, replayed, max_seconds=400)
            assert resilience.pending_status_writes() == 0
            assert breaker.state == STATE_CLOSED
            await asyncio.sleep(0.1)
            resilience.refresh()
            assert (
                metrics.sample_value("healthcheck_controller_degraded", {})
                == 0.0
            )
            got = await client.get("health", "chaos-breaker")
            assert got.status.status == "Succeeded"
            assert got.status.success_count == 2
            # the whole storm produced exactly one workflow per fire
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 2
            # and the schedule survived: the next fire is on the books
            assert manager.reconciler.timers.exists(key)
        finally:
            player.cancel()
            await manager.stop()
            await player_api.close()


@pytest.mark.asyncio
async def test_sharded_fleet_handoff_fires_owed_runs_exactly_once():
    """ISSUE-6 acceptance (tier-1 slice; the ≥50k version lives in
    tests/test_stress.py): a 3-replica sharded fleet on the stub
    apiserver, seeded FakeClock. One replica is hard-killed mid-cycle
    (no release — its shard lease rots); a survivor adopts the dead
    shard, rebuilds timers from durable status, and the next cycle's
    owed runs fire EXACTLY once fleet-wide. The corpse's late status
    write is rejected by the resourceVersion fence, and the /statusz
    rollup's per-shard ownership counts sum to the check total before
    and after the handoff."""
    from activemonitor_tpu.controller.sharding import ShardCoordinator
    from activemonitor_tpu.kube import KubeApi, KubeConfig
    from activemonitor_tpu.obs.slo import rollup_statusz
    from activemonitor_tpu.utils.clock import FakeClock

    from tests.kube_harness import drive_until

    N = 24
    async with stub_env() as (server, api_a):
        clock = FakeClock()
        apis = {
            "a": api_a,
            "b": KubeApi(KubeConfig(server=server.url)),
            "c": KubeApi(KubeConfig(server=server.url)),
        }
        player_api = KubeApi(KubeConfig(server=server.url))
        managers, coords, mets = {}, {}, {}
        for i, tag in enumerate("abc"):
            metrics = MetricsCollector()
            coord = ShardCoordinator(
                api=apis[tag],
                namespace="health",
                shards=3,
                shard_id=i,
                identity=f"replica-{tag}",
                clock=clock,
                metrics=metrics,
                lease_seconds=15.0,
                # this scenario pins the handoff invariants; the
                # work-stealing policy has its own test — a shed mid-
                # adoption would only churn the ownership assertions
                steal_threshold=10**6,
            )
            client = KubernetesHealthCheckClient(apis[tag], owns=coord.owns_event)
            reconciler = HealthCheckReconciler(
                client=client,
                engine=ArgoWorkflowEngine(apis[tag]),
                rbac=RBACProvisioner(KubernetesRBACBackend(apis[tag])),
                recorder=KubernetesEventRecorder(apis[tag]),
                metrics=metrics,
                clock=clock,
            )
            managers[tag] = Manager(
                client=client,
                reconciler=reconciler,
                max_parallel=4,
                shard_coordinator=coord,
            )
            coords[tag], mets[tag] = coord, metrics
        seeder = KubernetesHealthCheckClient(apis["a"])  # unfiltered view
        player = argo_player(server, player_api)
        names = [f"shard-chk-{i:02d}" for i in range(N)]
        try:
            await asyncio.gather(*(m.start() for m in managers.values()))
            for name in names:
                hc = chaos_check(name)
                hc.spec.repeat_after_sec = 300
                hc.spec.workflow.timeout = 120
                hc.spec.workflow.generate_name = f"{name}-"
                await seeder.apply(hc)
            # the router must spread these names over all 3 shards
            # (deterministic md5 routing; renaming would re-roll)
            spread = {coords["a"].shard_for(f"health/{n}") for n in names}
            assert spread == {0, 1, 2}

            def all_ran(n):
                async def check():
                    for name in names:
                        got = await seeder.get("health", name)
                        if got is None or got.status.total_healthcheck_runs < n:
                            return False
                    return True

                return check

            await drive_until(clock, all_ran(1), max_seconds=200)
            # every check fired exactly once across the whole fleet
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == N
            for i, tag in enumerate("abc"):
                assert coords[tag].owned_shards() == [i]

            async def payloads(tags):
                out = []
                for tag in tags:
                    manager = managers[tag]
                    out.append(
                        manager.reconciler.fleet.statusz(
                            await manager.client.list()
                        )
                    )
                return out

            rollup = rollup_statusz(await payloads("abc"))
            assert rollup["fleet"]["checks"] == N
            assert (
                sum(rollup["fleet"]["sharding"]["checks_per_shard"].values()) == N
            )

            # ---- hard-kill replica b mid-cycle (no lease release) ----
            from tests.kube_harness import hard_kill_shards

            victim = managers["b"]
            for task in list(victim._tasks) + list(victim._requeue_tasks):
                task.cancel()
            hard_kill_shards(coords["b"])
            # a real crash takes the timers and watches with the process
            await victim.reconciler.shutdown()

            # a survivor's standby adopts shard 1 once the lease expires
            await drive_until(
                clock,
                lambda: asyncio.sleep(
                    0, 1 in coords["a"].set.owned or 1 in coords["c"].set.owned
                ),
                max_seconds=120,
            )

            # the next cycle: EVERY owed run (dead shard's included)
            # fires exactly once on the surviving owners
            await drive_until(clock, all_ran(2), max_seconds=500)
            assert len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL)) == 2 * N
            for name in names:
                got = await seeder.get("health", name)
                assert got.status.total_healthcheck_runs == 2, name

            # ---- the fenced old owner's late status write ------------
            fenced_name = next(
                n for n in names if coords["b"].shard_for(f"health/{n}") == 1
            )
            stale = await seeder.get("health", fenced_name)
            stale.status.error_message = "stale split-brain write"
            await victim.reconciler._update_status(stale)  # dropped, no raise
            fresh = await seeder.get("health", fenced_name)
            assert fresh.status.error_message != "stale split-brain write"
            assert (
                mets["b"].sample_value(
                    "healthcheck_shard_fenced_writes_total", {"shard": "1"}
                )
                == 1.0
            )
            # dropped means DROPPED: nothing parked for replay either
            assert victim.reconciler.resilience.pending_status_writes() == 0

            # ---- rollup after handoff: counts still sum, shard 1 has
            # exactly one (surviving) owner
            rollup = rollup_statusz(await payloads("ac"))
            assert rollup["fleet"]["checks"] == N
            assert (
                sum(rollup["fleet"]["sharding"]["checks_per_shard"].values()) == N
            )
            owners = rollup["fleet"]["sharding"]["owners"]
            assert set(owners) == {"0", "1", "2"}
            assert owners["1"] in ("replica-a", "replica-c")
        finally:
            player.cancel()
            for manager in managers.values():
                await manager.stop()
            for tag in ("b", "c"):
                await apis[tag].close()
            await player_api.close()


@pytest.mark.asyncio
async def test_timer_fired_resubmit_survives_submit_500s():
    """A 500 storm hitting the TIMER-fired resubmission (not the first
    submit) must not end the schedule: the timer entry is consumed, so
    without the requeue ladder this is a permanently dead check —
    owed run, no timer, no watch (the dead-schedule shape the
    chaos-soak tier first caught)."""
    async with stub_env() as (server, api):
        client, manager = build_controller(api)
        await manager.start()
        player = argo_player(server, api)
        try:
            hc = chaos_check("timer-resubmit")
            hc.spec.repeat_after_sec = 2  # fast cadence, real clock
            await client.apply(hc)

            async def first_done():
                got = await client.get("health", "timer-resubmit")
                return got if got and got.status.total_healthcheck_runs >= 1 else None

            await wait_for(first_done, timeout=20.0)
            # every submit for the next little while fails
            server.inject_fault(f"/{WF_PLURAL}", status=500, times=3, method="POST")

            async def second_done():
                got = await client.get("health", "timer-resubmit")
                return got if got and got.status.total_healthcheck_runs >= 2 else None

            got = await wait_for(second_done, timeout=30.0)
            assert got.status.status == "Succeeded"
        finally:
            player.cancel()
            await manager.stop()
