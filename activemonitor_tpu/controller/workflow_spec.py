"""Workflow manifest parsing and mutation.

Turns a HealthCheck's artifact into a submittable workflow manifest
(reference: healthcheck_controller.go:876-1125):

- resolve + read the artifact, YAML-parse it
- labels: manifest labels are used when present and map-shaped,
  otherwise the default controller-instanceid label is applied.
  Divergence from the reference, on purpose: labels are computed
  per-check instead of accumulated in a shared reconciler-wide map, so
  labels can't leak between HealthChecks (the reference defect noted in
  SURVEY.md §2 — workflowLabels at healthcheck_controller.go:140,910-928).
- inject: GVK, namespace, generateName, ownerReference (controller=true
  ⇒ workflows are GC'd with their HealthCheck), podGC OnPodCompletion
  default, serviceAccountName, activeDeadlineSeconds default
- timeout defaulting: an unset workflow timeout becomes repeatAfterSec
  (mutating the in-memory spec, reference: :981-986); a remedy's
  timeout is taken from its manifest's activeDeadlineSeconds when
  numeric, else repeatAfterSec (:1107-1120)
"""

from __future__ import annotations


import yaml

from activemonitor_tpu import API_VERSION, KIND
from activemonitor_tpu.api.types import HealthCheck
from activemonitor_tpu.engine.base import (
    WF_API_VERSION,
    WF_INSTANCE_ID,
    WF_INSTANCE_ID_LABEL_KEY,
    WF_KIND,
)
from activemonitor_tpu.store import get_artifact_reader
POD_GC_ON_POD_COMPLETION = "OnPodCompletion"


class WorkflowSpecError(ValueError):
    pass


def _load_manifest(source) -> dict:
    reader = get_artifact_reader(source)
    content = reader.read()
    data = yaml.safe_load(content)
    if not isinstance(data, dict):
        raise WorkflowSpecError("invalid spec file passed")
    return data


def _resolve_labels(data: dict) -> dict:
    """Labels for the submitted workflow (per-check, no shared state)."""
    metadata = data.get("metadata")
    if isinstance(metadata, dict):
        labels = metadata.get("labels")
        if isinstance(labels, dict):
            return {str(k): str(v) for k, v in labels.items()}
    return {WF_INSTANCE_ID_LABEL_KEY: WF_INSTANCE_ID}


def _owner_reference(hc: HealthCheck) -> dict:
    # reference: healthcheck_controller.go:512-522
    return {
        "apiVersion": API_VERSION,
        "kind": KIND,
        "name": hc.metadata.name,
        "uid": hc.metadata.uid,
        "controller": True,
    }


def _injected_metadata(data: dict, generate_name: str, namespace: str, hc: HealthCheck) -> dict:
    """Controller-owned metadata; manifest annotations are preserved
    (the reference overwrites name/labels/ns/ownerRefs via setters,
    which keeps other metadata keys — healthcheck_controller.go:505-522)."""
    meta = {
        "generateName": generate_name,
        "namespace": namespace,
        "labels": _resolve_labels(data),
        "ownerReferences": [_owner_reference(hc)],
    }
    old = data.get("metadata")
    if isinstance(old, dict) and isinstance(old.get("annotations"), dict):
        meta["annotations"] = old["annotations"]
    return meta


def _spec_of(data: dict, what: str) -> dict:
    spec = data.get("spec")
    if spec is None:
        raise WorkflowSpecError(f"invalid {what}, missing spec")
    if not isinstance(spec, dict):
        raise WorkflowSpecError(f"invalid {what}, spec is not a map")
    return spec


def _inject_tpu_placement(spec: dict, tpu) -> None:
    """Place the probe onto a TPU node pool: GKE TPU node selectors at
    the workflow level, chip resources on every container template
    (framework extension — SURVEY.md §7.7)."""
    if tpu.accelerator or tpu.topology:
        selector = spec.get("nodeSelector")
        if not isinstance(selector, dict):
            selector = {}
        if tpu.accelerator:
            selector.setdefault("cloud.google.com/gke-tpu-accelerator", tpu.accelerator)
        if tpu.topology:
            selector.setdefault("cloud.google.com/gke-tpu-topology", tpu.topology)
        spec["nodeSelector"] = selector
    tolerations = spec.get("tolerations")
    if not isinstance(tolerations, list):
        tolerations = []
    if not any(
        isinstance(t, dict) and t.get("key") == "google.com/tpu" for t in tolerations
    ):
        tolerations.append(
            {"key": "google.com/tpu", "operator": "Exists", "effect": "NoSchedule"}
        )
    spec["tolerations"] = tolerations
    if tpu.chips > 0:
        for template in spec.get("templates") or []:
            if not isinstance(template, dict):
                continue
            for kind in ("container", "script"):  # both run as pods
                runnable = template.get(kind)
                if isinstance(runnable, dict):
                    resources = runnable.setdefault("resources", {})
                    limits = resources.setdefault("limits", {})
                    limits.setdefault("google.com/tpu", tpu.chips)
                    requests = resources.setdefault("requests", {})
                    requests.setdefault("google.com/tpu", tpu.chips)


def parse_workflow_from_healthcheck(hc: HealthCheck) -> dict:
    """Build the probe workflow manifest
    (reference: healthcheck_controller.go:876-1000 + submit-side
    metadata at :502-522)."""
    wf = hc.spec.workflow
    if wf.resource is None:
        raise WorkflowSpecError("workflow resource is nil")
    data = _load_manifest(wf.resource.source)
    spec = _spec_of(data, "workflow")

    if spec.get("podGC") is None:
        spec["podGC"] = {"strategy": POD_GC_ON_POD_COMPLETION}

    # default the timeout from the repeat interval (reference: :981-986)
    if wf.timeout == 0:
        hc.spec.workflow.timeout = hc.spec.repeat_after_sec
    timeout = hc.spec.workflow.timeout

    if wf.resource.service_account:
        spec["serviceAccountName"] = wf.resource.service_account
    if spec.get("activeDeadlineSeconds") is None:
        spec["activeDeadlineSeconds"] = timeout
    if wf.tpu is not None:
        _inject_tpu_placement(spec, wf.tpu)

    data["apiVersion"] = WF_API_VERSION
    data["kind"] = WF_KIND
    data["metadata"] = _injected_metadata(
        data, wf.generate_name, wf.resource.namespace, hc
    )
    data["spec"] = spec
    return data


def parse_remedy_workflow_from_healthcheck(hc: HealthCheck, remedy=None) -> dict:
    """Build the remedy workflow manifest
    (reference: healthcheck_controller.go:1002-1125 + :536-559).

    ``remedy`` is the workflow to build — the plain
    ``spec.remedyworkflow`` by default, or a bucket-targeted entry the
    reconciler selected from ``byBucket``. A targeted entry without its
    own serviceAccount inherits the plain remedy's (the one the RBAC
    provisioner actually created)."""
    fallback = hc.spec.remedy_workflow
    if remedy is None:
        remedy = fallback
    if remedy.resource is None:
        raise WorkflowSpecError("RemedyWorkflow Resource is nil")
    data = _load_manifest(remedy.resource.source)
    spec = _spec_of(data, "remedy workflow")

    if spec.get("podGC") is None:
        spec["podGC"] = {"strategy": POD_GC_ON_POD_COMPLETION}
    service_account = remedy.resource.service_account or (
        fallback.resource.service_account
        if fallback.resource is not None
        else ""
    )
    if service_account:
        spec["serviceAccountName"] = service_account

    if remedy.tpu is not None:
        # remedies inherit the placement machinery: a fix for a TPU node
        # pool usually has to run on/next to that pool
        _inject_tpu_placement(spec, remedy.tpu)

    default_timeout = hc.spec.repeat_after_sec
    deadline = spec.get("activeDeadlineSeconds")
    if deadline is None:
        spec["activeDeadlineSeconds"] = default_timeout
        remedy.timeout = default_timeout
    elif isinstance(deadline, (int, float)) and not isinstance(deadline, bool):
        remedy.timeout = int(deadline)
    else:
        # non-numeric deadline in the manifest: fall back (reference: :1114-1119)
        remedy.timeout = default_timeout

    data["apiVersion"] = WF_API_VERSION
    data["kind"] = WF_KIND
    data["metadata"] = _injected_metadata(
        data, remedy.generate_name, remedy.resource.namespace, hc
    )
    data["spec"] = spec
    return data
