"""Fused flash attention (Pallas) — single-chip attention hot op.

A fused online-softmax attention kernel: for each Q block the kernel
sweeps K/V blocks, keeping the running max/denominator and the output
accumulator in VMEM scratch — the [S, S] score matrix is never
materialized in HBM. This is the op the decode/ring/training probes
lean on XLA fusion for; owning the schedule buys two things XLA cannot
guarantee:

- scores live entirely in VMEM (HBM traffic is O(S·D), not O(S²)), so
  long sequences stay bandwidth-feasible on one chip;
- causal blocks strictly above the diagonal are skipped inside the
  kernel (``pl.when``), so the dead half of the causal grid costs no
  MXU time.

On non-TPU platforms the kernel runs in interpret mode (functionally
identical, slow) so the same code path is exercised by the CPU test
suite — mirrors ops/stream.py.

The grid is (batch, heads, q_blocks, k_blocks) with the K sweep
innermost: TPU grids execute sequentially, so VMEM scratch carries the
online-softmax state across K iterations of one Q block, and the output
block is written once, at each Q row's last visible K block.

Complements ops/ring_attention.py: ring attention shards the sequence
ACROSS chips (ICI traffic, sequence parallelism); flash attention fuses
the per-chip block compute. Reference has no analogue (active-monitor
is a Go controller; this is part of the TPU probe library built per
SURVEY.md §5.7-5.8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
# lane width of the m/l scratch rows; TPU vregs are (8, 128) so scalars
# carried per Q row live broadcast across one 128-lane vector
_LANES = 128


def _make_kernel(causal: bool, block_q: int, block_k: int, num_k: int, scale: float):
    from jax.experimental import pallas as pl

    def kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
        qi = pl.program_id(2)
        ki = pl.program_id(3)

        @pl.when(ki == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)

        # causal: K blocks strictly after this Q block's last row have
        # nothing to attend — skip the matmuls entirely
        q_last = qi * block_q + block_q - 1
        visible = (ki * block_k <= q_last) if causal else (ki >= 0)

        @pl.when(visible)
        def _attend():
            q = q_ref[0, 0].astype(jnp.float32)  # [block_q, D]
            k = k_ref[0, 0].astype(jnp.float32)  # [block_k, D]
            v = v_ref[0, 0].astype(jnp.float32)
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [block_q, block_k]
            if causal:
                q_pos = qi * block_q + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                k_pos = ki * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

            m_prev = m_ref[:]  # [block_q, LANES] (broadcast rows)
            l_prev = l_ref[:]
            m_curr = jnp.max(s, axis=1)[:, None]  # [block_q, 1]
            m_next = jnp.maximum(m_prev, m_curr)  # [block_q, LANES]
            # rows fully masked so far have m_next == NEG_INF; shifting
            # by it would make exp(NEG_INF - NEG_INF)=1 for masked
            # entries, so clamp the shift (the row's p is 0 either way)
            shift = jnp.maximum(m_next[:, :1], _NEG_INF / 2)
            p = jnp.exp(s - shift)  # [block_q, block_k]
            if causal:
                p = jnp.where(q_pos >= k_pos, p, 0.0)
            alpha = jnp.exp(m_prev - jnp.maximum(m_next, _NEG_INF / 2))
            l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1)[:, None]
            m_ref[:] = m_next
            pv = jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [block_q, D]
            acc_ref[:] = acc_ref[:] * alpha[:, :1] + pv

        # write the output once, at this Q block's last visible K block
        last_visible = (q_last // block_k) if causal else (num_k - 1)

        @pl.when(ki == last_visible)
        def _finalize():
            o_ref[0, 0] = (
                acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
            ).astype(o_ref.dtype)

    return kernel


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
    layout: str = "bshd",
) -> jax.Array:
    """Fused attention. ``layout="bshd"`` takes ``[batch, seq, heads,
    head_dim]`` (what ops/ring_attention.py uses) and transposes to the
    kernel's native ``[batch, heads, seq, head_dim]``; pass
    ``layout="bhsd"`` when the caller already keeps heads-major arrays
    to skip the transpose passes (3 HBM round-trips per call).
    Sequence length must be divisible by the block sizes (blocks are
    clamped to seq).

    Default blocks are the measured optimum on v5e (bq=bk=1024:
    ~90 TFLOP/s causal at S=4096, ~4-5x the unfused XLA attention on
    the same chip; bigger blocks exceed the 16 MB scoped-VMEM limit)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if layout == "bshd":
        batch, seq, heads, head_dim = q.shape
    elif layout == "bhsd":
        batch, heads, seq, head_dim = q.shape
    else:
        raise ValueError(f"layout must be bshd or bhsd, got {layout!r}")
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    block_q = min(block_q, seq)
    block_k = min(block_k, seq)
    if seq % block_q or seq % block_k:
        raise ValueError(
            f"seq {seq} not divisible by blocks ({block_q}, {block_k})"
        )
    num_q, num_k = seq // block_q, seq // block_k
    scale = 1.0 / (head_dim ** 0.5)
    interpret = jax.devices()[0].platform != "tpu"

    # [B, S, H, D] -> [B, H, S, D]: the kernel tiles the last two dims
    # (seq-block × head_dim), which is the MXU-friendly layout
    if layout == "bshd":
        qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    else:
        qt, kt, vt = q, k, v

    kernel = _make_kernel(causal, block_q, block_k, num_k, scale)
    spec_q = pl.BlockSpec(
        (1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0)
    )
    spec_kv = pl.BlockSpec(
        (1, 1, block_k, head_dim), lambda b, h, i, j: (b, h, j, 0)
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        grid=(batch, heads, num_q, num_k),
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2) if layout == "bshd" else out


def attention_flops(batch: int, seq: int, heads: int, head_dim: int, causal: bool) -> float:
    """Model FLOPs for one attention forward (QK^T + PV matmuls)."""
    pairs = seq * (seq + 1) / 2 if causal else float(seq * seq)
    return 4.0 * head_dim * batch * heads * pairs
