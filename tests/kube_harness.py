"""Shared harness for cluster-mode tests.

Each coroutine test runs in its own event loop (see conftest.py), so the
stub API server must be started *inside* the test body — an async
context manager, not a fixture.
"""

import asyncio
from contextlib import asynccontextmanager

from activemonitor_tpu.kube import KubeApi, KubeConfig
from activemonitor_tpu.kube.stub import StubApiServer


@asynccontextmanager
async def stub_env(token: str = ""):
    """An in-process API server plus a client pointed at it.

    The HealthCheck CRD schema is installed, so every cluster-mode test
    runs under real server-side 422 validation — any schema-invalid
    object the controller writes fails the test, the way envtest's real
    apiserver would fail the reference's suite."""
    from activemonitor_tpu.api.crd import build_crd

    server = StubApiServer(token=token)
    server.register_crd(build_crd())
    await server.start()
    api = KubeApi(KubeConfig(server=server.url, token=token))
    try:
        yield server, api
    finally:
        await api.close()
        await server.stop()


def hard_kill_shards(coord) -> None:
    """Simulate a process crash for a shard coordinator: every lease
    (shard AND member/presence) stops renewing WITHOUT release — the
    corpse a real crash leaves behind for the survivors' expiry-based
    adoption. One definition so the tier-1 chaos slice, the 50k soak,
    and the unit tier can never drift on what 'hard kill' means."""
    electors = list(coord.set.owned.values())
    if coord.set.member is not None:
        electors.append(coord.set.member)
    for elector in electors:
        if elector._renew_task is not None:
            elector._renew_task.cancel()
        elector._stop = True
    for task in coord.set._tasks:
        task.cancel()
    coord.set._stopping = True


async def advance(clock, seconds, step=2.5):
    """Advance a FakeClock in small steps with real-time pauses so HTTP
    roundtrips triggered by woken coroutines can complete."""
    remaining = seconds
    while remaining > 0:
        await clock.advance(min(step, remaining))
        await asyncio.sleep(0.05)
        remaining -= step


async def drive_until(clock, predicate, max_seconds=60.0, step=2.5):
    """Fake-clock-aware wait: everything time-driven (workflow polls,
    election, timers) sleeps on the FakeClock — interleave predicate
    checks with clock advances, stopping the moment the predicate holds
    so fake time never runs ahead of the scenario."""
    elapsed = 0.0
    while True:
        result = await predicate()
        if result:
            return result
        if elapsed >= max_seconds:
            raise TimeoutError(f"condition not met after {elapsed}s fake time")
        await advance(clock, step)
        elapsed += step
