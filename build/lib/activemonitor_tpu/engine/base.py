"""Workflow execution boundary.

The reference talks to Argo only through Workflow CRs — create one, then
poll its ``status.phase`` across a process boundary
(reference: healthcheck_controller.go:502-534 submit, :617 poll). That
boundary is reproduced here as a small protocol with three
implementations:

- :class:`~activemonitor_tpu.engine.fake.FakeWorkflowEngine` — data model
  real, no executor (the envtest trick, SURVEY.md §4): for tests.
- :class:`~activemonitor_tpu.engine.local.LocalProcessEngine` — executes
  workflow steps as local subprocesses: single-host TPU probe mode, no
  Kubernetes required.
- :class:`~activemonitor_tpu.engine.argo.ArgoWorkflowEngine` — real Argo
  Workflow CRs via the Kubernetes API (import-gated).
"""

from __future__ import annotations

from typing import Optional, Protocol

# GVK constants for Argo Workflow objects
# (reference: healthcheck_controller.go:53-57)
WF_API_VERSION = "argoproj.io/v1alpha1"
WF_KIND = "Workflow"

# instance-id label contract every submitted workflow carries
# (reference: healthcheck_controller.go:64-65); also scopes the Argo
# engine's watch cache to this controller's workflows
WF_INSTANCE_ID_LABEL_KEY = "workflows.argoproj.io/controller-instanceid"
WF_INSTANCE_ID = "activemonitor-workflows"

PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"
PHASE_RUNNING = "Running"
PHASE_PENDING = "Pending"


class WorkflowEngine(Protocol):
    """Submit and poll probe workflows."""

    async def submit(self, manifest: dict) -> str:
        """Create the workflow; returns the generated name.

        ``manifest`` carries metadata.namespace and metadata.generateName;
        the engine resolves the final name (like the API server does for
        generateName).
        """
        ...

    async def get(self, namespace: str, name: str) -> Optional[dict]:
        """Return the workflow object (with ``status.phase`` once known)
        or None if it does not exist (deleted / GC'd)."""
        ...


def generate_name(prefix: str) -> str:
    """Kubernetes-style generateName suffix: 5 chars from the reduced
    alphanumeric alphabet the API server uses."""
    import random

    alphabet = "bcdfghjklmnpqrstvwxz2456789"
    return prefix + "".join(random.choices(alphabet, k=5))
