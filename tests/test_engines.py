"""Workflow engine tests: fake scripting and local-process execution."""

import asyncio
import sys

import pytest

from activemonitor_tpu.engine import (
    FakeWorkflowEngine,
    LocalProcessEngine,
    fail_after,
    succeed_after,
)

MANIFEST = {
    "apiVersion": "argoproj.io/v1alpha1",
    "kind": "Workflow",
    "metadata": {"generateName": "probe-", "namespace": "health"},
    "spec": {"entrypoint": "main", "templates": []},
}


@pytest.mark.asyncio
async def test_fake_submit_generates_name():
    eng = FakeWorkflowEngine()
    name = await eng.submit(MANIFEST)
    assert name.startswith("probe-") and len(name) > len("probe-")
    wf = await eng.get("health", name)
    assert wf["metadata"]["name"] == name


@pytest.mark.asyncio
async def test_fake_default_never_completes():
    eng = FakeWorkflowEngine()
    name = await eng.submit(MANIFEST)
    for _ in range(5):
        wf = await eng.get("health", name)
    assert "status" not in wf or wf["status"].get("phase") not in ("Succeeded", "Failed")


@pytest.mark.asyncio
async def test_fake_succeed_after_and_outputs():
    outputs = {"parameters": [{"name": "m", "value": '{"metrics": []}'}]}
    eng = FakeWorkflowEngine(succeed_after(2, outputs=outputs))
    name = await eng.submit(MANIFEST)
    wf1 = await eng.get("health", name)
    assert wf1.get("status") is None
    wf2 = await eng.get("health", name)
    assert wf2["status"]["phase"] == "Succeeded"
    assert wf2["status"]["outputs"] == outputs


@pytest.mark.asyncio
async def test_fake_prefix_scripting():
    eng = FakeWorkflowEngine(succeed_after(1))
    eng.on_prefix("bad-", fail_after(1, "boom"))
    good = await eng.submit(MANIFEST)
    bad = await eng.submit({**MANIFEST, "metadata": {"generateName": "bad-", "namespace": "health"}})
    assert (await eng.get("health", good))["status"]["phase"] == "Succeeded"
    assert (await eng.get("health", bad))["status"]["phase"] == "Failed"
    assert (await eng.get("health", bad))["status"]["message"] == "boom"


@pytest.mark.asyncio
async def test_fake_get_missing_returns_none():
    eng = FakeWorkflowEngine()
    assert await eng.get("health", "nope") is None


@pytest.mark.asyncio
async def test_fake_delete_owned_by():
    eng = FakeWorkflowEngine()
    m = {**MANIFEST, "metadata": {**MANIFEST["metadata"], "ownerReferences": [{"uid": "u1"}]}}
    await eng.submit(m)
    await eng.submit(m)
    await eng.submit(MANIFEST)
    assert eng.delete_owned_by("u1") == 2
    assert len(eng.workflows) == 1


# -- local process engine ---------------------------------------------


def container_wf(command, args=None, deadline=None):
    spec = {
        "entrypoint": "main",
        "templates": [
            {"name": "main", "container": {"image": "ignored", "command": command, "args": args or []}}
        ],
    }
    if deadline is not None:
        spec["activeDeadlineSeconds"] = deadline
    return {
        "metadata": {"generateName": "local-", "namespace": "default"},
        "spec": spec,
    }


async def wait_terminal(eng, name, timeout=10.0):
    for _ in range(int(timeout / 0.05)):
        wf = await eng.get("default", name)
        if wf["status"]["phase"] in ("Succeeded", "Failed"):
            return wf
        await asyncio.sleep(0.05)
    raise TimeoutError(wf)


@pytest.mark.asyncio
async def test_local_container_success():
    eng = LocalProcessEngine()
    name = await eng.submit(container_wf(["/bin/sh", "-c"], ["exit 0"]))
    wf = await wait_terminal(eng, name)
    assert wf["status"]["phase"] == "Succeeded"


@pytest.mark.asyncio
async def test_local_container_failure_has_message():
    eng = LocalProcessEngine()
    name = await eng.submit(container_wf(["/bin/sh", "-c"], ["echo oh no; exit 3"]))
    wf = await wait_terminal(eng, name)
    assert wf["status"]["phase"] == "Failed"
    assert "exited 3" in wf["status"]["message"]
    assert "oh no" in wf["status"]["message"]


@pytest.mark.asyncio
async def test_local_deadline_kills_and_fails():
    eng = LocalProcessEngine()
    name = await eng.submit(container_wf(["/bin/sh", "-c"], ["sleep 30"], deadline=1))
    wf = await wait_terminal(eng, name, timeout=15)
    assert wf["status"]["phase"] == "Failed"
    assert "activeDeadlineSeconds" in wf["status"]["message"]


@pytest.mark.asyncio
async def test_local_script_template():
    eng = LocalProcessEngine()
    manifest = {
        "metadata": {"generateName": "script-", "namespace": "default"},
        "spec": {
            "entrypoint": "main",
            "templates": [
                {
                    "name": "main",
                    "script": {
                        "command": [sys.executable],
                        "source": "print('hello from probe')",
                    },
                }
            ],
        },
    }
    name = await eng.submit(manifest)
    wf = await wait_terminal(eng, name)
    assert wf["status"]["phase"] == "Succeeded"


@pytest.mark.asyncio
async def test_local_metrics_contract_captured_as_outputs():
    payload = '{"metrics": [{"name": "bw", "value": 42.0, "metrictype": "gauge", "help": "x"}]}'
    eng = LocalProcessEngine()
    name = await eng.submit(
        container_wf(["/bin/sh", "-c"], [f"echo 'starting'; echo '{payload}'"])
    )
    wf = await wait_terminal(eng, name)
    assert wf["status"]["phase"] == "Succeeded"
    params = wf["status"]["outputs"]["parameters"]
    assert params[0]["value"] == payload


@pytest.mark.asyncio
async def test_local_steps_run_sequentially(tmp_path):
    out = tmp_path / "order.txt"
    manifest = {
        "metadata": {"generateName": "steps-", "namespace": "default"},
        "spec": {
            "entrypoint": "main",
            "templates": [
                {
                    "name": "main",
                    "steps": [[{"name": "a", "template": "one"}], [{"name": "b", "template": "two"}]],
                },
                {"name": "one", "container": {"command": ["/bin/sh", "-c"], "args": [f"echo 1 >> {out}"]}},
                {"name": "two", "container": {"command": ["/bin/sh", "-c"], "args": [f"echo 2 >> {out}"]}},
            ],
        },
    }
    eng = LocalProcessEngine()
    name = await eng.submit(manifest)
    wf = await wait_terminal(eng, name)
    assert wf["status"]["phase"] == "Succeeded"
    assert out.read_text().split() == ["1", "2"]


@pytest.mark.asyncio
async def test_local_bad_entrypoint_fails():
    eng = LocalProcessEngine()
    name = await eng.submit(
        {"metadata": {"generateName": "bad-", "namespace": "default"},
         "spec": {"entrypoint": "missing", "templates": []}}
    )
    wf = await wait_terminal(eng, name)
    assert wf["status"]["phase"] == "Failed"


@pytest.mark.asyncio
async def test_local_ttl_prunes_finished_workflows():
    eng = LocalProcessEngine(default_ttl_seconds=0.2)
    eng.MIN_TTL_SECONDS = 0.0  # tests bypass the safety floor
    name = await eng.submit(container_wf(["/bin/true"]))
    await wait_terminal(eng, name)
    assert await eng.get("default", name) is not None
    await asyncio.sleep(0.3)
    # pruning happens on the next submit
    other = await eng.submit(container_wf(["/bin/true"]))
    assert await eng.get("default", name) is None
    await wait_terminal(eng, other)


@pytest.mark.asyncio
async def test_local_ttl_respects_manifest_override():
    eng = LocalProcessEngine(default_ttl_seconds=0.1)
    eng.MIN_TTL_SECONDS = 0.0
    wf = container_wf(["/bin/true"])
    wf["spec"]["ttlSecondsAfterFinished"] = 3600
    name = await eng.submit(wf)
    await wait_terminal(eng, name)
    await asyncio.sleep(0.3)
    await eng.submit(container_wf(["/bin/true"]))
    assert await eng.get("default", name) is not None  # long TTL kept it
