"""End-to-end cluster mode: the full controller against the stub API
server — KubernetesHealthCheckClient + ArgoWorkflowEngine +
KubernetesRBACBackend + KubernetesEventRecorder under the Manager,
with the test playing the Argo controller (patching Workflow status),
and a kubectl-equivalent client applying the HealthCheck.

This is the automated version of the reference's manual kind flow
(reference: README.md:54-79) and the check VERDICT round 1 asked for:
apply a check, assert Succeeded/counters/events/RBAC objects — all
through the real REST path.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import RBACProvisioner
from activemonitor_tpu.controller.client_k8s import KubernetesHealthCheckClient
from activemonitor_tpu.controller.events import KubernetesEventRecorder
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.controller.rbac import KubernetesRBACBackend
from activemonitor_tpu.controller.reconciler import HealthCheckReconciler
from activemonitor_tpu.engine.argo import WF_GROUP, WF_PLURAL, WF_VERSION, ArgoWorkflowEngine
from activemonitor_tpu.kube import api_path
from activemonitor_tpu.metrics import MetricsCollector

from tests.kube_harness import stub_env

RBAC_GROUP = "rbac.authorization.k8s.io"

INLINE_HELLO = """
apiVersion: argoproj.io/v1alpha1
kind: Workflow
metadata:
  generateName: hello-tpu-
spec:
  entrypoint: main
  templates:
    - name: main
      container:
        image: python:3.12-slim
        command: [python, -c, "print('hello')"]
"""


def hello_check():
    return HealthCheck.from_dict(
        {
            "metadata": {"name": "inline-hello", "namespace": "health"},
            "spec": {
                "repeatAfterSec": 60,
                "level": "cluster",
                "workflow": {
                    "generateName": "hello-tpu-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "hello-sa",
                        "source": {"inline": INLINE_HELLO},
                    },
                },
            },
        }
    )


async def wait_for(predicate, timeout=10.0, interval=0.05):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        result = await predicate()
        if result:
            return result
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError("condition not met")
        await asyncio.sleep(interval)


@pytest.mark.asyncio
async def test_full_cluster_mode_check_lifecycle():
    async with stub_env() as (server, api):
        client = KubernetesHealthCheckClient(api)
        recorder = KubernetesEventRecorder(api)
        metrics = MetricsCollector()
        reconciler = HealthCheckReconciler(
            client=client,
            engine=ArgoWorkflowEngine(api),
            rbac=RBACProvisioner(KubernetesRBACBackend(api)),
            recorder=recorder,
            metrics=metrics,
        )
        manager = Manager(client=client, reconciler=reconciler, max_parallel=4)
        await manager.start()
        try:
            # "kubectl apply" through a second, independent session
            await client.apply(hello_check())

            # the controller submits a real Workflow CR
            workflows = await wait_for(
                lambda: asyncio.sleep(0, server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))
            )
            wf = workflows[0]
            assert wf["metadata"]["name"].startswith("hello-tpu-")
            assert wf["metadata"]["namespace"] == "health"
            # ownerRef enables GC of workflows on HC delete
            # (reference: healthcheck_controller.go:512-522)
            owner = wf["metadata"]["ownerReferences"][0]
            assert owner["kind"] == "HealthCheck" and owner["name"] == "inline-hello"
            # spec mutation parity: SA + instance-id label injected
            assert wf["spec"]["serviceAccountName"] == "hello-sa"

            # per-check RBAC is REAL cluster state now
            assert server.obj("", "v1", "serviceaccounts", "health", "hello-sa")
            assert server.obj(RBAC_GROUP, "v1", "clusterroles", "", "hello-sa-cluster-role")
            assert server.obj(
                RBAC_GROUP, "v1", "clusterrolebindings", "", "hello-sa-cluster-role-binding"
            )

            # play the Argo controller: complete the workflow via the API
            await api.merge_patch(
                api_path(
                    WF_GROUP, WF_VERSION, WF_PLURAL,
                    "health", wf["metadata"]["name"], "status",
                ),
                {"status": {"phase": "Succeeded"}},
            )

            async def succeeded():
                hc = await client.get("health", "inline-hello")
                return hc if hc and hc.status.status == "Succeeded" else None

            hc = await wait_for(succeeded)
            assert hc.status.success_count == 1
            assert hc.status.total_healthcheck_runs == 1
            assert hc.status.last_successful_workflow == wf["metadata"]["name"]

            # Events were posted as core/v1 objects
            await recorder.flush()
            reasons = {e["reason"] for e in server.objs("", "v1", "events")}
            assert "Normal" in reasons or len(reasons) > 0
            messages = [e["message"] for e in server.objs("", "v1", "events")]
            assert any("Succeeded" in m for m in messages)

            # metrics recorded through the same path as local mode
            assert (
                metrics.sample_value(
                    "healthcheck_success_count",
                    {"healthcheck_name": "inline-hello", "workflow": "healthCheck"},
                )
                == 1
            )
        finally:
            await manager.stop()


@pytest.mark.asyncio
async def test_cluster_mode_delete_stops_timer_and_cleans_up():
    async with stub_env() as (server, api):
        client = KubernetesHealthCheckClient(api)
        reconciler = HealthCheckReconciler(
            client=client,
            engine=ArgoWorkflowEngine(api),
            rbac=RBACProvisioner(KubernetesRBACBackend(api)),
            recorder=KubernetesEventRecorder(api),
            metrics=MetricsCollector(),
        )
        manager = Manager(client=client, reconciler=reconciler, max_parallel=2)
        await manager.start()
        try:
            await client.apply(hello_check())
            await wait_for(
                lambda: asyncio.sleep(0, server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))
            )
            # delete while the workflow is in flight: the reconciler
            # observes the deletion and stops the schedule
            await client.delete("health", "inline-hello")

            async def timer_gone():
                return not reconciler.timers.exists("health/inline-hello")

            await wait_for(timer_gone)
            assert await client.get("health", "inline-hello") is None
        finally:
            await manager.stop()


@pytest.mark.asyncio
async def test_cluster_mode_remedy_lifecycle():
    """Failure path in cluster mode: the check fails, the remedy runs
    under its OWN ephemeral write-scoped RBAC (reference: remedy rules
    :104-120, delete after :779), remedy status lands, and the remedy
    RBAC is gone afterwards while the check RBAC stays."""
    remedy_inline = INLINE_HELLO.replace("hello-tpu-", "remedy-tpu-")
    hc = HealthCheck.from_dict(
        {
            "metadata": {"name": "remedy-check", "namespace": "health"},
            "spec": {
                "repeatAfterSec": 60,
                "level": "cluster",
                "workflow": {
                    "generateName": "hello-tpu-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "check-sa",
                        "source": {"inline": INLINE_HELLO},
                    },
                },
                "remedyworkflow": {
                    "generateName": "remedy-tpu-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "fix-sa",
                        "source": {"inline": remedy_inline},
                    },
                },
            },
        }
    )
    async with stub_env() as (server, api):
        client = KubernetesHealthCheckClient(api)
        reconciler = HealthCheckReconciler(
            client=client,
            engine=ArgoWorkflowEngine(api),
            rbac=RBACProvisioner(KubernetesRBACBackend(api)),
            recorder=KubernetesEventRecorder(api),
            metrics=MetricsCollector(),
        )
        manager = Manager(client=client, reconciler=reconciler, max_parallel=2)
        await manager.start()
        try:
            await client.apply(hc)
            workflows = await wait_for(
                lambda: asyncio.sleep(0, server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))
            )
            check_wf = workflows[0]["metadata"]["name"]
            assert check_wf.startswith("hello-tpu-")
            # fail the check -> the remedy must be provisioned + submitted
            await api.merge_patch(
                api_path(WF_GROUP, WF_VERSION, WF_PLURAL, "health", check_wf, "status"),
                {"status": {"phase": "Failed", "message": "probe died"}},
            )

            async def remedy_wf():
                for wf in server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
                    if wf["metadata"]["name"].startswith("remedy-tpu-"):
                        return wf
                return None

            wf = await wait_for(remedy_wf)
            assert wf["spec"]["serviceAccountName"] == "fix-sa"
            # remedy RBAC exists while the remedy is in flight, with
            # WRITE verbs (the check role is read-only)
            fix_role = server.obj(RBAC_GROUP, "v1", "clusterroles", "", "fix-sa-cluster-role")
            assert fix_role is not None
            fix_verbs = {v for rule in fix_role["rules"] for v in rule["verbs"]}
            assert {"create", "delete"} <= fix_verbs
            # the check role is read-only except the documented
            # workflowtaskresults divergence (Argo >=3.4 executor reporting)
            check_role = server.obj(RBAC_GROUP, "v1", "clusterroles", "", "check-sa-cluster-role")
            writable = {
                (group, resource)
                for rule in check_role["rules"]
                for group in rule["apiGroups"]
                for resource in rule["resources"]
                if {"create", "update", "patch", "delete"} & set(rule["verbs"])
            }
            assert writable == {("argoproj.io", "workflowtaskresults")}

            await api.merge_patch(
                api_path(
                    WF_GROUP, WF_VERSION, WF_PLURAL,
                    "health", wf["metadata"]["name"], "status",
                ),
                {"status": {"phase": "Succeeded"}},
            )

            async def remedy_done():
                got = await client.get("health", "remedy-check")
                return got if got and got.status.remedy_success_count == 1 else None

            got = await wait_for(remedy_done)
            assert got.status.status == "Failed"  # the CHECK failed
            assert got.status.remedy_total_runs == 1
            assert got.status.failed_count == 1

            # ephemeral remedy RBAC deleted after the run; check RBAC stays
            async def remedy_rbac_gone():
                return (
                    server.obj(RBAC_GROUP, "v1", "clusterroles", "", "fix-sa-cluster-role")
                    is None
                    and server.obj("", "v1", "serviceaccounts", "health", "fix-sa") is None
                )

            await wait_for(remedy_rbac_gone)
            assert server.obj("", "v1", "serviceaccounts", "health", "check-sa")
        finally:
            await manager.stop()


@pytest.mark.asyncio
async def test_cluster_mode_soak_with_churn_and_gc():
    """Half an hour of simulated schedule churn through the FULL
    cluster-mode stack — REST client, validating stub, argo engine
    watch cache, real RBAC objects, ownerRef GC. Complements the
    in-memory soak tier (tests/test_stress.py): here every status
    write crosses HTTP and server-side schema validation, and deleted
    checks' workflows must be garbage-collected by the stub, not
    assumed away. Invariants are quantified: per-check run counts,
    bounded live watch connections on the server, and zero surviving
    workflows owned by deleted checks."""
    from activemonitor_tpu.utils.clock import FakeClock

    N = 24
    SIM = 1800  # 30 simulated minutes, 300 s cadence -> ~6 runs/check

    def soak_check(i):
        return HealthCheck.from_dict(
            {
                "metadata": {"name": f"csoak-{i:02d}", "namespace": "health"},
                "spec": {
                    "repeatAfterSec": 300,
                    "level": "cluster",
                    "workflow": {
                        "generateName": f"csoak-{i:02d}-",
                        "workflowtimeout": 30,
                        "resource": {
                            "namespace": "health",
                            "serviceAccount": f"csoak-sa-{i:02d}",
                            "source": {"inline": INLINE_HELLO},
                        },
                    },
                },
            }
        )

    async with stub_env() as (server, api):
        clock = FakeClock()
        client = KubernetesHealthCheckClient(api)
        reconciler = HealthCheckReconciler(
            client=client,
            engine=ArgoWorkflowEngine(api),
            rbac=RBACProvisioner(KubernetesRBACBackend(api)),
            recorder=KubernetesEventRecorder(api),
            metrics=MetricsCollector(),
            clock=clock,
        )
        manager = Manager(client=client, reconciler=reconciler, max_parallel=8)
        await manager.start()

        async def play_argo():
            """Complete every Running workflow, like Argo would."""
            for wf in server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
                status = wf.get("status") or {}
                if status.get("phase") in ("Succeeded", "Failed"):
                    continue
                await api.merge_patch(
                    api_path(
                        WF_GROUP, WF_VERSION, WF_PLURAL,
                        wf["metadata"]["namespace"],
                        wf["metadata"]["name"],
                        "status",
                    ),
                    {"status": {"phase": "Succeeded"}},
                )

        async def run_sim(seconds):
            for _ in range(seconds // 15):
                await clock.advance(15)
                await asyncio.sleep(0.03)  # let HTTP roundtrips land
                await play_argo()
                await asyncio.sleep(0.02)

        churned = [f"csoak-{i:02d}" for i in range(6)]
        deleted_uids = set()
        try:
            for i in range(N):
                await client.apply(soak_check(i))
            await asyncio.sleep(0.3)
            await run_sim(600)
            # churn: delete a quarter; their workflows must be GC'd
            for name in churned:
                hc = await client.get("health", name)
                deleted_uids.add(hc.metadata.uid)
                await client.delete("health", name)
            await asyncio.sleep(0.3)
            for wf in server.objs(WF_GROUP, WF_VERSION, WF_PLURAL):
                refs = wf["metadata"].get("ownerReferences") or []
                assert not any(r.get("uid") in deleted_uids for r in refs), wf[
                    "metadata"
                ]["name"]
            await run_sim(600)
            for i, name in enumerate(churned):  # same names return
                await client.apply(soak_check(i))
            await asyncio.sleep(0.3)
            await run_sim(SIM - 1200)
            # drain any in-flight run then quiesce
            for _ in range(6):
                await clock.advance(15)
                await asyncio.sleep(0.05)
                await play_argo()
            await reconciler.wait_watches()

            for i in range(N):
                name = f"csoak-{i:02d}"
                hc = await client.get("health", name)
                runs = hc.status.total_healthcheck_runs
                if name in churned:
                    assert 3 <= runs <= 9, (name, runs)
                else:
                    assert 4 <= runs <= 9, (name, runs)
                assert hc.status.status == "Succeeded", (name, hc.status)
            # live watch connections on the SERVER stay bounded: the
            # controller's healthcheck watch + per-namespace argo watch
            # (reconnects must replace, not accumulate)
            assert server.live_watch_count() <= 4, server.live_watch_count()
            # workflow population ≈ one per completed run (nothing
            # double-submitted; deleted checks' workflows gone)
            wf_count = len(server.objs(WF_GROUP, WF_VERSION, WF_PLURAL))
            total_runs = 0
            for i in range(N):
                hc = await client.get("health", f"csoak-{i:02d}")
                total_runs += hc.status.total_healthcheck_runs
            assert wf_count <= total_runs + N, (wf_count, total_runs)
            # per-check RBAC is reused, not re-minted per run
            sas = [
                o["metadata"]["name"]
                for o in server.objs("", "v1", "serviceaccounts")
            ]
            assert len(sas) == len(set(sas)) and len(sas) <= N
        finally:
            await manager.stop()
