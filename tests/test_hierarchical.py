"""Hierarchical DCN×ICI collective tests (ISSUE 13).

The two-tier compositions (parallel/schedules.py) must be allclose to
the joint ``lax.psum`` across composed meshes, send exactly their
per-tier hop budgets (``_HOP_TIER_LOG`` vs ``theoretical_hier_hops``),
and collapse BITWISE to the flat schedules on a degenerate 1-slice
mesh. The tier-keyed autotuner must keep its tiers separate, tune a
latency-path threshold from an injectable bench, and the tuned
surface must demonstrably flip between the latency and bandwidth
compositions across it — all on the virtual 8-device CPU mesh."""

import collections
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import activemonitor_tpu.parallel.schedules as schedules
from activemonitor_tpu.parallel import autotune
from activemonitor_tpu.parallel.partition import (
    resolve_tiers,
    shard_map,
)
from activemonitor_tpu.parallel.schedules import (
    hier_all_gather,
    hier_all_reduce,
    hier_all_reduce_bandwidth,
    hier_all_reduce_latency,
    hier_reduce_scatter,
    hier_reduce_scatter_slot,
    theoretical_hier_hops,
)

DCN, ICI = "dcn", "ici"


def tier_mesh(n_dcn, n_ici):
    devices = jax.devices()[: n_dcn * n_ici]
    return Mesh(np.array(devices).reshape(n_dcn, n_ici), (DCN, ICI))


def apply_tiered(mesh, fn, x, gathered=False):
    out_specs = P(None) if gathered else P((DCN, ICI))
    run = shard_map(
        fn, mesh=mesh, in_specs=P((DCN, ICI)), out_specs=out_specs,
        check_vma=False,
    )
    return run(x)


def tier_hops(mesh, fn, x):
    """Per-tier hop counts of one traced application."""
    schedules._HOP_TIER_LOG = log = []
    try:
        apply_tiered(mesh, fn, x)
    finally:
        schedules._HOP_TIER_LOG = None
    counts = collections.Counter(axis for axis, _tag, _step in log)
    return {DCN: counts.get(DCN, 0), ICI: counts.get(ICI, 0)}


# ---------------------------------------------------------------------------
# schedule correctness + per-tier hop contracts
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("shape", [(2, 2), (2, 3), (2, 4), (4, 2)])
@pytest.mark.parametrize("variant", ["bandwidth", "latency"])
def test_hier_all_reduce_matches_psum(shape, variant):
    """allclose vs the joint psum across composed meshes, odd 5
    rows/shard so the bandwidth path's ici padding is exercised."""
    n_dcn, n_ici = shape
    mesh = tier_mesh(n_dcn, n_ici)
    n = n_dcn * n_ici
    x = jax.random.normal(jax.random.key(n), (n * 5, 3), jnp.float32)
    fn = (
        (lambda v: hier_all_reduce(v, DCN, ICI, n_dcn, n_ici))
        if variant == "bandwidth"
        else (lambda v: hier_all_reduce_latency(v, DCN, ICI, n_dcn, n_ici))
    )
    got = apply_tiered(mesh, fn, x)
    want = apply_tiered(mesh, lambda v: jax.lax.psum(v, (DCN, ICI)), x)
    assert jnp.allclose(got, want, atol=1e-5), (
        shape, variant, float(jnp.max(jnp.abs(got - want)))
    )


@pytest.mark.parametrize(
    "shape,dcn_schedule",
    [((2, 4), "recdouble"), ((2, 4), "tree"), ((2, 2), "rsag"),
     ((4, 2), "recdouble")],
)
def test_hier_bandwidth_per_tier_hop_budget(shape, dcn_schedule):
    """The bandwidth composition sends exactly 2(n_ici−1) ICI rounds
    (rs+ag) and the dcn schedule's own round count over DCN — counted
    per tier via _HOP_TIER_LOG, pinned by theoretical_hier_hops."""
    n_dcn, n_ici = shape
    mesh = tier_mesh(n_dcn, n_ici)
    x = jnp.ones((n_dcn * n_ici * 4, 2 + n_dcn + n_ici), jnp.float32)
    got = tier_hops(
        mesh,
        lambda v: hier_all_reduce(
            v, DCN, ICI, n_dcn, n_ici, dcn_schedule=dcn_schedule
        ),
        x,
    )
    want = theoretical_hier_hops(
        n_dcn, n_ici, "bandwidth", dcn_schedule=dcn_schedule
    )
    assert got == want, (shape, dcn_schedule, got, want)


@pytest.mark.parametrize("ici_schedule", ["recdouble", "tree"])
def test_hier_latency_per_tier_hop_budget(ici_schedule):
    n_dcn, n_ici = 2, 4
    mesh = tier_mesh(n_dcn, n_ici)
    x = jnp.ones((8 * 2, 3 + len(ici_schedule)), jnp.float32)
    got = tier_hops(
        mesh,
        lambda v: hier_all_reduce_latency(
            v, DCN, ICI, n_dcn, n_ici, ici_schedule=ici_schedule
        ),
        x,
    )
    want = theoretical_hier_hops(
        n_dcn, n_ici, "latency", ici_schedule=ici_schedule
    )
    assert got == want, (ici_schedule, got, want)


def test_hier_xla_dcn_tier_issues_no_explicit_dcn_hops():
    """A tier riding its XLA builtin ("xla" psum for the scattered
    exchange) issues zero explicit hops on that tier — the contract
    theoretical_hier_hops states."""
    mesh = tier_mesh(2, 4)
    x = jnp.ones((8 * 4, 5), jnp.float32)
    got = tier_hops(
        mesh,
        lambda v: hier_all_reduce(v, DCN, ICI, 2, 4, dcn_schedule="xla"),
        x,
    )
    assert got == {DCN: 0, ICI: 6}
    assert theoretical_hier_hops(2, 4, "bandwidth", dcn_schedule="xla") == {
        "ici": 6, "dcn": 0,
    }


def test_hier_degenerate_single_slice_is_bitwise_flat():
    """On a 1-slice ("dcn"=1) mesh the bandwidth composition IS the
    flat rsag — bitwise — and the gather composition the flat ring."""
    mesh = tier_mesh(1, 8)
    x = jax.random.normal(jax.random.key(7), (8 * 5, 3), jnp.float32)
    got = apply_tiered(mesh, lambda v: hier_all_reduce(v, DCN, ICI, 1, 8), x)
    want = apply_tiered(
        mesh, lambda v: schedules.all_reduce_rsag(v, ICI, 8), x
    )
    assert bool((got == want).all())
    gathered = apply_tiered(
        mesh, lambda v: hier_all_gather(v, DCN, ICI, 1, 8), x, gathered=True
    )
    flat = apply_tiered(
        mesh, lambda v: schedules.all_gather_ring(v, ICI, 8), x,
        gathered=True,
    )
    assert bool((gathered == flat).all())


@pytest.mark.parametrize("shape", [(2, 4), (2, 3), (4, 2)])
def test_hier_all_gather_bitwise_matches_joint_gather(shape):
    """The two-tier gather only MOVES data: bitwise equality with the
    joint ``lax.all_gather((dcn, ici), tiled=True)`` — the dcn-major
    P(("dcn","ici")) layout — is the contract."""
    n_dcn, n_ici = shape
    mesh = tier_mesh(n_dcn, n_ici)
    n = n_dcn * n_ici

    @partial(
        shard_map, mesh=mesh, in_specs=P((DCN, ICI)), out_specs=P(None),
        check_vma=False,
    )
    def diff(v):
        got = hier_all_gather(v, DCN, ICI, n_dcn, n_ici)
        want = jax.lax.all_gather(v, (DCN, ICI), tiled=True)
        return jnp.max(jnp.abs(got - want))[None]

    x = jax.random.normal(jax.random.key(3 + n), (n * 5, 2), jnp.float32)
    assert float(diff(x)[0]) == 0.0


def test_hier_reduce_scatter_slots_and_divisibility():
    n_dcn, n_ici = 2, 4
    n = n_dcn * n_ici
    mesh = tier_mesh(n_dcn, n_ici)
    rows = n  # one row per global chunk
    x = jax.random.normal(jax.random.key(9), (n * rows, 2), jnp.float32)

    @partial(
        shard_map, mesh=mesh, in_specs=P((DCN, ICI)),
        out_specs=P((DCN, ICI)), check_vma=False,
    )
    def scattered(v):
        return hier_reduce_scatter(v, DCN, ICI, n_dcn, n_ici)

    got = scattered(x)
    full = np.asarray(x).reshape(n, rows, 2).sum(axis=0)
    for d in range(n_dcn):
        for i in range(n_ici):
            device = d * n_ici + i
            slot = hier_reduce_scatter_slot(n_dcn, n_ici, d, i)
            assert np.allclose(
                np.asarray(got)[device], full[slot], atol=1e-5
            ), (d, i, slot)
    with pytest.raises(ValueError, match="hierarchical chunks"):
        apply_tiered(
            mesh,
            lambda v: hier_reduce_scatter(v, DCN, ICI, n_dcn, n_ici),
            jnp.ones((n * 3, 2), jnp.float32),  # 3 rows/shard: not /8
        )


def test_theoretical_hier_hops_table():
    assert theoretical_hier_hops(2, 4, "bandwidth") == {"ici": 6, "dcn": 1}
    assert theoretical_hier_hops(2, 4, "latency") == {"ici": 2, "dcn": 1}
    assert theoretical_hier_hops(1, 8, "bandwidth") == {"ici": 14, "dcn": 0}
    assert theoretical_hier_hops(4, 1, "bandwidth") == {"ici": 0, "dcn": 2}
    assert theoretical_hier_hops(
        2, 3, "bandwidth", dcn_schedule="tree"
    ) == {"ici": 4, "dcn": 2}
    assert theoretical_hier_hops(
        2, 2, "latency", ici_schedule="tree"
    ) == {"ici": 2, "dcn": 1}
    assert theoretical_hier_hops(2, 4, collective="allgather") == {
        "ici": 3, "dcn": 1,
    }
    assert theoretical_hier_hops(2, 4, collective="reducescatter") == {
        "ici": 3, "dcn": 1,
    }
    with pytest.raises(ValueError, match="unknown hierarchical variant"):
        theoretical_hier_hops(2, 4, "bogus")
    with pytest.raises(ValueError, match="unknown hierarchical collective"):
        theoretical_hier_hops(2, 4, collective="alltoall")


def test_hier_bench_wrapper_reports_flat_conventions():
    """The timed wrapper reports busbw in the flat all-reduce
    convention (2(n−1)/n, n = TOTAL devices) for all three variants,
    so tiered and flat numbers compare directly."""
    mesh = tier_mesh(2, 4)
    for variant in ("bandwidth", "latency", "flat"):
        r = hier_all_reduce_bandwidth(
            mesh, size_mb=0.01, iters=1, variant=variant
        )
        assert r.n_devices == 8
        assert r.algbw_gbps > 0
        assert r.busbw_gbps == pytest.approx(r.algbw_gbps * 2 * 7 / 8)
    with pytest.raises(ValueError, match="unknown hierarchical bench"):
        hier_all_reduce_bandwidth(mesh, size_mb=0.01, variant="bogus")


# ---------------------------------------------------------------------------
# tier-keyed autotuner + latency threshold
# ---------------------------------------------------------------------------


def test_tier_keyed_table_keeps_tiers_separate():
    autotune.clear()
    try:
        autotune.record(
            "allreduce", 2, 4096, jnp.float32, {"tree": 2.0, "xla": 1.0},
            tier="dcn",
        )
        assert (
            autotune.lookup("allreduce", 2, 4096, jnp.float32, tier="dcn")
            == "tree"
        )
        # the ici tier (and the tier-less default spelling) never
        # serves a dcn decision
        assert autotune.lookup("allreduce", 2, 4096, jnp.float32) is None
        assert (
            autotune.lookup("allreduce", 2, 4096, jnp.float32, tier="ici")
            is None
        )
        # serialized cells carry the tier suffix; default-tier cells
        # keep the pre-hierarchy spelling
        autotune.record("allreduce", 2, 4096, jnp.float32, {"rsag": 3.0})
        table = autotune.table_as_dict()
        assert set(table) == {
            "allreduce/n2/2^12B/float32@dcn",
            "allreduce/n2/2^12B/float32",
        }
    finally:
        autotune.clear()


def test_latency_threshold_default_recorded_and_cleared():
    autotune.clear()
    try:
        assert (
            autotune.latency_threshold("allreduce", 2, 4, jnp.bfloat16)
            == autotune.DEFAULT_LATENCY_THRESHOLD_BYTES
        )
        autotune.record_latency_threshold("allreduce", 2, 4, jnp.bfloat16, 1 << 20)
        assert (
            autotune.latency_threshold("allreduce", 2, 4, jnp.bfloat16)
            == 1 << 20
        )
        # other topologies/dtypes keep the default
        assert (
            autotune.latency_threshold("allreduce", 2, 8, jnp.bfloat16)
            == autotune.DEFAULT_LATENCY_THRESHOLD_BYTES
        )
        with pytest.raises(ValueError, match=">= 0"):
            autotune.record_latency_threshold("allreduce", 2, 4, jnp.bfloat16, -1)
    finally:
        autotune.clear()
    # clear() wipes thresholds too
    assert (
        autotune.latency_threshold("allreduce", 2, 4, jnp.bfloat16)
        == autotune.DEFAULT_LATENCY_THRESHOLD_BYTES
    )


def test_sweep_grid_reaches_the_latency_floor_and_octave_bound_holds():
    """ISSUE satellite: the default grid reaches ~4KB, the payload
    shaper actually produces ~4KB (not a silently clamped 16KB), and
    the ±2-octave lookup fallback still holds at the new floor."""
    from activemonitor_tpu.parallel.collectives import _payload

    assert min(autotune.DEFAULT_SWEEP_SIZES_MB) == pytest.approx(0.004)
    _rows, _cols, nbytes = _payload(0.004, jnp.bfloat16)
    assert 2048 <= nbytes <= 8192, nbytes  # ~4KB, not the old 16KB floor
    # the historical shape is untouched above the old floor
    rows, cols, big = _payload(0.25, jnp.bfloat16)
    assert cols == 1024 and big >= 244 * 1024
    autotune.clear()
    try:
        floor_payload = nbytes  # bucket 11 for ~4KB
        autotune.record(
            "allreduce", 8, floor_payload, jnp.bfloat16,
            {"recdouble": 2.0, "xla": 1.0},
        )
        bucket = autotune.payload_bucket(floor_payload)
        # within 2 octaves below the floor: served
        assert (
            autotune.lookup(
                "allreduce", 8, 1 << (bucket - 2), jnp.bfloat16
            )
            == "recdouble"
        )
        # 3 octaves below: the bound holds — fall back to the builtin
        assert (
            autotune.lookup("allreduce", 8, 1 << (bucket - 3), jnp.bfloat16)
            is None
        )
    finally:
        autotune.clear()


class _FakeResult:
    def __init__(self, busbw_gbps, payload_bytes):
        self.busbw_gbps = busbw_gbps
        self.payload_bytes = payload_bytes


def _scripted_hier_benches(alpha_us=200.0, dcn_alpha_us=2000.0):
    """Scripted α/B timings for both injectables: the latency
    composition pays few rounds at full payload, the bandwidth one
    many rounds of chunks at higher effective bandwidth — the
    crossover in miniature, no hardware involved."""

    def flat_bench(_collective, schedule, mesh, axis, size_mb, _dt, _it):
        n = mesh.shape[axis]
        payload = int(size_mb * 1e6)
        rounds, beta = {
            "xla": (2 * (n - 1), 5.0),
            "rsag": (2 * (n - 1), 10.0),
            "recdouble": (2, 1.0),
            "tree": (3, 0.5),
        }[schedule]
        alpha = dcn_alpha_us if axis == "dcn" else alpha_us
        seconds = alpha * 1e-6 * rounds + payload / (beta * 1e9)
        return _FakeResult(payload / seconds / 1e9, payload)

    def hier_bench(variant, mesh, dcn_axis, ici_axis, size_mb, _dt, _it):
        n_dcn, n_ici = mesh.shape[dcn_axis], mesh.shape[ici_axis]
        payload = int(size_mb * 1e6)
        if variant == "latency":
            rounds = 2 + 1  # few full-payload rounds
            seconds = alpha_us * 1e-6 * rounds + payload / (1.0 * 1e9)
        elif variant == "bandwidth":
            rounds = 2 * (n_ici - 1) + 1
            seconds = alpha_us * 1e-6 * rounds + payload / (10.0 * 1e9)
        else:  # flat: one slow joint ring
            rounds = 2 * (n_dcn * n_ici - 1)
            seconds = dcn_alpha_us * 1e-6 * rounds + payload / (8.0 * 1e9)
        return _FakeResult(payload / seconds / 1e9, payload)

    return flat_bench, hier_bench


def test_tune_hierarchical_records_threshold_and_decision_flips():
    """The acceptance-criterion unit test (PR-8 style, injectable
    bench): tune_hierarchical finds the scripted latency/bandwidth
    crossover, records the threshold, and the tuned surface then
    dispatches the LATENCY composition below it and the BANDWIDTH one
    above — proven by per-tier hop signatures."""
    mesh = tier_mesh(2, 4)
    flat_bench, hier_bench = _scripted_hier_benches()
    autotune.clear()
    try:
        run = autotune.tune_hierarchical(
            mesh, sizes_mb=(0.01, 2.0), dtype=jnp.float32, iters=1,
            bench=flat_bench, hier_bench=hier_bench,
        )
        # scripted regime (α crossover ≈ 0.9 MB): latency wins 10KB,
        # bandwidth wins 2MB → the threshold lands between them
        assert run.threshold_source == "crossover"
        assert int(0.01 * 1e6) < run.threshold_bytes <= int(2.0 * 1e6)
        assert (
            autotune.latency_threshold("allreduce", 2, 4, jnp.float32)
            == run.threshold_bytes
        )
        # both tiers were flat-tuned under their own tier key
        assert set(run.tier_runs) == {"dcn", "ici"}
        assert any(k.tier == "dcn" for k in run.keys)
        assert any(k.tier == "ici" for k in run.keys)

        # decision flip, hop-proven: a small payload rides the latency
        # composition (few full-payload rounds), a large one the
        # bandwidth composition (hier-rs/hier-ag ici rings)
        small = jnp.ones((8 * 2, 4), jnp.float32)  # 32B/shard
        big = jnp.ones((8 * 2, 1 << 19), jnp.float32)  # 4MB/shard > threshold

        def auto(v):
            return autotune.all_reduce(
                v, (DCN, ICI), schedule="auto", n=(2, 4)
            )

        schedules._HOP_LOG = log = []
        try:
            apply_tiered(mesh, auto, small)
        finally:
            schedules._HOP_LOG = None
        small_tags = {tag for tag, _s in log}
        assert not small_tags & {"hier-rs", "hier-ag"}, small_tags

        schedules._HOP_LOG = log = []
        try:
            apply_tiered(mesh, auto, big)
        finally:
            schedules._HOP_LOG = None
        big_tags = {tag for tag, _s in log}
        assert {"hier-rs", "hier-ag"} <= big_tags, big_tags
    finally:
        autotune.clear()


def test_tune_hierarchical_threshold_edge_sources():
    mesh = tier_mesh(2, 4)
    flat_bench, _ = _scripted_hier_benches()

    def latency_always(variant, *_a):
        return _FakeResult(
            {"latency": 5.0, "bandwidth": 1.0, "flat": 0.5}[variant], 10**6
        )

    def bandwidth_always(variant, *_a):
        return _FakeResult(
            {"latency": 1.0, "bandwidth": 5.0, "flat": 0.5}[variant], 10**6
        )

    autotune.clear()
    try:
        run = autotune.tune_hierarchical(
            mesh, sizes_mb=(1.0, 2.0), dtype=jnp.float32, iters=1,
            bench=flat_bench, hier_bench=latency_always,
        )
        assert run.threshold_source == "latency-everywhere"
        assert run.threshold_bytes == 2 * 10**6
        run = autotune.tune_hierarchical(
            mesh, sizes_mb=(1.0, 2.0), dtype=jnp.float32, iters=1,
            bench=flat_bench, hier_bench=bandwidth_always,
        )
        assert run.threshold_source == "bandwidth-everywhere"
        assert run.threshold_bytes == 10**6
    finally:
        autotune.clear()


def test_hier_plan_paths_and_tuned_tier_winners():
    autotune.clear()
    try:
        flat = autotune.hier_plan("allreduce", 1, 8, 4096, jnp.float32)
        assert flat["path"] == "flat" and "dcn=1" in flat["reason"]
        plan = autotune.hier_plan("allreduce", 2, 4, 4096, jnp.float32)
        assert plan["variant"] == "latency"  # below the default 64KB
        assert plan["threshold_bytes"] == autotune.DEFAULT_LATENCY_THRESHOLD_BYTES
        big = autotune.hier_plan("allreduce", 2, 4, 1 << 20, jnp.float32)
        assert big["variant"] == "bandwidth"
        assert big["ici_schedule"] == "rsag"  # the composition's rings
        # a tuned dcn cell at the CHUNK payload steers the exchange
        autotune.record(
            "allreduce", 2, (1 << 20) // 4, jnp.float32,
            {"tree": 9.0, "recdouble": 1.0}, tier="dcn",
        )
        assert (
            autotune.hier_plan("allreduce", 2, 4, 1 << 20, jnp.float32)[
                "dcn_schedule"
            ]
            == "tree"
        )
        with pytest.raises(ValueError, match="unknown hierarchical schedule"):
            autotune.hier_plan("allreduce", 2, 4, 4096, jnp.float32, "rsag")
    finally:
        autotune.clear()


def test_tuple_axis_surface_edges():
    mesh = tier_mesh(2, 4)
    x = jnp.ones((8 * 2, 3), jnp.float32)
    autotune.clear()
    try:
        # a 1-tuple degrades to the flat path
        got = apply_tiered(
            mesh,
            lambda v: autotune.all_reduce(
                jax.lax.psum(v, DCN), (ICI,), schedule="auto"
            ),
            x,
        )
        want = apply_tiered(mesh, lambda v: jax.lax.psum(v, (DCN, ICI)), x)
        assert jnp.allclose(got, want)
        # >2 tiers is a hard error, as is a scalar n for tuple axes
        with pytest.raises(ValueError, match="exactly two tiers"):
            apply_tiered(
                mesh,
                lambda v: autotune.all_reduce(v, (DCN, ICI, "x")),
                x,
            )
        with pytest.raises(ValueError, match="tuple n per axis"):
            apply_tiered(
                mesh,
                lambda v: autotune.all_reduce(v, (DCN, ICI), n=8),
                x,
            )
        # "xla" is the joint builtin; scalars always ride it
        got = apply_tiered(
            mesh,
            lambda v: autotune.all_reduce(v, (DCN, ICI), schedule="xla"),
            x,
        )
        assert jnp.allclose(got, want)

        @partial(
            shard_map, mesh=mesh, in_specs=P((DCN, ICI)),
            out_specs=P(None), check_vma=False,
        )
        def scalar_auto(v):
            return autotune.all_reduce(
                jnp.sum(v), (DCN, ICI), schedule="auto", n=(2, 4)
            )[None]

        assert float(scalar_auto(x)[0]) == pytest.approx(8 * 2 * 3)
        with pytest.raises(ValueError, match="unknown hierarchical schedule"):
            apply_tiered(
                mesh, lambda v: autotune.all_reduce(v, (DCN, ICI), "rsag"), x
            )
        # the gather surface has NO latency/bandwidth variants: a
        # forced one errors instead of silently auto-tuning
        with pytest.raises(ValueError, match="no\\s+latency/bandwidth"):
            apply_tiered(
                mesh,
                lambda v: autotune.all_gather(v, (DCN, ICI), "latency"),
                x,
            )
    finally:
        autotune.clear()


def test_degenerate_tuple_dispatch_is_bitwise_flat():
    """auto over a ("dcn", "ici") pair with dcn=1 must be BITWISE the
    flat auto dispatch — the acceptance criterion's degenerate-mesh
    equivalence, at the tuned-surface level."""
    mesh = tier_mesh(1, 8)
    x = jnp.arange(8 * 4 * 3, dtype=jnp.float32).reshape(8 * 4, 3) % 13
    autotune.clear()
    try:
        # tune a flat ici cell so BOTH paths dispatch the same zoo
        # schedule (not just the builtin)
        payload = (x.size // 8) * x.dtype.itemsize
        autotune.record(
            "allreduce", 8, payload, jnp.float32, {"tree": 2.0, "xla": 1.0}
        )
        got = apply_tiered(
            mesh,
            lambda v: autotune.all_reduce(v, (DCN, ICI), "auto", n=(1, 8)),
            x,
        )
        want = apply_tiered(
            mesh,
            lambda v: autotune.all_reduce(v, ICI, "auto", n=8),
            x,
        )
        assert bool((got == want).all())
    finally:
        autotune.clear()


# ---------------------------------------------------------------------------
# partition tier resolution + ops dispatch
# ---------------------------------------------------------------------------


def test_resolve_tiers_rules():
    mesh2 = tier_mesh(2, 4)
    assert resolve_tiers(mesh2, "data") == (("dcn", "ici"), "")
    axes, reason = resolve_tiers(tier_mesh(1, 8), "data")
    assert axes == ("ici",) and "dcn=1" in reason
    from activemonitor_tpu.parallel.mesh import make_2d_mesh

    flat = make_2d_mesh(shape=(2, 4))
    axes, reason = resolve_tiers(flat, "data")
    assert axes == ("data",) and "flat" in reason
    with pytest.raises(ValueError, match="neither axis"):
        resolve_tiers(flat, "ep")


def test_moe_dispatches_hierarchically_on_tier_mesh():
    from activemonitor_tpu.ops.moe import (
        init_moe_params,
        moe_ffn_expert_parallel,
        moe_ffn_reference,
    )

    mesh = tier_mesh(2, 4)
    params = init_moe_params(jax.random.key(2), 16, 32, n_experts=8)
    x = jax.random.normal(jax.random.key(3), (16, 16), jnp.float32)
    autotune.clear()
    try:
        schedules._HOP_TIER_LOG = log = []
        try:
            got = moe_ffn_expert_parallel(params, x, mesh, axis="ep")
        finally:
            schedules._HOP_TIER_LOG = None
        want = moe_ffn_reference(params, x)
        assert jnp.allclose(got, want, atol=1e-4)
        # the token gather really rode the two-tier composition
        assert {axis for axis, _t, _s in log} == {"dcn", "ici"}
    finally:
        autotune.clear()


def test_pipeline_combines_hierarchically_on_tier_mesh():
    from activemonitor_tpu.models.probe_model import (
        ProbeModelConfig,
        init_params,
    )
    from activemonitor_tpu.ops.pipeline import (
        pipeline_forward_blocks,
        stack_layer_params,
    )

    cfg = ProbeModelConfig(
        vocab_size=64, d_model=16, n_heads=2, n_layers=8, d_ff=32,
        max_seq_len=32, dtype=jnp.float32,
    )
    stacked = stack_layer_params(
        init_params(jax.random.key(4), cfg)["layers"]
    )
    x = jax.random.normal(jax.random.key(5), (8, 8, cfg.d_model), jnp.float32)
    autotune.clear()
    try:
        hier = pipeline_forward_blocks(
            stacked, x, cfg, tier_mesh(2, 4), axis="pp"
        )
        flat = pipeline_forward_blocks(
            stacked, x, cfg, Mesh(np.array(jax.devices()), ("pp",)),
            axis="pp",
        )
        # same stage ring (dcn-major linearization == flat device
        # order), same combine sum: bitwise
        assert bool((hier == flat).all())
        # a flat zoo token on the two-tier combine is an error, not a
        # silent downgrade to "auto"
        with pytest.raises(ValueError, match="flat\\s+schedule token"):
            pipeline_forward_blocks(
                stacked, x, cfg, tier_mesh(2, 4), axis="pp",
                allreduce_schedule="tree",
            )
    finally:
        autotune.clear()


# ---------------------------------------------------------------------------
# training-step hierarchical grad sync
# ---------------------------------------------------------------------------


def test_resolve_grad_sync_tier_gates():
    from activemonitor_tpu.probes.training_step import resolve_grad_sync

    mesh = tier_mesh(2, 4)
    assert resolve_grad_sync(mesh, "dense", "auto") == ("hierarchical", "")
    assert resolve_grad_sync(mesh, "dense", "xla") == ("hierarchical", "")
    mode, why = resolve_grad_sync(mesh, "dense", "rsag")
    assert mode == "implicit" and "two-tier" in why
    mode, why = resolve_grad_sync(mesh, "flash", "auto")
    assert mode == "implicit" and "flash" in why
    mode, why = resolve_grad_sync(mesh, "dense", "auto", accum_steps=2)
    assert mode == "implicit" and "accum" in why
    # degenerate single-slice still rides the hierarchical resolve
    # (the surface falls back to flat internally, reason recorded)
    assert resolve_grad_sync(tier_mesh(1, 8), "dense", "auto") == (
        "hierarchical", "",
    )


def test_training_step_hier_zero1_is_a_clear_error():
    from activemonitor_tpu.models.probe_model import tiny_config
    from activemonitor_tpu.probes.training_step import (
        build_sharded_train_step,
    )

    with pytest.raises(ValueError, match="zero1 needs a 'data' mesh axis"):
        build_sharded_train_step(
            tiny_config(), tier_mesh(2, 4), zero1=True, init_state=False
        )


def test_training_step_runs_hierarchical_sync_and_exports_plan():
    """The flagship acceptance path: run() on a ("dcn", "ici") mesh
    dispatches the hierarchical grad sync with zero call-site changes
    and exports the per-tier plan in its stdout-contract details."""
    from activemonitor_tpu.probes import training_step

    autotune.clear()
    try:
        r = training_step.run(
            tiny=True, batch_per_device=2, seq=16, steps=1,
            mesh=tier_mesh(2, 4), roofline=False,
        )
        assert r.ok, r.summary
        assert r.details["grad_sync"] == "hierarchical"
        plan = r.details["hier_sync"]
        assert plan["path"] == "hierarchical"
        assert plan["n_dcn"] == 2 and plan["n_ici"] == 4
        assert {"variant", "ici_schedule", "dcn_schedule",
                "threshold_bytes"} <= set(plan)
        assert r.details["allreduce_schedule"].startswith(
            f"hier/{plan['variant']}"
        )
        assert r.details["mesh"] == {"dcn": 2, "ici": 4}
        assert r.details["batch"] == 2 * 8  # batch_per_device × n_dcn×n_ici
        # the decision also rides the contract LINE as a gauge (help
        # carries the per-tier schedule string)
        by_name = {m.name: m for m in r.metrics}
        gauge = by_name["training-step-hier-sync"]
        assert gauge.value == (1.0 if plan["variant"] == "latency" else 0.0)
        assert r.details["allreduce_schedule"] in gauge.help
    finally:
        autotune.clear()


def test_training_step_degenerate_tier_mesh_reports_flat():
    from activemonitor_tpu.probes import training_step

    autotune.clear()
    try:
        r = training_step.run(
            tiny=True, batch_per_device=2, seq=16, steps=1,
            mesh=tier_mesh(1, 8), roofline=False,
        )
        assert r.ok, r.summary
        assert r.details["grad_sync"] == "hierarchical"
        assert r.details["allreduce_schedule"] == "hier-flat(dcn=1)"
        assert r.details["hier_sync"]["path"] == "flat"
    finally:
        autotune.clear()


# ---------------------------------------------------------------------------
# probes + matrix surfaces
# ---------------------------------------------------------------------------


def test_collectives_probe_hier_cases_and_structured_skip(monkeypatch):
    from activemonitor_tpu.probes import collectives as collectives_probe

    r = collectives_probe.run(
        size_mb=0.01, iters=1,
        cases=("allreduce-hier", "allreduce-hier-latency"),
    )
    assert r.ok
    names = [m.name for m in r.metrics]
    assert "collective-allreduce-hier-busbw-gbps" in names
    assert "collective-allreduce-hier-latency-busbw-gbps" in names

    real = jax.devices()
    monkeypatch.setattr(jax, "devices", lambda *a, **k: real[:2])
    skipped = collectives_probe.run(
        size_mb=0.01, iters=1, cases=("allreduce", "allreduce-hier")
    )
    assert skipped.ok
    skip = skipped.details["hier_skipped"]["allreduce-hier"]
    assert skip["mesh"] == {"dcn": 2, "ici": 1}
    assert "even" in skip["reason"]
    # only the possible case was measured
    assert [m.name for m in skipped.metrics if "busbw" in m.name] == [
        "collective-allreduce-busbw-gbps"
    ]
    with pytest.raises(ValueError, match="cannot be restricted"):
        collectives_probe.run_per_axis(cases=("allreduce-hier",))


def test_matrix_expands_hier_cells_with_payload_octaves():
    from activemonitor_tpu.analysis import matrix as matrix_mod

    spec = {
        "ops": ["hier-allreduce"],
        "meshes": [{"dcn": 2, "ici": 4}, {"dcn": 2, "ici": 8}],
        "dtypes": ["bf16"],
        "payloads_kb": [16, 4096],
    }
    cells, skipped = matrix_mod.expand(spec, n_devices=8)
    assert [c.cell_id for c in cells] == [
        "hier-allreduce/dcn2xici4/bf16/auto/16kb",
        "hier-allreduce/dcn2xici4/bf16/auto/4096kb",
    ]
    # the impossible single-process expansion is a structured
    # device-deficit skip, not a hole
    deficit = [
        r for r in skipped
        if r.cell.mesh_id == "dcn2xici8"
    ]
    assert len(deficit) == 2
    assert all("needs 16 devices" in r.reason for r in deficit)
    # malformed payload tokens degrade to the default octaves
    bad = dict(spec, payloads_kb=["x", -3])
    cells, _ = matrix_mod.expand(bad, n_devices=8)
    assert [c.payload_kb for c in cells] == list(
        matrix_mod.DEFAULT_PAYLOADS_KB
    )
    # non-payload ops never multiply and keep their stable ids
    flash = matrix_mod.expand(
        {"ops": ["flash"], "meshes": [{}], "dtypes": ["f32"],
         "payloads_kb": [16, 4096]},
        n_devices=8,
    )[0]
    assert [c.cell_id for c in flash] == ["flash/1chip/f32"]


def test_matrix_hier_runner_stamps_plan(monkeypatch):
    import time

    from activemonitor_tpu.analysis import matrix as matrix_mod

    autotune.clear()
    try:
        cell = matrix_mod.CellSpec(
            op="hier-allreduce", mesh=(("dcn", 2), ("ici", 4)),
            dtype="float32", schedule="auto", payload_kb=16,
        )
        result = matrix_mod.execute_cell(cell, iters=1, timer=time.monotonic)
        assert result.status == matrix_mod.STATUS_OK
        assert result.schedule.startswith("hier/")
        assert result.details["hier_plan"]["n_ici"] == 4
        assert result.seconds > 0
    finally:
        autotune.clear()
