"""Paged KV cache — fixed-size blocks with per-sequence block tables.

The serving runtime's memory system (ROADMAP item 5). Static-batch
decode gives every sequence a contiguous ``[B, S]`` cache slab sized
for the worst case, so admission is all-or-nothing and the slack in
short sequences is dead HBM. Continuous batching instead pools K/V in
fixed-size BLOCKS: a sequence owns an ordered block table, admission is
a free-list question, retirement returns blocks for immediate reuse,
and the only waste is the measurable slack inside each sequence's last
partially-filled block (the vLLM PagedAttention idea, sized for the
probe model).

Three layers, same file so the layout story has one home:

- :class:`KVBlockManager` — the pure-Python allocator: free list,
  per-sequence block tables, allocate/append/free, and EXPLICIT
  fragmentation accounting (:meth:`~KVBlockManager.fragmentation_ratio`
  — reserved-but-unwritten slots over reserved slots). Deficits are
  structured refusals (``None``/``False``), never exceptions: the
  admission scheduler turns them into queueing decisions, and an
  out-of-blocks storm must not crash the serving loop.
- the jax storage — :func:`init_paged_kv` allocates
  ``[n_layers, n_blocks, kv_heads, block_size, head_dim]`` pools whose
  layout is expressed as PARTITION RULES (:func:`kv_partition_rules`)
  resolved through ``parallel/partition.py`` like every other op:
  kv heads shard over the tensor-parallel axis, the block pool is
  replicated, re-meshing is an edit to a rules tuple, a rule naming an
  axis the mesh lacks raises up front, and scalar leaves never
  partition.
- the compute — :func:`bank_prompt` scatters a prefilled sequence's
  K/V into its blocks; :func:`paged_decode_step` is ``decode_step``'s
  paged sibling: per-sequence positions (a continuous batch has no
  single scalar ``pos``), K/V gathered through the block tables, new
  K/V scattered to each sequence's (block, offset). The serving probe
  pins its logits against the static per-sequence path — the two
  implementations must not drift.

Slot-padding convention for fixed-shape batches: callers reserve one
block index OUTSIDE the manager's pool as a trash block (the serving
engine allocates ``n_blocks + 1`` storage blocks and points every
inactive slot's table at the last one), so inactive batch slots scatter
into garbage no live sequence reads instead of corrupting block 0.

No wall-clock reads here (``hack/lint.py`` bans them: the manager's
whole state is allocation arithmetic and the compute is pure) — any
timing belongs to the caller's injectable timer.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from activemonitor_tpu.models.probe_model import ProbeModelConfig, _rmsnorm


def kv_bytes_per_token(cfg: ProbeModelConfig) -> float:
    """HBM bytes one generated token ADDS to the cache (K and V, every
    layer) — the single bytes-per-token figure both the static decode
    probe (``decode-kv-bytes-per-token``) and the serving probe's
    memory-bound ceiling derive from, so the two roofline inputs cannot
    drift apart."""
    return float(
        2 * cfg.n_layers * cfg.kv_heads * cfg.head_dim
        * jnp.dtype(cfg.dtype).itemsize
    )


# ---------------------------------------------------------------------
# the allocator (pure Python — no jax, no clock)
# ---------------------------------------------------------------------


class KVBlockManager:
    """Free-list block allocator with per-sequence block tables.

    Capacity is reserved whole at :meth:`allocate` (admission time) and
    consumed by :meth:`append` as tokens bank their K/V — so a sequence
    admitted under the block budget can never hit a mid-flight
    out-of-memory; the only refusal point is admission itself, where
    the scheduler can queue. Freed blocks return to the free list LIFO,
    so a retirement's blocks are the very next admission's grant
    (locality + a deterministic reuse order tests can pin).
    """

    def __init__(self, n_blocks: int, block_size: int):
        if n_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need n_blocks >= 1 and block_size >= 1, got "
                f"{n_blocks}/{block_size}"
            )
        self.n_blocks = n_blocks
        self.block_size = block_size
        # stack: pop() grants from the END, so seed it reversed (first
        # grant is block 0) and append frees for LIFO reuse
        self._free: List[int] = list(range(n_blocks - 1, -1, -1))
        self._tables: Dict[int, List[int]] = {}
        self._lengths: Dict[int, int] = {}  # tokens appended (banked K/V)

    def blocks_for(self, n_tokens: int) -> int:
        """Blocks needed to hold ``n_tokens`` K/V entries."""
        return -(-max(0, n_tokens) // self.block_size)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.n_blocks - len(self._free)

    @property
    def banked_tokens(self) -> int:
        """Total K/V entries written across live sequences — the live
        cache footprint the serving roofline's bytes model reads."""
        return sum(self._lengths.values())

    def can_allocate(self, capacity_tokens: int) -> bool:
        return self.blocks_for(capacity_tokens) <= len(self._free)

    def allocate(self, seq_id: int, capacity_tokens: int) -> Optional[List[int]]:
        """Reserve blocks for a sequence's full K/V capacity. Returns
        the granted block table, or ``None`` when the free list cannot
        cover it — the structured admission refusal, never a raise.
        Re-allocating a live sequence id IS a raise: that is a caller
        bug, not a capacity condition."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already holds blocks")
        need = self.blocks_for(capacity_tokens)
        if need > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(need)]
        self._tables[seq_id] = blocks
        self._lengths[seq_id] = 0
        return list(blocks)

    def table(self, seq_id: int) -> List[int]:
        return list(self._tables[seq_id])

    def length(self, seq_id: int) -> int:
        return self._lengths[seq_id]

    def append(self, seq_id: int, n_tokens: int = 1) -> bool:
        """Advance a sequence's banked-token count. ``False`` (the
        structured refusal) when the reserved capacity cannot hold the
        new tokens — the caller under-reserved at admission."""
        if seq_id not in self._tables:
            return False
        capacity = len(self._tables[seq_id]) * self.block_size
        if self._lengths[seq_id] + n_tokens > capacity:
            return False
        self._lengths[seq_id] += n_tokens
        return True

    def free(self, seq_id: int) -> int:
        """Return a retired sequence's blocks to the free list (LIFO —
        the next allocation reuses them first). Returns the number of
        blocks released; freeing an unknown id is 0, not a raise."""
        blocks = self._tables.pop(seq_id, None)
        if blocks is None:
            return 0
        del self._lengths[seq_id]
        self._free.extend(blocks)
        return len(blocks)

    def fragmentation_ratio(self) -> float:
        """Reserved-but-unwritten K/V slots over all reserved slots —
        the explicit fragmentation account: block-granular reservation
        means every sequence carries up to ``block_size - 1`` slack
        slots plus whatever capacity it reserved but has not banked
        yet. 0.0 with nothing allocated (no reservation, no waste)."""
        reserved = self.used_blocks * self.block_size
        if reserved == 0:
            return 0.0
        used = sum(self._lengths.values())
        return (reserved - used) / reserved

    def stats(self) -> dict:
        return {
            "n_blocks": self.n_blocks,
            "block_size": self.block_size,
            "free_blocks": self.free_blocks,
            "used_blocks": self.used_blocks,
            "sequences": len(self._tables),
            "fragmentation_ratio": self.fragmentation_ratio(),
        }


# ---------------------------------------------------------------------
# the storage + its partition rules
# ---------------------------------------------------------------------


def init_paged_kv(
    cfg: ProbeModelConfig, n_blocks: int, block_size: int
) -> Dict[str, jax.Array]:
    """The pooled K/V storage: ``[L, n_blocks, Hkv, block_size, Dh]``
    per tensor, compute-dtyped. Block-major so one sequence's gather is
    a take along dim 1; heads on dim 2 so the tensor-parallel shard is
    whole kv heads (the same GQA memory story as ``init_kv_cache``)."""
    shape = (cfg.n_layers, n_blocks, cfg.kv_heads, block_size, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


def kv_partition_rules(tp_axis: str = "model"):
    """The paged-cache layout as DATA (parallel/partition.py): kv heads
    shard over ``tp_axis`` — each shard owns whole heads of every block
    — and the block pool itself is replicated across the axis, the same
    megatron split the probe model's attention weights use. Re-meshing
    the cache is an edit to this tuple, never to the compute."""
    return ((r"^k$|^v$", P(None, None, tp_axis, None, None)),)


def paged_kv_specs(
    cfg: ProbeModelConfig,
    n_blocks: int,
    block_size: int,
    tp_axis: str = "model",
    mesh: Optional[Mesh] = None,
):
    """The rules resolved over the abstract storage tree. Passing
    ``mesh`` validates up front: a rules tuple naming an axis the mesh
    does not carry is a ValueError here, never a tracer crash inside
    the serving loop — and scalar leaves resolve to ``P()`` like
    everywhere else."""
    from activemonitor_tpu.parallel.partition import match_partition_rules

    abstract = jax.eval_shape(lambda: init_paged_kv(cfg, n_blocks, block_size))
    return match_partition_rules(
        kv_partition_rules(tp_axis), abstract, mesh=mesh
    )


def shard_paged_kv(
    storage: Dict[str, jax.Array],
    cfg: ProbeModelConfig,
    mesh: Mesh,
    tp_axis: str = "model",
):
    """Place the storage on its resolved shardings (validated). Returns
    the sharded tree; the specs come from the same rules tuple, so a
    wrong layout raises before any device_put."""
    from activemonitor_tpu.parallel.partition import make_shard_fns

    n_blocks, block_size = storage["k"].shape[1], storage["k"].shape[3]
    specs = paged_kv_specs(cfg, n_blocks, block_size, tp_axis, mesh=mesh)
    fns = make_shard_fns(specs, mesh)
    return jax.tree.map(lambda fn, x: fn(x), fns, storage)


# ---------------------------------------------------------------------
# the compute: bank a prefilled prompt, step a continuous batch
# ---------------------------------------------------------------------


def bank_prompt(
    storage: Dict[str, jax.Array],
    prompt_k: jax.Array,
    prompt_v: jax.Array,
    blocks: jax.Array,
) -> Dict[str, jax.Array]:
    """Scatter one prefilled sequence's K/V (``[L, Hkv, S, Dh]``,
    heads-major like the contiguous cache) into its block table. The
    tail of the last block stays zero — inert slack the position mask
    never exposes, and exactly what the fragmentation ratio counts."""
    n_layers, heads, seq, head_dim = prompt_k.shape
    blocks = jnp.asarray(blocks, jnp.int32)
    block_size = storage["k"].shape[3]
    cap = int(blocks.shape[0]) * block_size
    pad = [(0, 0), (0, 0), (0, cap - seq), (0, 0)]

    def blocked(x: jax.Array) -> jax.Array:
        x = jnp.pad(x, pad)  # [L, Hkv, cap, Dh]
        x = x.reshape(n_layers, heads, blocks.shape[0], block_size, head_dim)
        return jnp.moveaxis(x, 1, 2)  # [L, n_blk, Hkv, bs, Dh]

    return {
        "k": storage["k"].at[:, blocks].set(blocked(prompt_k)),
        "v": storage["v"].at[:, blocks].set(blocked(prompt_v)),
    }


def paged_decode_step(
    params: Dict,
    storage: Dict[str, jax.Array],
    token: jax.Array,
    pos: jax.Array,
    block_tables: jax.Array,
    cfg: ProbeModelConfig,
):
    """One decode step over a continuous batch of paged sequences.

    ``token``: ``[B]`` int32; ``pos``: ``[B]`` int32 — each sequence's
    own write position (a continuous batch has no shared scalar pos);
    ``block_tables``: ``[B, max_blocks]`` int32, inactive slots padded
    with a trash block id (module docstring). Returns
    ``(logits [B, V], storage)``. Static shapes throughout: the batch
    width and table width are fixed, so the step jits once and reruns
    for the whole soak — the same contract as ``decode_step``, whose
    per-position math this must match within numeric tolerance (the
    serving probe's correctness gate)."""
    dt = cfg.dtype
    x = params["embed"].astype(dt)[token]  # [B, D]
    batch = token.shape[0]
    block_size = storage["k"].shape[3]
    cap = block_tables.shape[1] * block_size
    visible = jnp.arange(cap)[None, :] <= pos[:, None]  # [B, S]
    group = cfg.n_heads // cfg.kv_heads
    write_block = jnp.take_along_axis(
        block_tables, (pos // block_size)[:, None], axis=1
    )[:, 0]  # [B]
    offset = pos % block_size  # [B]
    for li, layer in enumerate(params["layers"]):
        h = _rmsnorm(x, layer["ln1"]["scale"])
        if "wqkv" in layer:
            qkv = jnp.einsum("bd,dthk->tbhk", h, layer["wqkv"].astype(dt))
            q, k_new, v_new = qkv[0], qkv[1], qkv[2]  # [B, H, K]
        else:  # GQA: q over n_heads, k/v over the narrower kv_heads
            q = jnp.einsum("bd,dhk->bhk", h, layer["wq"].astype(dt))
            kv = jnp.einsum("bd,dthk->tbhk", h, layer["wkv"].astype(dt))
            k_new, v_new = kv[0], kv[1]  # [B, Hkv, K]
        # scatter each sequence's new K/V to its own (block, offset)
        storage["k"] = storage["k"].at[li, write_block, :, offset].set(k_new)
        storage["v"] = storage["v"].at[li, write_block, :, offset].set(v_new)
        # gather the batch's caches through the block tables:
        # [B, n_blk, Hkv, bs, Dh] -> heads-major contiguous [B, Hkv, S, Dh]
        keys = jnp.moveaxis(storage["k"][li][block_tables], 2, 1).reshape(
            batch, cfg.kv_heads, cap, cfg.head_dim
        )
        values = jnp.moveaxis(storage["v"][li][block_tables], 2, 1).reshape(
            batch, cfg.kv_heads, cap, cfg.head_dim
        )
        qg = q.reshape(batch, cfg.kv_heads, group, cfg.head_dim)
        scores = jnp.einsum("bhgk,bhsk->bhgs", qg, keys) / jnp.sqrt(
            jnp.asarray(cfg.head_dim, dt)
        )
        scores = jnp.where(
            visible[:, None, None, :], scores, jnp.asarray(-1e9, dt)
        )
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(dt)
        attn = jnp.einsum("bhgs,bhsk->bhgk", probs, values).reshape(
            batch, cfg.n_heads, cfg.head_dim
        )
        x = x + jnp.einsum("bhk,hkd->bd", attn, layer["wo"].astype(dt))
        h = _rmsnorm(x, layer["ln2"]["scale"])
        up = jax.nn.gelu(jnp.einsum("bd,df->bf", h, layer["w_up"].astype(dt)))
        x = x + jnp.einsum("bf,fd->bd", up, layer["w_down"].astype(dt))
    x = _rmsnorm(x, params["final_ln"]["scale"])
    logits = jnp.einsum("bd,vd->bv", x, params["embed"].astype(dt))
    return logits.astype(jnp.float32), storage
