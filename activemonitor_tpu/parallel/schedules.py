"""Collective algorithm zoo — explicit ppermute schedules for
all-reduce / all-gather (ROADMAP item 2, the Demystifying-NCCL family).

``parallel/collectives.py`` times the XLA-built-in collectives (psum /
all_gather) plus raw ring hops; this module implements the classical
alternative *schedules* as explicit ``ppermute`` compositions so each
regime of the latency-vs-bandwidth tradeoff has a measurable
representative:

- **ring reduce-scatter + all-gather** (``all_reduce_rsag``) — the
  NCCL ring decomposition: 2(n−1) rounds of (shard/n)-sized chunks.
  Bandwidth-optimal (per-device wire volume 2(n−1)/n × S, the
  theoretical minimum), latency-poor (rounds grow linearly with n).
- **recursive doubling/halving** (``all_reduce_recdouble``) — log2(n)
  full-payload pairwise exchanges. Latency-optimal (fewest rounds),
  bandwidth-poor (log2(n) × S wire volume). Power-of-two native; other
  sizes fold the remainder ranks in/out with one extra round each way.
- **binomial tree reduce + broadcast** (``all_reduce_tree``) —
  2·ceil(log2 n) rounds, each a one-direction full-payload hop; the
  logical tree NCCL uses for small payloads on high-diameter rings.
- **ring all-gather** (``all_gather_ring``) and **recursive-doubling
  all-gather** (``all_gather_recdouble``) — the same two regimes for
  the gather family (recdouble falls back to the ring off power-of-two
  sizes, where block-doubling has no clean pairing).

Every schedule is shape-polymorphic (rsag pads odd rows internally),
numerically equivalent to the ``jax.lax.psum`` / ``all_gather``
reference (tests/test_schedules.py: allclose across meshes n∈{2,3,4,8},
bitwise where the schedule only moves data), and traced through the
``_hop`` choke point so the PR-5 hop-budget contract applies: each
schedule sends exactly its theoretical round count (``theoretical_hops``)
— asserted by tests, not asserted in comments.

**Hierarchical (DCN×ICI) compositions** — real scale is two-tier: fast
ICI inside a slice, slow DCN between slices (the topology-aware
algorithm split Demystifying NCCL analyzes). Over a ``("dcn", "ici")``
mesh:

- :func:`hier_all_reduce` — the bandwidth path: intra-slice ring
  reduce-scatter over ICI, inter-slice all-reduce of the scattered
  1/n_ici shard over DCN (any zoo schedule, or the psum builtin), and
  an intra-slice ring all-gather back. DCN carries only 1/n_ici of the
  payload — the whole point of the hierarchy.
- :func:`hier_all_reduce_latency` — the small-message path (the NCCL
  LL-protocol insight): full-payload few-round schedules per tier
  (recursive doubling / tree), no chunking — fewer rounds beat thinner
  wires below the α/B crossover.
- :func:`hier_all_gather` / :func:`hier_reduce_scatter` — the same
  two-tier factoring for the gather/scatter family; gather output is
  dcn-major (the ``P(("dcn", "ici"))`` layout).

Each tier's hops are traced through the same ``_hop`` choke point and
additionally logged per tier via ``_HOP_TIER_LOG``, so
:func:`theoretical_hier_hops` is a per-tier contract, not prose. On a
degenerate single-slice mesh (n_dcn == 1) the bandwidth composition IS
the flat ``all_reduce_rsag`` — bitwise, by construction.

Timed wrappers (``*_bandwidth``) reuse the chain-delta scaffold and
``CollectiveResult``/busbw accounting from parallel/collectives.py, so
zoo numbers are directly comparable against the XLA baselines; the
per-schedule *rated ceilings* (wire volume ≠ busbw convention) live in
probes/collectives._rated_busbw.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from activemonitor_tpu.parallel.collectives import CollectiveResult, _bench
from activemonitor_tpu.utils.compat import axis_size
from jax.sharding import Mesh


# Schedule tokens, in the spelling the probes/autotuner/docs share.
# "xla" is the psum/all_gather builtin the zoo is raced against.
ALL_REDUCE_SCHEDULES = ("xla", "rsag", "recdouble", "tree")
ALL_GATHER_SCHEDULES = ("xla", "ring", "recdouble")

# Hierarchical composition variants (the two sides of the LL-style
# small-message crossover parallel/autotune tunes a threshold for).
HIER_VARIANTS = ("bandwidth", "latency")

# Test hook (the ops/ring_attention.py pattern): when set to a list,
# every ppermute round a schedule issues appends (schedule_tag, round).
# Schedules unroll python loops, so one traced application logs each
# round individually and the log length IS the hop count.
_HOP_LOG = None

# Per-tier hook for the hierarchical compositions: appends
# (axis_name, schedule_tag, round), so a test can count the ICI tier's
# hops separately from the DCN tier's. Kept as a SECOND hook (not a
# wider tuple in _HOP_LOG) so the PR-5/PR-8 hop-contract tests keep
# their 2-tuple spelling.
_HOP_TIER_LOG = None


def _hop(x, axis_name, perm, tag, step):
    """One ppermute round, routed through a single site so the traced
    hop counter sees every transfer a schedule issues."""
    if _HOP_LOG is not None:
        _HOP_LOG.append((tag, step))
    if _HOP_TIER_LOG is not None:
        _HOP_TIER_LOG.append((axis_name, tag, step))
    return jax.lax.ppermute(x, axis_name, perm)


def _resolve_n(axis_name, n=None) -> int:
    return int(n) if n is not None else axis_size(axis_name)


def theoretical_hops(schedule: str, n: int, collective: str = "allreduce") -> int:
    """Rounds (ppermute calls) schedule issues on an n-device axis —
    the contract the hop-budget tests pin.

    The public token "recdouble" names a different algorithm per
    family (ALL_REDUCE_SCHEDULES vs ALL_GATHER_SCHEDULES), so pass
    ``collective="allgather"`` for the gather variant — its non-pow2
    fallback is the ring (n−1 hops), not the fold/unfold."""
    if collective == "allgather":
        schedule = {"recdouble": "ag-recdouble"}.get(schedule, schedule)
    if n <= 1:
        return 0
    p = 1 << (n.bit_length() - 1)  # largest power of two ≤ n
    r = n - p
    if schedule == "rsag":
        return 2 * (n - 1)
    if schedule == "recdouble":
        return int(math.log2(p)) + (2 if r else 0)
    if schedule == "tree":
        return 2 * math.ceil(math.log2(n))
    if schedule == "ring":  # all-gather ring
        return n - 1
    if schedule == "ag-recdouble":
        # falls back to the ring off power-of-two sizes
        return int(math.log2(n)) if r == 0 else n - 1
    raise ValueError(f"unknown schedule {schedule!r}")


# ---------------------------------------------------------------------------
# all-reduce schedules (per-shard x → per-shard sum over axis)
# ---------------------------------------------------------------------------


def _pad_rows(x, multiple: int):
    """Zero-pad the leading dim up to ``multiple`` (zeros are
    psum-neutral). Returns (padded, original_rows, pad)."""
    rows = x.shape[0]
    pad = (-rows) % multiple
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0
        )
    return x, rows, pad


def _ring_reduce_scatter(x, axis_name: str, n: int, tag: str):
    """Ring reduce-scatter of ``x`` (rows divisible by n): n−1 rounds of
    (rows/n)-chunks, accumulating; this device ends holding the fully
    reduced chunk (idx + 1) mod n. The scatter half of the NCCL ring."""
    chunk = x.shape[0] // n
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def take(j):
        return jax.lax.dynamic_slice_in_dim(x, j * chunk, chunk, axis=0)

    # after round s the arriving partial is of chunk (idx − s − 1)
    # mod n; add the local copy and pass it on
    buf = take(idx)
    for s in range(n - 1):
        buf = _hop(buf, axis_name, perm, tag, s)
        buf = buf + take((idx - s - 1) % n)
    return buf


def _ring_all_gather_chunks(buf, axis_name: str, n: int, tag: str):
    """Inverse of :func:`_ring_reduce_scatter`: ``buf`` is chunk
    (idx + 1) mod n; n−1 more rotations rebuild the full [n·chunk, ...]
    array on every device."""
    chunk = buf.shape[0]
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = jnp.zeros((n * chunk,) + buf.shape[1:], buf.dtype)
    out = jax.lax.dynamic_update_slice_in_dim(
        out, buf, ((idx + 1) % n) * chunk, axis=0
    )
    # own reduced chunk is (idx + 1) mod n; each further round delivers
    # chunk (idx − s) mod n from the left neighbor
    cur = buf
    for s in range(n - 1):
        cur = _hop(cur, axis_name, perm, tag, s)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, cur, ((idx - s) % n) * chunk, axis=0
        )
    return out


def all_reduce_rsag(x, axis_name: str, n: int | None = None):
    """Ring reduce-scatter + all-gather (the NCCL ring decomposition).

    Phase 1 rotates (shard/n)-chunks clockwise n−1 times, accumulating
    so device i ends holding the fully-reduced chunk (i+1) mod n; phase
    2 rotates the reduced chunks n−1 more times to rebuild the full
    sum everywhere. 2(n−1) rounds of S/n bytes — the bandwidth-optimal
    2(n−1)/n × S wire volume. Rows that don't divide n are zero-padded
    for the rotation and trimmed after (zeros are psum-neutral).
    """
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    x, rows, pad = _pad_rows(x, n)
    buf = _ring_reduce_scatter(x, axis_name, n, "rsag-rs")
    out = _ring_all_gather_chunks(buf, axis_name, n, "rsag-ag")
    return out[:rows] if pad else out


def all_reduce_recdouble(x, axis_name: str, n: int | None = None):
    """Recursive doubling: log2(n) full-payload pairwise exchanges
    (partner = idx XOR 2^s), latency-optimal. Off power-of-two sizes
    the r = n − 2^⌊log2 n⌋ remainder ranks fold their vector into rank
    (idx − p) first and receive the finished sum back last — one extra
    round each way, the standard MPI_Allreduce fixup."""
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    p = 1 << (n.bit_length() - 1)
    r = n - p
    idx = jax.lax.axis_index(axis_name)
    step = 0
    if r:
        # fold: ranks p+j send into j (non-destinations receive zeros)
        fold = [(p + j, j) for j in range(r)]
        x = x + _hop(x, axis_name, fold, "recdouble-fold", step)
        step += 1
    bit = 1
    while bit < p:
        pairs = [(i, i ^ bit) for i in range(p)]
        x = x + _hop(x, axis_name, pairs, "recdouble-xchg", step)
        bit <<= 1
        step += 1
    if r:
        # unfold: ranks j broadcast the finished sum back to p+j
        unfold = [(j, p + j) for j in range(r)]
        got = _hop(x, axis_name, unfold, "recdouble-unfold", step)
        x = jnp.where(idx >= p, got, x)
    return x


def all_reduce_tree(x, axis_name: str, n: int | None = None):
    """Binomial-tree reduce to rank 0, then binomial broadcast back:
    2·ceil(log2 n) one-direction full-payload rounds. Works for any n
    (ranks whose partner would fall off the end just sit the round
    out); the latency/bandwidth middle ground NCCL's tree algorithm
    occupies."""
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    rounds = math.ceil(math.log2(n))
    idx = jax.lax.axis_index(axis_name)
    # reduce: at round s, ranks ≡ 2^s (mod 2^{s+1}) send down to
    # idx − 2^s and retire; non-receivers add zeros
    for s in range(rounds):
        stride = 1 << s
        pairs = [
            (i, i - stride) for i in range(n) if i % (2 * stride) == stride
        ]
        x = x + _hop(x, axis_name, pairs, "tree-reduce", s)
    # broadcast: mirror image, receivers REPLACE their (stale) vector
    for s in reversed(range(rounds)):
        stride = 1 << s
        pairs = [
            (i, i + stride)
            for i in range(n)
            if i % (2 * stride) == 0 and i + stride < n
        ]
        got = _hop(x, axis_name, pairs, "tree-bcast", s)
        x = jnp.where(idx % (2 * stride) == stride, got, x)
    return x


# ---------------------------------------------------------------------------
# all-gather schedules (per-shard x[rows,...] → concatenated [n*rows,...])
# ---------------------------------------------------------------------------


def all_gather_ring(x, axis_name: str, n: int | None = None):
    """Ring all-gather: rotate shards clockwise n−1 times, placing each
    arrival at its owner's slot — tiled output ([n·rows, ...], device
    order), bitwise-identical to ``lax.all_gather(..., tiled=True)``."""
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    rows = x.shape[0]
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    out = jnp.zeros((n * rows,) + x.shape[1:], x.dtype)
    out = jax.lax.dynamic_update_slice_in_dim(out, x, idx * rows, axis=0)
    cur = x
    for s in range(n - 1):
        cur = _hop(cur, axis_name, perm, "ag-ring", s)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, cur, ((idx - s - 1) % n) * rows, axis=0
        )
    return out


def all_gather_recdouble(x, axis_name: str, n: int | None = None):
    """Recursive-doubling all-gather: log2(n) exchanges, the gathered
    block doubling each round (partner = idx XOR 2^s; the half owning
    the lower ranks prepends what it receives). Power-of-two only —
    other sizes fall back to the ring schedule, where the ISSUE-pinned
    hop contract records n−1 ring hops instead."""
    n = _resolve_n(axis_name, n)
    if n == 1:
        return x
    if n & (n - 1):
        return all_gather_ring(x, axis_name, n)
    idx = jax.lax.axis_index(axis_name)
    g = x
    bit = 1
    step = 0
    while bit < n:
        pairs = [(i, i ^ bit) for i in range(n)]
        got = _hop(g, axis_name, pairs, "ag-recdouble", step)
        # partner above me: my block comes first; partner below: second
        g = jnp.where(
            (idx & bit) == 0,
            jnp.concatenate([g, got], axis=0),
            jnp.concatenate([got, g], axis=0),
        )
        bit <<= 1
        step += 1
    return g


# ---------------------------------------------------------------------------
# hierarchical (DCN×ICI) compositions — two-tier schedules over a
# ("dcn", "ici") mesh. The dcn/ici axis NAMES are parameters; "dcn" is
# the slow outer tier, "ici" the fast inner one.
# ---------------------------------------------------------------------------

# per-tier schedule resolvers: "xla" rides the builtin for that tier
_ALL_REDUCE_TIER_IMPL = {
    "xla": lambda x, axis, n: jax.lax.psum(x, axis),
}


def _tier_all_reduce(schedule: str):
    if schedule in _ALL_REDUCE_TIER_IMPL:
        return _ALL_REDUCE_TIER_IMPL[schedule]
    impl = {
        "rsag": all_reduce_rsag,
        "recdouble": all_reduce_recdouble,
        "tree": all_reduce_tree,
    }.get(schedule)
    if impl is None:
        raise ValueError(
            f"unknown tier all-reduce schedule {schedule!r}; pick from "
            f"{ALL_REDUCE_SCHEDULES}"
        )
    return impl


def _tier_all_gather(schedule: str):
    impl = {
        "xla": lambda x, axis, n: jax.lax.all_gather(x, axis, tiled=True),
        "ring": all_gather_ring,
        "recdouble": all_gather_recdouble,
    }.get(schedule)
    if impl is None:
        raise ValueError(
            f"unknown tier all-gather schedule {schedule!r}; pick from "
            f"{ALL_GATHER_SCHEDULES}"
        )
    return impl


def hier_all_reduce(
    x,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    n_dcn: int | None = None,
    n_ici: int | None = None,
    dcn_schedule: str = "recdouble",
):
    """Two-tier all-reduce, bandwidth path: intra-slice ring
    reduce-scatter over ICI → inter-slice all-reduce of the scattered
    1/n_ici shard over DCN (``dcn_schedule``: any zoo token or "xla"
    psum) → intra-slice ring all-gather. The slow tier carries only
    S/n_ici bytes per device, the fast tier the full 2(n_ici−1)/n_ici·S
    ring volume — the NCCL two-level decomposition.

    On a degenerate single-slice mesh (n_dcn == 1) this IS the flat
    :func:`all_reduce_rsag`, bitwise — the composition collapses to its
    ICI phases. Rows that don't divide n_ici are zero-padded/trimmed
    like the flat rsag."""
    n_dcn = _resolve_n(dcn_axis, n_dcn)
    n_ici = _resolve_n(ici_axis, n_ici)
    if n_dcn == 1:
        return all_reduce_rsag(x, ici_axis, n_ici)
    if n_ici == 1:
        return _tier_all_reduce(dcn_schedule)(x, dcn_axis, n_dcn)
    x, rows, pad = _pad_rows(x, n_ici)
    shard = _ring_reduce_scatter(x, ici_axis, n_ici, "hier-rs")
    shard = _tier_all_reduce(dcn_schedule)(shard, dcn_axis, n_dcn)
    out = _ring_all_gather_chunks(shard, ici_axis, n_ici, "hier-ag")
    return out[:rows] if pad else out


def hier_all_reduce_latency(
    x,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    n_dcn: int | None = None,
    n_ici: int | None = None,
    ici_schedule: str = "recdouble",
    dcn_schedule: str = "recdouble",
):
    """Two-tier all-reduce, latency path (the LL-protocol analog):
    full-payload few-round schedules per tier — slice-local sum over
    ICI, then cross-slice sum over DCN — no chunking, no scatter/gather
    bookends. More wire bytes than :func:`hier_all_reduce` (log₂ rounds
    of the FULL payload per tier), far fewer rounds: below the α/B
    crossover the round count is the bill, so small messages ride this
    path (parallel/autotune tunes the threshold)."""
    n_dcn = _resolve_n(dcn_axis, n_dcn)
    n_ici = _resolve_n(ici_axis, n_ici)
    if n_ici > 1:
        x = _tier_all_reduce(ici_schedule)(x, ici_axis, n_ici)
    if n_dcn > 1:
        x = _tier_all_reduce(dcn_schedule)(x, dcn_axis, n_dcn)
    return x


def hier_all_gather(
    x,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    n_dcn: int | None = None,
    n_ici: int | None = None,
    ici_schedule: str = "ring",
    dcn_schedule: str = "ring",
):
    """Two-tier all-gather: gather the slice over ICI first, then the
    slices over DCN. Output is [n_dcn·n_ici·rows, ...] in **dcn-major**
    device order — exactly the ``P(("dcn", "ici"))`` tiled layout, so
    it bitwise-matches ``lax.all_gather(x, (dcn, ici), tiled=True)``.
    Degenerate single-slice meshes collapse to the flat ICI gather."""
    n_dcn = _resolve_n(dcn_axis, n_dcn)
    n_ici = _resolve_n(ici_axis, n_ici)
    if n_ici > 1:
        x = _tier_all_gather(ici_schedule)(x, ici_axis, n_ici)
    if n_dcn > 1:
        x = _tier_all_gather(dcn_schedule)(x, dcn_axis, n_dcn)
    return x


def hier_reduce_scatter_slot(
    n_dcn: int, n_ici: int, dcn_rank: int, ici_rank: int
) -> int:
    """Global chunk index device (dcn_rank, ici_rank) holds after
    :func:`hier_reduce_scatter`, with rows split into n_ici·n_dcn
    chunks ici-major: the ICI ring leaves chunk (i+1) mod n_ici, the
    DCN ring sub-scatters it to (d+1) mod n_dcn."""
    return ((ici_rank + 1) % n_ici) * n_dcn + (dcn_rank + 1) % n_dcn


def hier_reduce_scatter(
    x,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    n_dcn: int | None = None,
    n_ici: int | None = None,
):
    """Two-tier reduce-scatter: ICI ring reduce-scatter into rows/n_ici
    chunks, then a DCN ring reduce-scatter of that chunk into
    rows/(n_ici·n_dcn). Device (d, i) ends holding the fully reduced
    global chunk :func:`hier_reduce_scatter_slot`. Rows must divide
    n_ici·n_dcn (a scattered output has no clean trim for padding)."""
    n_dcn = _resolve_n(dcn_axis, n_dcn)
    n_ici = _resolve_n(ici_axis, n_ici)
    if x.shape[0] % max(1, n_ici * n_dcn):
        raise ValueError(
            f"{x.shape[0]} rows do not split into {n_ici * n_dcn} "
            "hierarchical chunks (pad the payload: a scattered output "
            "cannot trim)"
        )
    if n_ici > 1:
        x = _ring_reduce_scatter(x, ici_axis, n_ici, "hier-rs")
    if n_dcn > 1:
        x = _ring_reduce_scatter(x, dcn_axis, n_dcn, "hier-rs-dcn")
    return x


def theoretical_hier_hops(
    n_dcn: int,
    n_ici: int,
    variant: str = "bandwidth",
    collective: str = "allreduce",
    ici_schedule: str = "",
    dcn_schedule: str = "",
) -> dict:
    """Per-tier hop budget of the hierarchical compositions — the
    contract tests count against ``_HOP_TIER_LOG``. Returns
    ``{"ici": rounds, "dcn": rounds}``; a tier riding its XLA builtin
    issues zero explicit hops by definition."""

    def tier(schedule, n, family="allreduce"):
        if n <= 1 or schedule == "xla":
            return 0
        return theoretical_hops(schedule, n, collective=family)

    if collective == "allreduce":
        dcn_schedule = dcn_schedule or "recdouble"
        if variant == "bandwidth":
            # n_dcn == 1 collapses to flat rsag (ici only); n_ici == 1
            # runs the dcn schedule on the full payload (dcn only)
            return {
                "ici": 2 * (n_ici - 1) if n_ici > 1 else 0,
                "dcn": tier(dcn_schedule, n_dcn),
            }
        if variant == "latency":
            return {
                "ici": tier(ici_schedule or "recdouble", n_ici),
                "dcn": tier(dcn_schedule, n_dcn),
            }
        raise ValueError(
            f"unknown hierarchical variant {variant!r}; pick from "
            f"{HIER_VARIANTS}"
        )
    if collective == "allgather":
        return {
            "ici": tier(ici_schedule or "ring", n_ici, "allgather"),
            "dcn": tier(dcn_schedule or "ring", n_dcn, "allgather"),
        }
    if collective == "reducescatter":
        return {
            "ici": max(0, n_ici - 1),
            "dcn": max(0, n_dcn - 1),
        }
    raise ValueError(f"unknown hierarchical collective {collective!r}")


# ---------------------------------------------------------------------------
# timed wrappers — CollectiveResult/busbw accounting shared with the
# XLA baselines (parallel/collectives._bench)
# ---------------------------------------------------------------------------


def _allreduce_bench(name: str, schedule_fn):
    def bench(
        mesh: Mesh,
        size_mb: float = 64.0,
        dtype=jnp.bfloat16,
        iters: int = 5,
        axis: str = "",
    ) -> CollectiveResult:
        def make_body(n, ax):
            inv_n = jnp.asarray(1.0 / n, dtype)
            return lambda x: schedule_fn(x, ax, n) * inv_n  # mean: stable chain

        return _bench(
            name, mesh, axis, size_mb, dtype, iters, make_body,
            rows_multiple_of_n=True,  # time the rotation, not the padding
            busbw_factor=lambda n: 2 * (n - 1) / n,
        )

    return bench


all_reduce_rsag_bandwidth = _allreduce_bench("all_reduce_rsag", all_reduce_rsag)
all_reduce_recdouble_bandwidth = _allreduce_bench(
    "all_reduce_recdouble", all_reduce_recdouble
)
all_reduce_tree_bandwidth = _allreduce_bench("all_reduce_tree", all_reduce_tree)


def _allgather_bench(name: str, schedule_fn):
    def bench(
        mesh: Mesh,
        size_mb: float = 64.0,
        dtype=jnp.bfloat16,
        iters: int = 5,
        axis: str = "",
    ) -> CollectiveResult:
        def make_body(n, ax):
            inv_n = jnp.asarray(1.0 / n, dtype)

            def body(x):
                g = schedule_fn(x, ax, n)  # [n*rows, cols]
                return jnp.sum(g.reshape((n,) + x.shape), axis=0) * inv_n

            return body

        n = mesh.shape[axis or mesh.axis_names[0]]
        return _bench(
            name, mesh, axis, size_mb, dtype, iters, make_body,
            payload_mult=float(n),  # NCCL all-gather: total gathered data
            busbw_factor=lambda n: (n - 1) / n,
        )

    return bench


all_gather_ring_bandwidth = _allgather_bench("all_gather_ring", all_gather_ring)
all_gather_recdouble_bandwidth = _allgather_bench(
    "all_gather_recdouble", all_gather_recdouble
)


def hier_all_reduce_bandwidth(
    mesh: Mesh,
    size_mb: float = 64.0,
    dtype=jnp.bfloat16,
    iters: int = 5,
    dcn_axis: str = "dcn",
    ici_axis: str = "ici",
    variant: str = "bandwidth",
    dcn_schedule: str = "recdouble",
    ici_schedule: str = "recdouble",
) -> CollectiveResult:
    """Timed hierarchical all-reduce over a two-tier mesh.

    ``variant``: "bandwidth" (rs→dcn-exchange→ag), "latency"
    (full-payload per-tier schedules), or "flat" (one psum over both
    axes — the single-level baseline the tiered compositions are judged
    against). busbw uses the flat all-reduce convention 2(n−1)/n with
    n = total devices, so tiered and flat numbers compare directly."""
    from functools import partial as _partial

    from activemonitor_tpu.parallel.partition import shard_map
    from activemonitor_tpu.utils.timing import chain_delta_seconds
    from jax.sharding import PartitionSpec as P

    n_dcn = mesh.shape[dcn_axis]
    n_ici = mesh.shape[ici_axis]
    n = n_dcn * n_ici
    itemsize = jnp.dtype(dtype).itemsize
    cols = 128
    rows = max(1, int(size_mb * 1e6 / itemsize) // cols)
    # divisible shards keep the two-level chunking static-shaped
    rows = max(n, rows - rows % n)
    shard_bytes = rows * cols * itemsize
    inv_n = jnp.asarray(1.0 / n, dtype)

    if variant == "bandwidth":
        body = lambda x: hier_all_reduce(  # noqa: E731 - bench lambda idiom
            x, dcn_axis, ici_axis, n_dcn, n_ici, dcn_schedule=dcn_schedule
        ) * inv_n
    elif variant == "latency":
        body = lambda x: hier_all_reduce_latency(  # noqa: E731
            x, dcn_axis, ici_axis, n_dcn, n_ici,
            ici_schedule=ici_schedule, dcn_schedule=dcn_schedule,
        ) * inv_n
    elif variant == "flat":
        axes = (dcn_axis, ici_axis)
        body = lambda x: jax.lax.psum(x, axes) * inv_n  # noqa: E731
    else:
        raise ValueError(
            f"unknown hierarchical bench variant {variant!r}; pick from "
            f"{HIER_VARIANTS + ('flat',)}"
        )

    def chain_of(k):
        @jax.jit
        @_partial(
            shard_map,
            mesh=mesh,
            in_specs=P((dcn_axis, ici_axis), None),
            out_specs=P(None),
            check_vma=False,
        )
        def chain(x):
            for _ in range(k):
                x = body(x)
            return jax.lax.psum(
                x.astype(jnp.float32).sum(), (dcn_axis, ici_axis)
            )[None]

        return lambda x: chain(x)[0]

    x = jnp.ones((rows * n, cols), dtype=dtype)
    seconds = chain_delta_seconds(chain_of, x, k1=2, k2=6, iters=iters)
    algbw = shard_bytes / seconds / 1e9
    busbw = algbw * 2 * (n - 1) / n if n > 1 else algbw
    return CollectiveResult(
        name=f"hier_all_reduce_{variant}",
        payload_bytes=shard_bytes,
        n_devices=n,
        seconds_per_op=seconds,
        algbw_gbps=algbw,
        busbw_gbps=busbw,
    )
