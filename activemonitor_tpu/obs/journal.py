"""Durable telemetry journal: restart-proof observability windows.

Every window built in PRs 1–15 — result rings, SLO availability,
error-budget burn, goodput attribution, front-door ledgers — lives in
bounded in-memory deques and dies with the process. The journal is the
append-only sidecar that makes the "measure" leg of ML Productivity
Goodput (PAPERS.md) survive a restart: three event streams recorded at
their EXISTING choke points (no new call sites), replayed into the
fresh rings on boot, and doubling as the workload-trace recorder the
replay bench (obs/replay.py, the ``frontdoor-replay`` matrix op)
consumes.

Streams (the ``stream`` field of every line):

- ``result`` — one finished check run, the full
  :class:`~activemonitor_tpu.obs.history.CheckResult` wire dict plus
  its ``key``. Tapped via ``ResultHistory.subscribe`` — the hook PR 15
  added for the coalescing cache — so the reconciler's record path is
  untouched.
- ``attribution`` — the lost-goodput bucket/why-line stamped on the
  same record path (``result.bucket`` non-empty). Redundant with the
  result stream BY DESIGN: ``hack/journal_check.py`` cross-checks the
  two (conservation across streams) so a dropped line cannot silently
  skew the attribution decomposition.
- ``arrival`` — one front-door submission: booked tenant, check key,
  outcome, refusal reason, shard, inter-arrival gap and (when the
  submit came from ``run_dag``) the DAG shape. This is the workload
  trace ROADMAP item 6 asks for.

Wire format: segmented JSONL. Segments are ``journal-000001.jsonl``,
``journal-000002.jsonl``, … — a contiguous chain whose highest sequence
number is the active segment. Every segment opens with a header line
``{"v": 1, "stream": "header", "segment": N, "ts": …}``; every event
line carries the same ``"v"`` so version skew is detected per line.
Rotation is size-capped (``max_bytes`` per segment); compaction drops
the oldest segments beyond ``max_segments`` so the sidecar directory is
bounded like every other ring in the repo.

Restore discipline (the ``analysis/baseline.py`` ``load_blob``
contract): :func:`read_journal` either returns the full event list with
no warnings, or returns NOTHING plus a structured warning —
``{"reason": "version-skew" | "corrupt-line" | "missing-segment" |
"corrupt-header" | "unreadable", "detail": …}``. A torn journal
restores FRESH: partially applying a corrupt chain is how windows
silently double-count, and a fresh window is merely short, never wrong.
(The writer flushes whole lines, so a SIGKILL between events leaves a
clean chain — the fresh-restore path is for real corruption, not for
ordinary crashes.)

Design constraints shared with the rest of ``obs/``: **injectable
Clock** (``hack/lint.py`` bans wall-clock reads in this module, same
module-name keying as ``flightrec.py``) and **never raises into the
recording path** — a full disk costs durability and increments the
``dropped`` counter, never the reconcile or the submit that fed it.
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import re
from typing import Dict, List, Optional, Tuple

from activemonitor_tpu.obs.history import CheckResult
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.journal")

JOURNAL_VERSION = 1

STREAM_RESULT = "result"
STREAM_ATTRIBUTION = "attribution"
STREAM_ARRIVAL = "arrival"
STREAMS = (STREAM_RESULT, STREAM_ATTRIBUTION, STREAM_ARRIVAL)

# header pseudo-stream: the first line of every segment
STREAM_HEADER = "header"

SEGMENT_PREFIX = "journal-"
SEGMENT_SUFFIX = ".jsonl"
_SEGMENT_RE = re.compile(r"^journal-(\d{6})\.jsonl$")

# one segment's byte cap before rotation; small enough that compaction
# granularity is useful, large enough that a day of 60 s-cadence checks
# fits in a handful of segments
DEFAULT_MAX_BYTES = 1 << 20
# segments retained by compaction (cap × count bounds the directory)
DEFAULT_MAX_SEGMENTS = 8


def segment_name(seq: int) -> str:
    return f"{SEGMENT_PREFIX}{seq:06d}{SEGMENT_SUFFIX}"


def list_segments(journal_dir: str) -> List[Tuple[int, str]]:
    """``(seq, absolute path)`` for every segment, oldest first."""
    try:
        names = os.listdir(journal_dir)
    except OSError:
        return []
    out = []
    for name in names:
        m = _SEGMENT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(journal_dir, name)))
    out.sort()
    return out


def rotate_capped(path: str, max_bytes: int, keep: int = 4) -> bool:
    """Size-capped shift rotation for a single-file JSONL sink (the
    flight recorder's ``flightrec.jsonl``): when ``path`` has reached
    ``max_bytes``, shift ``<stem>-(keep-1)`` off the end, bump every
    ``<stem>-N`` to ``<stem>-(N+1)``, and move the active file to
    ``<stem>-1`` — so ``path`` itself stays the active file the tests
    and ``jq`` pipelines read. Returns True when a rotation happened.
    Best-effort: an OSError costs the rotation, never the append."""
    if max_bytes <= 0:
        return False
    try:
        if not os.path.exists(path) or os.path.getsize(path) < max_bytes:
            return False
        stem, ext = os.path.splitext(path)
        oldest = f"{stem}-{keep}{ext}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for n in range(keep - 1, 0, -1):
            src = f"{stem}-{n}{ext}"
            if os.path.exists(src):
                os.replace(src, f"{stem}-{n + 1}{ext}")
        os.replace(path, f"{stem}-1{ext}")
        return True
    except OSError:
        log.exception("rotation failed for %s", path)
        return False


def prune_empty_dirs(root: str) -> int:
    """Remove empty directories under (and including) ``root``,
    deepest first; returns how many were removed. The profiler paths
    share this: ``jax.profiler.trace`` creates its capture directory
    eagerly, so a probe that dies before the first device event leaves
    an empty dir behind — both ``probes/cli.py --profile`` and the
    manager's profile-on-anomaly captures sweep it away rather than
    shipping operators an empty artifact. Best-effort: an OSError
    (concurrent writer, permissions) costs the prune, never the run."""
    removed = 0
    try:
        for dirpath, _dirnames, _filenames in os.walk(root, topdown=False):
            # re-list: bottom-up pruning may have just emptied dirpath,
            # and the walk's cached listing wouldn't know
            if not os.listdir(dirpath):
                os.rmdir(dirpath)
                removed += 1
    except OSError:
        log.debug("empty-dir prune failed under %s", root, exc_info=True)
    return removed


def _parse_ts(value) -> Optional[datetime.datetime]:
    try:
        ts = datetime.datetime.fromisoformat(str(value))
    except (TypeError, ValueError):
        return None
    if ts.tzinfo is None:
        ts = ts.replace(tzinfo=datetime.timezone.utc)
    return ts


def result_from_doc(doc: dict) -> CheckResult:
    """Rebuild a :class:`CheckResult` from its journaled wire dict
    (the ``to_dict`` spelling: ``latency_seconds``, isoformat ts)."""
    ts = _parse_ts(doc.get("ts"))
    if ts is None:
        raise ValueError(f"unparseable result ts: {doc.get('ts')!r}")
    return CheckResult(
        ts=ts,
        ok=bool(doc.get("ok")),
        latency=max(0.0, float(doc.get("latency_seconds", 0.0))),
        workflow=str(doc.get("workflow", "")),
        trace_id=str(doc.get("trace_id", "")),
        metrics={str(k): float(v) for k, v in (doc.get("metrics") or {}).items()},
        timings={str(k): float(v) for k, v in (doc.get("timings") or {}).items()},
        roofline=dict(doc.get("roofline") or {}),
        bucket=str(doc.get("bucket", "")),
        why=str(doc.get("why", "")),
    )


def read_journal(journal_dir: str) -> Tuple[List[dict], List[dict]]:
    """Read every event from a journal directory, oldest first.

    Returns ``(events, warnings)``. All-or-nothing per the module
    docstring: any warning means ``events`` is empty (restore fresh).
    An absent or empty directory is a clean first boot — no events, no
    warning."""
    segments = list_segments(journal_dir)
    if not segments:
        return [], []
    seqs = [seq for seq, _ in segments]
    expected = list(range(seqs[0], seqs[0] + len(seqs)))
    if seqs != expected:
        missing = sorted(set(range(seqs[0], seqs[-1] + 1)) - set(seqs))
        return [], [
            {
                "reason": "missing-segment",
                "detail": (
                    f"chain {seqs[0]}..{seqs[-1]} is missing segment(s) "
                    f"{missing}"
                ),
            }
        ]
    events: List[dict] = []
    for seq, path in segments:
        name = os.path.basename(path)
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError as exc:
            return [], [{"reason": "unreadable", "detail": f"{name}: {exc}"}]
        if not lines:
            return [], [{"reason": "corrupt-header", "detail": f"{name}: empty segment"}]
        try:
            header = json.loads(lines[0])
        except ValueError:
            return [], [
                {"reason": "corrupt-header", "detail": f"{name}:1 is not JSON"}
            ]
        if not isinstance(header, dict) or header.get("stream") != STREAM_HEADER:
            return [], [
                {"reason": "corrupt-header", "detail": f"{name}:1 is not a header"}
            ]
        if header.get("v") != JOURNAL_VERSION:
            return [], [
                {
                    "reason": "version-skew",
                    "detail": (
                        f"{name} is journal version {header.get('v')!r}, "
                        f"this build reads {JOURNAL_VERSION}"
                    ),
                }
            ]
        for lineno, line in enumerate(lines[1:], start=2):
            if not line.strip():
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                return [], [
                    {
                        "reason": "corrupt-line",
                        "detail": f"{name}:{lineno} is truncated or not JSON",
                    }
                ]
            if (
                not isinstance(doc, dict)
                or doc.get("v") != JOURNAL_VERSION
                or doc.get("stream") not in STREAMS
            ):
                return [], [
                    {
                        "reason": "corrupt-line",
                        "detail": f"{name}:{lineno} has no valid stream/version",
                    }
                ]
            events.append(doc)
    return events, []


class TelemetryJournal:
    """Append-only, segmented, never-raises telemetry sidecar.

    One instance per journal directory, owned by the Manager (wired via
    ``--journal-dir``); ``FleetStatus.attach_journal`` replays it into
    the fresh rings and then subscribes :meth:`record_result` as a
    result-history tap, and the front door records its arrival stream
    through :meth:`record_arrival`."""

    def __init__(
        self,
        journal_dir: str,
        *,
        clock: Optional[Clock] = None,
        max_bytes: int = DEFAULT_MAX_BYTES,
        max_segments: int = DEFAULT_MAX_SEGMENTS,
        metrics=None,  # MetricsCollector (duck-typed; optional)
    ):
        if not journal_dir:
            raise ValueError("journal_dir is required")
        self.journal_dir = journal_dir
        self.clock = clock or Clock()
        self.max_bytes = max(1024, int(max_bytes))
        self.max_segments = max(1, int(max_segments))
        self.metrics = metrics
        self.appended: Dict[str, int] = {s: 0 for s in STREAMS}
        self.replayed: Dict[str, int] = {s: 0 for s in STREAMS}
        self.dropped = 0
        self.compacted_segments = 0
        self.restore_warning: Optional[dict] = None
        self._fh = None
        self._bytes = 0
        self._header_bytes = 0
        # continue an existing chain: the next append rotates onto a
        # NEW segment past the highest existing one, never appends into
        # a segment an earlier incarnation may have torn
        segments = list_segments(journal_dir)
        self._seq = segments[-1][0] if segments else 0
        # newest event's wall ts (isoformat) for the lag gauge
        self._last_event_iso: Optional[str] = None

    # -- recording taps --------------------------------------------------
    def record_result(self, key: str, result: CheckResult) -> None:
        """``ResultHistory.subscribe`` tap: journal the run, and — when
        the record path stamped a lost-goodput bucket — the attribution
        event alongside it."""
        doc = dict(result.to_dict())
        doc["key"] = key
        self._append(STREAM_RESULT, doc)
        if result.bucket:
            self._append(
                STREAM_ATTRIBUTION,
                {
                    "key": key,
                    "ts": doc["ts"],
                    "ok": result.ok,
                    "bucket": result.bucket,
                    "why": result.why,
                },
            )

    def record_arrival(
        self,
        *,
        tenant: str,
        check: str,
        outcome: str,
        gap: float,
        reason: str = "",
        shard: int = 0,
        freshness: Optional[float] = None,
        dag: Optional[dict] = None,
    ) -> None:
        """One front-door submission (the workload trace). ``gap`` is
        the inter-arrival gap in seconds on the door's monotonic
        timeline; ``dag`` the shape dict when the submit came from
        ``run_dag``."""
        self._append(
            STREAM_ARRIVAL,
            {
                "ts": self.clock.now().isoformat(),
                "tenant": tenant,
                "check": check,
                "outcome": outcome,
                "reason": reason,
                "shard": int(shard),
                "gap": max(0.0, float(gap)),
                "freshness": freshness,
                "dag": dag,
            },
        )

    # -- the append path (never raises) ----------------------------------
    def _append(self, stream: str, doc: dict) -> None:
        try:
            line = json.dumps(
                {"v": JOURNAL_VERSION, "stream": stream, **doc}, default=str
            )
            self._ensure_segment(len(line) + 1)
            self._fh.write(line + "\n")
            # whole-line flush: a kill between appends leaves a clean
            # chain, which is what makes fresh-restore-on-corruption an
            # acceptable discipline (see module docstring)
            self._fh.flush()
            self._bytes += len(line) + 1
            self.appended[stream] += 1
            ts = doc.get("ts")
            if ts:
                self._last_event_iso = str(ts)
            if self.metrics is not None:
                self.metrics.record_journal_append(stream)
        except Exception:
            self.dropped += 1
            log.exception("journal append failed (%s)", stream)
            if self.metrics is not None:
                try:
                    self.metrics.record_journal_dropped()
                except Exception:
                    log.exception("journal drop counter failed")

    def _ensure_segment(self, incoming: int) -> None:
        if (
            self._fh is not None
            and self._bytes + incoming > self.max_bytes
            # a segment always takes at least one event past its
            # header, so an oversized single event cannot wedge the
            # writer into rotating forever
            and self._bytes > self._header_bytes
        ):
            self._fh.close()
            self._fh = None
        if self._fh is None:
            os.makedirs(self.journal_dir, exist_ok=True)
            self._seq += 1
            path = os.path.join(self.journal_dir, segment_name(self._seq))
            self._fh = open(path, "w")
            header = json.dumps(
                {
                    "v": JOURNAL_VERSION,
                    "stream": STREAM_HEADER,
                    "segment": self._seq,
                    "ts": self.clock.now().isoformat(),
                }
            )
            self._fh.write(header + "\n")
            self._fh.flush()
            self._bytes = self._header_bytes = len(header) + 1
            self.compact()

    def compact(self) -> int:
        """Drop the oldest segments beyond ``max_segments`` (never the
        active one). Returns how many were removed; driven inline on
        rotation and by the manager's goodput loop."""
        removed = 0
        try:
            segments = list_segments(self.journal_dir)
            while len(segments) > self.max_segments:
                _seq, path = segments.pop(0)
                os.remove(path)
                removed += 1
        except OSError:
            log.exception("journal compaction failed in %s", self.journal_dir)
        self.compacted_segments += removed
        return removed

    def close(self) -> None:
        try:
            if self._fh is not None:
                self._fh.close()
        except OSError:
            pass
        self._fh = None

    # -- replay ----------------------------------------------------------
    def replay_into(self, history=None) -> dict:
        """Replay the journal tail into a fresh ``ResultHistory`` (and
        count every stream). All-or-nothing: a torn chain restores
        fresh and parks the structured warning on
        :attr:`restore_warning` — never crashes, never double-counts.
        Result events bypass ``ResultHistory.record`` (via
        ``restore``) so replay re-stamps nothing and re-notifies no
        subscriber — re-journaling the journal is the double-count this
        API shape exists to prevent."""
        events, warnings = read_journal(self.journal_dir)
        counts = {s: 0 for s in STREAMS}
        if warnings:
            self.restore_warning = warnings[0]
            log.warning("journal restored fresh: %s", warnings[0])
            return {"replayed": counts, "warnings": warnings}
        for doc in events:
            stream = doc["stream"]
            if stream == STREAM_RESULT and history is not None:
                try:
                    history.restore(doc["key"], result_from_doc(doc))
                except Exception:
                    # one unbuildable result (schema drift inside a
                    # valid line) is dropped, counted, and logged —
                    # the window stays conservative, never wrong
                    self.dropped += 1
                    log.exception("journal replay skipped a result")
                    continue
            counts[stream] += 1
            self.replayed[stream] += 1
            ts = doc.get("ts")
            if ts:
                self._last_event_iso = str(ts)
        if self.metrics is not None:
            for stream, n in counts.items():
                if n:
                    self.metrics.record_journal_replayed(stream, n)
        return {"replayed": counts, "warnings": []}

    # -- surfaces --------------------------------------------------------
    def lag_seconds(self) -> float:
        """Seconds between now and the newest journaled event — how
        stale the durable tail is. 0.0 before any event."""
        ts = _parse_ts(self._last_event_iso) if self._last_event_iso else None
        if ts is None:
            return 0.0
        return max(0.0, (self.clock.now() - ts).total_seconds())

    def segments(self) -> List[dict]:
        out = []
        for seq, path in list_segments(self.journal_dir):
            try:
                size = os.path.getsize(path)
            except OSError:
                size = 0
            out.append(
                {
                    "segment": seq,
                    "name": os.path.basename(path),
                    "bytes": size,
                    "active": seq == self._seq,
                }
            )
        return out

    def snapshot(self) -> dict:
        """The /statusz fleet ``journal`` block (rollup_statusz merges
        these across replicas via ``merge_journal_blocks``)."""
        segments = self.segments()
        return {
            "dir": self.journal_dir,
            "segments": segments,
            "segment_count": len(segments),
            "max_bytes": self.max_bytes,
            "max_segments": self.max_segments,
            "appended": dict(self.appended),
            "replayed": dict(self.replayed),
            "dropped": self.dropped,
            "compacted_segments": self.compacted_segments,
            "lag_seconds": self.lag_seconds(),
            "restore_warning": self.restore_warning,
        }

    def export_gauges(self) -> None:
        """Refresh the level gauges (segment count, lag) — driven by
        the manager's goodput loop next to the fleet-goodput refresh;
        the counters increment at append/replay/drop time."""
        if self.metrics is None:
            return
        try:
            self.metrics.set_journal_segments(len(list_segments(self.journal_dir)))
            self.metrics.set_journal_lag(self.lag_seconds())
        except Exception:
            log.exception("journal gauge export failed")
