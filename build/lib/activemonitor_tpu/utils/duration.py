"""Go-style duration parsing.

The reference's cron library accepts "@every <duration>" with Go
duration syntax ("300ms", "1.5h", "2h45m"); this parser accepts the
same grammar so reference specs (e.g. examples/inlineHello.yaml
"@every 1m") work unchanged.
"""

from __future__ import annotations

import re

_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "μs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}

_TOKEN = re.compile(r"(\d+(?:\.\d*)?|\.\d+)(ns|us|µs|μs|ms|s|m|h)")


def parse_go_duration(text: str) -> float:
    """Parse a Go duration string into seconds. Raises ValueError on bad input."""
    s = text.strip()
    if not s:
        raise ValueError("empty duration")
    sign = 1.0
    if s[0] in "+-":
        sign = -1.0 if s[0] == "-" else 1.0
        s = s[1:]
    if not s:
        raise ValueError(f"invalid duration {text!r}")
    if s == "0":
        return 0.0
    total = 0.0
    pos = 0
    for m in _TOKEN.finditer(s):
        if m.start() != pos:
            raise ValueError(f"invalid duration {text!r}")
        total += float(m.group(1)) * _UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s):
        raise ValueError(f"invalid duration {text!r}")
    return sign * total
