"""Open-loop front-door traffic: seeded Poisson check requests.

The stress soak's offered load, built on the SAME seeded-determinism
contract as the serving probe's generator
(:class:`~activemonitor_tpu.scheduler.arrivals.PoissonArrivals` — one
rng, fixed draw order: arrival then check identity, tenants
round-robin like serving's). Open-loop on purpose: the schedule never
adapts to admission latency, so an overloaded front door shows up as
queue depth and refusals, not as a generator politely slowing down.

A bounded ``checks`` set is the coalescing knob: duplicate traffic is
the POINT (N tenants asking about the same slice), and shrinking the
set raises the duplicate rate the soak's hit-ratio gate measures.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

from activemonitor_tpu.scheduler.arrivals import PoissonArrivals


@dataclass(frozen=True)
class CheckRequest:
    """One front-door request as the generator emits it."""

    rid: int
    tenant: str
    arrival: float  # seconds since schedule start
    check: str  # "namespace/name" identity submitted
    freshness: Optional[float]  # per-request window; None = door default


def open_loop_checks(
    n_requests: int,
    rate_rps: float,
    seed: int,
    checks: Sequence[str],
    tenants: Sequence[str] = ("tenant-a", "tenant-b"),
    freshness: Optional[float] = None,
) -> List[CheckRequest]:
    """Seeded Poisson schedule of check requests: exponential
    inter-arrivals at ``rate_rps``, check identities drawn from the
    bounded ``checks`` set, tenants round-robin. Same seed ⇒
    byte-identical schedule — the same contract the serving trace
    tests pin for their generator."""
    if n_requests < 1 or not checks:
        raise ValueError(
            f"need n_requests >= 1 and a non-empty check set, got "
            f"{n_requests}/{len(checks)}"
        )
    process = PoissonArrivals(rate_rps, seed)
    out: List[CheckRequest] = []
    for rid in range(n_requests):
        now = process.next()
        out.append(
            CheckRequest(
                rid=rid,
                tenant=tenants[rid % len(tenants)],
                arrival=now,
                check=process.choice(checks),
                freshness=freshness,
            )
        )
    return out


def replayed_checks(schedule) -> List[CheckRequest]:
    """A recorded trace as front-door requests: same emission shape as
    :func:`open_loop_checks`, but arrivals/tenants/checks come from a
    :class:`~activemonitor_tpu.obs.replay.RecordedArrivals` schedule
    (draw order per its contract: ``next()``, then tenant, then check)
    instead of a seeded Poisson process. Same recording ⇒
    byte-identical request list — replay's half of the determinism
    contract."""
    out: List[CheckRequest] = []
    for rid in range(len(schedule)):
        now = schedule.next()
        out.append(
            CheckRequest(
                rid=rid,
                tenant=schedule.choice(schedule.tenants),
                arrival=now,
                check=schedule.choice(schedule.checks),
                freshness=schedule.freshness,
            )
        )
    return out
