"""Roofline layer (ISSUE 9): ridge-point math and bound classification
(obs/roofline.py), the compat cost-analysis shim, probe-side capture
with structured skips (`cost_source: model` off-TPU, never a TPU-bar
comparison), the contract `roofline` block through the collector's
pinned families, /statusz + flight bundles + `am-tpu roofline`, and
the attribution↔roofline consistency acceptance (memory-bound verdict
⇒ `hbm` bucket, conservation intact).
"""

import asyncio
import collections
import json

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine
from activemonitor_tpu.engine.base import PHASE_FAILED, PHASE_SUCCEEDED
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.obs import FleetStatus
from activemonitor_tpu.obs import roofline as roofline_model
from activemonitor_tpu.obs.attribution import BUCKETS, classify_run
from activemonitor_tpu.probes.rated import RatedSpec, ridge_point
from activemonitor_tpu.utils.clock import FakeClock

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"

V5E = RatedSpec(
    "v5e", bf16_tflops=197.0, hbm_gbps=819.0, ici_unidir_gbps=45.0, ici_links=4
)


def make_hc(name="hc-roof", repeat=60):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": "health"},
            "spec": {
                "repeatAfterSec": repeat,
                "level": "cluster",
                "backoffMax": 1,
                "backoffMin": 1,
                "workflow": {
                    "generateName": f"{name}-",
                    "workflowtimeout": 30,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "sa",
                        "source": {"inline": WF_INLINE},
                    },
                },
            },
        }
    )


def verdict_entry(
    bound="memory",
    fraction=0.41,
    intensity=0.5,
    cost_source="xla",
    **extra,
):
    entry = {
        "bound": bound,
        "intensity": intensity,
        "fraction": fraction,
        "ceiling_flops": 4.1e11,
        "achieved_flops": 1.7e11,
        "ridge": 240.5,
        "cost_source": cost_source,
        "flops": 8.4e6,
        "hbm_bytes": 1.7e7,
    }
    entry.update(extra)
    return entry


# ---------------------------------------------------------------------
# rated table: ridge point + validated override (ISSUE satellite)
# ---------------------------------------------------------------------


def test_ridge_point_derivation_and_override(monkeypatch):
    # v5e: 197e12 / 819e9 ≈ 240.5 FLOPs/byte, exactly P/B
    assert V5E.ridge_flops_per_byte == pytest.approx(197e12 / 819e9)
    assert ridge_point(V5E) == pytest.approx(V5E.ridge_flops_per_byte)
    # valid override wins
    monkeypatch.setenv("ACTIVEMONITOR_RATED_RIDGE_FLOPS_PER_BYTE", "120.5")
    assert ridge_point(V5E) == pytest.approx(120.5)
    # malformed / non-positive / non-finite fall back (same _override
    # rules as every rated figure) — the ridge is the pivot of every
    # bound classification and must never go invalid
    for bad in ("twelve", "0", "-3", "inf", "nan"):
        monkeypatch.setenv("ACTIVEMONITOR_RATED_RIDGE_FLOPS_PER_BYTE", bad)
        assert ridge_point(V5E) == pytest.approx(V5E.ridge_flops_per_byte)


# ---------------------------------------------------------------------
# pure classification math
# ---------------------------------------------------------------------


def test_classify_memory_bound_exact():
    # intensity 0.5 F/B, far left of the ridge: ceiling = I × B
    v = roofline_model.classify(
        flops=1e6, hbm_bytes=2e6, seconds=1e-3, spec=V5E
    )
    assert v.bound == "memory"
    assert v.intensity == pytest.approx(0.5)
    assert v.ceiling_flops == pytest.approx(0.5 * 819e9)
    assert v.achieved_flops == pytest.approx(1e9)
    assert v.fraction == pytest.approx(1e9 / (0.5 * 819e9))
    assert v.ridge == pytest.approx(197e12 / 819e9)


def test_classify_compute_bound_exact():
    # a 4096³ matmul: intensity ≈ 1365 F/B, right of the ridge
    dim = 4096
    flops = 2 * dim**3
    hbm_bytes = 3 * dim * dim * 2
    v = roofline_model.classify(
        flops=flops, hbm_bytes=hbm_bytes, seconds=flops / 150e12, spec=V5E
    )
    assert v.bound == "compute"
    assert v.intensity > v.ridge
    assert v.ceiling_flops == pytest.approx(197e12)
    assert v.fraction == pytest.approx(150e12 / 197e12)


def test_classify_honors_the_ridge_override(monkeypatch):
    # intensity 100 F/B sits LEFT of the derived v5e ridge (~240) —
    # memory-bound by default; an operator declaring the effective
    # ridge at 50 (silicon diverging from paper numbers) must flip the
    # bound to compute, ceiling at the flat peak — the override is the
    # pivot of classification, not just a displayed field
    kwargs = dict(flops=100e6, hbm_bytes=1e6, seconds=1e-3, spec=V5E)
    default = roofline_model.classify(**kwargs)
    assert default.bound == "memory"
    monkeypatch.setenv("ACTIVEMONITOR_RATED_RIDGE_FLOPS_PER_BYTE", "50")
    overridden = roofline_model.classify(**kwargs)
    assert overridden.bound == "compute"
    assert overridden.ridge == pytest.approx(50.0)
    assert overridden.ceiling_flops == pytest.approx(197e12)


def test_memory_ceiling_is_clamped_to_the_flat_peak(monkeypatch):
    # ridge overridden ABOVE the derived one: intensity 300 F/B is now
    # memory-bound, but I×B (~246 TF/s) exceeds the 197 TF/s peak — the
    # ceiling must clamp to min(P, I×B) or a healthy chip at 96% of
    # peak reads as a sub-floor degradation
    monkeypatch.setenv("ACTIVEMONITOR_RATED_RIDGE_FLOPS_PER_BYTE", "500")
    v = roofline_model.classify(
        flops=300e6, hbm_bytes=1e6, seconds=300e6 / 190e12, spec=V5E
    )
    assert v.bound == "memory"
    assert v.ceiling_flops == pytest.approx(197e12)
    assert v.fraction == pytest.approx(190e12 / 197e12)


def test_classify_rejects_degenerate_inputs():
    for kwargs in (
        {"flops": 0, "hbm_bytes": 1, "seconds": 1},
        {"flops": 1, "hbm_bytes": 0, "seconds": 1},
        {"flops": 1, "hbm_bytes": 1, "seconds": 0},
    ):
        assert roofline_model.classify(spec=V5E, **kwargs) is None


def test_classify_comm_uses_the_ici_roofline():
    v = roofline_model.classify_comm(
        busbw_gbps=60.0, rated_busbw_gbps=90.0, payload_bytes=1e6, flops=5e5
    )
    assert v.bound == "comm"
    assert v.fraction == pytest.approx(60.0 / 90.0)
    assert v.intensity == pytest.approx(0.5)
    assert roofline_model.classify_comm(busbw_gbps=1.0, rated_busbw_gbps=0) is None


def test_entry_validation_and_prefix_match():
    good = verdict_entry()
    assert roofline_model.valid_entry(good)
    assert not roofline_model.valid_entry({"bound": "comm"})  # trio missing
    assert not roofline_model.valid_entry(verdict_entry(bound="weird"))
    assert not roofline_model.valid_entry(verdict_entry(fraction="0.4"))
    assert not roofline_model.valid_entry("nope")
    block = {"mxu": verdict_entry(bound="compute"), "mxu-int8": good}
    # longest prefix wins: the int8 fraction maps to the int8 verdict
    assert (
        roofline_model.entry_for_metric(block, "mxu-int8-fraction-of-rated")
        is block["mxu-int8"]
    )
    assert (
        roofline_model.entry_for_metric(block, "mxu-fraction-of-rated")
        is block["mxu"]
    )
    assert roofline_model.entry_for_metric(block, "hbm-stream-gbps") is None
    assert roofline_model.entry_for_metric(None, "mxu") is None


def test_valid_entry_rejects_non_finite_values():
    # JSON round-trips NaN/Infinity without error; the trust gate must
    # drop them before they poison min(), the gauges, or strict-JSON
    # /statusz consumers
    for bad in (float("nan"), float("inf"), float("-inf")):
        assert not roofline_model.valid_entry(verdict_entry(fraction=bad))
        assert not roofline_model.valid_entry(verdict_entry(intensity=bad))
    nan_payload = json.loads(json.dumps({"fraction": float("nan")}))
    assert nan_payload["fraction"] != nan_payload["fraction"]  # NaN survives


def test_int8_without_an_int8_mode_skips_instead_of_misjudging(monkeypatch):
    # v4 has no int8 MXU mode (int8_tops=0): the probe must record an
    # explicit skip, NOT let the capture fall back to the device's bf16
    # roofline and flag a healthy chip as a rated degradation
    from activemonitor_tpu.probes import matmul

    v4 = RatedSpec(
        "v4", bf16_tflops=275.0, hbm_gbps=1228.0, ici_unidir_gbps=45.0,
        ici_links=6,
    )
    monkeypatch.setattr(matmul, "rated_for", lambda _kind: v4)
    result = matmul.run(dim=128, iters=1, dtype="int8")
    names = [m.name for m in result.metrics]
    assert "mxu-int8-roofline-fraction" not in names
    assert "mxu-int8-arithmetic-intensity" not in names
    skip = result.details["roofline"]["mxu-int8"]["skipped"]
    assert "no rated int8 roofline" in skip and "v4" in skip


def test_verdict_line_spelling():
    assert (
        roofline_model.verdict_line(verdict_entry())
        == "0.41 of memory-bound ceiling (xla cost model)"
    )


# ---------------------------------------------------------------------
# compat shim
# ---------------------------------------------------------------------


def test_compile_cost_analysis_normalizes_shapes():
    import jax.numpy as jnp

    from activemonitor_tpu.utils.compat import compile_cost_analysis

    cost = compile_cost_analysis(
        lambda a, b: a @ b,
        jnp.ones((128, 128), jnp.bfloat16),
        jnp.ones((128, 128), jnp.bfloat16),
    )
    # this container's jaxlib returns a one-dict LIST with XLA's
    # space-separated keys; the shim must hand back the normalized trio
    assert cost is not None
    assert cost["flops"] >= 2 * 128**3
    assert cost["bytes_accessed"] > 0
    assert set(cost) == {"flops", "bytes_accessed", "output_bytes"}
    # a non-lowerable input reads as unavailable, never a raise
    assert compile_cost_analysis("not a function") is None
    # an analysis missing either half is no analysis: the caller's
    # analytic fallback must engage instead of a degenerate-cost skip
    from activemonitor_tpu.utils.compat import compiled_cost_analysis

    class FlopsOnly:
        @staticmethod
        def cost_analysis():
            return [{"flops": 5.0}]

    class BytesOnly:
        @staticmethod
        def cost_analysis():
            return {"bytes accessed": 7.0}

    assert compiled_cost_analysis(FlopsOnly()) is None
    assert compiled_cost_analysis(BytesOnly()) is None


def test_capture_reuses_a_precomputed_xla_cost_on_tpu():
    # an AOT probe (training-step) hands capture() the cost analysis of
    # the VERY executable it timed — no second compile; honored only on
    # TPU (interpret-mode policy: analytic model, labeled as such)
    class FakeTpu:
        platform = "tpu"
        device_kind = "TPU v5 lite"

        @staticmethod
        def memory_stats():
            return {}

    cost = {"flops": 2e12, "bytes_accessed": 4e9, "output_bytes": 1e9}
    cap = roofline_model.capture(
        "train", seconds=0.02, xla_cost=cost, spec=V5E, device=FakeTpu()
    )
    entry = cap.block["train"]
    assert entry["cost_source"] == "xla"
    assert entry["intensity"] == pytest.approx(2e12 / 4e9)
    assert entry["achieved_flops"] == pytest.approx(2e12 / 0.02)
    # off-TPU the same precomputed cost is ignored for the analytic model
    cap = roofline_model.capture(
        "train", seconds=0.02, xla_cost=cost,
        model_flops=1e12, model_bytes=2e9, spec=V5E,
    )
    assert cap.block["train"]["cost_source"] == "model"
    assert cap.block["train"]["flops"] == pytest.approx(1e12)


# ---------------------------------------------------------------------
# probe-side capture
# ---------------------------------------------------------------------


def test_capture_model_fallback_is_labeled_and_verdicts(monkeypatch):
    # CPU + injected spec: the analytic model classifies, labeled
    # `model` — the full verdict path without TPU hardware
    cap = roofline_model.capture(
        "mxu",
        seconds=1e-3,
        model_flops=2 * 4096**3,
        model_bytes=3 * 4096 * 4096 * 2,
        spec=V5E,
    )
    assert not cap.skipped
    assert cap.block["mxu"]["cost_source"] == "model"
    assert cap.block["mxu"]["bound"] == "compute"
    names = [m.name for m in cap.metrics]
    assert names == ["mxu-arithmetic-intensity", "mxu-roofline-fraction"]
    assert cap.details["roofline"]["mxu"] is cap.block["mxu"]


def test_capture_without_spec_keeps_intensity_and_skips_fraction():
    # interpret mode on unknown silicon: intensity is still evidence,
    # but there is no rated roofline — the fraction is a STRUCTURED
    # skip, never a TPU-bar comparison
    cap = roofline_model.capture(
        "hbm", seconds=1e-3, model_flops=1e6, model_bytes=2e6
    )
    assert [m.name for m in cap.metrics] == ["hbm-arithmetic-intensity"]
    assert cap.block == {}
    assert "no rated roofline" in cap.details["roofline"]["hbm"]["skipped"]


def test_capture_skip_reasons_are_structured():
    disabled = roofline_model.capture("mxu", seconds=1.0, enabled=False)
    assert disabled.skipped
    assert "disabled" in disabled.details["roofline"]["mxu"]["skipped"]
    no_model = roofline_model.capture("mxu", seconds=1.0, spec=V5E)
    assert "no analytic model" in no_model.details["roofline"]["mxu"]["skipped"]
    degenerate = roofline_model.capture(
        "mxu", seconds=0.0, model_flops=1.0, model_bytes=1.0, spec=V5E
    )
    assert "degenerate" in degenerate.details["roofline"]["mxu"]["skipped"]


def test_probe_contract_carries_the_roofline_block():
    from activemonitor_tpu.probes.base import ProbeResult

    result = ProbeResult(ok=True, summary="s")
    roofline_model.apply(
        result,
        roofline_model.capture(
            "mxu", seconds=1e-3, model_flops=2e12, model_bytes=3e7, spec=V5E
        ),
    )
    doc = json.loads(result.contract_line())
    assert "roofline" in doc
    assert doc["roofline"]["mxu"]["bound"] == "compute"
    # and skips stay OUT of the contract (details-only)
    skipped = ProbeResult(ok=True, summary="s")
    roofline_model.apply(
        skipped, roofline_model.capture("mxu", seconds=1.0, enabled=False)
    )
    assert "roofline" not in json.loads(skipped.contract_line())
    assert "roofline" in skipped.details


def test_matmul_probe_emits_intensity_and_structured_skip_on_cpu():
    from activemonitor_tpu.probes import matmul

    result = matmul.run(dim=128, iters=1)
    names = [m.name for m in result.metrics]
    assert "mxu-arithmetic-intensity" in names
    # CPU: no rated spec, so no fraction — and the omission is recorded
    assert "mxu-roofline-fraction" not in names
    assert "skipped" in result.details["roofline"]["mxu"]
    # --no-roofline drops the capture but still records why
    result = matmul.run(dim=128, iters=1, roofline=False)
    assert "mxu-arithmetic-intensity" not in [m.name for m in result.metrics]
    assert "disabled" in result.details["roofline"]["mxu"]["skipped"]


def test_collectives_probe_records_skips_on_non_rated_hardware():
    # the collectives sweep on CPU/interpret hardware has no ICI
    # roofline: every builtin case must record a structured skip, not
    # silently omit the fields (the same contract as every capture).
    # Driven through _emit with canned measurements — the skip logic
    # lives there, and real collectives would spend tier-1 budget on
    # compiles that prove nothing extra.
    from activemonitor_tpu.parallel.collectives import CollectiveResult
    from activemonitor_tpu.probes import collectives

    def entry(label, base):
        return (
            label, base, 4,
            CollectiveResult(
                name=base, payload_bytes=1 << 20, n_devices=4,
                seconds_per_op=1e-3, algbw_gbps=1.0, busbw_gbps=1.0,
            ),
        )

    # CPU run (this test's platform): no rated spec ⇒ structured skip
    result = collectives._emit(
        [entry("allgather", "allgather")], 0.8, "ctx", {}
    )
    skip = result.details["roofline"]["collective-allgather"]["skipped"]
    assert "no rated ICI ceiling" in skip
    # zoo cases say WHY they carry no verdict even on rated silicon
    result = collectives._emit(
        [entry("allgather-ring", "allgather-ring")], 0.8, "ctx", {}
    )
    skip = result.details["roofline"]["collective-allgather-ring"]["skipped"]
    assert "modeled algorithmic bar" in skip
    # --no-roofline wins over every other reason
    result = collectives._emit(
        [entry("allgather", "allgather")], 0.8, "ctx", {}, roofline=False
    )
    skip = result.details["roofline"]["collective-allgather"]["skipped"]
    assert "disabled" in skip


def test_suite_collects_structured_skip_reasons():
    # the quick-mode contract (ISSUE satellite): a battery whose probes
    # could not run cost analysis carries the reasons in details —
    # asserted on the suite's merge logic with canned sub-results
    from activemonitor_tpu.probes import suite as suite_module
    from activemonitor_tpu.probes.base import ProbeResult

    verdict = ProbeResult(ok=True, summary="ok")
    roofline_model.apply(
        verdict,
        roofline_model.capture(
            "mxu", seconds=1e-3, model_flops=2e12, model_bytes=3e7, spec=V5E
        ),
    )
    skipped = ProbeResult(ok=True, summary="ok")
    roofline_model.apply(
        skipped,
        roofline_model.capture("hbm", seconds=1.0, model_flops=1e6, model_bytes=2e6),
    )

    results = [("matmul", verdict), ("hbm", skipped)]
    merged: dict = {}
    skips: dict = {}
    for _name, result in results:
        merged.update(result.roofline)
        for prefix, entry in (result.details.get("roofline") or {}).items():
            if isinstance(entry, dict) and "skipped" in entry:
                skips[prefix] = entry["skipped"]
    assert "mxu" in merged and "hbm" not in merged
    assert "no rated roofline" in skips["hbm"]
    # the shipped suite.run really implements that merge (source pin —
    # the fake above must not drift from the real battery)
    import inspect

    src = inspect.getsource(suite_module.run)
    assert "roofline_skipped" in src and "merged_roofline" in src


# ---------------------------------------------------------------------
# collector: parse + pinned families
# ---------------------------------------------------------------------


def contract_status(metrics=None, roofline=None):
    doc = {"metrics": metrics or []}
    if roofline is not None:
        doc["roofline"] = roofline
    return {
        "outputs": {
            "parameters": [{"name": "metrics", "value": json.dumps(doc)}]
        }
    }


def test_parse_roofline_validates_entries():
    status = contract_status(
        roofline={
            "hbm": verdict_entry(),
            "bad-bound": verdict_entry(bound="mystery"),
            "bad-types": {"bound": "memory", "intensity": "x", "fraction": 1},
            "": verdict_entry(),
        }
    )
    parsed = MetricsCollector.parse_roofline(status)
    assert list(parsed) == ["hbm"]
    assert MetricsCollector.parse_roofline({}) == {}
    assert MetricsCollector.parse_roofline({"outputs": {"parameters": [
        {"name": "m", "value": "not json"}
    ]}}) == {}


def test_record_roofline_families_and_bound_flip():
    mc = MetricsCollector()
    labels = lambda bound: {  # noqa: E731 - tiny local shorthand
        "healthcheck_name": "hc-a", "metric": "hbm", "bound": bound,
    }
    mc.record_custom_metrics(
        "hc-a",
        contract_status(roofline={"hbm": verdict_entry(hbm_peak_bytes=2.5e9)}),
        run_id="wf-1",
    )
    assert mc.sample_value(
        "healthcheck_probe_roofline_fraction", labels("memory")
    ) == pytest.approx(0.41)
    assert mc.sample_value(
        "healthcheck_probe_arithmetic_intensity",
        {"healthcheck_name": "hc-a", "metric": "hbm"},
    ) == pytest.approx(0.5)
    assert mc.sample_value(
        "healthcheck_hbm_peak_bytes", {"healthcheck_name": "hc-a"}
    ) == pytest.approx(2.5e9)
    assert mc.sample_value(
        "healthcheck_probe_roofline_runs_total",
        {"healthcheck_name": "hc-a", "bound": "memory"},
    ) == 1.0
    # a replay with the same run id records nothing (shared dedupe)
    mc.record_custom_metrics(
        "hc-a", contract_status(roofline={"hbm": verdict_entry()}), run_id="wf-1"
    )
    assert mc.sample_value(
        "healthcheck_probe_roofline_runs_total",
        {"healthcheck_name": "hc-a", "bound": "memory"},
    ) == 1.0
    # a multi-metric block on ONE bound increments the runs counter
    # once (per run per bound), not once per entry — coverage
    # dashboards divide by it as a run count
    mc.record_custom_metrics(
        "hc-b",
        contract_status(
            roofline={
                "hbm": verdict_entry(),
                "decode": verdict_entry(fraction=0.8),
                "mxu": verdict_entry(bound="compute"),
            }
        ),
        run_id="wf-b1",
    )
    assert mc.sample_value(
        "healthcheck_probe_roofline_runs_total",
        {"healthcheck_name": "hc-b", "bound": "memory"},
    ) == 1.0
    assert mc.sample_value(
        "healthcheck_probe_roofline_runs_total",
        {"healthcheck_name": "hc-b", "bound": "compute"},
    ) == 1.0
    # the kernel crosses the ridge: the stale bound series must drop,
    # not linger beside the new one
    mc.record_custom_metrics(
        "hc-a",
        contract_status(roofline={"hbm": verdict_entry(bound="compute")}),
        run_id="wf-2",
    )
    assert mc.sample_value(
        "healthcheck_probe_roofline_fraction", labels("memory")
    ) is None
    assert mc.sample_value(
        "healthcheck_probe_roofline_fraction", labels("compute")
    ) == pytest.approx(0.41)


# ---------------------------------------------------------------------
# /statusz + flight bundle + history snapshots
# ---------------------------------------------------------------------


def test_latest_snapshot_skips_blockless_runs():
    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    hc = make_hc()
    fleet.record(
        hc, ok=True, latency=1.0, workflow="w1",
        roofline={"hbm": verdict_entry(fraction=0.9)},
    )
    fleet.record(hc, ok=True, latency=1.0, workflow="w2")  # quick run: none
    snapshot = fleet.check_roofline(hc.key)
    assert snapshot is not None
    assert snapshot["worst"] == "hbm"
    assert snapshot["worst_fraction"] == pytest.approx(0.9)
    assert snapshot["worst_bound"] == "memory"
    # and the /statusz entry carries it (schema test pins the field)
    entry = json.loads(json.dumps(fleet.check_summary(hc)))
    assert entry["roofline"]["metrics"]["hbm"]["fraction"] == pytest.approx(0.9)
    # history entries round-trip the block
    assert entry["history"][0]["roofline"]["hbm"]["bound"] == "memory"
    assert entry["history"][1]["roofline"] == {}


def test_worst_fraction_headline_picks_the_minimum():
    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    hc = make_hc()
    fleet.record(
        hc, ok=True, latency=1.0, workflow="w",
        roofline={
            "mxu": verdict_entry(bound="compute", fraction=0.93),
            "hbm": verdict_entry(fraction=0.58),
        },
    )
    snapshot = fleet.check_roofline(hc.key)
    assert snapshot["worst"] == "hbm"
    assert snapshot["worst_fraction"] == pytest.approx(0.58)


def test_flight_bundle_attaches_the_roofline_snapshot():
    from activemonitor_tpu.obs.flightrec import KIND_DEGRADED, FlightRecorder

    clock = FakeClock()
    fleet = FleetStatus(clock, MetricsCollector())
    hc = make_hc()
    fleet.record(
        hc, ok=False, latency=1.0, workflow="w",
        metrics={"hbm-fraction-of-rated": 0.41},
        roofline={"hbm": verdict_entry()},
    )
    recorder = FlightRecorder(clock)
    recorder.fleet = fleet
    recorder.history = fleet.history
    bundle = recorder.record(KIND_DEGRADED, hc.key)
    assert bundle["roofline"]["worst"] == "hbm"
    assert bundle["roofline"]["metrics"]["hbm"]["cost_source"] == "xla"
    # a bundle for a check with no roofline evidence carries null
    fleet.record(make_hc("hc-bare"), ok=True, latency=1.0, workflow="w")
    bare = recorder.record(KIND_DEGRADED, "health/hc-bare")
    assert bare["roofline"] is None


# ---------------------------------------------------------------------
# attribution ↔ roofline consistency
# ---------------------------------------------------------------------


def test_classify_run_cites_the_roofline_verdict():
    verdict = classify_run(
        ok=False,
        metrics={"hbm-fraction-of-rated": 0.41},
        roofline={"hbm": verdict_entry()},
    )
    assert verdict.bucket == "hbm"
    assert "0.41 of memory-bound ceiling (xla cost model)" in verdict.why
    # floored roofline fractions are first-class floor evidence too
    verdict = classify_run(
        ok=False,
        metrics={"ici-allreduce-roofline-fraction": 0.3},
        roofline={"ici-allreduce": verdict_entry(bound="comm", fraction=0.3)},
    )
    assert verdict.bucket == "ici"
    assert "comm-bound ceiling" in verdict.why
    # without a matching block entry the why stays the bare floor line
    verdict = classify_run(ok=False, metrics={"hbm-fraction-of-rated": 0.41})
    assert verdict.bucket == "hbm"
    assert "ceiling" not in verdict.why


# acceptance (ISSUE satellite): scripted FakeClock+FakeEngine fleet —
# the roofline verdict says memory-bound, the lost-goodput share lands
# in `hbm`, and conservation still holds through the gauges.

SCRIPT = (
    [(True, {"hbm-fraction-of-rated": 0.95}, {"hbm": verdict_entry(fraction=0.97)})]
    * 8
    + [
        (
            False,
            {"hbm-fraction-of-rated": 0.41},
            {"hbm": verdict_entry(fraction=0.41)},
        )
    ]
    * 2
)


def scripted_engine(script):
    engine = FakeWorkflowEngine()
    queue = collections.deque(script)
    assigned = {}

    def completer(wf, _count):
        name = wf["metadata"]["name"]
        if name not in assigned:
            if not queue:
                return None
            assigned[name] = queue.popleft()
        ok, metrics, roofline = assigned[name]
        status = {"phase": PHASE_SUCCEEDED if ok else PHASE_FAILED}
        if not ok:
            status["message"] = "scripted failure"
        doc = {
            "metrics": [
                {"name": name_, "value": value}
                for name_, value in (metrics or {}).items()
            ]
        }
        if roofline is not None:
            doc["roofline"] = roofline
        status["outputs"] = {
            "parameters": [{"name": "metrics", "value": json.dumps(doc)}]
        }
        return status

    engine._default_completer = completer
    return engine


async def settle():
    for _ in range(50):
        await asyncio.sleep(0)


async def drive_runs(clock, count, interval=60.0, first=False):
    for i in range(count):
        if not first or i > 0:
            await clock.advance(interval)
        await settle()
        await clock.advance(1.0)
        await settle()


def build_controller(clock, client, engine):
    metrics = MetricsCollector()
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=metrics,
        clock=clock,
    )
    manager = Manager(client=client, reconciler=reconciler, max_parallel=2)
    manager._health_addr = "127.0.0.1:0"
    return manager, reconciler, metrics


@pytest.mark.asyncio
async def test_acceptance_memory_bound_lands_in_hbm_and_conserves(capsys):
    import aiohttp

    from activemonitor_tpu.__main__ import _roofline, build_parser

    clock = FakeClock()
    client = InMemoryHealthCheckClient()
    manager, reconciler, metrics = build_controller(
        clock, client, scripted_engine(SCRIPT)
    )
    await manager.start()
    try:
        hc = make_hc("hc-roof")
        await client.apply(hc)
        await drive_runs(clock, len(SCRIPT), first=True)
        key = "health/hc-roof"
        results = reconciler.fleet.history.results(key)
        assert [r.ok for r in results] == [ok for ok, _m, _r in SCRIPT]
        # record-time attribution: the memory-bound roofline verdict
        # lands the lost runs in the hbm bucket, citing the ceiling
        for lost in results[8:]:
            assert lost.bucket == "hbm"
            assert "0.41 of memory-bound ceiling" in lost.why
            assert lost.roofline["hbm"]["bound"] == "memory"

        # /statusz: per-check roofline block + conservation intact
        port = manager._http_runners[0].addresses[0][1]
        async with aiohttp.ClientSession() as session:
            async with session.get(f"http://127.0.0.1:{port}/statusz") as r:
                assert r.status == 200
                payload = await r.json()
        fleet = payload["fleet"]
        assert fleet["goodput_ratio"] == pytest.approx(0.8)
        assert fleet["goodput"]["attribution"]["hbm"] == pytest.approx(0.2)
        assert sum(fleet["goodput"]["attribution"].values()) == pytest.approx(
            1.0 - fleet["goodput_ratio"], abs=1e-9
        )
        [entry] = payload["checks"]
        assert entry["roofline"]["worst"] == "hbm"
        assert entry["roofline"]["worst_bound"] == "memory"
        assert entry["roofline"]["metrics"]["hbm"]["fraction"] == pytest.approx(0.41)

        # the same conservation through the gauges
        lost = {
            bucket: metrics.sample_value(
                "healthcheck_goodput_lost_ratio", {"subsystem": bucket}
            )
            for bucket in BUCKETS
        }
        ratio = metrics.sample_value("healthcheck_fleet_goodput_ratio", {})
        assert ratio == pytest.approx(0.8)
        assert sum(lost.values()) == pytest.approx(1.0 - ratio, abs=1e-9)
        assert lost["hbm"] == pytest.approx(0.2)
        # the roofline families landed from the same contract
        assert metrics.sample_value(
            "healthcheck_probe_roofline_fraction",
            {"healthcheck_name": "hc-roof", "metric": "hbm", "bound": "memory"},
        ) == pytest.approx(0.41)
        assert metrics.sample_value(
            "healthcheck_probe_roofline_runs_total",
            {"healthcheck_name": "hc-roof", "bound": "memory"},
        ) == float(len(SCRIPT))

        # `am-tpu roofline` renders from the live endpoint
        url = f"http://127.0.0.1:{port}/statusz"
        args = build_parser().parse_args(["roofline", "hc-roof", "--url", url])
        assert await _roofline(args) == 0
        out = capsys.readouterr().out
        assert "worst=hbm" in out
        assert "memory" in out and "0.410" in out
        # unknown check: clean usage failure
        args = build_parser().parse_args(["roofline", "nope", "--url", url])
        assert await _roofline(args) == 1
    finally:
        await manager.stop()


# ---------------------------------------------------------------------
# CLI rendering + flags
# ---------------------------------------------------------------------


def test_roofline_cli_flags_parse():
    from activemonitor_tpu.__main__ import build_parser

    args = build_parser().parse_args(["roofline", "hc-a"])
    assert args.name == "hc-a"
    assert args.namespace is None and args.url is None
    assert args.output == "text"
    args = build_parser().parse_args(
        ["roofline", "hc-a", "-n", "prod", "-o", "json", "--url", "http://x/statusz"]
    )
    assert args.namespace == "prod" and args.output == "json"
    # the probe CLI grew the --roofline toggle
    from activemonitor_tpu.probes.cli import build_parser as probe_parser

    probe_args = probe_parser().parse_args(["matmul"])
    assert probe_args.roofline is True
    probe_args = probe_parser().parse_args(["--no-roofline", "matmul"])
    assert probe_args.roofline is False


def test_render_roofline_pins_the_table():
    from activemonitor_tpu.__main__ import render_roofline

    check = {
        "key": "health/hc-a",
        "roofline": {
            "ts": "2026-01-01T00:00:00+00:00",
            "trace_id": "abc123",
            "worst": "hbm",
            "worst_fraction": 0.58,
            "worst_bound": "memory",
            "metrics": {
                "hbm": verdict_entry(fraction=0.58),
                "mxu": verdict_entry(
                    bound="compute",
                    fraction=0.93,
                    intensity=1365.0,
                    cost_source="model",
                    ceiling_flops=197e12,
                    achieved_flops=183e12,
                ),
                "ici-allreduce": verdict_entry(
                    bound="comm",
                    fraction=0.91,
                    intensity=0.5,
                    ceiling_flops=90e9,
                    achieved_flops=82e9,
                ),
            },
        },
    }
    text = render_roofline(check)
    lines = text.splitlines()
    assert lines[0].startswith("health/hc-a  worst=hbm 0.58 (memory-bound)")
    header = lines[1].split()
    assert header == [
        "METRIC", "BOUND", "INTENSITY", "RIDGE", "CEILING", "ACHIEVED",
        "FRACTION", "SOURCE",
    ]
    body = "\n".join(lines[2:])
    assert "memory" in body and "compute" in body and "comm" in body
    # comm rows render GB/s against their byte/s ceilings, no ridge
    assert "90.0 GB/s" in body and "197.0 TF/s" in body
    # model-sourced rows get the never-a-TPU-bar note
    assert "never compared against a TPU bar" in lines[-1]
    # a check with no evidence says so instead of an empty table
    empty = render_roofline({"key": "health/hc-b", "roofline": None})
    assert "no roofline evidence" in empty
