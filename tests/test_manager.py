"""Manager tests: watch→queue→workers, coalescing, boot resync, requeue."""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.controller.leader import FileLeaderElector
from activemonitor_tpu.controller.manager import Manager
from activemonitor_tpu.engine import FakeWorkflowEngine, succeed_after
from activemonitor_tpu.metrics import MetricsCollector

WF_INLINE = "apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec:\n  entrypoint: m\n"


def make_hc(name="hc-a", repeat=60):
    return HealthCheck.from_dict(
        {
            "metadata": {"name": name, "namespace": "health"},
            "spec": {
                "repeatAfterSec": repeat,
                "level": "cluster",
                "workflow": {
                    "generateName": f"{name}-",
                    "workflowtimeout": 5,
                    "resource": {
                        "namespace": "health",
                        "serviceAccount": "sa",
                        "source": {"inline": WF_INLINE},
                    },
                },
            },
        }
    )


def make_manager(client=None, engine=None, **kwargs):
    client = client or InMemoryHealthCheckClient()
    engine = engine or FakeWorkflowEngine(succeed_after(1))
    reconciler = HealthCheckReconciler(
        client=client,
        engine=engine,
        rbac=RBACProvisioner(InMemoryRBACBackend()),
        recorder=EventRecorder(),
        metrics=MetricsCollector(),
    )
    return Manager(client=client, reconciler=reconciler, **kwargs), client, engine


@pytest.mark.asyncio
async def test_watch_event_drives_reconcile():
    manager, client, engine = make_manager()
    await manager.start()
    try:
        await client.apply(make_hc())
        for _ in range(100):
            await asyncio.sleep(0.02)
            hc = await client.get("health", "hc-a")
            if hc and hc.status.success_count >= 1:
                break
        assert hc.status.status == "Succeeded"
        assert hc.status.success_count == 1
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_boot_resync_reconciles_existing():
    client = InMemoryHealthCheckClient()
    await client.apply(make_hc("pre-existing"))
    manager, client, engine = make_manager(client=client)
    await manager.start()
    try:
        for _ in range(100):
            await asyncio.sleep(0.02)
            hc = await client.get("health", "pre-existing")
            if hc.status.success_count >= 1:
                break
        assert hc.status.success_count == 1
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_queue_coalesces_duplicate_keys():
    manager, client, engine = make_manager()
    manager.enqueue("health", "hc-a")
    manager.enqueue("health", "hc-a")
    manager.enqueue("health", "hc-a")
    assert manager._queue.qsize() == 1


@pytest.mark.asyncio
async def test_requeue_after_error():
    client = InMemoryHealthCheckClient()
    hc = make_hc()
    hc.spec.level = ""  # provokes RBAC "level is not set" -> 1s requeue
    await client.apply(hc)
    manager, client, engine = make_manager(client=client)
    await manager.start()
    try:
        await asyncio.sleep(0.1)
        # fix the spec; the requeue (1s) should pick it up and succeed
        fixed = make_hc()
        await client.apply(fixed)
        for _ in range(200):
            await asyncio.sleep(0.02)
            got = await client.get("health", "hc-a")
            if got.status.success_count >= 1:
                break
        assert got.status.success_count >= 1
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_ready_flag_and_stop_idempotence():
    manager, client, engine = make_manager()
    assert not manager.ready
    await manager.start()
    assert manager.ready
    await manager.stop()
    await manager.stop()  # second stop must not raise


@pytest.mark.asyncio
async def test_file_leader_election_excludes_second_acquirer(tmp_path):
    lock = str(tmp_path / "leader.lock")
    a = FileLeaderElector(lock, poll_seconds=0.05)
    b = FileLeaderElector(lock, poll_seconds=0.05)
    await a.acquire()
    waiter = asyncio.create_task(b.acquire())
    await asyncio.sleep(0.2)
    assert not waiter.done()  # b blocked while a leads
    a.release()
    await asyncio.wait_for(waiter, 5)  # b takes over
    b.release()


@pytest.mark.asyncio
async def test_http_endpoints(unused_tcp_port_factory=None):
    import aiohttp

    port_metrics = 18600
    port_health = 18601
    manager, client, engine = make_manager(
        metrics_bind_address=f"127.0.0.1:{port_metrics}",
        health_probe_bind_address=f"127.0.0.1:{port_health}",
    )
    await manager.start()
    try:
        await client.apply(make_hc())
        await asyncio.sleep(0.3)
        async with aiohttp.ClientSession() as session:
            async with session.get(f"http://127.0.0.1:{port_health}/healthz") as r:
                assert r.status == 200
            async with session.get(f"http://127.0.0.1:{port_health}/readyz") as r:
                assert r.status == 200
            async with session.get(f"http://127.0.0.1:{port_metrics}/metrics") as r:
                text = await r.text()
                assert "healthcheck_success_count" in text
    finally:
        await manager.stop()


@pytest.mark.asyncio
async def test_event_during_processing_requeues_after(monkeypatch):
    """Workqueue semantics: a key being reconciled is marked dirty and
    re-processed after, never concurrently."""
    manager, client, engine = make_manager()
    in_flight = asyncio.Event()
    release = asyncio.Event()
    concurrent = []
    active = set()

    orig = manager.reconciler.reconcile

    async def slow_reconcile(ns, name):
        key = f"{ns}/{name}"
        assert key not in active, "concurrent reconcile of one key"
        active.add(key)
        in_flight.set()
        await release.wait()
        active.discard(key)
        concurrent.append(key)
        return None

    manager.reconciler.reconcile = slow_reconcile
    await manager.start()
    try:
        manager.enqueue("health", "hc-a")
        await asyncio.wait_for(in_flight.wait(), 2)
        manager.enqueue("health", "hc-a")  # event mid-reconcile -> dirty
        await asyncio.sleep(0.05)
        release.set()
        for _ in range(100):
            await asyncio.sleep(0.01)
            if len(concurrent) == 2:
                break
        assert len(concurrent) == 2  # processed twice, sequentially
    finally:
        manager.reconciler.reconcile = orig
        await manager.stop()


@pytest.mark.asyncio
async def test_goodput_rollup():
    import datetime

    manager, client, engine = make_manager()
    await manager.start()
    try:
        # healthy recent check + stale failed check + paused check
        good = make_hc("good")
        await client.apply(good)
        for _ in range(100):
            await asyncio.sleep(0.02)
            hc = await client.get("health", "good")
            if hc.status.success_count >= 1:
                break
        bad = make_hc("bad")
        await client.apply(bad)
        fresh = await client.get("health", "bad")
        fresh.status.status = "Failed"
        fresh.status.finished_at = datetime.datetime.now(datetime.timezone.utc)
        await client.update_status(fresh)
        paused = make_hc("paused", repeat=0)
        await client.apply(paused)

        # run one rollup pass directly instead of waiting 30s
        task = asyncio.create_task(manager._goodput_loop(interval=3600))
        await asyncio.sleep(0.2)
        task.cancel()
        value = manager.reconciler.metrics.registry.get_sample_value(
            "healthcheck_cadence_goodput"
        )
        assert value == 0.5  # good=1 of scheduled=2 (paused excluded)
    finally:
        await manager.stop()
