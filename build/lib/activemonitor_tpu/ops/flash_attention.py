"""Fused flash attention (Pallas) — training-grade single-chip attention.

A fused online-softmax attention kernel with a custom VJP: forward
sweeps K/V blocks per Q block keeping the running max/denominator and
output accumulator in VMEM (the [S, S] score matrix never touches HBM),
and the backward pass recomputes attention probabilities blockwise from
the saved logsumexp — the standard flash-attention recompute strategy,
so training memory stays O(S·D) too. Owning the schedule buys what XLA
fusion cannot guarantee:

- scores/probabilities live entirely in VMEM, forward AND backward
  (HBM traffic O(S·D), not O(S²)) — long sequences stay feasible;
- causal blocks strictly above the diagonal are skipped inside every
  kernel (``pl.when``), so the dead half of the causal grid costs no
  MXU time in either pass.

On non-TPU platforms the kernels run in interpret mode (functionally
identical, slow) so the same code paths are exercised by the CPU test
suite — mirrors ops/stream.py.

Grids put the reduction sweep innermost (TPU grids execute
sequentially, so VMEM scratch carries state across the sweep): forward
and dQ sweep K blocks per Q block; dK/dV sweeps Q blocks per K block.

Complements ops/ring_attention.py: ring attention shards the sequence
ACROSS chips (ICI traffic, sequence parallelism); flash attention fuses
the per-chip block compute. Reference has no analogue (active-monitor
is a Go controller; this is part of the TPU probe library built per
SURVEY.md §5.7-5.8).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30
# lane width of the m/l scratch rows; TPU vregs are (8, 128) so scalars
# carried per Q row live broadcast across one 128-lane vector
_LANES = 128
# backward blocks default smaller than forward: the backward body holds
# four [bq, bk] f32 temporaries (s, p, dp, ds) against the ~16 MB
# scoped-VMEM limit.
# Tuned from the reproducible sweep `python -m activemonitor_tpu.probes
# flash-attention --sweep` (probes/flash.py sweep(); interleaved
# best-of-rounds against tunnel contention). Measured on v5e at S=2048:
# 512x512 ~25 TFLOP/s effective fwd+bwd, 1024x256 ~111, 2048x256 ~117 —
# the tall-q/narrow-k shape wins decisively; 1024x256 keeps the causal
# block skip meaningful at long sequence lengths. Re-run the sweep on
# new silicon before trusting these.
_BWD_BLOCK_Q = 1024
_BWD_BLOCK_K = 256


def _causal_mask(qi, ki, block_q: int, block_k: int, offset: int = 0):
    """Causal visibility for one block pair. ``offset = seq_k - seq_q``
    aligns the diagonal BOTTOM-RIGHT for cross-length attention (the
    flash-attn convention): query row i attends keys ≤ i + offset, so a
    decode-shaped call (q shorter than the KV it extends) sees the full
    prefix and squares reduce to the standard mask (offset 0)."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return q_pos + offset >= k_pos


def _block_mask(causal, qi, ki, block_q: int, block_k: int, offset: int,
                kv_mask_from: int | None):
    """Combined visibility mask for one block pair, or None when every
    entry attends. ``kv_mask_from`` is the first INVALID key position
    (real seq_k) when K/V were padded to a tileable length — padded
    keys must never receive weight."""
    mask = _causal_mask(qi, ki, block_q, block_k, offset) if causal else None
    if kv_mask_from is not None:
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        valid = k_pos < kv_mask_from
        mask = valid if mask is None else (mask & valid)
    return mask


def _seg_mask(qseg_ref, kseg_ref):
    """Packed-sequence visibility: q row attends k only within the same
    segment. Refs hold the [1, block] int32 id slices for this block
    pair."""
    q_seg = qseg_ref[0]  # [block_q]
    k_seg = kseg_ref[0]  # [block_k]
    return q_seg[:, None] == k_seg[None, :]


def _make_attention_kernel(
    causal: bool, block_q: int, block_k: int, num_k: int, scale: float,
    partial: bool, offset: int = 0, kv_len: int | None = None,
    segmented: bool = False,
):
    """One builder for both forward flavors — identical online-softmax
    body (init, causal visibility, attend, last-visible write point);
    only the finalize differs: the full kernel emits the normalized
    output + logsumexp, the ``partial`` kernel emits the raw
    (accumulator, max, denominator) merge state ring attention combines
    across devices (ops/ring_attention.py). ``offset``/``kv_len``
    generalize to cross-length attention and padded K/V (see
    :func:`_block_mask`); ``segmented`` adds per-row segment-id masking
    for packed sequences (two extra [B, S] int32 inputs)."""
    from jax.experimental import pallas as pl

    # only mask keys when padding actually added invalid positions
    kv_mask_from = (
        kv_len if kv_len is not None and kv_len < num_k * block_k else None
    )
    # last K block holding any VALID key (padded tail blocks are dead)
    last_k = (kv_mask_from - 1) // block_k if kv_mask_from else num_k - 1

    def kernel(q_ref, k_ref, v_ref, *rest):
        if segmented:
            qseg_ref, kseg_ref = rest[:2]
            rest = rest[2:]
        if partial:
            acc_out, m_out, l_out, acc_ref, m_ref, l_ref = rest
        else:
            o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
        qi = pl.program_id(2)
        ki = pl.program_id(3)

        @pl.when(ki == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)

        # causal: K blocks strictly after this Q block's last attendable
        # key have nothing to attend — skip the matmuls entirely (same
        # for all-padding K blocks)
        q_last = qi * block_q + block_q - 1 + offset
        visible = (ki * block_k <= q_last) if causal else (ki >= 0)
        visible &= ki <= last_k

        @pl.when(visible)
        def _attend():
            q = q_ref[0, 0].astype(jnp.float32)  # [block_q, D]
            k = k_ref[0, 0].astype(jnp.float32)  # [block_k, D]
            v = v_ref[0, 0].astype(jnp.float32)
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [block_q, block_k]
            mask = _block_mask(causal, qi, ki, block_q, block_k, offset, kv_mask_from)
            if segmented:
                seg = _seg_mask(qseg_ref, kseg_ref)
                mask = seg if mask is None else (mask & seg)
            if mask is not None:
                s = jnp.where(mask, s, _NEG_INF)

            m_prev = m_ref[:]  # [block_q, LANES] (broadcast rows)
            l_prev = l_ref[:]
            m_curr = jnp.max(s, axis=1)[:, None]  # [block_q, 1]
            m_next = jnp.maximum(m_prev, m_curr)  # [block_q, LANES]
            # rows fully masked so far have m_next == NEG_INF; shifting
            # by it would make exp(NEG_INF - NEG_INF)=1 for masked
            # entries, so clamp the shift (the row's p is 0 either way)
            shift = jnp.maximum(m_next[:, :1], _NEG_INF / 2)
            p = jnp.exp(s - shift)  # [block_q, block_k]
            if mask is not None:
                p = jnp.where(mask, p, 0.0)
            alpha = jnp.exp(m_prev - jnp.maximum(m_next, _NEG_INF / 2))
            l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1)[:, None]
            m_ref[:] = m_next
            pv = jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [block_q, D]
            acc_ref[:] = acc_ref[:] * alpha[:, :1] + pv

        # write the outputs once, at this Q block's last visible K block
        # (clamped into range: a negative-offset Q block with nothing to
        # attend still needs its write point so the output is zeroed)
        if causal:
            last_visible = jnp.clip(q_last // block_k, 0, last_k)
        else:
            last_visible = last_k

        @pl.when(ki == last_visible)
        def _finalize():
            if partial:
                acc_out[0, 0] = acc_ref[:]
                m_out[0, 0] = m_ref[:, :1]
                l_out[0, 0] = l_ref[:, :1]
            else:
                l_final = jnp.maximum(l_ref[:, :1], 1e-30)
                o_ref[0, 0] = (acc_ref[:] / l_final).astype(o_ref.dtype)
                # logsumexp of the scaled scores — the backward
                # recompute reconstructs p = exp(s - lse) from this
                lse_ref[0, 0] = (
                    jnp.maximum(m_ref[:, :1], _NEG_INF / 2) + jnp.log(l_final)
                )

    return kernel


def flash_attention_partial(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    block_q: int = 1024,
    block_k: int = 1024,
):
    """Unnormalized fused attention for one (Q block, KV block) pair in
    ``[batch, seq_q, heads, head_dim]`` layout (ring attention's). K/V
    may carry fewer heads (GQA: any divisor of q's heads) — the index
    map points each query-head group at its shared K/V head.

    Returns ``(block_max [B, H, Sq], out_unnormalized [B, Sq, H, D]
    float32, denom [B, H, Sq])`` — the exact contract of ring
    attention's ``_block_attend`` so the K/V ring can merge fused block
    results across devices with its online-softmax recurrence. Not
    differentiable itself — ring attention's own custom VJP pairs it
    with :func:`flash_attention_backward_block` on the backward ring
    pass; use :func:`flash_attention` for single-chip training."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    if heads % k.shape[2]:
        # Pallas clamps out-of-range block indices on TPU, so a bad
        # group here would silently mis-associate heads, not crash
        raise ValueError(
            f"GQA needs n_heads ({heads}) divisible by n_kv_heads ({k.shape[2]})"
        )
    group = heads // k.shape[2]  # GQA: Hkv divides H, same as the full kernel
    block_q = _fit_block(seq_q, block_q)
    block_k = _fit_block(seq_k, block_k)
    num_q, num_k = seq_q // block_q, seq_k // block_k
    scale = 1.0 / (head_dim ** 0.5)
    interpret = jax.devices()[0].platform != "tpu"

    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    kernel = _make_attention_kernel(
        causal, block_q, block_k, num_k, scale, partial=True
    )
    spec_q = pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0))
    spec_kv = pl.BlockSpec(
        (1, 1, block_k, head_dim), lambda b, h, i, j: (b, h // group, j, 0)
    )
    spec_row = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))
    acc, m, l = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(qt.shape[:3] + (head_dim,), jnp.float32),
            jax.ShapeDtypeStruct((batch, heads, seq_q, 1), jnp.float32),
            jax.ShapeDtypeStruct((batch, heads, seq_q, 1), jnp.float32),
        ),
        grid=(batch, heads, num_q, num_k),
        in_specs=[spec_q, spec_kv, spec_kv],
        out_specs=(spec_q, spec_row, spec_row),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return m[..., 0], jnp.swapaxes(acc, 1, 2), l[..., 0]


def _make_dq_kernel(causal: bool, block_q: int, block_k: int, num_k: int,
                    scale: float, offset: int = 0, kv_len: int | None = None,
                    segmented: bool = False):
    from jax.experimental import pallas as pl

    kv_mask_from = (
        kv_len if kv_len is not None and kv_len < num_k * block_k else None
    )
    last_k = (kv_mask_from - 1) // block_k if kv_mask_from else num_k - 1

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest):
        if segmented:
            qseg_ref, kseg_ref, dq_ref, dq_acc = rest
        else:
            dq_ref, dq_acc = rest
        qi = pl.program_id(2)
        ki = pl.program_id(3)

        @pl.when(ki == 0)
        def _init():
            dq_acc[:] = jnp.zeros_like(dq_acc)

        q_last = qi * block_q + block_q - 1 + offset
        visible = (ki * block_k <= q_last) if causal else (ki >= 0)
        visible &= ki <= last_k

        @pl.when(visible)
        def _accumulate():
            q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
            k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
            v = v_ref[0, 0].astype(jnp.float32)
            do = do_ref[0, 0].astype(jnp.float32)  # [bq, D]
            lse = lse_ref[0, 0]  # [bq, 1]
            delta = delta_ref[0, 0]  # [bq, 1]
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            mask = _block_mask(causal, qi, ki, block_q, block_k, offset, kv_mask_from)
            if segmented:
                seg = _seg_mask(qseg_ref, kseg_ref)
                mask = seg if mask is None else (mask & seg)
            if mask is not None:
                s = jnp.where(mask, s, _NEG_INF)
            p = jnp.exp(s - lse)  # masked entries underflow to 0
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk]
            ds = p * (dp - delta) * scale
            dq_acc[:] += jax.lax.dot_general(
                ds, k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        if causal:
            last_visible = jnp.clip(q_last // block_k, 0, last_k)
        else:
            last_visible = last_k

        @pl.when(ki == last_visible)
        def _finalize():
            dq_ref[0, 0] = dq_acc[:].astype(dq_ref.dtype)

    return kernel


def _make_dkv_kernel(causal: bool, block_q: int, block_k: int, num_q: int,
                     scale: float, group: int = 1, offset: int = 0,
                     kv_len: int | None = None, num_k: int | None = None,
                     segmented: bool = False):
    """dK/dV kernel. Grid is (batch, heads_KV, num_k, group·num_q): for
    GQA the inner sweep enumerates every (query head in the group,
    Q block) pair while the SAME dk/dv accumulator block stays resident
    in VMEM — the cross-head gradient sum happens in one consecutive
    write window, never via racy revisits or a materialized per-q-head
    gradient."""
    from jax.experimental import pallas as pl

    kv_mask_from = (
        kv_len
        if kv_len is not None and num_k is not None and kv_len < num_k * block_k
        else None
    )

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest):
        if segmented:
            qseg_ref, kseg_ref, dk_ref, dv_ref, dk_acc, dv_acc = rest
        else:
            dk_ref, dv_ref, dk_acc, dv_acc = rest
        ki = pl.program_id(2)  # K block owns this grid row
        t = pl.program_id(3)  # (group, Q) sweep innermost
        qi = jax.lax.rem(t, num_q)

        @pl.when(t == 0)
        def _init():
            dk_acc[:] = jnp.zeros_like(dk_acc)
            dv_acc[:] = jnp.zeros_like(dv_acc)

        q_last = qi * block_q + block_q - 1 + offset
        visible = (ki * block_k <= q_last) if causal else (t >= 0)

        @pl.when(visible)
        def _accumulate():
            q = q_ref[0, 0].astype(jnp.float32)  # [bq, D]
            k = k_ref[0, 0].astype(jnp.float32)  # [bk, D]
            v = v_ref[0, 0].astype(jnp.float32)
            do = do_ref[0, 0].astype(jnp.float32)  # [bq, D]
            lse = lse_ref[0, 0]  # [bq, 1]
            delta = delta_ref[0, 0]
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )
            mask = _block_mask(causal, qi, ki, block_q, block_k, offset, kv_mask_from)
            if segmented:
                seg = _seg_mask(qseg_ref, kseg_ref)
                mask = seg if mask is None else (mask & seg)
            if mask is not None:
                s = jnp.where(mask, s, _NEG_INF)
            p = jnp.exp(s - lse)  # [bq, bk]
            dv_acc[:] += jax.lax.dot_general(
                p, do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # p^T @ dO -> [bk, D]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk]
            ds = p * (dp - delta) * scale
            dk_acc[:] += jax.lax.dot_general(
                ds, q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # ds^T @ q -> [bk, D]

        # the LAST (head, Q block) attends every K block even under
        # causality, so the write point is unconditional
        @pl.when(t == group * num_q - 1)
        def _finalize():
            dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
            dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)

    return kernel


def _check_block(seq: int, block: int) -> int:
    """Clamp a requested block to ``seq`` under the same tileability
    rule ``_fit_block`` enforces: the block must divide seq AND be a
    multiple of 8 (the vreg sublane width). A non-8-multiple tile fails
    Mosaic compilation on real TPU even though CPU interpret mode
    happily runs it — rejecting it here keeps the CPU test suite honest
    about what the hardware accepts. (The public wrapper pads + adapts
    instead; this exact-fit validator guards the direct kernel entry
    points the sweep measures.)"""
    block = min(block, seq)
    if seq % block:
        raise ValueError(f"seq {seq} not divisible by block {block}")
    if block % 8:
        raise ValueError(
            f"block {block} must be a multiple of 8 to tile on TPU; "
            f"pad seq {seq} to a multiple of 8 or use unfused attention"
        )
    return block


def _fit_block(seq: int, preferred: int) -> int:
    """Largest divisor of ``seq`` that is <= preferred and TPU-tileable
    (a multiple of 8). An 8-aligned ``seq`` always has one (itself, if
    nothing smaller divides); a non-8-aligned ``seq`` has none, and the
    only candidate tile (the whole seq) fails Mosaic compilation on real
    TPU even though CPU interpret mode would run it — raise the same
    clear error everywhere (_check_block, flash_attention_partial, the
    backward pass) instead of letting CPU tests green-light a shape the
    hardware rejects. The backward pass uses this so ANY sequence the
    forward accepted can be differentiated — its block preference must
    never re-impose a divisibility the caller's forward blocks did not."""
    for block in range(min(preferred, seq), 7, -1):
        if seq % block == 0 and block % 8 == 0:
            return block
    if seq % 8:
        raise ValueError(
            f"seq {seq} has no TPU-tileable block (blocks must be multiples "
            "of 8); pad seq to a multiple of 8 or use unfused attention"
        )
    return seq


def _forward_bhsd(q, k, v, causal: bool, block_q: int, block_k: int,
                  offset: int = 0, kv_len: int | None = None,
                  segments: tuple | None = None):
    """(out, lse) on [B, H, S, D] arrays; lse is [B, H, Sq, 1] float32.

    Generalized shapes: ``k``/``v`` may carry a different sequence
    length (cross-attention; ``offset`` bottom-right-aligns the causal
    diagonal) and FEWER heads than ``q`` (GQA/MQA — the BlockSpec index
    map points each group of ``heads_q // heads_kv`` query heads at the
    same K/V head, so grouped keys are read in place, never
    materialized per-query-head). ``segments`` = (q_seg [B, Sq],
    kv_seg [B, Sk]) int32 adds packed-sequence masking."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq_q, head_dim = q.shape
    heads_kv, seq_k = k.shape[1], k.shape[2]
    group = heads // heads_kv
    block_q = _check_block(seq_q, block_q)
    block_k = _check_block(seq_k, block_k)
    num_q, num_k = seq_q // block_q, seq_k // block_k
    scale = 1.0 / (head_dim ** 0.5)
    interpret = jax.devices()[0].platform != "tpu"

    kernel = _make_attention_kernel(
        causal, block_q, block_k, num_k, scale, partial=False,
        offset=offset, kv_len=kv_len, segmented=segments is not None,
    )
    spec_q = pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0))
    spec_kv = pl.BlockSpec(
        (1, 1, block_k, head_dim), lambda b, h, i, j: (b, h // group, j, 0)
    )
    inputs = [q, k, v]
    in_specs = [spec_q, spec_kv, spec_kv]
    if segments is not None:
        inputs += [segments[0], segments[1]]
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b, h, i, j: (b, i)),
            pl.BlockSpec((1, block_k), lambda b, h, i, j: (b, j)),
        ]
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            # [B, H, S, 1]: the trailing singleton satisfies the TPU
            # block rule (last dim equal to the array's) without padding
            # the row statistics out to a full 128-lane vector
            jax.ShapeDtypeStruct((batch, heads, seq_q, 1), jnp.float32),
        ),
        grid=(batch, heads, num_q, num_k),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(*inputs)
    return out, lse


def _backward_bhsd(q, k, v, out, lse, dout, causal: bool, block_q=None,
                   block_k=None, offset: int = 0, kv_len: int | None = None,
                   segments: tuple | None = None):
    """dQ/dK/dV on [B, H, S, D] arrays via blockwise recompute.
    ``block_q``/``block_k`` override the tuned defaults (the flash
    probe's ``--sweep`` uses this to re-measure the table the defaults
    cite)."""
    # D_i = rowsum(dO ∘ O) — cheap elementwise pass XLA fuses; the
    # kernels read it per Q row like the logsumexp
    delta = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1,
        keepdims=True,
    )  # [B, H, Sq, 1]
    return _backward_bhsd_core(
        q, k, v, lse, delta, dout, causal,
        _fit_block(q.shape[2], block_q or _BWD_BLOCK_Q),
        _fit_block(k.shape[2], block_k or _BWD_BLOCK_K),
        offset=offset, kv_len=kv_len, segments=segments,
    )


def _backward_bhsd_core(
    q, k, v, lse, delta, dout, causal: bool, block_q: int, block_k: int,
    out_dtype=None, offset: int = 0, kv_len: int | None = None,
    segments: tuple | None = None,
):
    """The backward pallas calls with EXTERNAL per-row statistics.

    ``lse``/``delta`` are [B, H, Sq, 1] float32. Factored out of
    :func:`_backward_bhsd` so ring attention's backward can recompute
    block probabilities against the GLOBAL logsumexp saved by its
    forward (ops/ring_attention.py) — p = exp(s - lse) is then the true
    global probability, and per-device dK/dV block contributions sum
    exactly. ``out_dtype`` overrides the gradient dtype (the ring path
    accumulates blocks across devices in float32). K/V may carry fewer
    heads (GQA) and a different sequence length (cross-attention) than
    Q — dK/dV come back in K/V's own shape with the query-head group
    already summed."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, seq_q, head_dim = q.shape
    heads_kv, seq_k = k.shape[1], k.shape[2]
    group = heads // heads_kv
    num_q, num_k = seq_q // block_q, seq_k // block_k
    scale = 1.0 / (head_dim ** 0.5)
    interpret = jax.devices()[0].platform != "tpu"
    grad_dtype = out_dtype or q.dtype

    spec_q = pl.BlockSpec((1, 1, block_q, head_dim), lambda b, h, i, j: (b, h, i, 0))
    spec_kv = pl.BlockSpec(
        (1, 1, block_k, head_dim), lambda b, h, i, j: (b, h // group, j, 0)
    )
    spec_row = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    dq_inputs = [q, k, v, dout, lse, delta]
    dq_specs = [spec_q, spec_kv, spec_kv, spec_q, spec_row, spec_row]
    if segments is not None:
        dq_inputs += [segments[0], segments[1]]
        dq_specs += [
            pl.BlockSpec((1, block_q), lambda b, h, i, j: (b, i)),
            pl.BlockSpec((1, block_k), lambda b, h, i, j: (b, j)),
        ]
    dq = pl.pallas_call(
        _make_dq_kernel(causal, block_q, block_k, num_k, scale,
                        offset=offset, kv_len=kv_len,
                        segmented=segments is not None),
        out_shape=jax.ShapeDtypeStruct(q.shape, grad_dtype),
        grid=(batch, heads, num_q, num_k),
        in_specs=dq_specs,
        out_specs=spec_q,
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        interpret=interpret,
    )(*dq_inputs)

    # dK/dV grid: K block outer, (group·Q) sweep inner — the index maps
    # decompose the inner counter j into (query head in group, Q block)
    spec_q_t = pl.BlockSpec(
        (1, 1, block_q, head_dim),
        lambda b, h, i, j: (b, h * group + j // num_q, j % num_q, 0),
    )
    spec_kv_t = pl.BlockSpec((1, 1, block_k, head_dim), lambda b, h, i, j: (b, h, i, 0))
    spec_row_t = pl.BlockSpec(
        (1, 1, block_q, 1),
        lambda b, h, i, j: (b, h * group + j // num_q, j % num_q, 0),
    )
    dkv_inputs = [q, k, v, dout, lse, delta]
    dkv_specs = [spec_q_t, spec_kv_t, spec_kv_t, spec_q_t, spec_row_t, spec_row_t]
    if segments is not None:
        dkv_inputs += [segments[0], segments[1]]
        dkv_specs += [
            pl.BlockSpec((1, block_q), lambda b, h, i, j: (b, j % num_q)),
            pl.BlockSpec((1, block_k), lambda b, h, i, j: (b, i)),
        ]
    dk, dv = pl.pallas_call(
        _make_dkv_kernel(causal, block_q, block_k, num_q, scale, group=group,
                         offset=offset, kv_len=kv_len, num_k=num_k,
                         segmented=segments is not None),
        out_shape=(
            jax.ShapeDtypeStruct(k.shape, grad_dtype),
            jax.ShapeDtypeStruct(v.shape, grad_dtype),
        ),
        grid=(batch, heads_kv, num_k, group * num_q),
        in_specs=dkv_specs,
        out_specs=(spec_kv_t, spec_kv_t),
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        interpret=interpret,
    )(*dkv_inputs)
    return dq, dk, dv


def flash_attention_backward_block(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    lse: jax.Array,
    delta: jax.Array,
    dout: jax.Array,
    causal: bool,
    block_q: int | None = None,
    block_k: int | None = None,
):
    """Fused backward for ONE (Q block, KV block) pair against GLOBAL
    row statistics — ring attention's backward building block
    (ops/ring_attention.py).

    Layout matches :func:`flash_attention_partial`: q/dout are
    ``[B, Sq, H, D]``, k/v ``[B, Sk, Hkv, D]`` with Hkv dividing H
    (GQA: dK/dV come back group-summed in K/V's own narrow shape;
    ``Sq == Sk`` per ring step); ``lse``/``delta`` are ``[B, H, Sq]``
    float32 — the GLOBAL
    logsumexp from the ring forward and rowsum(dO ∘ O). Because p =
    exp(s − lse_global) is the true global attention probability, the
    (dq, dk, dv) this returns are exact per-block contributions that
    the ring sums across devices. Gradients come back float32 in the
    same ``[B, S, H, D]`` layout for that cross-device accumulation."""
    seq_q, seq_k = q.shape[1], k.shape[1]
    if seq_q != seq_k:
        raise ValueError(
            f"ring block backward needs equal local blocks, got {seq_q} vs {seq_k}"
        )
    qt, kt, vt, dot = (jnp.swapaxes(x, 1, 2) for x in (q, k, v, dout))
    dq, dk, dv = _backward_bhsd_core(
        qt, kt, vt,
        lse[..., None].astype(jnp.float32),
        delta[..., None].astype(jnp.float32),
        dot, causal,
        _fit_block(seq_q, block_q or _BWD_BLOCK_Q),
        _fit_block(seq_k, block_k or _BWD_BLOCK_K),
        out_dtype=jnp.float32,
    )
    return (
        jnp.swapaxes(dq, 1, 2),
        jnp.swapaxes(dk, 1, 2),
        jnp.swapaxes(dv, 1, 2),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, causal: bool, block_q: int, block_k: int,
                offset: int = 0, kv_len: int | None = None):
    out, _ = _forward_bhsd(q, k, v, causal, block_q, block_k, offset, kv_len)
    return out


def _flash_bhsd_fwd(q, k, v, causal, block_q, block_k, offset, kv_len):
    out, lse = _forward_bhsd(q, k, v, causal, block_q, block_k, offset, kv_len)
    return out, (q, k, v, out, lse)


def _flash_bhsd_bwd(causal, _block_q, _block_k, offset, kv_len, residuals, dout):
    # the forward's blocks arrive as nondiff args but the backward
    # picks its own (_backward_bhsd fits against the VMEM limit)
    q, k, v, out, lse = residuals
    dq, dk, dv = _backward_bhsd(
        q, k, v, out, lse, dout, causal, offset=offset, kv_len=kv_len
    )
    return dq, dk, dv


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_bhsd_seg(q, k, v, q_seg, kv_seg, causal, block_q, block_k,
                    offset, kv_len):
    out, _ = _forward_bhsd(q, k, v, causal, block_q, block_k, offset,
                           kv_len, segments=(q_seg, kv_seg))
    return out


def _flash_bhsd_seg_fwd(q, k, v, q_seg, kv_seg, causal, block_q, block_k,
                        offset, kv_len):
    out, lse = _forward_bhsd(q, k, v, causal, block_q, block_k, offset,
                             kv_len, segments=(q_seg, kv_seg))
    return out, (q, k, v, q_seg, kv_seg, out, lse)


def _flash_bhsd_seg_bwd(causal, _block_q, _block_k, offset, kv_len,
                        residuals, dout):
    q, k, v, q_seg, kv_seg, out, lse = residuals
    dq, dk, dv = _backward_bhsd(
        q, k, v, out, lse, dout, causal, offset=offset, kv_len=kv_len,
        segments=(q_seg, kv_seg),
    )
    # segment ids are integer inputs: None = symbolic-zero cotangent
    return dq, dk, dv, None, None


_flash_bhsd_seg.defvjp(_flash_bhsd_seg_fwd, _flash_bhsd_seg_bwd)


def _pad_seq(x: jax.Array, pad: int) -> jax.Array:
    """Zero-pad the seq dim (axis 2 of [B, H, S, D])."""
    if not pad:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


def _plan_padding(seq: int, preferred: int) -> tuple:
    """(padded_seq, block): how much to pad one sequence side and which
    block to run it with.

    Padding to the next 8-multiple is always needed (Mosaic's tiling
    unit). On top of that, when the only tileable divisor COLLAPSES far
    below the requested block (e.g. seq=136 → sole divisor 8 — a
    17×17 grid of tiny tiles instead of one MXU-sized block), padding
    further to the next multiple of the requested block can win: a few
    masked rows are far cheaper than an order-of-magnitude block-size
    cliff. But padding also SQUARES into attention work (both padded
    halves of a [S, S] score matrix are computed; only fully-dead K
    blocks are skipped), so the two options are compared on estimated
    cost: rows² weighted by a block-efficiency factor that rises
    linearly to a knee at 512 (small tiles under-fill the MXU pipeline;
    past ~512 the measured v5e sweep is flat). seq=192 with 128-blocks
    keeps 96-blocks on 192 rows (beats 128-blocks on a padded 256);
    seq=1000 pads to 1024 for 512-blocks (2.4% extra rows buys a 2.5×
    better block); seq=1032 keeps 344-blocks rather than doubling to
    2048 rows for 1024-blocks."""
    pad8 = seq + ((-seq) % 8)
    block = _fit_block(pad8, preferred)
    target = min(preferred, pad8)
    target = max(8, target - target % 8)
    if block < target:
        padded = -(-seq // target) * target

        def cost(rows: int, b: int) -> float:
            return rows * rows / (min(b, 512) / 512)

        if cost(padded, target) < cost(pad8, block):
            return padded, target
    return pad8, block


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 1024,
    block_k: int = 1024,
    layout: str = "bshd",
    segment_ids=None,
) -> jax.Array:
    """Fused attention, differentiable (custom VJP with blockwise
    recompute from the saved logsumexp — flash-attention backward).

    Shapes real models run (all differentiable):

    - **GQA/MQA** — ``k``/``v`` may carry fewer heads than ``q`` (any
      divisor, down to 1 for MQA). The kernels point each query-head
      group at its shared K/V head via the BlockSpec index map; grouped
      K/V are never materialized per query head, and the dK/dV kernel
      sums the group's gradient in one resident VMEM accumulator.
    - **Cross-attention / decode** — ``seq_k`` may differ from
      ``seq_q``. Causal masking is bottom-right aligned (query row i
      attends keys ≤ i + seq_k − seq_q), so a short-q-long-KV decode
      call sees its full prefix; equal lengths reduce to the standard
      mask.
    - **Any sequence length** — non-8-multiple lengths (Mosaic's tiling
      unit) are zero-padded to the next multiple and the padded keys
      masked out; outputs/gradients are sliced back, so callers never
      see the padding.
    - **Packed sequences** — ``segment_ids`` masks attention to
      same-segment pairs: one ``[B, S]`` int array for self-attention,
      or a ``(q_ids [B, Sq], kv_ids [B, Sk])`` tuple for cross-length
      calls. Ids must be ≥ 0 (padding uses negative sentinels that
      match nothing). Causal + segments composes to the standard
      packed-causal mask.

    ``layout="bshd"`` takes ``[batch, seq, heads, head_dim]`` (what
    ops/ring_attention.py uses) and transposes to the kernel's native
    ``[batch, heads, seq, head_dim]``; pass ``layout="bhsd"`` when the
    caller already keeps heads-major arrays to skip the transpose passes
    (3 HBM round-trips per call). Requested blocks adapt to the largest
    tileable divisor of each (padded) sequence; the backward pass picks
    its own blocks — preferring 1024x256 against the scoped-VMEM limit,
    shrunk to fit any seq the forward accepted.

    Default forward blocks are the measured optimum on v5e (bq=bk=1024:
    ~90 TFLOP/s causal at S=4096, ~4-5x the unfused XLA attention on
    the same chip; bigger blocks exceed the 16 MB scoped-VMEM limit)."""
    if layout == "bshd":
        seq_axis, head_axis = 1, 2
    elif layout == "bhsd":
        seq_axis, head_axis = 2, 1
    else:
        raise ValueError(f"layout must be bshd or bhsd, got {layout!r}")
    batch, head_dim = q.shape[0], q.shape[3]
    seq_q, heads = q.shape[seq_axis], q.shape[head_axis]
    seq_k, heads_kv = k.shape[seq_axis], k.shape[head_axis]
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes differ: {k.shape} {v.shape}")
    if k.shape[0] != batch or k.shape[3] != head_dim:
        raise ValueError(
            f"q/k batch or head_dim differ: {q.shape} vs {k.shape}"
        )
    if heads % heads_kv:
        raise ValueError(
            f"GQA needs n_heads ({heads}) divisible by n_kv_heads ({heads_kv})"
        )

    # [B, S, H, D] -> [B, H, S, D]: the kernels tile the last two dims
    # (seq-block × head_dim), which is the MXU-friendly layout
    if layout == "bshd":
        qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    else:
        qt, kt, vt = q, k, v

    if causal and seq_q > seq_k:
        raise ValueError(
            f"causal attention with seq_q ({seq_q}) > seq_k ({seq_k}) leaves "
            "leading queries with no visible keys (undefined softmax rows); "
            "pass causal=False or align the sequences"
        )

    # pad to Mosaic's 8-row tiling unit — or further, to the requested
    # block, when the seq's divisor structure would collapse the block
    # size (_plan_padding); padded keys are masked via kv_len, padded
    # query rows produce zero cotangents (the output slice's
    # pad-transpose) so they perturb nothing
    seq_q_p, block_q = _plan_padding(seq_q, block_q)
    seq_k_p, block_k = _plan_padding(seq_k, block_k)
    qt = _pad_seq(qt, seq_q_p - seq_q)
    kt, vt = _pad_seq(kt, seq_k_p - seq_k), _pad_seq(vt, seq_k_p - seq_k)
    # causal alignment uses REAL lengths: padding never shifts the diagonal
    offset = (seq_k - seq_q) if causal else 0
    kv_len = seq_k if seq_k_p != seq_k else None

    if segment_ids is not None:
        if isinstance(segment_ids, (tuple, list)):
            q_seg, kv_seg = segment_ids
        else:
            if seq_q != seq_k:
                raise ValueError(
                    "cross-length attention needs a (q_ids, kv_ids) "
                    "segment_ids tuple, got one array for "
                    f"seq_q={seq_q} vs seq_k={seq_k}"
                )
            q_seg = kv_seg = segment_ids
        if q_seg.shape != (batch, seq_q) or kv_seg.shape != (batch, seq_k):
            raise ValueError(
                f"segment_ids shapes {q_seg.shape}/{kv_seg.shape} do not "
                f"match [batch, seq] = [{batch}, {seq_q}]/[{batch}, {seq_k}]"
            )
        # distinct negative sentinels: padded queries and padded keys
        # match nothing, including each other
        q_seg = jnp.pad(
            q_seg.astype(jnp.int32),
            ((0, 0), (0, seq_q_p - seq_q)), constant_values=-1,
        )
        kv_seg = jnp.pad(
            kv_seg.astype(jnp.int32),
            ((0, 0), (0, seq_k_p - seq_k)), constant_values=-2,
        )
        out = _flash_bhsd_seg(
            qt, kt, vt, q_seg, kv_seg, causal, block_q, block_k, offset, kv_len
        )
    else:
        out = _flash_bhsd(qt, kt, vt, causal, block_q, block_k, offset, kv_len)
    if seq_q_p != seq_q:
        out = out[:, :, :seq_q]
    return jnp.swapaxes(out, 1, 2) if layout == "bshd" else out


def _make_decode_kernel(block_k: int, scale: float, group_p: int):
    """Online-softmax decode step: one Q row group against the KV
    cache, swept blockwise. Mirrors the forward kernel's recurrence
    with the position mask driven by the prefetched scalar ``pos``."""
    from jax.experimental import pallas as pl

    def kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref):
        j = pl.program_id(2)
        pos = pos_ref[0]

        @pl.when(j == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)
            m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)

        @pl.when(j * block_k <= pos)
        def _attend():
            q = q_ref[0, 0].astype(jnp.float32)  # [Gp, D]
            k = k_ref[0, 0].astype(jnp.float32)  # [block_k, D]
            v = v_ref[0, 0].astype(jnp.float32)
            s = (
                jax.lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [Gp, block_k]
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (group_p, block_k), 1
            )
            mask = k_pos <= pos
            s = jnp.where(mask, s, _NEG_INF)
            m_prev = m_ref[:]
            l_prev = l_ref[:]
            m_curr = jnp.max(s, axis=1)[:, None]
            m_next = jnp.maximum(m_prev, m_curr)
            shift = jnp.maximum(m_next[:, :1], _NEG_INF / 2)
            p = jnp.where(mask, jnp.exp(s - shift), 0.0)
            alpha = jnp.exp(m_prev - jnp.maximum(m_next, _NEG_INF / 2))
            l_ref[:] = l_prev * alpha + jnp.sum(p, axis=1)[:, None]
            m_ref[:] = m_next
            pv = jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc_ref[:] = acc_ref[:] * alpha[:, :1] + pv

        @pl.when(j == pos // block_k)
        def _finalize():
            o_ref[0, 0] = (
                acc_ref[:] / jnp.maximum(l_ref[:, :1], 1e-30)
            ).astype(o_ref.dtype)

    return kernel


def flash_decode(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    pos: jax.Array,
    block_k: int = 512,
) -> jax.Array:
    """Fused single-token decode attention — the serving hot loop.

    ``q``: ``[B, H, D]`` (this step's query); ``k_cache``/``v_cache``:
    ``[B, Hkv, S, D]`` full-capacity caches (``S`` a multiple of 8,
    ``Hkv`` dividing ``H`` — GQA reads each narrow K/V head once for
    its whole query group); ``pos``: scalar int32 — keys ``0..pos``
    are visible (the static-shape masked-cache recipe
    models/probe_model.decode_step uses). Returns ``[B, H, D]``.

    One blockwise HBM pass over the cache with the online-softmax state
    in VMEM: no ``[B, H, S]`` score tensor is ever materialized, and
    cache blocks past ``pos`` are skipped via the prefetched scalar —
    dead capacity costs no bandwidth, which is the decode bottleneck.
    Not differentiable (decoding is inference); train with
    :func:`flash_attention`."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    batch, heads, head_dim = q.shape
    heads_kv, cap = k_cache.shape[1], k_cache.shape[2]
    if heads % heads_kv:
        raise ValueError(
            f"GQA needs n_heads ({heads}) divisible by n_kv_heads ({heads_kv})"
        )
    if cap % 8:
        raise ValueError(f"cache capacity {cap} must be a multiple of 8")
    group = heads // heads_kv
    # pad the query group to the 8-row sublane tile; padded rows compute
    # garbage that is sliced away (bandwidth-bound: the cost is nil)
    group_p = -(-group // 8) * 8
    block_k = _fit_block(cap, block_k)
    num_kb = cap // block_k
    scale = 1.0 / (head_dim ** 0.5)
    interpret = jax.devices()[0].platform != "tpu"

    qg = q.reshape(batch, heads_kv, group, head_dim)
    if group_p != group:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, group_p - group), (0, 0)))

    def kv_index(b, h, j, pos):
        # THE point of the prefetched scalar: blocks past pos re-map to
        # the last live block, so the pipeline issues no new DMA for
        # dead cache capacity (their compute is already skipped by the
        # kernel's pl.when) — decode reads only ~pos bytes per head,
        # not the full rounded-up capacity
        return (b, h, jnp.minimum(j, pos[0] // block_k), 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(batch, heads_kv, num_kb),
        in_specs=[
            pl.BlockSpec(
                (1, 1, group_p, head_dim), lambda b, h, j, pos: (b, h, 0, 0)
            ),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_index),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group_p, head_dim), lambda b, h, j, pos: (b, h, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((group_p, head_dim), jnp.float32),
            pltpu.VMEM((group_p, _LANES), jnp.float32),
            pltpu.VMEM((group_p, _LANES), jnp.float32),
        ],
    )
    # pos is traced (unvalidatable at trace time); out of range in
    # EITHER direction it would gate the finalize write off every grid
    # step and return an UNWRITTEN output buffer — clamp so overflow
    # attends the full cache and negative pos attends position 0
    pos = jnp.clip(jnp.asarray(pos, jnp.int32), 0, cap - 1)
    out = pl.pallas_call(
        _make_decode_kernel(block_k, scale, group_p),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (batch, heads_kv, group_p, head_dim), q.dtype
        ),
        interpret=interpret,
    )(pos.reshape(1), qg, k_cache, v_cache)
    return out[:, :, :group].reshape(batch, heads, head_dim)


def attention_flops(batch: int, seq: int, heads: int, head_dim: int, causal: bool) -> float:
    """Model FLOPs for one attention forward (QK^T + PV matmuls)."""
    pairs = seq * (seq + 1) / 2 if causal else float(seq * seq)
    return 4.0 * head_dim * batch * heads * pairs
