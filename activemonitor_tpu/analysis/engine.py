"""AnalysisEngine — the reconciler-owned anomaly-detection façade.

Owned by the reconciler the same way it owns the tracer, the fleet SLO
aggregate, and the resilience coordinator. One call per finished run
(:meth:`observe`) does everything the subsystem promises:

- filters the run's numeric samples through ``spec.analysis.metrics[]``;
- updates the per-(check, metric) baselines (baseline.py) — warm-up
  samples always feed the baseline; after warm-up, samples whose raw
  level is anomalous are QUARANTINED from it, so a degraded regime
  cannot teach the baseline that sick is the new normal (the alarm
  would otherwise clear itself in one window);
- runs the detector chain (detector.py) and the per-metric hysteresis,
  then reports the check's anomaly state as the WORST metric's state;
- exports ``healthcheck_metric_baseline{stat=}``,
  ``healthcheck_metric_zscore`` and the lazy one-hot
  ``healthcheck_anomaly_state``;
- feeds cohort values into the straggler index (fleet.py);
- serializes the whole thing into ``hc.status.analysis`` so it rides
  the very status write that records the run — baselines survive
  controller restarts through the existing merge-patch path, and
  :meth:`observe` adopts a durable blob the first time it sees a check.

Never raises into the reconcile path: like the SLO recorder, analysis
is observability + policy input, and a bug here must not fail the
status write that feeds it. The reconciler consumes the returned
:class:`AnalysisVerdict` for events, flap-tracker damping, and the
``triggerOnDegraded`` remedy gate.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from activemonitor_tpu.analysis.baseline import CheckBaselines
from activemonitor_tpu.analysis.detector import (
    DetectorConfig,
    Hysteresis,
    LEVEL_OK,
    combine_raw_levels,
    default_detectors,
    finite,
    level_name,
)
from activemonitor_tpu.analysis.fleet import CohortIndex
from activemonitor_tpu.utils.clock import Clock

log = logging.getLogger("activemonitor.analysis")

# schedule damping while a check's metrics are confirmed-degraded: the
# same containment shape as flap damping (resilience/health.py) — a
# degraded slice burns probe budget at half cadence until it recovers
DEGRADED_DAMP_FACTOR = 2.0

STATUS_VERSION = 1


def analysis_spec(hc) -> Optional[object]:
    """The spec's ``analysis:`` block, or None when the check has not
    opted in (absent block ⇒ the subsystem is inert for the check)."""
    return getattr(hc.spec, "analysis", None)


def _config_from_spec(spec) -> DetectorConfig:
    z = float(getattr(spec, "z_threshold", 0.0) or 0.0)
    return DetectorConfig(z_threshold=z) if z > 0 else DetectorConfig()


@dataclass(frozen=True)
class AnalysisVerdict:
    """One run's analysis outcome, for the reconciler to act on."""

    state: str  # ok | warning | degraded (post-hysteresis, worst metric)
    transition: Optional[Tuple[str, str]] = None  # (old, new) on change
    metric_transitions: List[Tuple[str, str, str]] = field(default_factory=list)
    zscores: Dict[str, float] = field(default_factory=dict)

    @property
    def degraded(self) -> bool:
        return self.state == "degraded"


class _CheckAnalysis:
    """One check's live analysis state."""

    __slots__ = (
        "baselines",
        "hysteresis",
        "last_values",
        "last_zscores",
        "last_run_id",
        "name",
        "namespace",
    )

    def __init__(self, baselines: CheckBaselines):
        self.baselines = baselines
        self.hysteresis: Dict[str, Hysteresis] = {}
        self.last_values: Dict[str, float] = {}
        # the z each metric's LAST sample scored against the baseline
        # of its time (None pre-warm-up) — kept so /statusz reports the
        # same number the zscore gauge exported, instead of recomputing
        # against a baseline the sample itself may have since updated
        self.last_zscores: Dict[str, Optional[float]] = {}
        self.last_run_id = ""
        self.name = ""
        self.namespace = ""

    @property
    def level(self) -> int:
        """Check-wide anomaly level: the worst metric's reported state."""
        if not self.hysteresis:
            return LEVEL_OK
        return max(state.level for state in self.hysteresis.values())


class AnalysisEngine:
    def __init__(self, clock: Optional[Clock] = None, metrics=None):
        self.clock = clock or Clock()
        self.metrics = metrics
        self.detectors = default_detectors()
        self.cohorts = CohortIndex()
        self._checks: Dict[str, _CheckAnalysis] = {}

    # -- recording (reconciler status-write path) -----------------------
    def observe(
        self,
        hc,
        samples: Dict[str, float],
        *,
        ok: bool,
        run_id: str = "",
    ) -> Optional[AnalysisVerdict]:
        try:
            return self._observe(hc, samples, ok=ok, run_id=run_id)
        except Exception:
            # analysis must not fail the status write that feeds it
            log.exception("analysis failed for %s", getattr(hc, "key", "?"))
            return None

    def _observe(
        self, hc, samples: Dict[str, float], *, ok: bool, run_id: str
    ) -> Optional[AnalysisVerdict]:
        spec = analysis_spec(hc)
        key = hc.key
        if spec is None:
            # the analysis: block was edited off a live check (or never
            # existed): drop state and series, stop advertising verdicts
            if key in self._checks:
                self.forget(key, hc.metadata.name, hc.metadata.namespace)
            if getattr(hc.status, "analysis", None) is not None:
                # a durable blob from before the removal (possibly from
                # a previous incarnation — no live state needed) must
                # not keep advertising a verdict nobody computes; None
                # rides the pending write and merge-patch deletes it
                hc.status.analysis = None
            return None
        rec = self._ensure(hc, spec)
        if run_id and rec.last_run_id == run_id:
            # the same workflow run replayed through a second path must
            # not feed the baseline twice (mirrors the custom-metric
            # run-id dedupe in metrics/collector.py)
            return AnalysisVerdict(state=level_name(rec.level))
        if run_id:
            rec.last_run_id = run_id
        if not ok:
            # failed runs already alarm through pass/fail and rarely
            # carry a trustworthy contract; never let them poison the
            # baseline. The reported state persists unchanged.
            self._persist(hc, rec, spec)
            return AnalysisVerdict(state=level_name(rec.level))

        wanted = list(getattr(spec, "metrics", None) or [])
        config = _config_from_spec(spec)
        cohort = str(getattr(spec, "cohort", "") or "")
        old_level = rec.level
        metric_transitions: List[Tuple[str, str, str]] = []
        zscores: Dict[str, float] = {}
        seen: set = set()
        for metric, raw_value in samples.items():
            if wanted and metric not in wanted:
                continue
            value = finite(raw_value)
            if value is None:
                continue
            seen.add(metric)
            baseline = rec.baselines.baseline(metric)
            warmed = rec.baselines.warmed(metric)
            levels = []
            for detector in self.detectors:
                if detector.needs_baseline and not warmed:
                    continue  # warm-up gate: no statistics, no opinion
                levels.append(detector.evaluate(metric, value, baseline, config))
            raw_level = combine_raw_levels(levels)
            if warmed:
                zscores[metric] = baseline.zscore(value)
            state = rec.hysteresis.get(metric)
            if state is None:
                state = rec.hysteresis[metric] = Hysteresis()
            transition = state.update(raw_level)
            if transition is not None:
                metric_transitions.append(
                    (metric, level_name(transition[0]), level_name(transition[1]))
                )
            # baseline update policy (module docstring): warm-up always
            # feeds; post-warm-up anomalous samples are quarantined
            if not warmed or raw_level == LEVEL_OK:
                rec.baselines.observe(metric, value)
            rec.last_values[metric] = value
            rec.last_zscores[metric] = zscores.get(metric)
            if cohort:
                self.cohorts.record(cohort, metric, key, value)
            self._export_metric(hc, metric, baseline, zscores.get(metric))
        # metrics with a reported state but NO sample this run: an
        # entry excluded by the metrics[] filter drops outright (the
        # operator edited it out); a still-wanted metric the probe
        # stopped emitting decays back toward ok through the normal
        # calm hysteresis — absence is not evidence of continued
        # degradation, and a vanished metric must not hold the check
        # degraded (damped, remedy-triggering) forever
        for metric in [m for m in rec.hysteresis if m not in seen]:
            if wanted and metric not in wanted:
                del rec.hysteresis[metric]
                rec.last_values.pop(metric, None)
                rec.last_zscores.pop(metric, None)
                continue
            transition = rec.hysteresis[metric].update(LEVEL_OK)
            if transition is not None:
                metric_transitions.append(
                    (metric, level_name(transition[0]), level_name(transition[1]))
                )
            if rec.hysteresis[metric].level == LEVEL_OK:
                # fully recovered AND absent: nothing left to report
                # (the baseline stays, in case the metric returns)
                del rec.hysteresis[metric]
                rec.last_values.pop(metric, None)
                rec.last_zscores.pop(metric, None)
        new_level = rec.level
        self._export_state(hc, new_level, materialize=new_level != LEVEL_OK)
        self._persist(hc, rec, spec)
        transition = (
            (level_name(old_level), level_name(new_level))
            if new_level != old_level
            else None
        )
        if transition is not None:
            log.log(
                logging.WARNING if new_level > old_level else logging.INFO,
                "analysis state of %s: %s -> %s",
                key,
                transition[0],
                transition[1],
            )
        return AnalysisVerdict(
            state=level_name(new_level),
            transition=transition,
            metric_transitions=metric_transitions,
            zscores=zscores,
        )

    def _ensure(self, hc, spec) -> _CheckAnalysis:
        key = hc.key
        rec = self._checks.get(key)
        warmup = max(1, int(getattr(spec, "warmup_runs", 0) or 5))
        if rec is None:
            rec = self._restore(hc, warmup)
            self._checks[key] = rec
            if rec.level != LEVEL_OK:
                # a durable non-ok mark must resurface on the scrape
                # immediately, not wait for the next transition
                self._export_state(hc, rec.level, materialize=True)
        rec.baselines.warmup_runs = warmup
        rec.name = hc.metadata.name
        rec.namespace = hc.metadata.namespace
        return rec

    def _restore(self, hc, warmup: int) -> _CheckAnalysis:
        """Adopt a durable ``.status.analysis`` blob written by a
        previous controller incarnation; anything malformed yields a
        fresh state (defensive like the CRD loaders)."""
        blob = getattr(hc.status, "analysis", None)
        if not isinstance(blob, dict):
            return _CheckAnalysis(CheckBaselines(self.clock, warmup))
        rec = _CheckAnalysis(
            CheckBaselines.from_dict(blob.get("baselines") or {}, self.clock, warmup)
        )
        states = blob.get("states")
        if isinstance(states, dict):
            for metric, entry in states.items():
                if isinstance(metric, str) and isinstance(entry, dict):
                    rec.hysteresis[metric] = Hysteresis.from_dict(entry)
        return rec

    # -- persistence ----------------------------------------------------
    def _persist(self, hc, rec: _CheckAnalysis, spec) -> None:
        """Serialize the check's analysis state onto ``hc.status`` so it
        rides the pending status write (merge-patch replaces the whole
        ``analysis`` key, so stale sub-keys can never linger)."""
        hc.status.analysis = {
            "v": STATUS_VERSION,
            "state": level_name(rec.level),
            "updatedAt": self.clock.now().isoformat(),
            "baselines": rec.baselines.to_dict(),
            "states": {
                metric: state.to_dict()
                for metric, state in rec.hysteresis.items()
            },
        }

    # -- metric export --------------------------------------------------
    def _export_metric(self, hc, metric, baseline, zscore) -> None:
        if self.metrics is None:
            return
        name, namespace = hc.metadata.name, hc.metadata.namespace
        self.metrics.set_metric_baseline(
            name,
            namespace,
            metric,
            mean=baseline.mean,
            std=baseline.std,
            median=baseline.median,
            mad=baseline.mad,
            count=float(baseline.n),
        )
        if zscore is not None:
            self.metrics.set_metric_zscore(name, namespace, metric, zscore)

    def _export_state(self, hc, level: int, *, materialize: bool) -> None:
        if self.metrics is None:
            return
        self.metrics.set_anomaly_state(
            hc.metadata.name,
            hc.metadata.namespace,
            level_name(level),
            materialize=materialize,
        )

    # -- queries --------------------------------------------------------
    def state(self, key: str) -> str:
        rec = self._checks.get(key)
        return level_name(rec.level) if rec is not None else "ok"

    def metric_states(self, key: str) -> Dict[str, str]:
        """Per-metric post-hysteresis states ({} when the check has no
        live analysis) — the goodput attribution layer's evidence that
        a specific subsystem metric is confirmed-off-baseline."""
        rec = self._checks.get(key)
        if rec is None:
            return {}
        return {
            metric: level_name(state.level)
            for metric, state in rec.hysteresis.items()
        }

    def baselines_snapshot(self, key: str) -> Optional[dict]:
        """The check's learned baseline stats in durable-blob form, or
        None — the flight recorder's evidence slice."""
        rec = self._checks.get(key)
        return rec.baselines.to_dict() if rec is not None else None

    def summary(self, hc) -> Optional[dict]:
        """The check's /statusz ``analysis`` block (None when the check
        has not opted in). Schema pinned by the statusz contract test."""
        spec = analysis_spec(hc)
        if spec is None:
            return None
        key = hc.key
        rec = self._checks.get(key)
        cohort = str(getattr(spec, "cohort", "") or "")
        if rec is None:
            # opted in but no run analyzed yet (or a restart before the
            # first run): report the durable state if one exists
            blob = getattr(hc.status, "analysis", None)
            durable = (
                blob.get("state") if isinstance(blob, dict) else None
            )
            return {
                "state": durable if durable in ("ok", "warning", "degraded") else "ok",
                "cohort": cohort or None,
                "cohort_score": None,
                "metrics": {},
            }
        metrics_block = {}
        for metric, state in rec.hysteresis.items():
            baseline = rec.baselines.peek(metric)
            metrics_block[metric] = {
                "state": level_name(state.level),
                "last": rec.last_values.get(metric),
                "baseline_median": baseline.median if baseline else None,
                "baseline_mean": baseline.mean if baseline else None,
                # the run-time z (what the gauge exported), not a
                # recompute against a baseline the sample may have
                # since updated
                "zscore": rec.last_zscores.get(metric),
                "warmed_up": rec.baselines.warmed(metric),
            }
        return {
            "state": level_name(rec.level),
            "cohort": cohort or None,
            "cohort_score": (
                self.cohorts.worst_score(cohort, key) if cohort else None
            ),
            "metrics": metrics_block,
        }

    # -- lifecycle ------------------------------------------------------
    def forget(self, key: str, name: str = "", namespace: str = "") -> None:
        """Deleted check (or analysis block removed): drop live state,
        cohort membership, and exported series."""
        self._checks.pop(key, None)
        self.cohorts.forget(key)
        if self.metrics is not None and name:
            self.metrics.clear_analysis(name, namespace)
