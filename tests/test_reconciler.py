"""Reconciler integration tests — FakeClock + FakeWorkflowEngine.

The controller equivalent of the reference's envtest suites
(healthcheck_controller_test.go, healthcheck_controller_edge_test.go):
the data model is real, the executor is scripted, and timing is
deterministic via the fake clock.
"""

import asyncio

import pytest

from activemonitor_tpu.api import HealthCheck
from activemonitor_tpu.controller import (
    EventRecorder,
    HealthCheckReconciler,
    InMemoryHealthCheckClient,
    InMemoryRBACBackend,
    RBACProvisioner,
)
from activemonitor_tpu.engine import FakeWorkflowEngine, fail_after, succeed_after
from activemonitor_tpu.metrics import MetricsCollector
from activemonitor_tpu.utils.clock import FakeClock

WF_INLINE = """
apiVersion: argoproj.io/v1alpha1
kind: Workflow
spec:
  entrypoint: main
  templates:
    - name: main
      container:
        command: [probe]
"""


def make_hc(
    name="hc-a",
    repeat=60,
    timeout=10,
    cron="",
    remedy=False,
    remedy_runs_limit=0,
    remedy_reset_interval=0,
):
    spec = {
        "repeatAfterSec": repeat,
        "level": "cluster",
        "workflow": {
            "generateName": "check-",
            "workflowtimeout": timeout,
            "resource": {
                "namespace": "health",
                "serviceAccount": "check-sa",
                "source": {"inline": WF_INLINE},
            },
        },
    }
    if cron:
        spec["schedule"] = {"cron": cron}
    if remedy:
        spec["remedyworkflow"] = {
            "generateName": "remedy-",
            "resource": {
                "namespace": "health",
                "serviceAccount": "remedy-sa",
                "source": {"inline": WF_INLINE},
            },
        }
    if remedy_runs_limit:
        spec["remedyRunsLimit"] = remedy_runs_limit
    if remedy_reset_interval:
        spec["remedyResetInterval"] = remedy_reset_interval
    return HealthCheck.from_dict(
        {"metadata": {"name": name, "namespace": "health"}, "spec": spec}
    )


class Harness:
    def __init__(self, completer=None):
        self.clock = FakeClock()
        self.client = InMemoryHealthCheckClient()
        self.engine = FakeWorkflowEngine(completer)
        self.backend = InMemoryRBACBackend()
        self.recorder = EventRecorder()
        self.metrics = MetricsCollector()
        self.reconciler = HealthCheckReconciler(
            client=self.client,
            engine=self.engine,
            rbac=RBACProvisioner(self.backend),
            recorder=self.recorder,
            metrics=self.metrics,
            clock=self.clock,
        )

    async def apply_and_reconcile(self, hc):
        created = await self.client.apply(hc)
        await self.reconciler.reconcile(created.namespace, created.name)
        return created

    async def settle(self, seconds=0.0):
        if seconds:
            await self.clock.advance(seconds)
        else:
            for _ in range(20):
                await asyncio.sleep(0)

    async def status(self, name="hc-a"):
        return (await self.client.get("health", name)).status


@pytest.mark.asyncio
async def test_success_flow_updates_status_and_metrics():
    h = Harness(succeed_after(1))
    await h.apply_and_reconcile(make_hc())
    await h.settle()
    await h.reconciler.wait_watches()
    st = await h.status()
    assert st.status == "Succeeded"
    assert st.success_count == 1
    assert st.total_healthcheck_runs == 1
    assert st.started_at is not None and st.finished_at is not None
    assert st.last_successful_workflow.startswith("check-")
    assert (
        h.metrics.sample_value(
            "healthcheck_success_count",
            {"healthcheck_name": "hc-a", "workflow": "healthCheck"},
        )
        == 1
    )
    # RBAC provisioned
    assert ("ServiceAccount", "health", "check-sa") in h.backend.objects


@pytest.mark.asyncio
async def test_periodic_reschedule_runs_again():
    h = Harness(succeed_after(1))
    await h.apply_and_reconcile(make_hc(repeat=60))
    await h.settle()
    assert (await h.status()).success_count == 1
    # timer fires at +60s -> second run -> counts advance
    await h.clock.advance(61)
    await h.reconciler.wait_watches()
    assert (await h.status()).success_count == 2
    assert len(h.engine.submitted) == 2


@pytest.mark.asyncio
async def test_failure_flow_records_error():
    h = Harness(fail_after(1, "deliberate failure"))
    await h.apply_and_reconcile(make_hc())
    await h.settle()
    st = await h.status()
    assert st.status == "Failed"
    assert st.failed_count == 1
    assert st.error_message == "deliberate failure"
    assert st.last_failed_at is not None
    assert st.last_failed_workflow.startswith("check-")
    assert (
        h.metrics.sample_value(
            "healthcheck_error_count",
            {"healthcheck_name": "hc-a", "workflow": "healthCheck"},
        )
        == 1
    )


@pytest.mark.asyncio
async def test_poll_timeout_synthesizes_failure():
    # fake engine never completes; workflow timeout 10s -> synthesized Failed
    # (reference: healthcheck_controller.go:627-632; envtest exploits the
    # same behavior since no Argo controller runs)
    h = Harness()  # never_complete
    await h.apply_and_reconcile(make_hc(timeout=10))
    await h.clock.advance(30)
    await h.reconciler.wait_watches()
    st = await h.status()
    assert st.status == "Failed"
    assert st.failed_count == 1


@pytest.mark.asyncio
async def test_pause_sets_stopped():
    h = Harness()
    hc = make_hc(repeat=0)
    await h.apply_and_reconcile(hc)
    st = await h.status()
    assert st.status == "Stopped"
    assert "stopped" in st.error_message
    assert st.finished_at is not None
    assert len(h.engine.submitted) == 0


@pytest.mark.asyncio
async def test_cron_schedule_runs_and_reschedules():
    h = Harness(succeed_after(1))
    await h.apply_and_reconcile(make_hc(repeat=0, cron="@every 30s", timeout=5))
    await h.settle()
    assert (await h.status()).success_count == 1
    await h.clock.advance(32)
    await h.reconciler.wait_watches()
    assert (await h.status()).success_count == 2


@pytest.mark.asyncio
async def test_invalid_cron_no_panic():
    # reference edge test: invalid cron must not crash the controller
    h = Harness()
    requeue = None
    hc = make_hc(repeat=0, cron="not-a-cron")
    created = await h.client.apply(hc)
    requeue = await h.reconciler.reconcile(created.namespace, created.name)
    assert requeue == 1.0  # 1s requeue on process error (reference: :204)
    assert len(h.engine.submitted) == 0


@pytest.mark.asyncio
async def test_dedupe_skips_recent_run():
    h = Harness(succeed_after(1))
    created = await h.apply_and_reconcile(make_hc(repeat=60))
    await h.settle()
    assert len(h.engine.submitted) == 1
    # a watch-event-driven reconcile right after completion must dedupe
    await h.reconciler.reconcile(created.namespace, created.name)
    await h.settle()
    assert len(h.engine.submitted) == 1


@pytest.mark.asyncio
async def test_cron_dedupe_no_churn():
    """Divergence 4: status-write events must not resubmit cron checks
    (the reference resubmits immediately on every event)."""
    h = Harness(succeed_after(1))
    created = await h.apply_and_reconcile(make_hc(repeat=0, cron="@every 60s", timeout=5))
    await h.settle()
    assert len(h.engine.submitted) == 1
    await h.reconciler.reconcile(created.namespace, created.name)
    await h.settle()
    assert len(h.engine.submitted) == 1  # deduped, next run comes from the timer


@pytest.mark.asyncio
async def test_delete_cancels_timer():
    h = Harness(succeed_after(1))
    created = await h.apply_and_reconcile(make_hc(repeat=60))
    await h.settle()
    assert h.reconciler.timers.pending("health/hc-a")
    await h.client.delete("health", "hc-a")
    await h.reconciler.reconcile(created.namespace, created.name)
    assert not h.reconciler.timers.pending("health/hc-a")
    # time passes; nothing new submitted
    await h.clock.advance(120)
    assert len(h.engine.submitted) == 1


@pytest.mark.asyncio
async def test_conflict_on_status_write_retries():
    h = Harness(succeed_after(1))
    await h.client.apply(make_hc())
    h.client.force_conflicts(2)
    await h.reconciler.reconcile("health", "hc-a")
    await h.settle()
    await h.reconciler.wait_watches()  # waits through the retry backoff
    assert (await h.status()).success_count == 1


@pytest.mark.asyncio
async def test_nil_workflow_resource_is_noop():
    # reference edge test: nil Workflow.Resource must no-op, not crash
    h = Harness()
    hc = make_hc()
    hc.spec.workflow.resource = None
    created = await h.client.apply(hc)
    requeue = await h.reconciler.reconcile(created.namespace, created.name)
    assert requeue is None
    assert len(h.engine.submitted) == 0


@pytest.mark.asyncio
async def test_missing_level_errors_and_requeues():
    h = Harness()
    hc = make_hc()
    hc.spec.level = ""
    created = await h.client.apply(hc)
    requeue = await h.reconciler.reconcile(created.namespace, created.name)
    assert requeue == 1.0


# -- remedy paths ------------------------------------------------------


@pytest.mark.asyncio
async def test_failure_triggers_remedy_and_cleans_rbac():
    h = Harness(succeed_after(1))
    h.engine.on_prefix("check-", fail_after(1, "check failed"))
    await h.apply_and_reconcile(make_hc(remedy=True))
    await h.settle()
    st = await h.status()
    assert st.status == "Failed"
    assert st.remedy_status == "Succeeded"
    assert st.remedy_success_count == 1
    assert st.remedy_total_runs == 1
    # remedy RBAC was created then deleted (ephemeral)
    assert ("ServiceAccount", "health", "remedy-sa") not in h.backend.objects
    # but the check RBAC remains
    assert ("ServiceAccount", "health", "check-sa") in h.backend.objects
    assert (
        h.metrics.sample_value(
            "healthcheck_success_count",
            {"healthcheck_name": "hc-a", "workflow": "remedy"},
        )
        == 1
    )


@pytest.mark.asyncio
async def test_remedy_rbac_cleaned_when_engine_fails_mid_watch():
    # an engine exception while polling the remedy workflow must not
    # leak the ephemeral WRITE-capable SA/Role/Binding into the cluster
    # (the reference leaks here, healthcheck_controller.go:773-784)
    def explode(wf, count):
        raise RuntimeError("apiserver gone mid-remedy-watch")

    h = Harness(succeed_after(1))
    h.engine.on_prefix("check-", fail_after(1, "check failed"))
    h.engine.on_prefix("remedy-", explode)
    await h.apply_and_reconcile(make_hc(remedy=True))
    # transient errors pace rather than abort: the verdict comes from
    # the poll deadline (workflow timeout 10s), so drive time past it
    await h.settle(15)
    await h.reconciler.wait_watches()
    st = await h.status()
    assert st.remedy_status == "Failed"  # synthesized at the deadline
    assert st.remedy_failed_count == 1
    assert ("ServiceAccount", "health", "remedy-sa") not in h.backend.objects
    assert ("ClusterRole", "", "remedy-sa-cluster-role") not in h.backend.objects
    assert (
        "ClusterRoleBinding",
        "",
        "remedy-sa-cluster-role-binding",
    ) not in h.backend.objects
    # the check's own (read-only) RBAC is not ephemeral and stays
    assert ("ServiceAccount", "health", "check-sa") in h.backend.objects


@pytest.mark.asyncio
async def test_remedy_rbac_cleaned_when_submit_fails():
    # same guarantee one step earlier: a submit() rejection (e.g. a 5xx
    # storm) may not strand the write-capable identity either
    h = Harness(succeed_after(1))
    h.engine.on_prefix("check-", fail_after(1, "check failed"))
    real_submit = h.engine.submit

    async def submit(manifest):
        name = manifest.get("metadata", {}).get("generateName", "")
        if name.startswith("remedy-"):
            raise RuntimeError("503 submitting remedy")
        return await real_submit(manifest)

    h.engine.submit = submit
    await h.apply_and_reconcile(make_hc(remedy=True))
    await h.settle()
    await h.reconciler.wait_watches()
    assert ("ServiceAccount", "health", "remedy-sa") not in h.backend.objects
    assert ("ClusterRole", "", "remedy-sa-cluster-role") not in h.backend.objects


@pytest.mark.asyncio
async def test_remedy_failure_records_remedy_error():
    h = Harness(fail_after(1, "all failing"))
    await h.apply_and_reconcile(make_hc(remedy=True))
    await h.settle()
    st = await h.status()
    assert st.remedy_status == "Failed"
    assert st.remedy_failed_count == 1
    assert st.remedy_error_message == "all failing"
    assert st.remedy_last_failed_at is not None


@pytest.mark.asyncio
async def test_success_resets_remedy_state():
    # reference: healthcheck_controller.go:649-660
    h = Harness(succeed_after(1))
    h.engine.on_prefix("check-", fail_after(1))
    await h.apply_and_reconcile(make_hc(repeat=60, remedy=True))
    await h.settle()
    assert (await h.status()).remedy_total_runs == 1
    # next run: check succeeds -> remedy state reset
    h.engine._prefix_completers.clear()
    await h.clock.advance(61)
    await h.reconciler.wait_watches()
    st = await h.status()
    assert st.status == "Succeeded"
    assert st.remedy_total_runs == 0
    assert st.remedy_success_count == 0
    assert st.remedy_status == "HealthCheck Passed so Remedy is reset"


@pytest.mark.asyncio
async def test_remedy_runs_limit_gates_until_reset_interval():
    # reference: healthcheck_controller.go:679-711; examples:
    # Remedy_Examples/inlineMemoryRemedy_limit.yaml (limit 2, reset 300)
    h = Harness(fail_after(1, "persistent failure"))
    await h.apply_and_reconcile(
        make_hc(repeat=30, remedy=True, remedy_runs_limit=2, remedy_reset_interval=300)
    )
    await h.settle()
    assert (await h.status()).remedy_total_runs == 1
    # run 2: still under limit
    await h.clock.advance(31)
    await h.reconciler.wait_watches()
    assert (await h.status()).remedy_total_runs == 2
    # run 3: limit reached, within reset interval -> remedy skipped
    await h.clock.advance(31)
    await h.reconciler.wait_watches()
    st = await h.status()
    assert st.remedy_total_runs == 2
    assert st.failed_count == 3
    # after the reset interval elapses -> reset and run again
    await h.clock.advance(301)
    await h.reconciler.wait_watches()
    st = await h.status()
    assert st.remedy_total_runs == 1  # reset to 0, then ran once
    assert st.failed_count >= 4


@pytest.mark.asyncio
async def test_remedy_without_gates_always_runs():
    h = Harness(fail_after(1))
    await h.apply_and_reconcile(make_hc(repeat=30, remedy=True))
    await h.settle()
    for i in range(2, 5):
        await h.clock.advance(31)
        await h.reconciler.wait_watches()
        assert (await h.status()).remedy_total_runs == i


@pytest.mark.asyncio
async def test_events_recorded():
    h = Harness(succeed_after(1))
    await h.apply_and_reconcile(make_hc())
    await h.settle()
    reasons = [e.message for e in h.recorder.events_for("health", "hc-a")]
    assert "Successfully created workflow" in reasons
    assert "Workflow status is Succeeded" in reasons
    assert "Rescheduled workflow for next run" in reasons


@pytest.mark.asyncio
async def test_custom_metrics_wired_from_outputs():
    """The reference implements custom metrics but never calls them
    (SURVEY.md §2 known defects) — here they must actually flow."""
    outputs = {
        "parameters": [
            {
                "name": "metrics",
                "value": '{"metrics": [{"name": "ici-bw-gbps", "value": 512.3,'
                ' "metrictype": "gauge", "help": "measured ICI bandwidth"}]}',
            }
        ]
    }
    h = Harness(succeed_after(1, outputs=outputs))
    await h.apply_and_reconcile(make_hc())
    await h.settle()
    assert (
        h.metrics.sample_value("hc_a_ici_bw_gbps", {"healthcheck_name": "hc-a"})
        == 512.3
    )


@pytest.mark.asyncio
async def test_checkpoint_resume_from_status():
    """SURVEY.md §5.4: durable state lives in the CR status; a fresh
    reconciler (controller restart) rebuilds its schedule idempotently
    without double-running a recently-finished check."""
    h = Harness(succeed_after(1))
    created = await h.apply_and_reconcile(make_hc(repeat=60))
    await h.settle()
    assert (await h.status()).success_count == 1

    # "restart": the old process dies (its timers with it), new
    # reconciler over the same durable client state
    await h.reconciler.shutdown()
    r2 = make_restarted_reconciler(h)
    # boot-time reconcile: finished recently, no timer -> divergence 10:
    # the schedule is REBUILT for the remaining interval instead of
    # re-running immediately (the reference resubmits everything on
    # restart — a restart storm)
    await r2.reconcile(created.namespace, created.name)
    await h.settle()
    assert (await h.status()).success_count == 1  # no double-run
    assert r2.timers.exists(created.key)
    # subsequent reconciles stay deduped
    await r2.reconcile(created.namespace, created.name)
    await h.settle()
    assert (await h.status()).success_count == 1
    # ...and the rebuilt timer fires at the original cadence
    await h.clock.advance(61)
    await r2.wait_watches()
    assert (await h.status()).success_count == 2
    await r2.shutdown()


def make_restarted_reconciler(h):
    return HealthCheckReconciler(
        client=h.client,
        engine=h.engine,
        rbac=RBACProvisioner(h.backend),
        recorder=h.recorder,
        metrics=h.metrics,
        clock=h.clock,
    )


@pytest.mark.asyncio
async def test_checkpoint_resume_cron_keeps_anchored_cadence():
    """Cron resume: the rebuilt timer is anchored at the fire owed when
    the process died (finished_at + period), so downtime neither fires
    early (double-counting elapsed) nor stretches the cadence."""
    h = Harness(succeed_after(1))
    created = await h.apply_and_reconcile(make_hc(repeat=0, cron="@every 60s", timeout=5))
    await h.settle()
    await h.reconciler.wait_watches()
    assert (await h.status()).success_count == 1

    await h.clock.advance(20)  # controller "down" for 20s
    await h.reconciler.shutdown()
    r2 = make_restarted_reconciler(h)
    await r2.reconcile(created.namespace, created.name)
    await h.settle()
    assert (await h.status()).success_count == 1  # no immediate re-run
    # anchored fire at finished+60 = restart+40: not at +35...
    await h.clock.advance(35)
    await r2.wait_watches()
    assert (await h.status()).success_count == 1
    # ...but by +45
    await h.clock.advance(10)
    await r2.wait_watches()
    assert (await h.status()).success_count == 2
    await r2.shutdown()


@pytest.mark.asyncio
async def test_checkpoint_resume_absolute_cron_late_in_period():
    """Absolute cron restarted LATE in its period (elapsed > time to the
    next fire): still current — no spurious boot run, and the timer
    lands on the real next fire. (Comparing elapsed against the next-
    fire delta would wrongly call this overdue.)"""
    h = Harness(succeed_after(1))
    # FakeClock epoch is midnight: hourly fires at :00
    created = await h.apply_and_reconcile(make_hc(repeat=0, cron="0 * * * *", timeout=5))
    await h.settle()
    await h.reconciler.wait_watches()
    assert (await h.status()).success_count == 1  # first run at apply

    await h.clock.advance(2400)  # restart at :40 — no fire missed
    await h.reconciler.shutdown()
    r2 = make_restarted_reconciler(h)
    await r2.reconcile(created.namespace, created.name)
    await h.settle()
    assert (await h.status()).success_count == 1  # NO spurious re-run
    assert r2.timers.exists(created.key)
    await h.clock.advance(1300)  # past the 01:00 fire
    await r2.wait_watches()
    assert (await h.status()).success_count == 2
    await r2.shutdown()


@pytest.mark.asyncio
async def test_spec_edit_to_slower_cadence_rearms_instead_of_firing():
    """A spec edited to a slower cadence must not run at the old faster
    cadence: the already-armed timer re-checks the CURRENT spec at fire
    time and re-arms for the remaining interval."""
    h = Harness(succeed_after(1))
    created = await h.apply_and_reconcile(make_hc(repeat=60))
    await h.settle()
    assert (await h.status()).success_count == 1

    slow = make_hc(repeat=3600)
    await h.client.apply(slow)
    await h.reconciler.reconcile(created.namespace, created.name)
    await h.settle()

    # the old 60s timer fires, sees nothing owed under the new spec,
    # and re-arms — no run
    await h.clock.advance(100)
    await h.reconciler.wait_watches()
    assert (await h.status()).success_count == 1
    # the new cadence is honored (next run at finished+3600)
    await h.clock.advance(3600)
    await h.reconciler.wait_watches()
    assert (await h.status()).success_count == 2


@pytest.mark.asyncio
async def test_spec_edit_to_faster_cadence_takes_effect():
    """The opposite direction: shrinking the cadence must not wait out
    the old long timer."""
    h = Harness(succeed_after(1))
    created = await h.apply_and_reconcile(make_hc(repeat=3600))
    await h.settle()
    assert (await h.status()).success_count == 1

    fast = make_hc(repeat=30)
    await h.client.apply(fast)
    await h.clock.advance(31)  # old timer far away; new cadence owed
    await h.reconciler.reconcile(created.namespace, created.name)
    await h.settle()
    await h.reconciler.wait_watches()
    assert (await h.status()).success_count == 2


@pytest.mark.asyncio
async def test_checkpoint_resume_runs_missed_cron_fire_immediately():
    """A cron fire missed during downtime must run at boot — skipping it
    would leave a daily check silent for a full extra period."""
    h = Harness(succeed_after(1))
    created = await h.apply_and_reconcile(make_hc(repeat=0, cron="@every 60s", timeout=5))
    await h.settle()
    await h.reconciler.wait_watches()
    assert (await h.status()).success_count == 1

    await h.clock.advance(90)  # down PAST the next fire (finished+60)
    await h.reconciler.shutdown()
    r2 = make_restarted_reconciler(h)
    await r2.reconcile(created.namespace, created.name)
    await h.settle()
    await r2.wait_watches()
    assert (await h.status()).success_count == 2  # missed fire ran at boot
    await r2.shutdown()


# -- review-finding regressions ---------------------------------------


@pytest.mark.asyncio
async def test_same_name_different_namespace_timers_independent():
    """Timers are keyed namespace/name: same-named checks in different
    namespaces must not clobber each other (reference keys by bare name)."""
    h = Harness(succeed_after(1))
    a = make_hc(name="disk-check")
    b = make_hc(name="disk-check")
    b.metadata.namespace = "team-b"
    created_a = await h.client.apply(a)
    created_b = await h.client.apply(b)
    await h.reconciler.reconcile(created_a.namespace, created_a.name)
    await h.reconciler.reconcile(created_b.namespace, created_b.name)
    await h.settle()
    assert h.reconciler.timers.pending("health/disk-check")
    assert h.reconciler.timers.pending("team-b/disk-check")
    # deleting one cancels only its own timer
    await h.client.delete("team-b", "disk-check")
    await h.reconciler.reconcile("team-b", "disk-check")
    assert h.reconciler.timers.pending("health/disk-check")
    assert not h.reconciler.timers.pending("team-b/disk-check")


@pytest.mark.asyncio
async def test_watch_engine_error_requeues_instead_of_dying():
    """A transient engine error in the detached watch must re-reconcile
    after ~1s, not silently kill the schedule."""
    h = Harness(succeed_after(1))
    calls = {"n": 0}
    orig_get = h.engine.get

    async def flaky_get(namespace, name):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient API blip")
        return await orig_get(namespace, name)

    h.engine.get = flaky_get
    await h.apply_and_reconcile(make_hc(repeat=60))
    await h.settle()
    await h.clock.advance(2)  # ride out the 1s requeue delay
    await h.reconciler.wait_watches()
    st = await h.status()
    assert st.success_count == 1  # recovered and completed


@pytest.mark.asyncio
async def test_no_duplicate_submission_while_workflow_in_flight():
    """A reconcile event landing while the workflow is still running
    (run outlives the interval) must not stack a second workflow."""
    h = Harness(succeed_after(10))  # needs 10 polls -> long-running
    created = await h.apply_and_reconcile(make_hc(repeat=5, timeout=1000))
    await h.settle()
    assert len(h.engine.submitted) == 1
    # interval elapses but the run is still in flight; event-driven
    # reconciles must not submit a duplicate
    await h.clock.advance(6)
    await h.reconciler.reconcile(created.namespace, created.name)
    await h.settle()
    assert len(h.engine.submitted) == 1


@pytest.mark.asyncio
async def test_terminal_phase_on_final_poll_wins_over_timeout():
    """A workflow observed Succeeded on the final (post-deadline) poll is
    recorded as a success, not a synthesized failure."""
    h = Harness(succeed_after(3))  # succeeds on the 3rd poll
    await h.apply_and_reconcile(make_hc(timeout=4))  # max 2s, min 1s
    # polls: t=0 (1), t=2 (2), deadline at 4 -> final poll sees Succeeded
    await h.clock.advance(10)
    await h.reconciler.wait_watches()
    st = await h.status()
    assert st.status == "Succeeded"
    assert st.failed_count == 0


@pytest.mark.asyncio
async def test_remedy_terminal_phase_on_final_poll_wins_over_timeout():
    """Same final-poll policy for the remedy loop: a remedy that reached a
    terminal phase right at the deadline is not miscounted as failed."""
    h = Harness()
    h.engine.on_prefix("check-", fail_after(1))
    # remedy stays pending through the deadline; the final (post-timeout)
    # poll observes Succeeded
    h.engine.on_prefix("remedy-", succeed_after(5))
    await h.apply_and_reconcile(make_hc(timeout=4, remedy=True))
    await h.clock.advance(30)
    await h.reconciler.wait_watches()
    st = await h.status()
    assert st.remedy_status == "Succeeded"
    assert st.remedy_success_count == 1
    assert st.remedy_failed_count == 0


@pytest.mark.asyncio
async def test_shutdown_ends_standalone_requeue_loops():
    """A standalone reconciler (no Manager workqueue) whose timer-fired
    resubmit keeps failing lives in the in-task requeue ladder;
    shutdown() must end that loop promptly — it may not keep
    reconciling (and attempting submits) after the controller stopped.
    With a Manager the loop never exists: requeues ride the workqueue
    (requeue_hook)."""
    h = Harness(succeed_after(1))

    class FailSecondSubmitEngine:
        """First submit works (run 1 completes + reschedules); every
        later submit explodes, so the timer-fired resubmit falls onto
        the requeue ladder and stays there."""

        def __init__(self, inner):
            self._inner = inner
            self.attempts = 0

        async def submit(self, manifest):
            self.attempts += 1
            if self.attempts > 1:
                raise RuntimeError("boom")
            return await self._inner.submit(manifest)

        def __getattr__(self, name):
            return getattr(self._inner, name)

    h.reconciler.engine = FailSecondSubmitEngine(h.engine)
    await h.apply_and_reconcile(make_hc())  # run 1 completes
    await h.settle(61.0)  # timer fires; resubmit fails -> ladder
    for _ in range(3):
        await h.settle(2.0)  # the ladder keeps retrying at 1 s cadence
    assert h.reconciler.engine.attempts >= 3, h.reconciler.engine.attempts
    await h.reconciler.shutdown()
    assert not h.reconciler._requeue_loops
    # nothing reconciles after shutdown even if time keeps passing
    before = h.reconciler.engine.attempts
    await h.settle(10.0)
    assert h.reconciler.engine.attempts == before


@pytest.mark.asyncio
async def test_persistent_deterministic_poll_error_converges():
    """engine.get failing FOREVER with a non-transient error (revoked
    RBAC, a code bug) must not wedge the watch in silent 1 s retries:
    past the poll deadline the run synthesizes Failed and the schedule
    keeps going. (Transient 5xx storms, by contrast, deliberately ride
    past the deadline — the chaos tier pins that side.)"""
    h = Harness(succeed_after(1))

    class BrokenGetEngine:
        def __init__(self, inner):
            self._inner = inner

        async def submit(self, manifest):
            return await self._inner.submit(manifest)

        async def get(self, namespace, name):
            raise RuntimeError("deterministic boom")  # no .status attr

        def __getattr__(self, name):
            return getattr(self._inner, name)

    h.reconciler.engine = BrokenGetEngine(h.engine)
    await h.apply_and_reconcile(make_hc(timeout=5))
    # ride far past the poll deadline: 1 s retries, then the failed
    # authoritative confirm-read, then the synthesized verdict
    for _ in range(6):
        await h.settle(5.0)
    status = await h.status()
    assert status.status == "Failed", status
    assert status.failed_count == 1, status
    assert status.total_healthcheck_runs == 1
    # the schedule survived: the next run is armed
    assert h.reconciler.timers.pending("health/hc-a")


@pytest.mark.asyncio
async def test_slow_url_artifact_does_not_block_the_event_loop():
    """A url-source artifact fetch is a BLOCKING requests.get; run
    inline on the loop, a slow artifact server would freeze every
    other check, the watches, and lease renewal (a ~1 s stall already
    eats a sixth of a 10 s lease's renew deadline). The parse must
    ride a worker thread: while the fetch drags, loop heartbeats keep
    ticking."""
    import threading
    import time as time_mod
    from http.server import BaseHTTPRequestHandler, HTTPServer

    WF = b"apiVersion: argoproj.io/v1alpha1\nkind: Workflow\nspec: {}\n"

    class SlowHandler(BaseHTTPRequestHandler):
        def do_GET(self):
            time_mod.sleep(1.2)  # a slow artifact server
            self.send_response(200)
            self.end_headers()
            self.wfile.write(WF)

        def log_message(self, *args):
            pass

    srv = HTTPServer(("127.0.0.1", 0), SlowHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        h = Harness(succeed_after(1))
        hc = make_hc()
        hc.spec.workflow.resource.source.inline = None
        from activemonitor_tpu.api.types import URLArtifact

        hc.spec.workflow.resource.source.url = URLArtifact(
            path=f"http://127.0.0.1:{srv.server_port}/wf.yaml"
        )
        created = await h.client.apply(hc)

        heartbeats = []

        async def heartbeat():
            loop = asyncio.get_event_loop()
            last = loop.time()
            while True:
                await asyncio.sleep(0.05)
                now = loop.time()
                heartbeats.append(now - last)
                last = now

        hb = asyncio.create_task(heartbeat())
        await h.reconciler.reconcile(created.namespace, created.name)
        await h.reconciler.wait_watches()
        hb.cancel()
        assert (await h.status()).status == "Succeeded"
        # the loop never stalled anywhere near the fetch duration — a
        # blocked loop shows a ~1.2 s gap; the bound is relative to the
        # fetch so CI scheduler hiccups don't flake the signal
        assert heartbeats and max(heartbeats) < 0.9, max(heartbeats)
    finally:
        srv.shutdown()
