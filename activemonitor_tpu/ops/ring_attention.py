"""Ring attention — sequence-parallel attention over the device mesh.

The long-context path of the framework: the sequence axis is sharded
across devices, K/V blocks rotate around the ring via ``ppermute``
while each device accumulates attention for its resident Q block with
an online (flash-style) softmax — peak memory stays O(S/n) per device
and all communication is neighbor-hop ICI traffic that overlaps with
block compute under XLA's scheduler.

Used by the ``ring-attention`` probe both as a correctness check
(sequence-parallel result must match single-device attention) and as a
sequence-parallelism bandwidth/throughput canary for long-context
workloads.

Shapes inside ``shard_map`` (per device): q, k, v are
``[batch, seq_local, heads, head_dim]``; the global sequence is
``seq_local × n_devices`` with device i owning the i-th contiguous
block. Causality is enforced blockwise: a KV block strictly after the
Q block is skipped entirely, the diagonal block gets the triangular
mask, earlier blocks attend fully.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attend(q, k, v, mask):
    """Scores for one (Q-block, KV-block) pair.

    Returns (scores_max, exp_scores @ v, exp_scores row sums) for the
    online-softmax accumulation. q: [B,Sq,H,D]; k,v: [B,Sk,H,D];
    mask: [Sq,Sk] bool (True = attend) or None.
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    # upcast K/V here, not before the ring rotation: ppermute moves the
    # input-dtype blocks, so bf16 inputs cost bf16 (not f32) ICI traffic
    k = k.astype(jnp.float32)
    v = v.astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)
    block_max = jnp.max(scores, axis=-1)  # [B,H,Sq]
    exp = jnp.exp(scores - block_max[..., None])
    if mask is not None:
        # rows with no visible keys: exp(NEG_INF - NEG_INF) = 1 — zero them
        any_visible = jnp.any(mask, axis=-1)  # [Sq]
        exp = exp * any_visible[None, None, :, None]
    out = jnp.einsum("bhqk,bkhd->bqhd", exp, v)
    denom = jnp.sum(exp, axis=-1)  # [B,H,Sq]
    return block_max, out, denom


def _ring_attention_sharded(
    q, k, v, *, axis_name: str, n_devices: int, causal: bool, use_flash: bool
):
    """Body run per device inside shard_map. The ring rotation is a
    ``lax.scan`` — one traced step regardless of ring size, so compile
    time and HLO size stay flat as slices grow. With ``use_flash`` the
    per-step block compute runs the fused Pallas kernel
    (ops/flash_attention.py partial mode) instead of XLA einsums —
    same (max, unnormalized out, denom) merge contract, but the local
    score matrix stays in VMEM."""
    my_idx = jax.lax.axis_index(axis_name)
    batch, seq_local, heads, head_dim = q.shape

    causal_mask = jnp.tril(jnp.ones((seq_local, seq_local), jnp.bool_))
    perm = [(i, (i + 1) % n_devices) for i in range(n_devices)]

    if use_flash:
        from activemonitor_tpu.ops.flash_attention import flash_attention_partial

    qf = q.astype(jnp.float32)
    init = (
        k,  # rotated in input dtype — bf16 inputs keep bf16 ICI traffic
        v,
        jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32),  # acc
        jnp.zeros((batch, heads, seq_local), jnp.float32),  # denom
        jnp.full((batch, heads, seq_local), _NEG_INF, jnp.float32),  # running max
    )

    def step_fn(carry, step):
        kf, vf, acc, denom, running_max = carry
        kv_idx = (my_idx - step) % n_devices  # owner of the current K/V block
        def skip(q_in, kf, vf):
            # one skip state for every branch construct below: a
            # (NEG_INF max, zero acc, zero denom) triple the merge
            # treats as an empty block
            return (
                jnp.full((batch, heads, seq_local), _NEG_INF, jnp.float32),
                jnp.zeros((batch, seq_local, heads, head_dim), jnp.float32),
                jnp.zeros((batch, heads, seq_local), jnp.float32),
            )

        if use_flash:
            # fused path: diagonal block runs the causal kernel, earlier
            # blocks the unmasked one — two pallas variants under
            # lax.switch so each step's compute stays in VMEM. The
            # kernel upcasts internally, so it gets the ORIGINAL-dtype q
            # (bf16 inputs keep bf16 Q-block HBM traffic; the f32 qf
            # exists for the XLA einsum path)
            def attend_full(q_in, kf, vf):
                return flash_attention_partial(q_in, kf, vf, causal=False)

            def attend_diag(q_in, kf, vf):
                return flash_attention_partial(q_in, kf, vf, causal=True)

            if causal:
                branch = (
                    (kv_idx < my_idx).astype(jnp.int32)
                    + 2 * (kv_idx == my_idx).astype(jnp.int32)
                )  # 0 = skip (kv after us), 1 = full, 2 = diagonal
                block_max, block_out, block_denom = jax.lax.switch(
                    branch, (skip, attend_full, attend_diag), q, kf, vf
                )
            else:
                block_max, block_out, block_denom = attend_full(q, kf, vf)
        elif causal:
            # kv block strictly after our q block ⇒ nothing to attend:
            # skip the einsums entirely (lax.cond, so the dead ~half of
            # the causal grid costs nothing at runtime); diagonal block
            # gets the triangular mask, earlier blocks attend fully
            def attend(qf, kf, vf):
                mask = jnp.where(
                    kv_idx == my_idx, causal_mask, jnp.ones_like(causal_mask)
                )
                return _block_attend(qf, kf, vf, mask)

            block_max, block_out, block_denom = jax.lax.cond(
                kv_idx > my_idx, skip, attend, qf, kf, vf
            )
        else:
            block_max, block_out, block_denom = _block_attend(qf, kf, vf, None)
        new_max = jnp.maximum(running_max, block_max)
        old_scale = jnp.exp(running_max - new_max)
        blk_scale = jnp.exp(block_max - new_max)
        acc = acc * old_scale.transpose(0, 2, 1)[..., None] + block_out * (
            blk_scale.transpose(0, 2, 1)[..., None]
        )
        denom = denom * old_scale + block_denom * blk_scale
        # rotate K/V to the next neighbor (the final rotation returns
        # them home — a no-op cost-wise next to n-1 real hops)
        kf = jax.lax.ppermute(kf, axis_name, perm)
        vf = jax.lax.ppermute(vf, axis_name, perm)
        return (kf, vf, acc, denom, new_max), None

    (_, _, acc, denom, _), _ = jax.lax.scan(
        step_fn, init, jnp.arange(n_devices)
    )
    out = acc / jnp.maximum(denom.transpose(0, 2, 1)[..., None], 1e-30)
    return out.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = True,
    use_flash: bool = False,
) -> jax.Array:
    """Sequence-parallel attention over ``mesh[axis]``.

    q, k, v: global ``[batch, seq, heads, head_dim]`` arrays; the seq
    dim is sharded over the axis. Returns attention output with the
    same global shape/sharding. ``use_flash`` runs each ring step's
    block compute through the fused Pallas kernel (forward-only).
    """
    n = mesh.shape[axis]
    body = partial(
        _ring_attention_sharded,
        axis_name=axis,
        n_devices=n,
        causal=causal,
        use_flash=use_flash,
    )
    spec = P(None, axis, None, None)
    fn = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )
    return fn(q, k, v)


def reference_attention(q, k, v, causal: bool = True) -> jax.Array:
    """Single-device attention for correctness checks."""
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        seq_q, seq_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((seq_q, seq_k), jnp.bool_))
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32)).astype(q.dtype)
